"""Continuous-batching serving engine: slot-based decode over a paged KV pool.

``InferenceEngine.generate()`` is one-shot: it compiles a program per
``(B, S_pad, max_new)`` tuple, runs the whole batch in lockstep until the
longest request finishes, and admits no new work mid-flight — exactly the
regime Orca (OSDI '22, iteration-level scheduling) and vLLM (SOSP '23,
PagedAttention) showed leaves 2-10x decode throughput on the table under
mixed-length request streams.

:class:`ServingEngine` is the TPU-native redesign:

- a fixed fleet of ``b_slots`` decode slots backed by ONE persistent
  block-paged KV pool (``models.transformer.init_paged_cache``: lane-aligned
  128-token pages, physical page 0 reserved as the trash page);
- an iteration-level loop — each :meth:`step` runs ONE fixed-shape jitted
  decode program over all slots (inactive slots ride along masked), retires
  finished/EOS slots, and admits queued requests into free slots via
  bucketed fixed-shape ``[1, S_pad]`` prefill programs that scatter straight
  into the paged pool;
- exactly ``1 + len(prefill buckets)`` program shapes at steady state
  (:meth:`program_inventory`), so admission NEVER retraces or recompiles and
  short requests no longer convoy behind long ones.

Decode math stays on the XLA einsum path — the Pallas decode kernel was
retired in round 5 on an honest A/B; this win is scheduling, not kernels.

Multi-chip serving (docs/SERVING.md "Multi-chip serving"): the engine is
split into a HOST scheduling half (this class — admission, page tables,
prefix index, deadlines; pure Python over numpy) and a mesh-wide
execution half (:class:`~.execution.MeshExecutor` — the paged KV pool,
its NamedSharding placement, and every jitted fixed-shape program).
With ``mesh=`` the pool shards its KV-head dim over the mesh's
``'model'`` axis and the weights ride the same auto-TP specs
``generate()`` uses, so every steady-state program — decode tick,
bucketed prefill, COW snapshot, speculative draft/verify — is ONE GSPMD
program spanning the whole mesh, token-exact with the unsharded engine,
and per-device KV bytes shrink ~1/tp.  The zero-recompile inventory,
warm-restart program adoption and all the resilience paths below are
mesh-agnostic: they live on the host side of the split.

Scheduling policy (documented, deliberately simple): FIFO admission with
head-of-line blocking (no request skipping, so no starvation), and pages for
the whole request (prompt + max_new) are reserved at admission — a running
slot can never run out of pages mid-flight, so there is no preemption/swap
path to get wrong.

Cross-request KV reuse (docs/SERVING.md "Cross-request KV reuse"): physical
pages are REFCOUNTED and immutable-once-full, and a prefix index
(``prefix_cache.PrefixIndex``: rolling hash over page-aligned token chunks →
physical page) lets a request whose prompt prefix is already resident map
the shared pages into its page table and prefill only the unshared tail —
copy-on-write applies to the one partial boundary page (a fixed-shape
snapshot program; see ``models.transformer.cow_copy_page``).  Admission
reserves only unshared pages; retirement, expiry and quarantine DROP
refcounts instead of freeing, and the index holds one refcount per cached
page so hot prefixes survive their donors.  The pool invariant becomes
``free + quarantined + referenced == num_pages - 1``
(:meth:`ServingEngine.page_accounting`).  Sharing is pure page-table
indirection: the program inventory is unchanged at steady state and
shared-prefix outputs stay token-exact with the unshared path (K/V at
position ``t`` is a pure function of tokens ``0..t``).

KV-page tiering (docs/SERVING.md "KV-page tiering"): with
``host_tier_pages=N`` the reclaim path DEMOTES cold full prefix pages to a
host-RAM tier (``inference/kv_tiering.py``) instead of evicting them, and a
prefix hit on a demoted entry PROMOTES the page back into a free device
slot before admission maps it — the cache working set is bounded by host
RAM, not HBM.  The tier movers are fixed-shape programs compiled at init
(zero-recompile preserved), the device-pool invariant extends with a
demoted ledger (``demoted == host-tier size``, folded into
``page_accounting()["balanced"]``), and host buffers survive supervisor
warm restarts and ``recycle()`` (:meth:`adopt_host_tier`).

Generation runs per-slot RNG lanes (docs/SERVING.md "Sampling"): each
request may carry a :class:`~.sampling.SamplingParams` (temperature /
top-k / top-p / seed) and the ONE decode program samples with *traced*
per-slot parameter vectors — greedy is just the ``temperature <= 0`` lane
value, so any mix of greedy and sampled slots shares the same compiled
program and admission never recompiles.  Keys are counter-based
(``fold_in(PRNGKey(seed), position)``), which makes sampled streams
engine-independent and replay/failover-exact, and keeps the parity
contract: same seed/params ⇒ serving output token-identical to
``generate(sampling=...)``.  With ``speculative=``
(:class:`~.speculative.SpeculativeConfig`) a small draft model decodes k
candidates per tick against its own mirrored paged pool and the target
verifies all k in one fixed-shape pass — 1..k tokens per slot per tick,
target distribution preserved by in-graph rejection sampling, greedy
speculative token-exact vs non-speculative greedy.  The loop is
host-driven and synchronous: one device program + one [B_slots] token
fetch per tick (k+1 programs per tick under speculation).

Resilience (docs/SERVING.md "Failure handling"): per-request deadlines and a
bounded admission queue with explicit load shedding — expired or shed
requests finish with a typed :class:`RequestResult` (``finish_reason``
``"deadline"`` / ``"shed"``) carrying a ``retry_after_s`` hint instead of
occupying pages forever; a slot whose prefill fails repeatedly is
quarantined (fenced from scheduling, its pages leaked-and-accounted);
:meth:`health` snapshots the loop and :meth:`drain` stops admission,
finishes in-flight work and hands back unserved requests.  Fault-injection
sites: ``serve.tick`` (every tick), ``serve.admit`` (every admission),
``serve.prefill`` / ``serve.decode`` (immediately before the respective
device calls — see resilience/fault_injection.py).  An optional
:class:`~deepspeed_tpu.resilience.HangWatchdog` can be armed around each
device step so a wedged collective becomes a stack report + a
supervisor-recyclable exit instead of a silent forever-hang
(docs/RESILIENCE.md).  :class:`~.serving_supervisor.ServingSupervisor`
wraps this engine with a warm-restart loop that replays the queue and
in-flight requests token-exactly after a poisoned-pool or injected failure.

Observability (docs/OBSERVABILITY.md): every tick/admission/prefill/decode
runs under a ``serve.*`` span on the process-global tracer (no-op when
tracing is disabled), so a flight-recorder dump after a fault covers the
poisoned tick, and :class:`RequestResult` carries a per-request timeline
(``queued_s``, ``ttft_s``, ``decode_ticks``, ``replays``).
"""
from __future__ import annotations

import bisect
import dataclasses
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional

import numpy as np

import jax.numpy as jnp

from ..models.transformer import PAGE_SIZE
from ..observability.device_profiler import (device_trace_unit,
                                             maybe_capture_from_env)
from ..observability.program_stats import ProgramCatalog
from ..observability.slo import SloEvaluator, SloRule
from ..observability.trace import (get_tracer, new_trace_id, trace_count,
                                   trace_context, trace_span)
from ..resilience import (SITE_SERVE_ADMIT, SITE_SERVE_DECODE,
                          SITE_SERVE_PREFILL, SITE_SERVE_TICK, maybe_fire)
from ..utils.logging import log_dist, logger
from .adapters import AdapterRegistry
from .engine import InferenceEngine
from .execution import MeshExecutor
from .kv_tiering import HostTier
from .prefix_cache import PrefixIndex, PrefixMatch
from .sampling import SamplingParams, as_lanes
from .speculative import SpeculativeConfig, SpeculativeDecoder

_bucket = InferenceEngine._bucket   # shared prompt-length bucketing (pow2>=16)

# a COW boundary match must save at least this much prefill to be worth a
# cross-layer page snapshot — a 1-token match (first tokens coinciding by
# chance, ~1/vocab per prompt pair) would pay a pool-shaped copy to skip one
# token of prefill
MIN_COW_TOKENS = 2


class ServeTimeout(RuntimeError):
    """``run``/``drain`` exceeded its ``max_ticks`` budget.  Deliberately
    NOT retried by :class:`~.serving_supervisor.ServingSupervisor` — a tick
    budget is a test/caller bound, not a fault."""


class PoolConsumedError(RuntimeError):
    """The donated KV pool was consumed by a failed device call — the engine
    cannot continue and must be rebuilt (``ServingSupervisor`` does this
    automatically, replaying queue + in-flight requests)."""


class SlotPrefillError(RuntimeError):
    """A prefill failed in a way attributable to one slot/request; the
    reservation was unwound and the request re-queued.  When the pool
    survived (no donation, or the failure fired before the device call) the
    engine keeps serving — no restart needed."""

    def __init__(self, msg: str, slot: int, rid: Any, quarantined: bool):
        super().__init__(msg)
        self.slot = slot
        self.rid = rid
        self.quarantined = quarantined


@dataclasses.dataclass
class Request:
    """One generation request.  ``arrival_time`` is seconds relative to the
    start of :meth:`ServingEngine.run` (0 = available immediately);
    ``deadline_s`` is a serving budget measured from arrival — a request
    still queued (or still decoding) past it finishes with
    ``finish_reason="deadline"`` instead of occupying queue/pages forever."""
    rid: Any
    input_ids: np.ndarray
    max_new_tokens: int = 32
    eos_token_id: Optional[int] = None
    arrival_time: float = 0.0
    deadline_s: Optional[float] = None
    # absolute time.monotonic() stamp of when the request FIRST became
    # available, stamped by ServingSupervisor._rebase across a warm restart
    # (None = derive from this engine's clock).  Keeps queued-age gauges,
    # arrival_s/ttft_s stamps and retry hints anchored to the true arrival
    # instead of the replacement engine's reset clock (docs/SERVING.md).
    arrival_epoch_s: Optional[float] = None
    # per-request sampling lane (None = greedy, the historical contract).
    # Counter-based keys (fold_in(PRNGKey(seed), position)) make the
    # sampled stream a pure function of (seed, params, model), so replay,
    # failover resume and cross-engine parity with generate(sampling=...)
    # all stay token-exact (docs/SERVING.md "Sampling").
    sampling: Optional[SamplingParams] = None
    # fleet-wide trace id (docs/OBSERVABILITY.md "Distributed tracing"):
    # one id per REQUEST, assigned at first submission (router or engine)
    # and propagated verbatim through every hop — warm-restart replays,
    # failover re-dispatches and journal reconstructions all continue the
    # SAME trace, so one request is one trace across the whole fleet.
    trace_id: Optional[str] = None
    # tenant adapter (docs/SERVING.md "Multi-tenant adapter serving"):
    # None = the shared base model; an id must be registered with the
    # engine's AdapterRegistry — resolution happens at submission (under
    # the serve.adapter_resolve span) so an unknown tenant is a loud
    # ValueError, never a silently-base-served stream.  The id rides
    # every fleet hop (journal docs, failover re-dispatches) so a resumed
    # stream continues under the SAME tenant weights.
    adapter_id: Optional[str] = None


@dataclasses.dataclass
class RequestResult:
    rid: Any
    input_ids: np.ndarray
    output_ids: np.ndarray          # generated tokens (incl. eos when hit)
    finish_reason: str              # "eos" | "length" | "deadline" | "shed"
    prefill_bucket: int
    # absolute time.monotonic() stamps (arrival = admission availability)
    arrival_s: float = 0.0
    admit_s: float = 0.0
    first_token_s: float = 0.0
    finish_s: float = 0.0
    # set on "shed" and queue-expired "deadline" results: a backlog-derived
    # hint for when a resubmission is likely to be admitted
    retry_after_s: Optional[float] = None
    # ---- per-request timeline (docs/OBSERVABILITY.md): decode program
    # invocations that fed this request, and how many times a warm restart
    # re-prefilled it (ServingSupervisor stamps both when stitching replayed
    # results).  Prefill-emitted tokens (one per incarnation) are not decode
    # ticks, so for any result that generated tokens
    # decode_ticks == len(output_ids) - 1 - replays; empty-output terminals
    # (shed / queue-expired) carry 0/0.
    decode_ticks: int = 0
    replays: int = 0
    # prompt tokens served from the prefix index at admission (shared full
    # pages + the COW boundary) instead of being re-prefilled — 0 on a cold
    # admission or when prefix caching is disabled.  For a replayed request
    # this is the LAST incarnation's share (its replay prompt includes the
    # already-generated tokens, which often re-share against the rebuilt
    # index).
    shared_prefix_tokens: int = 0
    # times a fleet router re-routed this request to a surviving engine
    # after its assigned engine's lease lapsed (inference/fleet.py) —
    # distinct from `replays`, which counts SAME-engine warm-restart
    # re-prefills: a failover re-prefills the journaled stream (or, with
    # no journal, the ORIGINAL prompt) on a different engine.
    failovers: int = 0
    # tokens of this output that were RESUMED from the fleet token journal
    # after a failover rather than decoded by the engine that finished the
    # request: the replacement re-prefilled prompt + journaled tokens as
    # pure KV reconstruction and resumed decoding AFTER the last journaled
    # token, so these tokens were never re-emitted (inference/fleet.py).
    # They contribute no decode_ticks (decode_ticks counts the finishing
    # stream's own decode-program invocations).  0 = no mid-stream resume.
    resumed_tokens: int = 0
    # the request's fleet-wide trace id (mirrors Request.trace_id)
    trace_id: Optional[str] = None
    # the tenant adapter this stream was served under (mirrors
    # Request.adapter_id; None = shared base model) — per-tenant
    # token-exactness checks key results by this
    adapter_id: Optional[str] = None
    # structured lifecycle record (docs/OBSERVABILITY.md "Distributed
    # tracing"): ordered (event, t, src) tuples covering
    # queued→admit→[prefix_match/cow]→prefill→first_token→
    # [replay|failover|resume]→finish.  `t` is time.monotonic() on the
    # recording process; `src` is the engine incarnation (int) for
    # engine-recorded events and an engine/router id (str) for
    # fleet-recorded ones.  ServingSupervisor and FleetRouter stitch the
    # record across incarnations and engines exactly like they stitch
    # tokens, so a failed-over request's record reads end to end.
    lifecycle: List = dataclasses.field(default_factory=list)

    @property
    def ttft_s(self) -> float:
        """Time to first token, from arrival (includes queueing)."""
        return self.first_token_s - self.arrival_s

    @property
    def latency_s(self) -> float:
        return self.finish_s - self.arrival_s

    @property
    def queued_s(self) -> float:
        """Time from arrival to slot admission (pure queueing, no decode)."""
        return self.admit_s - self.arrival_s


@dataclasses.dataclass
class _Slot:
    request: Request
    pages: List[int]            # shared prefix pages first, then private
    tokens: List[int]
    bucket: int
    arrival_s: float
    admit_s: float
    first_token_s: float
    shared_tokens: int = 0      # prompt tokens mapped from the prefix index
    # decode-program invocations that fed this slot (the prefill token is
    # not one).  Without speculation this is len(tokens) - 1; a speculative
    # verify tick emits 1..k+1 tokens per invocation, so it can be less.
    decode_ticks: int = 0
    # lifecycle events recorded so far (moved from _lifecycle_pending at
    # admission; the finish event completes it into RequestResult)
    lifecycle: List = dataclasses.field(default_factory=list)


class ServingEngine:
    """Iteration-level scheduler over a fixed slot fleet + paged KV pool.

    ``model`` must expose the paged decode contract (``init_paged_cache`` /
    ``apply_paged`` — see ``models.CausalLM``); ``params`` are used as given
    (share ``InferenceEngine.params`` via :meth:`InferenceEngine.serving` to
    keep serving numerics identical to ``generate()``).
    """

    def __init__(self, model, params, b_slots: int = 4,
                 page_size: int = PAGE_SIZE, num_pages: Optional[int] = None,
                 max_model_len: Optional[int] = None, monitor=None,
                 watchdog=None, dtype=None, kv_dtype=None, mesh=None,
                 max_queue: Optional[int] = None, quarantine_limit: int = 2,
                 probe_after_ticks: Optional[int] = None,
                 prefix_cache: bool = True,
                 prefix_index_entries: int = 4096,
                 host_tier_pages: Optional[int] = None,
                 speculative: Optional[SpeculativeConfig] = None,
                 program_stats_sample_every: int = 0,
                 slo_rules: Optional[List[SloRule]] = None,
                 adapters: Optional[AdapterRegistry] = None):
        if not hasattr(model, "apply_paged"):
            raise ValueError(
                "ServingEngine needs a model with the paged decode contract "
                "(init_paged_cache/apply_paged) — see models.CausalLM")
        self.model, self.params = model, params
        self.b_slots = int(b_slots)
        self.page_size = int(page_size)
        self.max_model_len = int(max_model_len or model.config.max_seq_len)
        if self.max_model_len > model.config.max_seq_len:
            # forward_paged clamps positions at max_seq_len-1 (a learned
            # pos_embed has no rows past it), so longer slots would emit
            # silently-wrong tokens rather than fail
            raise ValueError(
                f"max_model_len={self.max_model_len} exceeds the model's "
                f"max_seq_len={model.config.max_seq_len}")
        self.pages_per_slot = -(-self.max_model_len // self.page_size)
        # +1: physical page 0 is the reserved trash page
        full = 1 + self.b_slots * self.pages_per_slot
        self.num_pages = int(num_pages) if num_pages is not None else full
        if self.num_pages < 1 + self.pages_per_slot:
            raise ValueError(
                f"num_pages={self.num_pages} cannot hold one full slot "
                f"({self.pages_per_slot} pages of {self.page_size} tokens "
                f"+ the trash page)")
        self.monitor = monitor
        self.watchdog = watchdog
        # bounded admission: submissions past max_queue waiting requests are
        # shed with a typed result + retry-after hint (None = unbounded)
        self.max_queue = int(max_queue) if max_queue is not None else None
        if self.max_queue is not None and self.max_queue < 1:
            raise ValueError(f"max_queue={self.max_queue} must be >= 1")
        # consecutive prefill failures before a slot is fenced
        self.quarantine_limit = int(quarantine_limit)
        if self.quarantine_limit < 1:
            # 0 would mean "never fence": a persistent slot fault then loops
            # forever without ever reaching the all-quarantined terminal
            # error that hands control to the supervisor
            raise ValueError(
                f"quarantine_limit={self.quarantine_limit} must be >= 1")

        # ---- the device half (docs/SERVING.md "Multi-chip serving"): pool
        # placement, auto-TP param sharding, program construction and the
        # zero-recompile inventory live in the MeshExecutor — the scheduling
        # code below never touches a device array directly, so the same
        # loop drives one chip or a tensor-sharded mesh unchanged.
        self.mesh = mesh
        if host_tier_pages is not None:
            if not prefix_cache:
                raise ValueError(
                    "host_tier_pages requires prefix_cache=True — the host "
                    "tier parks demoted PREFIX pages (docs/SERVING.md "
                    "\"KV-page tiering\")")
            if int(host_tier_pages) < 1:
                raise ValueError(
                    f"host_tier_pages={host_tier_pages} must be >= 1")
        # per-program device-time accounting (docs/OBSERVABILITY.md
        # "Per-program accounting"): FLOPs/bytes from lowered cost analysis
        # at each program's first invocation, invocation counts per call,
        # synced wall-time sampling every Nth invocation (default 0 = never
        # — steady-state async pipelining untouched)
        self._catalog = ProgramCatalog(
            sample_every=program_stats_sample_every)
        # SLO rules (docs/OBSERVABILITY.md "SLOs and alerts"): evaluated
        # once per working tick over monitor gauges + span quantiles;
        # firing states in health()["alerts"] and (via the alert{rule=...}
        # gauges) on /metrics as dstpu_alert{rule="..."}
        self._slo = SloEvaluator(slo_rules) if slo_rules else None
        # windowed device-trace capture, env-armed (DS_TPU_DEVICE_TRACE):
        # first engine in the process starts the capture; step() counts
        # the window down one unit per tick
        maybe_capture_from_env()
        self._exec = MeshExecutor(model, params, self.num_pages,
                                  self.page_size, self.b_slots, dtype=dtype,
                                  kv_dtype=kv_dtype, mesh=mesh,
                                  prefix_cache=prefix_cache,
                                  host_tier=host_tier_pages is not None,
                                  catalog=self._catalog, adapters=adapters)
        self.params = self._exec.params   # auto-TP-sharded on a mesh
        # ---- multi-tenant adapter serving (docs/SERVING.md "Multi-tenant
        # adapter serving"): with a registry attached, every decode/prefill
        # /verify program takes the per-slot LoRA factor stacks as ONE
        # fixed-shape traced operand — admission of any tenant mix never
        # changes program shape, so the zero-recompile inventory holds
        # bit-identically.  The host stacks mirror the RNG lanes: numpy at
        # rest, device-cached by the executor until a slot flip
        # invalidates them.  Without a registry the programs trace without
        # the operand — byte-identical to the pre-adapter engine.
        self.adapters = adapters
        self._adapter_stacks = (adapters.make_slot_stacks(self.b_slots)
                                if adapters is not None else None)
        # fused-view mode (hot tenant): while set, the engine serves
        # base+adapter FUSED weights under a fresh weight epoch and only
        # this tenant's requests are admissible (their slot delta stays
        # zero — the weights already carry it)
        self.fused_adapter_id: Optional[str] = None
        self._base_params = self.params
        self.adapter_admissions = 0        # adapter-tagged slots admitted
        self._adapter_admit_by_id: Dict[str, int] = {}
        self._adapter_tokens_by_id: Dict[str, int] = {}
        # at-rest storage dtype of the paged pool (docs/SERVING.md
        # "Quantized KV pages"): None = compute dtype, "int8" = quantize-
        # on-store pages + per-page scale rows.  A page is still a page —
        # accounting, prefix sharing, COW, tiering and epoch stamps are
        # dtype-blind
        self.kv_dtype = self._exec.kv_dtype
        self._free_pages: List[int] = list(range(self.num_pages - 1, 0, -1))
        # per-page reference counts (page 0, the trash page, is never
        # counted): 0 = free or quarantined, >0 = held by slots and/or the
        # prefix index.  Pages return to the free list only at refcount 0,
        # so an indexed page's contents can never be recycled under a
        # reader (docs/SERVING.md "Cross-request KV reuse").
        self._refcount = np.zeros((self.num_pages,), np.int64)
        self._prefix = (PrefixIndex(self.page_size,
                                    max_entries=prefix_index_entries)
                        if prefix_cache else None)
        # ---- KV-page tiering (docs/SERVING.md "KV-page tiering"): under
        # pool pressure cold FULL prefix pages demote to pinned host
        # buffers instead of being evicted; a prefix hit on a demoted
        # entry promotes the page back into a free device slot before
        # admission maps it.  None = legacy evict-only behavior.
        self.host_tier_pages = (int(host_tier_pages)
                                if host_tier_pages is not None else None)
        self._tier: Optional[HostTier] = None
        if self.host_tier_pages is not None:
            page_bytes = self._exec.pool_bytes["total"] // self.num_pages
            self._tier = HostTier(self.host_tier_pages,
                                  page_bytes=page_bytes)
            # entry removal (eviction, collision subtree, LRU cap) must
            # drop the host buffer in the same step — never strand a slab
            self._prefix.on_drop_host = self._tier.discard
        self.demotions = 0            # pages moved device -> host
        self.promotions = 0           # pages moved host -> device
        self._demoted_hwm = 0         # high-water mark of the demoted ledger
        self._promote_lat_s: Deque[float] = deque(maxlen=2048)
        self._demote_lat_s: Deque[float] = deque(maxlen=2048)
        # ---- weight epochs (docs/HYBRID.md): the live-weight generation
        # this engine is serving.  update_params() advances it and flushes
        # every cached K/V page / prefix entry / host-tier slab (K/V is a
        # pure function of (tokens, params) — a param update makes all of
        # it stale).  Pages are stamped at allocation and admission refuses
        # to map a page from another epoch — the runtime proof that a
        # post-update prefix lookup can never serve pre-update K/V.
        self._weight_epoch = 0
        self.weight_updates = 0       # update_params() calls
        self.kv_flushed_pages = 0     # HBM prefix pages flushed by updates
        self.kv_flushed_slabs = 0     # host-tier slabs flushed by updates
        self._refresh_lat_s: Deque[float] = deque(maxlen=2048)
        self._page_epoch = np.zeros((self.num_pages,), np.int64)
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.prefix_shared_tokens = 0
        self.prefix_pages_shared = 0   # full pages mapped instead of prefilled
        self.cow_copies = 0
        self._pages_hwm = 0            # high-water mark of occupied pages
        self._page_table = np.zeros((self.b_slots, self.pages_per_slot),
                                    np.int32)
        self._lengths = np.zeros((self.b_slots,), np.int32)
        self._last_tok = np.zeros((self.b_slots,), np.int32)
        self._active = np.zeros((self.b_slots,), bool)
        # per-slot RNG lanes (docs/SERVING.md "Sampling"): traced parameter
        # vectors the ONE decode program samples with — greedy is just the
        # temperature<=0 lane value, so a heterogeneous request mix never
        # changes program shape.  The seed lane + the slot's position
        # counter (== _lengths) fully determine every sampled token.
        self._lane_temp = np.zeros((self.b_slots,), np.float32)
        self._lane_top_k = np.zeros((self.b_slots,), np.int32)
        self._lane_top_p = np.ones((self.b_slots,), np.float32)
        self._lane_seed = np.zeros((self.b_slots,), np.uint32)
        self.sampled_admissions = 0   # non-greedy requests admitted
        self._slots: List[Optional[_Slot]] = [None] * self.b_slots
        self._queue: Deque[Request] = deque()
        self._pending: List[Request] = []   # arrival-gated, sorted by time
        # queued + pending + in-flight + unclaimed results, for O(1)
        # duplicate-rid rejection (removed when the result is claimed)
        self._live_rids: set = set()
        # which engine incarnation this is under its supervisor (0 = the
        # first build; warm restarts and recycles stamp replacement
        # engines +1) — lifecycle events carry it so a stitched record
        # shows which incarnation served each phase
        self.engine_incarnation = 0
        # rid -> lifecycle events recorded before the request owns a slot
        # (the "queued" stamp); moved into the slot at admission, or
        # flushed into the terminal result for shed/expired requests
        self._lifecycle_pending: Dict[Any, List] = {}
        self._results: Dict[Any, RequestResult] = {}
        self._finished_order: List[Any] = []
        self._tick = 0
        self._tokens_out = 0
        self._t0 = time.monotonic()
        # ---- resilience state (docs/SERVING.md "Failure handling")
        self._quarantined = np.zeros((self.b_slots,), bool)
        self._quarantined_pages: List[int] = []   # leaked-and-accounted
        self._slot_failures = np.zeros((self.b_slots,), np.int64)
        # background probe/unfence: after `probe_after_ticks` clean ticks
        # (no slot-attributable failure anywhere on the fleet) a fenced
        # slot gets ONE canary prefill; success restores the slot AND its
        # quarantined pages.  None = fenced slots only recover via a full
        # engine rebuild (the pre-probe behavior).
        self.probe_after_ticks = (int(probe_after_ticks)
                                  if probe_after_ticks is not None else None)
        if self.probe_after_ticks is not None and self.probe_after_ticks < 1:
            raise ValueError(
                f"probe_after_ticks={self.probe_after_ticks} must be >= 1")
        self._quarantine_pages_by_slot: Dict[int, List[int]] = {}
        self._fence_tick: Dict[int, int] = {}
        self._last_failure_tick = 0
        self.probe_count = 0
        self.unfence_count = 0
        self._draining = False
        # deadline-bearing requests currently waiting (queue + pending):
        # lets _expire skip its O(backlog) queue scan entirely in the
        # common no-deadlines case
        self._waiting_deadlines = 0
        self.shed_count = 0
        self.deadline_count = 0
        self._ema_service_s: Optional[float] = None   # drives retry hints

        # env-gated /metrics endpoint (DS_TPU_METRICS_PORT): process-global,
        # and a taken fixed port falls back to an ephemeral bind instead of
        # failing the Nth engine on a shared host — the ACTUAL bound port is
        # what health() (and the fleet store advertisement) reports
        from ..observability.export import maybe_start_metrics_server

        srv = maybe_start_metrics_server(monitor)
        self.metrics_port = srv.port if srv is not None else None

        # multi-chip gauges are CONSTANT for the engine's lifetime (the
        # pool never reallocates, the mesh never changes) — write them once
        # at init; the Prometheus exposition serves the latest value per
        # name, so /metrics carries them from the first scrape
        info = self._exec.mesh_info()
        if self.monitor is not None:
            pb = self._exec.pool_bytes
            # kvq_* (docs/OBSERVABILITY.md): storage-dtype facts, constant
            # for the engine's lifetime.  scale_bytes_total is the part of
            # kv_pool_bytes_total spent on per-page scale rows (0 on a
            # full-precision pool), page_bytes the all-in per-page cost —
            # the honest denominator of the 2× capacity claim
            scale_bytes = sum(int(a.nbytes) for a in self._exec.pools[2:])
            self.monitor.write_events(
                [("serve/mesh_devices", float(info["mesh_devices"]), 0),
                 ("serve/kv_pool_bytes_total", float(pb["total"]), 0),
                 ("serve/kv_pool_bytes_per_device",
                  float(pb["per_device"]), 0),
                 ("serve/kvq_enabled",
                  1.0 if self._exec.quantized else 0.0, 0),
                 ("serve/kvq_scale_bytes_total", float(scale_bytes), 0),
                 ("serve/kvq_page_bytes",
                  float(pb["total"] // self.num_pages), 0)]
                + [(f"serve/mesh_axis_{a}", float(s), 0)
                   for a, s in info["mesh_axes"].items()])

        # speculative decoding (docs/SERVING.md "Speculative decoding"): a
        # draft model over its OWN pool with the same page geometry,
        # indexed by the same per-slot page tables — admission prefills
        # both pools, COW snapshots both, page accounting stays the
        # engine's.  Draft decode + verify compile here, at init.
        self._spec: Optional[SpeculativeDecoder] = None
        if speculative is not None:
            speculative.validate(model, self.max_model_len)
            self._spec = SpeculativeDecoder(
                speculative, model, self.num_pages, self.page_size,
                self.b_slots, dtype=dtype, kv_dtype=kv_dtype, mesh=mesh,
                donate=bool(self._donate), catalog=self._catalog,
                adapters=adapters)
            if self._cow_prog is not None:
                # pre-warm the COW jit on the DRAFT pool aval too: a
                # boundary COW at admission must never compile
                self._spec.cow(self._cow_prog, 0, 0)
        log_dist(
            f"serving engine ready: b_slots={self.b_slots} "
            f"pages={self.num_pages}x{self.page_size} "
            f"(max_model_len={self.max_model_len})"
            + (f" mesh={info['mesh_devices']}dev {info['mesh_axes']}"
               if mesh is not None else ""), ranks=[0])

    # ---------------------------------------------- device-half delegation
    # The executor owns the pool, the compiled programs and the donation
    # policy (inference/execution.py).  These views exist for the
    # supervisor's adoption checks, the probe/canary tests that swap a
    # bucket's program, and the speculative tick's pool handoff.

    @property
    def _kpool(self):
        return self._exec.kpool

    @_kpool.setter
    def _kpool(self, value):
        self._exec.kpool = value

    @property
    def _vpool(self):
        return self._exec.vpool

    @_vpool.setter
    def _vpool(self, value):
        self._exec.vpool = value

    @property
    def _decode_prog(self):
        return self._exec._decode_prog

    @property
    def _prefill_progs(self) -> Dict[int, Any]:
        return self._exec._prefill_progs

    @property
    def _cow_prog(self):
        return self._exec._cow_prog

    @property
    def _donate(self):
        return self._exec._donate

    def program_inventory(self) -> Dict[str, Any]:
        """The full set of program shapes this engine has built: one decode
        step + one prefill per prompt bucket (+ the one fixed-shape COW
        page copy when prefix caching is on, compiled at init).  Constant
        at steady state — admission never grows it beyond the bucket set."""
        inv = {"decode": 1, "prefill_buckets": sorted(self._prefill_progs)}
        if self._cow_prog is not None:
            inv["cow"] = 1
        if self._tier is not None:
            # the tier movers compile at init (traced page ids = one shape
            # each); demote/promote cycling never grows the inventory
            inv["tier"] = {"extract": 1, "inject": 1}
        if self._spec is not None:
            # draft decode + verify compile at init; draft prefills track
            # the target's bucket set — admission (greedy, sampled or
            # speculative mix) never grows any of it
            inv["speculative"] = self._spec.program_inventory()
        return inv

    def program_stats(self) -> Dict[str, Dict[str, Any]]:
        """Per-program accounting table (docs/OBSERVABILITY.md): for every
        program this engine has invoked — decode, each prefill bucket,
        COW, the tier movers, draft/verify under speculation — the
        compile-time FLOPs/bytes, invocation count, executed-FLOPs ledger
        and (when ``program_stats_sample_every`` > 0) sampled device wall
        time.  Mirrored in ``health()["program_stats"]`` and the
        ``serve/program_flops{program=...}`` gauges."""
        return self._catalog.table()

    def slo_states(self) -> Dict[str, Dict[str, Any]]:
        """Per-rule SLO snapshot (empty when no rules are configured)."""
        return self._slo.states() if self._slo is not None else {}

    def adapter_stats(self) -> Dict[str, Dict[str, int]]:
        """Per-tenant admission/token counters, keyed by adapter id
        (empty without a registry) — what the multi-tenant bench reads."""
        if self.adapters is None:
            return {}
        return {aid: {"admissions": self._adapter_admit_by_id.get(aid, 0),
                      "tokens": self._adapter_tokens_by_id.get(aid, 0)}
                for aid in self.adapters.loaded()}

    # ---------------------------------------------------------- scheduling

    def _pages_needed(self, req: Request) -> int:
        return -(-(len(req.input_ids) + req.max_new_tokens) // self.page_size)

    # ------------------------------------------------- page refcounting

    def _alloc_pages(self, n: int) -> List[int]:
        """Pop ``n`` free pages and take the first reference on each.
        Every allocation stamps the page with the current weight epoch —
        the content about to be written is a function of the LIVE params
        (docs/HYBRID.md)."""
        pages = [self._free_pages.pop() for _ in range(n)]
        for p in pages:
            self._refcount[p] = 1
            self._page_epoch[p] = self._weight_epoch
        occupied = (self.num_pages - 1) - len(self._free_pages)
        if occupied > self._pages_hwm:
            self._pages_hwm = occupied
        return pages

    def _share_page(self, p: int) -> None:
        self._refcount[p] += 1

    def _drop_page(self, p: int) -> None:
        """Release one reference; the last reference frees the page.  A
        negative count means a double-free — fail loudly, the pool can no
        longer be trusted."""
        c = int(self._refcount[p]) - 1
        if c < 0:
            raise RuntimeError(
                f"page {p} dropped below zero references — double-free "
                "(page accounting is corrupt; rebuild the engine)")
        self._refcount[p] = c
        if c == 0:
            self._free_pages.append(p)

    def _leak_pages(self, pages: List[int]) -> None:
        """Quarantine path: zero the refs WITHOUT freeing — suspect
        contents are leaked-and-accounted, never recycled."""
        for p in pages:
            self._refcount[p] = 0
        self._quarantined_pages.extend(pages)

    def page_accounting(self) -> Dict[str, Any]:
        """The refcount pool invariant, one call: every page (minus the
        trash page) is exactly one of free, quarantined, or referenced
        (held by slots and/or the prefix index).  ``balanced`` is what the
        chaos tests assert after every kill; ``cached`` counts pages the
        prefix index pins (a subset of ``referenced``).  With KV-page
        tiering the invariant extends with the DEMOTED ledger: a demoted
        entry holds no device page, so the device equation is untouched,
        but every demoted index entry must have exactly one host-tier
        buffer (``demoted == host tier size``) — ``balanced`` checks both.
        """
        referenced = int((self._refcount[1:] > 0).sum())
        free = len(self._free_pages)
        quarantined = len(self._quarantined_pages)
        demoted = self._prefix.demoted if self._prefix is not None else 0
        return {
            "free": free,
            "quarantined": quarantined,
            "referenced": referenced,
            # entry↔page is one-to-one over HBM entries (PrefixIndex pins
            # each published page until its entry dies or demotes), so the
            # HBM entry count IS the distinct-page count — O(1), and
            # health() polls this per request.  A one-to-one violation
            # still trips the chaos audits: duplicate entries would push
            # cached ABOVE the quiescent referenced count.
            "cached": (self._prefix.hbm_entries()
                       if self._prefix is not None else 0),
            "demoted": demoted,
            "host_tier_bytes": self._tier.bytes() if self._tier is not None
            else 0,
            "total": self.num_pages - 1,
            "balanced": free + quarantined + referenced
            == self.num_pages - 1
            and demoted == (len(self._tier) if self._tier is not None
                            else 0),
        }

    def _adapter_salt(self, req: Request) -> int:
        """Per-tenant prefix-namespace salt (docs/SERVING.md "Multi-tenant
        adapter serving"): K/V under tenant weights is a function of
        (tokens, base params, ADAPTER), so two tenants' identical prompts
        must never share pages — every chain walk starts from a
        tenant-salted root.  0 (the unsalted base namespace) for
        adapter-less requests and registry-less engines."""
        if self.adapters is None:
            return 0
        return self.adapters.salt(req.adapter_id)

    def _prefix_lookup(self, req: Request) -> PrefixMatch:
        """Longest resident prefix for ``req`` (capped at prompt-1 so at
        least one token always goes through prefill — the first generated
        token reads off the last real prefill position)."""
        if self._prefix is None or len(self._prefix) == 0:
            return PrefixMatch(pages=[], n_tokens=0)
        with trace_span("serve.prefix_match", rid=req.rid):
            m = self._prefix.lookup(req.input_ids,
                                    limit=len(req.input_ids) - 1,
                                    salt=self._adapter_salt(req))
        if m.cow_src is not None and m.cow_valid < MIN_COW_TOKENS:
            # not worth a pool-shaped page snapshot: keep the full-page
            # share, prefill the boundary tokens like any other tail
            return PrefixMatch(pages=m.pages,
                               n_tokens=len(m.pages) * self.page_size,
                               keys=m.keys)
        return m

    def _reclaim_cached(self, n_pages: int) -> None:
        """Pool pressure: reclaim cached-but-idle prefix pages, LRU first,
        until ``n_pages`` more pages are actually free (a reclaimed page
        still held by a decoding slot frees nothing yet — keep going) or
        nothing reclaimable remains.  With a host tier configured, cold
        FULL pages DEMOTE (their K/V parks on the host, the entry stays
        matchable) instead of evicting; partial boundary pages are mutable
        and evict as before."""
        freed = 0
        while freed < n_pages and self._prefix is not None \
                and len(self._prefix):
            before = len(self._free_pages)
            if self._tier is not None:
                if not self._demote_lru_entry():
                    break   # every remaining entry is already on the host
            else:
                for p in self._prefix.evict(1):
                    self._drop_page(p)
            freed += len(self._free_pages) - before

    # ------------------------------------------------------ KV-page tiering

    def _demote_lru_entry(self) -> bool:
        """One reclaim step under tiering: demote the LRU full HBM entry
        (extract its page to the host tier, free the device page) or evict
        the LRU partial one.  Returns False when no entry holds a device
        page anymore."""
        cand = self._prefix.reclaim_candidate()
        if cand is None:
            return False
        key, e = cand
        if not e.full:
            # a partial boundary page is mutable (its owner may still be
            # appending) — it can never move to the host tier; evict it
            # exactly as the untiered engine would
            p = self._prefix.evict_key(key)
            if p is not None:
                self._drop_page(p)
            return True
        self._tier_make_room()
        with trace_span("serve.demote", page=int(e.page)):
            t0 = time.monotonic()
            slabs = self._exec.extract(int(e.page))
            self._tier.put(key, *slabs, epoch=self._weight_epoch)
            page = self._prefix.demote(key)
            self._drop_page(page)
            self._demote_lat_s.append(time.monotonic() - t0)
        self.demotions += 1
        if self._prefix.demoted > self._demoted_hwm:
            self._demoted_hwm = self._prefix.demoted
        return True

    def _tier_make_room(self) -> None:
        """Host-tier capacity: a full tier evicts its LRU buffers FOR REAL
        (the prefix entry dies with its only copy — this is the one place
        tiering still loses cache)."""
        while self._tier.full():
            key = self._tier.oldest_key()
            if key is None:   # pragma: no cover - defensive
                return
            self._prefix.evict_key(key)   # drops the buffer via the hook
            self._tier.discard(key)       # belt-and-suspenders: idempotent

    def _promote_match(self, match: PrefixMatch) -> bool:
        """Promote every demoted chunk of ``match`` back into free device
        pages (the caller checked the free count): inject the host slab,
        flip the index entry hot — the fresh page's first reference IS the
        index's — and patch the match in place so admission maps it like
        any resident page.  Returns False when a host buffer vanished
        (host-capacity eviction raced the lookup): the caller retries the
        head with a fresh, smaller lookup."""
        for i, p in enumerate(match.pages):
            if p >= 0:
                continue
            key = match.keys[i]
            # epoch-gated fetch: a slab extracted under retired weights is
            # treated exactly like a vanished one (docs/HYBRID.md) — the
            # entry dies and the caller retries with a smaller match
            data = self._tier.get(key, epoch=self._weight_epoch)
            if data is None:
                # the tier evicted this entry between lookup and now (or
                # its slab is from another weight epoch); make sure the
                # index agrees, then let the caller re-look-up
                self._prefix.evict_key(key)
                self._tier.discard(key)
                return False
            with trace_span("serve.promote"):
                t0 = time.monotonic()
                (dst,) = self._alloc_pages(1)
                try:
                    self._exec.inject(data, dst)
                except BaseException:
                    self._drop_page(dst)
                    raise
                self._prefix.promote(key, dst)
                self._tier.pop(key)
                self._promote_lat_s.append(time.monotonic() - t0)
            match.pages[i] = dst
            self.promotions += 1
        return True

    def tier_latencies(self) -> Dict[str, List[float]]:
        """Recent demote/promote wall times in seconds (bounded windows;
        the tiered bench reads promote p50/p99 from here)."""
        return {"promote_s": list(self._promote_lat_s),
                "demote_s": list(self._demote_lat_s)}

    def residency_digest(self, cap: int = 1024) -> List:
        """Compact prefix-residency digest — ``(chain_key, tier)`` per full
        cached chunk, MRU first — what a fleet member publishes through
        the coordination store so the router can route shared-prefix
        requests to the engine already holding them (docs/FLEET.md)."""
        if self._prefix is None:
            return []
        return self._prefix.digest(cap)

    def adopt_host_tier(self, old: "ServingEngine") -> int:
        """Warm-restart/recycle carry: adopt the dead engine's DEMOTED
        prefix entries and their host buffers.  Host slabs are plain host
        memory, valid even when the old device pool was consumed, and K/V
        is a pure function of (tokens, params) — the factory recreates the
        same params — so the replacement serves promotions from the
        carried cache instead of recomputing.  HBM entries died with the
        pool and rebuild organically through replay.  Returns the entries
        carried."""
        if (self._tier is None or old._tier is None or self._prefix is None
                or old._prefix is None):
            return 0
        keys = self._prefix.adopt_demoted(old._prefix)
        adopted = self._tier.adopt(old._tier, keys=keys)
        if len(adopted) < len(keys):
            # tier capacity clipped the carry: drop the index entries whose
            # buffers did not make it so the demoted ledger stays balanced
            for key in set(keys) - set(adopted):
                self._prefix.evict_key(key)
        if self._prefix.demoted > self._demoted_hwm:
            self._demoted_hwm = self._prefix.demoted
        return len(adopted)

    # ------------------------------------- live weight updates (hybrid)

    @property
    def weight_epoch(self) -> int:
        """The live-weight generation this engine is serving
        (docs/HYBRID.md).  Monotonic; advanced by :meth:`update_params`.
        Setting it directly (the supervisor's epoch carry, the rollout
        factory) re-stamps the prefix index so published entries tag
        correctly."""
        return self._weight_epoch

    @weight_epoch.setter
    def weight_epoch(self, value: int) -> None:
        self._weight_epoch = int(value)
        if self._prefix is not None:
            self._prefix.epoch = self._weight_epoch

    def update_params(self, params, draft_params=None,
                      epoch: Optional[int] = None) -> Dict[str, Any]:
        """Swap the LIVE weights under every compiled program and advance
        the **weight epoch** — the train↔serve handoff of the hybrid
        rollout subsystem (docs/HYBRID.md).

        Params are already program arguments, so the swap is
        zero-recompile by construction: the tree is resharded through the
        shared ``place_params``/``auto_tp_specs`` path and committed to
        the exact shardings the programs compiled against
        (:meth:`MeshExecutor.update_params`); a structurally different
        tree is rejected loudly.

        The hard contract is the flush: every paged K/V page the prefix
        index pins, every COW-donor boundary page, and every demoted
        host-tier slab describes activations of the OLD weights — all of
        it is invalidated here (flush), and everything is epoch-stamped
        (tag) so a stale page could not be served even if one survived.
        The page-accounting ledger stays balanced through the flip
        (flushed pages return to the free list; the demoted ledger drops
        to zero with its slabs).

        Requires no slot in flight (a mid-stream weight change would split
        one request's output across two weight generations); queued and
        pending requests are fine — they prefill from scratch under the
        new epoch.  ``draft_params`` optionally refreshes a speculative
        draft's weights (stale draft weights only cost acceptance rate,
        never correctness).  ``epoch`` overrides the new epoch number (the
        supervisor's restart carry); default is +1.

        Returns the update stats (also mirrored on the ``serve/weight_*``
        gauges): new epoch, flushed HBM pages / host slabs, the refresh
        wall time, and the post-flip ``page_accounting()`` verdict."""
        if self._active.any():
            raise RuntimeError(
                f"update_params with {int(self._active.sum())} slot(s) "
                "in flight: a live stream's K/V would straddle two weight "
                "epochs — drain or finish the tick loop first "
                "(RolloutEngine sequences rounds so this cannot happen)")
        t0 = time.monotonic()
        with trace_span("serve.weight_update", epoch=self._weight_epoch + 1):
            # swaps first (each validates BEFORE mutating), flush last, and
            # the DRAFT before the TARGET: any rejection then leaves a
            # correct engine — a draft-only partial swap can only cost
            # acceptance rate, while the target weights, the cache and the
            # epoch move together or not at all (stale cached K/V can never
            # coexist with swapped target weights).
            if draft_params is not None and self._spec is not None:
                self._spec.update_params(draft_params)
            self._exec.update_params(params)
            self.params = self._exec.params
            # the new tree is the serving base: any fused adapter view is
            # over (fuse_adapter() re-stamps both when IT is the caller)
            self._base_params = self.params
            self.fused_adapter_id = None
            flushed_pages, flushed_slabs = self._flush_cached_kv()
            self.weight_epoch = (int(epoch) if epoch is not None
                                 else self._weight_epoch + 1)
        self.weight_updates += 1
        dt = time.monotonic() - t0
        self._refresh_lat_s.append(dt)
        acct = self.page_accounting()
        if not acct["balanced"]:   # pragma: no cover - defensive
            raise RuntimeError(
                f"page accounting unbalanced after weight-epoch flip: "
                f"{acct} — the flush leaked or double-freed")
        if self.monitor is not None:
            self.monitor.write_events([
                ("serve/weight_epoch", float(self._weight_epoch),
                 self._tick),
                ("serve/weight_updates_total", float(self.weight_updates),
                 self._tick),
                ("serve/weight_refresh_s", dt, self._tick),
                ("serve/kv_flushed_pages_total",
                 float(self.kv_flushed_pages), self._tick),
            ])
        log_dist(
            f"serve: weight epoch -> {self._weight_epoch} "
            f"({flushed_pages} cached page(s) + {flushed_slabs} host "
            f"slab(s) flushed, refresh {dt * 1e3:.1f} ms)", ranks=[0])
        return {"weight_epoch": self._weight_epoch,
                "flushed_hbm_pages": flushed_pages,
                "flushed_host_slabs": flushed_slabs,
                "refresh_s": dt,
                "balanced": acct["balanced"]}

    def _flush_cached_kv(self) -> tuple:
        """Release every prefix-cached page and host-tier slab (the
        weight-epoch flip).  Slots are idle (checked by the caller), so
        after the flush the only non-free pages are quarantined ones —
        accounting stays exact."""
        flushed_pages = flushed_slabs = 0
        if self._prefix is not None:
            flushed_slabs = self._prefix.demoted
            for p in self._prefix.flush():
                self._drop_page(p)
                flushed_pages += 1
        if self._tier is not None and len(self._tier):
            # every demoted entry's removal dropped its slab via the
            # on_drop_host hook; anything left is a stranded-slab bug
            raise RuntimeError(
                f"host tier holds {len(self._tier)} slab(s) after the "
                "prefix flush — stranded buffers (ledger torn)")
        self.kv_flushed_pages += flushed_pages
        self.kv_flushed_slabs += flushed_slabs
        return flushed_pages, flushed_slabs

    def refresh_latencies(self) -> List[float]:
        """Recent ``update_params`` wall times in seconds (bounded window;
        the rollout bench reads weight-refresh p50/p99 from here)."""
        return list(self._refresh_lat_s)

    def fuse_adapter(self, adapter_id: Optional[str] = None,
                     epoch: Optional[int] = None) -> Dict[str, Any]:
        """Fused-view serving for a HOT tenant (docs/SERVING.md
        "Multi-tenant adapter serving"): swap ``base + A@B*scale`` fused
        weights in through the ordinary :meth:`update_params` path —
        zero-recompile (the fused tree has identical avals/shardings) and
        epoch-flipped, so every cached K/V page of the shared-base epoch
        is flushed and stamped unservable before the first fused token.

        While fused, ONLY this tenant's requests are admissible: a base
        or other-tenant request would decode against the wrong weights
        (their per-slot delta assumes the shared base), so :meth:`submit`
        rejects the mix loudly.  The tenant's own slots skip the batched
        delta — the weights already carry it — which is the point: a
        tenant hot enough to dominate the engine stops paying the
        per-token factor matmuls.  ``fuse_adapter(None)`` restores the
        shared base (another epoch flip) and reopens mixed admission.
        Requires idle slots, exactly like any weight update."""
        if self.adapters is None:
            raise RuntimeError(
                "fuse_adapter requires an AdapterRegistry — build the "
                "engine with adapters= (docs/SERVING.md)")
        base = self._base_params
        if adapter_id is None:
            view = base
        else:
            self.adapters.resolve(adapter_id)   # loud UnknownAdapter
            view = self.adapters.fuse(base, adapter_id)
        stats = self.update_params(view, epoch=epoch)
        # update_params made the view the new base and cleared the mode;
        # re-stamp both — the true base survives for the next flip
        self._base_params = base
        self.fused_adapter_id = adapter_id
        stats["fused_adapter_id"] = adapter_id
        log_dist(
            f"serve: fused-view "
            f"{'restored to shared base' if adapter_id is None else f'adapter {adapter_id!r}'} "
            f"at weight epoch {self._weight_epoch}", ranks=[0])
        return stats

    def _arrival_abs(self, req: Request) -> float:
        """Absolute arrival stamp: the rebased epoch when the request rode
        across a warm restart, else this engine's clock.  Everything
        REPORTING an arrival (gauges, RequestResult stamps) reads this;
        admission gating and deadline expiry stay on the engine-relative
        ``arrival_time``/``deadline_s`` pair the supervisor rebases."""
        if req.arrival_epoch_s is not None:
            return req.arrival_epoch_s
        return self._t0 + req.arrival_time

    def _usable_slots(self) -> int:
        return int(self.b_slots - self._quarantined.sum())

    def _retry_after_hint(self) -> float:
        """Backlog-derived resubmission hint: waves of requests ahead times
        the EMA of observed service time (a conservative floor before any
        request has completed)."""
        per_req = self._ema_service_s if self._ema_service_s else 0.25
        backlog = (len(self._queue) + len(self._pending)
                   + int(self._active.sum()))
        lanes = max(1, self._usable_slots())
        waves = max(1, -(-max(backlog, 1) // lanes))
        return round(per_req * waves, 4)

    def _shed(self, request: Request, why: str) -> Any:
        """Terminal "shed" result for a request admission refused: typed,
        counted, and carrying a retry-after hint — never silently dropped,
        never parked on an unbounded queue."""
        t = time.monotonic()
        hint = self._retry_after_hint()
        lc = self._lifecycle_pending.pop(request.rid, [])
        lc.append(("shed", t, self.engine_incarnation))
        self._results[request.rid] = RequestResult(
            rid=request.rid, input_ids=request.input_ids,
            output_ids=np.zeros((0,), np.int32), finish_reason="shed",
            prefill_bucket=0, arrival_s=t, admit_s=t, first_token_s=t,
            finish_s=t, retry_after_s=hint, trace_id=request.trace_id,
            lifecycle=lc)
        self._finished_order.append(request.rid)
        self._live_rids.add(request.rid)
        self.shed_count += 1
        logger.warning("serve: shed request %r (%s); retry_after=%.3fs",
                       request.rid, why, hint)
        return request.rid

    def _expire(self, now: float) -> None:
        """Finish every request whose deadline (arrival + deadline_s) has
        passed: queued requests exit with an empty "deadline" result and a
        retry hint; in-flight requests retire with the tokens generated so
        far and give their slot + pages back this tick.  The queue scan is
        skipped outright while no waiting request carries a deadline (the
        common case must not pay O(backlog) per tick)."""
        if self._queue and self._waiting_deadlines:
            keep: Deque[Request] = deque()
            for req in self._queue:
                if (req.deadline_s is not None
                        and now >= req.arrival_time + req.deadline_s):
                    self._waiting_deadlines -= 1
                    t = time.monotonic()
                    lc = self._lifecycle_pending.pop(req.rid, [])
                    lc.append(("deadline", t, self.engine_incarnation))
                    self._results[req.rid] = RequestResult(
                        rid=req.rid, input_ids=req.input_ids,
                        output_ids=np.zeros((0,), np.int32),
                        finish_reason="deadline", prefill_bucket=0,
                        arrival_s=self._arrival_abs(req), admit_s=t,
                        first_token_s=t, finish_s=t,
                        retry_after_s=self._retry_after_hint(),
                        trace_id=req.trace_id, lifecycle=lc)
                    self._finished_order.append(req.rid)
                    self.deadline_count += 1
                    logger.warning("serve: request %r expired in queue "
                                   "(deadline %.3fs)", req.rid, req.deadline_s)
                else:
                    keep.append(req)
            self._queue = keep
        for slot in np.flatnonzero(self._active):
            req = self._slots[slot].request
            if (req.deadline_s is not None
                    and now >= req.arrival_time + req.deadline_s):
                logger.warning("serve: request %r expired in flight after "
                               "%d token(s) (deadline %.3fs)", req.rid,
                               len(self._slots[slot].tokens), req.deadline_s)
                self._finish(slot, "deadline")

    def submit(self, request: Request) -> Any:
        """Queue a request (FIFO).  Validates it can ever be served.

        Admission control: while the engine is draining, or the bounded
        queue (``max_queue``) is full, the request is SHED — it still gets
        a terminal :class:`RequestResult` (``finish_reason="shed"``, with a
        ``retry_after_s`` hint) rather than an unbounded queue growing
        until every deadline in it is dead on arrival."""
        ids = np.asarray(request.input_ids, np.int32).reshape(-1)
        # flatten BEFORE validating: _pages_needed counts len(input_ids),
        # which on a [1, S] prompt would count rows, not tokens
        request = dataclasses.replace(request, input_ids=ids)
        if ids.size == 0:
            raise ValueError("empty prompt")
        if request.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        total = ids.size + request.max_new_tokens
        if total > self.max_model_len:
            raise ValueError(
                f"request {request.rid!r}: prompt {ids.size} + max_new "
                f"{request.max_new_tokens} exceeds max_model_len "
                f"{self.max_model_len}")
        if self._pages_needed(request) > self.num_pages - 1:
            raise ValueError(
                f"request {request.rid!r} needs {self._pages_needed(request)} "
                f"pages but the pool holds {self.num_pages - 1}")
        if request.deadline_s is not None and request.deadline_s <= 0:
            raise ValueError(
                f"request {request.rid!r}: deadline_s={request.deadline_s} "
                "must be > 0 (measured from arrival)")
        if request.sampling is not None:
            request.sampling.validate()
        if request.adapter_id is not None:
            # tenant resolution happens HERE, not at slot admission: an
            # unknown adapter must bounce at the door (a loud error to the
            # submitter) rather than fail a prefill attempt later and
            # count against the slot's quarantine budget
            if self.adapters is None:
                raise ValueError(
                    f"request {request.rid!r} names adapter "
                    f"{request.adapter_id!r} but this engine has no "
                    "AdapterRegistry — build it with adapters= "
                    "(docs/SERVING.md \"Multi-tenant adapter serving\")")
            with trace_span("serve.adapter_resolve", rid=request.rid,
                            adapter=request.adapter_id):
                self.adapters.resolve(request.adapter_id)   # UnknownAdapter
        if (self.fused_adapter_id is not None
                and request.adapter_id != self.fused_adapter_id):
            raise ValueError(
                f"request {request.rid!r} (adapter "
                f"{request.adapter_id!r}) rejected: the engine is serving "
                f"a FUSED view of adapter {self.fused_adapter_id!r} — "
                "only that tenant is admissible until fuse_adapter(None) "
                "restores the shared base (docs/SERVING.md)")
        rid = request.rid
        if rid in self._live_rids:
            raise ValueError(
                f"request id {rid!r} is already queued, in flight, or has "
                f"an unclaimed result — rids must be unique")
        if request.trace_id is None:
            # first hop of a standalone engine: assign the fleet-wide
            # trace id here (a FleetRouter assigns before dispatch, and
            # replays/failovers arrive with the original id — accepted
            # verbatim so the request stays ONE trace end to end)
            request = dataclasses.replace(request, trace_id=new_trace_id())
        backlog = len(self._queue) + len(self._pending)
        if self._draining or (self.max_queue is not None
                              and backlog >= self.max_queue):
            return self._shed(request,
                              "draining" if self._draining else "queue full")
        self._live_rids.add(rid)
        self._lifecycle_pending[rid] = [
            ("queued", time.monotonic(), self.engine_incarnation)]
        if request.deadline_s is not None:
            self._waiting_deadlines += 1
        if request.arrival_time > 0:
            bisect.insort(self._pending, request,
                          key=lambda r: r.arrival_time)
        else:
            self._queue.append(request)
        return request.rid

    def _admit(self, now: float) -> None:
        k = bisect.bisect_right(self._pending, now,
                                key=lambda r: r.arrival_time)
        if k:
            self._queue.extend(self._pending[:k])
            del self._pending[:k]
        while self._queue:
            req = self._queue[0]
            try:
                slot = next(i for i in range(self.b_slots)
                            if not self._active[i]
                            and not self._quarantined[i])
            except StopIteration:
                break
            admitted = freed_pins = promote_retry = False
            # the owning request's trace context (docs/OBSERVABILITY.md
            # "Distributed tracing"): every span this admission opens —
            # prefix_match, demote/promote under reclaim, COW, admit,
            # prefill — inherits the request's trace_id/rid tags
            with trace_context(req.trace_id, req.rid):
                match = self._prefix_lookup(req)
                # pin the matched DEVICE pages (incl. the COW source) for
                # the span of this admission: reclaim below — or a
                # concurrent eviction by the index's own LRU cap — must
                # never free a matched page back into the pool it is about
                # to be mapped from.  Demoted chunks (-1) have no device
                # page to pin; their host buffers are LRU-touched instead
                # so a capacity eviction during reclaim prefers other
                # victims.
                pinned = [p for p in match.pages if p >= 0]
                if match.cow_src is not None:
                    pinned.append(match.cow_src)
                for p in pinned:
                    self._share_page(p)
                n_demoted = sum(1 for p in match.pages if p < 0)
                if n_demoted and self._tier is not None:
                    for i, p in enumerate(match.pages):
                        if p < 0:
                            self._tier.touch(match.keys[i])
                try:
                    # demoted chunks each need one free device page for
                    # their promotion on top of the private remainder
                    need = self._pages_needed(req) - len(match.pages)
                    if len(self._free_pages) < need + n_demoted:
                        # reclaim (demote/evict) cached-but-idle prefix
                        # pages before blocking: a cache must never starve
                        # admission
                        self._reclaim_cached(need + n_demoted
                                             - len(self._free_pages))
                    if len(self._free_pages) >= need + n_demoted:
                        if n_demoted and not self._promote_match(match):
                            # a matched host buffer vanished (host-capacity
                            # eviction raced the lookup): retry with a
                            # fresh, strictly smaller lookup
                            promote_retry = True
                        else:
                            with trace_span("serve.admit", rid=req.rid,
                                            slot=slot):
                                self._admit_one(req, slot, match, need, now)
                            admitted = True
                finally:
                    # the slot takes its own references inside _admit_one;
                    # the lookup pins existed only to survive reclaim.  If
                    # reclaim evicted the head's OWN matched entries, our
                    # pins are now the last references — dropping them
                    # frees the pages.
                    if not admitted:
                        freed_pins = any(self._refcount[p] == 1
                                         for p in pinned)
                    for p in pinned:
                        self._drop_page(p)
            if admitted:
                continue
            if freed_pins or promote_retry:
                # pool pressure evicted the head's own matched prefix from
                # the index (or its host buffer from the tier), and either
                # the pages came free the instant the pins dropped or the
                # match must shrink — retry the head with a fresh lookup
                # instead of misreading this as head-of-line blocking.
                # Terminates: each retry means the index strictly shrank.
                continue
            break   # head-of-line: wait for retirements

    def _admit_one(self, req: Request, slot: int, match: PrefixMatch,
                   need: int, now: float) -> None:
        """Pop the queue head into ``slot`` and prefill its unshared tail
        (one admission — the ``serve.admit`` span/fault unit).  ``match``
        is the resident prefix (``need`` excludes its full pages): the
        slot takes one reference per shared page and allocates only the
        private remainder."""
        # fire BEFORE the pop: a raise-kind injected fault must leave the
        # request queued (recoverable), not silently dropped
        maybe_fire(SITE_SERVE_ADMIT, rid=req.rid, slot=slot)
        self._queue.popleft()
        self._lifecycle_pending.setdefault(req.rid, []).append(
            ("admit", time.monotonic(), self.engine_incarnation))
        if req.deadline_s is not None:
            self._waiting_deadlines -= 1
        shared = list(match.pages)
        for p in shared:
            self._share_page(p)
        pages = self._alloc_pages(need)
        try:
            self._prefill(slot, req, shared, pages, match, now)
        except BaseException as e:
            # a failed prefill (transient device error, injected fault)
            # must not leak its reservation or drop the request.  If the
            # slot never registered, unwind — request back at the head —
            # and count the failure against the slot: quarantine_limit
            # consecutive failures fence it, with THIS attempt's PRIVATE
            # pages leaked into the quarantine account (suspect contents
            # are never recycled) and scheduling continuing on the rest
            # of the fleet.  Shared pages were read-only in the attempt
            # and other slots may be decoding through them right now —
            # they are never quarantined, their references just drop.
            # If the slot did register (failure in the post-launch
            # bookkeeping), it owns the pages and the next run continues
            # it.  NOTE: with donation enabled a failed DEVICE call also
            # consumes the pool — step() then refuses with
            # PoolConsumedError; the unwind still leaves the queue
            # replayable (ServingSupervisor rebuilds + replays).
            if self._slots[slot] is None:
                self._page_table[slot, :] = 0
                self._queue.appendleft(req)
                if req.deadline_s is not None:
                    self._waiting_deadlines += 1
                for p in shared:
                    self._drop_page(p)
                if not isinstance(e, Exception):
                    # KeyboardInterrupt/SystemExit is the operator, not
                    # the slot: plain unwind, no quarantine accounting
                    for p in pages:
                        self._drop_page(p)
                    raise
                self._slot_failures[slot] += 1
                self._last_failure_tick = self._tick
                fails = int(self._slot_failures[slot])
                fenced = fails >= self.quarantine_limit
                if fenced:
                    self._quarantined[slot] = True
                    self._leak_pages(pages)
                    # remembered per slot so a later successful canary
                    # probe can hand exactly these pages back to the pool
                    self._quarantine_pages_by_slot[slot] = list(pages)
                    self._fence_tick[slot] = self._tick
                    logger.error(
                        "serve: slot %d quarantined after %d consecutive "
                        "prefill failures; %d page(s) leaked-and-"
                        "accounted, %d slot(s) remain", slot, fails,
                        len(pages), self._usable_slots())
                else:
                    for p in pages:
                        self._drop_page(p)
                raise SlotPrefillError(
                    f"prefill failed in slot {slot} for request "
                    f"{req.rid!r} (failure {fails}/"
                    f"{self.quarantine_limit}"
                    f"{', slot quarantined' if fenced else ''}): "
                    f"{e}", slot=slot, rid=req.rid,
                    quarantined=fenced) from e
            raise

    def _prefill(self, slot: int, req: Request, shared: List[int],
                 private: List[int], match: PrefixMatch, now: float) -> None:
        """Prefill ``req`` into ``slot``: the page-table row maps the
        shared prefix pages first, then the private allocation; only the
        UNSHARED tail of the prompt runs through the prefill program
        (``start`` = shared token count), attending to the shared pages
        through the ordinary causal gather.  When the match ends mid-page,
        the donor's partial boundary page is first snapshotted into this
        slot's own boundary page (copy-on-write)."""
        S = len(req.input_ids)
        n_shared = match.n_tokens
        pages = shared + private
        # weight-epoch invariant (docs/HYBRID.md): a mapped shared page (or
        # COW donor) must carry K/V of the CURRENT weights.  The prefix
        # index and host tier already refuse stale entries, so this firing
        # means the flush-or-tag machinery has a hole — fail loudly rather
        # than emit tokens conditioned on retired weights.
        suspects = shared + ([match.cow_src]
                             if match.cow_src is not None else [])
        stale = [p for p in suspects
                 if self._page_epoch[p] != self._weight_epoch]
        if stale:
            raise RuntimeError(
                f"weight-epoch invariant violated: request {req.rid!r} "
                f"would map page(s) {stale} stamped "
                f"{[int(self._page_epoch[p]) for p in stale]} at weight "
                f"epoch {self._weight_epoch} — pre-update K/V must never "
                "be served (docs/HYBRID.md)")
        tail = req.input_ids[n_shared:]
        S_tail = len(tail)   # >= 1: lookup is capped at prompt-1
        s_pad = _bucket(S_tail)
        self._page_table[slot, :] = 0
        self._page_table[slot, :len(pages)] = pages
        toks = np.zeros((1, s_pad), np.int32)
        toks[0, :S_tail] = tail
        lane_t, lane_k, lane_p, lane_s = as_lanes(req.sampling)
        adapter_row = None
        if self.adapters is not None:
            # install the tenant's factors into this slot of the host
            # stacks BEFORE the device calls: the prefill program reads the
            # one-slot row slice now and the next decode tick re-uploads
            # the full stacks.  Under a fused view the slot stays zero —
            # the swapped weights already carry the delta.  A base-model
            # request (adapter_id=None) also clears the slot: zero factors
            # make the traced delta exactly zero.
            ad = (None if self.fused_adapter_id is not None
                  else self.adapters.resolve(req.adapter_id))
            self.adapters.write_slot(self._adapter_stacks, slot, ad)
            self._exec.invalidate_adapters()
            adapter_row = self._exec.adapter_row(self._adapter_stacks, slot)
        with trace_span("serve.prefill", rid=req.rid, slot=slot,
                        bucket=s_pad, shared_tokens=n_shared):
            maybe_fire(SITE_SERVE_PREFILL, rid=req.rid, slot=slot)
            with self._armed(f"serve.prefill rid={req.rid!r}"):
                if match.cow_src is not None:
                    # COW the partial boundary page: private[0] is the
                    # boundary logical page (shared full pages cover
                    # exactly len(shared) logical pages before it).  Rows
                    # past cow_valid in the snapshot are donor garbage the
                    # tail prefill/decode overwrites before causality can
                    # expose them.
                    self._exec.cow(match.cow_src, private[0])
                    self.cow_copies += 1
                    if self._spec is not None:
                        # mirror the snapshot in the draft pool — the
                        # sharer's draft-side boundary must hold the same
                        # donor prefix its target-side boundary does
                        self._spec.cow(self._cow_prog, match.cow_src,
                                       private[0])
                pt_row = jnp.asarray(self._page_table[slot:slot + 1])
                toks_j = jnp.asarray(toks)
                tok = int(self._exec.prefill(
                    s_pad, pt_row, toks_j, S_tail, n_shared,
                    lane_t, lane_k, lane_p, lane_s, adapter_row))
                # host fetch above lands inside the watchdog window
                if self._spec is not None:
                    # draft-pool prefill of the same tail (same bucket,
                    # page-table row, start) — the draft emits nothing
                    self._spec.prefill(s_pad, pt_row, toks_j, S_tail,
                                       n_shared)
        t = time.monotonic()
        self._slot_failures[slot] = 0   # quarantine counts CONSECUTIVE fails
        lc = self._lifecycle_pending.pop(req.rid, [])
        inc = self.engine_incarnation
        if n_shared > 0:
            lc.append(("prefix_match", t, inc))
        if match.cow_src is not None:
            lc.append(("cow", t, inc))
        lc.append(("prefill", t, inc))
        lc.append(("first_token", t, inc))
        self._slots[slot] = _Slot(
            request=req, pages=pages, tokens=[tok], bucket=s_pad,
            arrival_s=self._arrival_abs(req), admit_s=self._t0 + now,
            first_token_s=t, shared_tokens=n_shared, lifecycle=lc)
        self._lengths[slot] = S
        self._last_tok[slot] = tok
        self._active[slot] = True
        self._lane_temp[slot] = lane_t
        self._lane_top_k[slot] = lane_k
        self._lane_top_p[slot] = lane_p
        self._lane_seed[slot] = lane_s
        self._exec.invalidate_lanes()
        if req.sampling is not None and not req.sampling.greedy:
            self.sampled_admissions += 1
        if req.adapter_id is not None:
            self.adapter_admissions += 1
            self._adapter_admit_by_id[req.adapter_id] = (
                self._adapter_admit_by_id.get(req.adapter_id, 0) + 1)
            self._adapter_tokens_by_id[req.adapter_id] = (
                self._adapter_tokens_by_id.get(req.adapter_id, 0) + 1)
        self._tokens_out += 1
        if self._prefix is not None:
            if n_shared > 0:
                self.prefix_hits += 1
                self.prefix_shared_tokens += n_shared
                self.prefix_pages_shared += len(shared)
            else:
                self.prefix_misses += 1
            # publish this prompt's chunks (full pages + the partial
            # boundary) so later requests can share them; the index takes
            # one reference per new entry.  Shared chunks just LRU-touch
            # their existing entries.
            newly, released = self._prefix.publish(
                req.input_ids, pages, salt=self._adapter_salt(req))
            for p in newly:
                self._share_page(p)
            for p in released:
                self._drop_page(p)
        if self.monitor is not None:
            self.monitor.write_events([
                ("serve/ttft_s", t - self._arrival_abs(req), self._tick)])
        if req.eos_token_id is not None and tok == req.eos_token_id:
            self._finish(slot, "eos")
        elif req.max_new_tokens == 1:
            self._finish(slot, "length")

    def _slot_rid_map(self) -> Dict[str, str]:
        """Active slot → rid, stringified for trace-event ``args`` (only
        built when tracing is enabled — the disabled tick never pays it)."""
        return {str(int(s)): str(self._slots[s].request.rid)
                for s in np.flatnonzero(self._active)}

    def _armed(self, label: str):
        """Watchdog deadline around a device call (+ its host fetch), or a
        no-op context when no watchdog is attached."""
        if self.watchdog is not None:
            return self.watchdog.armed(label)
        import contextlib

        return contextlib.nullcontext()

    def _lanes_jnp(self):
        return self._exec.lanes(self._lane_temp, self._lane_top_k,
                                self._lane_top_p, self._lane_seed)

    def _adapter_operand(self):
        """Device-cached per-slot adapter factor stacks (None without a
        registry — the programs then traced without the operand)."""
        if self.adapters is None:
            return None
        return self._exec.adapter_stacks(self._adapter_stacks)

    def _decode_tick(self, rid_map: Optional[Dict[str, str]] = None) -> None:
        if self._spec is not None:
            self._spec_tick(rid_map)
            return
        lanes = self._lanes_jnp()
        with trace_span("serve.decode", tick=self._tick) as sp:
            # tick-level slot→rid map (docs/OBSERVABILITY.md "Distributed
            # tracing"): a decode tick serves many requests at once, so
            # instead of one owning context the span is tagged with every
            # slot's rid — a poisoned-tick flight dump names exactly the
            # streams it was serving.  Built once per tick by step()
            # (None while tracing is off).
            if rid_map is not None:
                sp.set(slot_rids=rid_map)
            maybe_fire(SITE_SERVE_DECODE, tick=self._tick)
            with self._armed(f"serve.decode tick {self._tick}"):
                nxt = self._exec.decode(self._page_table, self._lengths,
                                        self._last_tok, self._active, lanes,
                                        adapters=self._adapter_operand())
                nxt = np.asarray(nxt)   # host fetch = device sync
        active_slots = np.flatnonzero(self._active)
        trace_count("serve.tokens", float(len(active_slots)))
        for slot in active_slots:
            st = self._slots[slot]
            req = st.request
            tok = int(nxt[slot])
            st.tokens.append(tok)
            st.decode_ticks += 1
            self._lengths[slot] += 1
            self._last_tok[slot] = tok
            self._tokens_out += 1
            if req.adapter_id is not None:
                self._adapter_tokens_by_id[req.adapter_id] = (
                    self._adapter_tokens_by_id.get(req.adapter_id, 0) + 1)
            if req.eos_token_id is not None and tok == req.eos_token_id:
                self._finish(slot, "eos")
            elif len(st.tokens) >= req.max_new_tokens:
                self._finish(slot, "length")

    def _spec_tick(self, rid_map: Optional[Dict[str, str]] = None) -> None:
        """Speculative decode tick: k draft proposals + one verify-k pass,
        then per-slot host bookkeeping consuming 1..k emitted tokens
        (truncated by the slot's own eos / remaining budget — rejected or
        over-budget draft K/V past the consumed length is causally
        invisible garbage the next tick's writes overwrite)."""
        with trace_span("serve.decode", tick=self._tick,
                        speculative=self._spec.k) as sp:
            if rid_map is not None:
                sp.set(slot_rids=rid_map)
            maybe_fire(SITE_SERVE_DECODE, tick=self._tick)
            with self._armed(f"serve.decode tick {self._tick} "
                             f"(speculative k={self._spec.k})"):
                emitted, n_emit, self._exec.pools = self._spec.tick(
                    self.params, self._exec.pools,
                    self._page_table, self._lengths, self._last_tok,
                    self._active, *self._lanes_jnp(),
                    adapters=self._adapter_operand())
        active_slots = np.flatnonzero(self._active)
        total = 0
        for slot in active_slots:
            st = self._slots[slot]
            req = st.request
            consumed = 0
            finish = None
            for j in range(int(n_emit[slot])):
                tok = int(emitted[slot, j])
                st.tokens.append(tok)
                consumed += 1
                self._tokens_out += 1
                if req.eos_token_id is not None and tok == req.eos_token_id:
                    finish = "eos"
                    break
                if len(st.tokens) >= req.max_new_tokens:
                    finish = "length"
                    break
            st.decode_ticks += 1
            total += consumed
            if req.adapter_id is not None and consumed:
                self._adapter_tokens_by_id[req.adapter_id] = (
                    self._adapter_tokens_by_id.get(req.adapter_id, 0)
                    + consumed)
            self._spec.emitted_tokens += consumed
            self._lengths[slot] += consumed
            self._last_tok[slot] = st.tokens[-1]
            if finish is not None:
                self._finish(slot, finish)
        trace_count("serve.tokens", float(total))

    def _finish(self, slot: int, reason: str) -> None:
        st = self._slots[slot]
        finish_t = time.monotonic()
        st.lifecycle.append(("finish", finish_t, self.engine_incarnation))
        result = RequestResult(
            rid=st.request.rid, input_ids=st.request.input_ids,
            output_ids=np.asarray(st.tokens, np.int32),
            finish_reason=reason, prefill_bucket=st.bucket,
            arrival_s=st.arrival_s, admit_s=st.admit_s,
            first_token_s=st.first_token_s, finish_s=finish_t,
            # the prefill produced tokens[0]; every later token came from a
            # decode-program invocation (== len(tokens) - 1 without
            # speculation; a speculative verify tick emits several)
            decode_ticks=st.decode_ticks,
            shared_prefix_tokens=st.shared_tokens,
            trace_id=st.request.trace_id,
            adapter_id=st.request.adapter_id, lifecycle=st.lifecycle)
        if reason == "deadline":
            self.deadline_count += 1
        else:
            # served-to-completion service time (admit -> finish) feeds the
            # retry-after hint; expired requests would bias it short
            dt = max(result.finish_s - result.admit_s, 1e-6)
            self._ema_service_s = (dt if self._ema_service_s is None
                                   else 0.8 * self._ema_service_s + 0.2 * dt)
        self._results[st.request.rid] = result
        self._finished_order.append(st.request.rid)
        # drop one reference per page — shared pages stay resident for
        # their other readers (and the prefix index), private pages whose
        # last reference this was return to the free list
        for p in st.pages:
            self._drop_page(p)
        self._slots[slot] = None
        self._active[slot] = False
        self._lengths[slot] = 0
        self._last_tok[slot] = 0
        self._page_table[slot, :] = 0
        self._lane_temp[slot] = 0.0
        self._lane_top_k[slot] = 0
        self._lane_top_p[slot] = 1.0
        self._lane_seed[slot] = 0
        self._exec.invalidate_lanes()
        if self.adapters is not None and st.request.adapter_id is not None:
            # retire the tenant's factors with the slot — a later base
            # admission must decode against zeros, not a stale delta
            self.adapters.clear_slot(self._adapter_stacks, slot)
            self._exec.invalidate_adapters()

    # ----------------------------------------------------- probe / unfence

    def _probe_quarantined(self) -> None:
        """Background unfence path: for each fenced slot, once
        ``probe_after_ticks`` ticks have passed with no slot-attributable
        failure anywhere (clean ticks — a fleet still throwing faults must
        not be probed into), run one canary prefill on the slot.  Success
        restores the slot and returns its quarantined pages to the free
        pool (free + quarantined == pool stays exact); failure re-fences
        and restarts the clean-tick clock."""
        for slot in np.flatnonzero(self._quarantined):
            slot = int(slot)
            since = self._tick - max(self._fence_tick.get(slot, 0),
                                     self._last_failure_tick)
            if since >= self.probe_after_ticks:
                self._probe_slot(slot)

    def _probe_slot(self, slot: int) -> None:
        pages = self._quarantine_pages_by_slot.get(slot)
        if not pages:
            return   # fenced without a page record (defensive): stay fenced
        self.probe_count += 1
        s_pad = _bucket(1)
        # one-token canary through the slot's own quarantined pages: the
        # same program shape real admissions use, against the same page row
        toks = np.zeros((1, s_pad), np.int32)
        self._page_table[slot, :] = 0
        self._page_table[slot, :len(pages)] = pages
        try:
            with trace_span("serve.probe", slot=slot):
                maybe_fire(SITE_SERVE_PREFILL, rid="__canary__", slot=slot)
                with self._armed(f"serve.probe slot={slot}"):
                    # greedy lane — the same program shape admissions use;
                    # the host fetch means the probe must really complete
                    int(self._exec.prefill(
                        s_pad, jnp.asarray(self._page_table[slot:slot + 1]),
                        jnp.asarray(toks), 1, 0, 0.0, 0, 1.0, 0))
        except BaseException as e:
            self._page_table[slot, :] = 0
            self._fence_tick[slot] = self._tick
            self._last_failure_tick = self._tick
            if not isinstance(e, Exception):
                raise   # operator interrupt, not a probe verdict
            logger.warning(
                "serve: canary probe of quarantined slot %d failed "
                "(%s: %s); slot stays fenced", slot, type(e).__name__, e)
            if not self.pool_alive():
                # with donation enabled the failed probe ALSO consumed the
                # pool: abort THIS tick — letting it continue into _admit
                # would feed deleted arrays to a healthy slot's prefill and
                # misattribute the failure to it.  The supervisor rebuilds,
                # the right escalation for a fault that still reproduces
                # after probe_after_ticks.
                raise PoolConsumedError(
                    f"KV pool consumed by the failed canary probe of "
                    f"quarantined slot {slot}; rebuild the engine "
                    "(ServingSupervisor automates this)") from e
            return
        self._page_table[slot, :] = 0
        self._quarantined[slot] = False
        self._slot_failures[slot] = 0
        self._fence_tick.pop(slot, None)
        self._quarantine_pages_by_slot.pop(slot, None)
        for p in pages:
            self._quarantined_pages.remove(p)
        self._free_pages.extend(pages)
        self.unfence_count += 1
        logger.info(
            "serve: slot %d passed its canary probe after quarantine; "
            "restored with %d page(s) (%d slot(s) usable)", slot,
            len(pages), self._usable_slots())

    # ------------------------------------------------------------ the loop

    def pool_alive(self) -> bool:
        """False once a failed donated device call consumed the pool
        buffers (the speculative draft pool counts: a consumed draft pool
        poisons every subsequent verify) — the engine can no longer decode
        and must be rebuilt."""
        if not self._exec.pool_alive():
            return False
        return self._spec is None or self._spec.pool_alive()

    def step(self, now: Optional[float] = None) -> int:
        """One scheduler tick: expire dead deadlines, admit into free
        slots, then ONE fixed-shape decode step over all active slots.
        Returns the number of requests still in flight or queued."""
        if not self.pool_alive():
            # a failed DONATED device call consumed the pool buffers (the
            # admission unwind preserved queue/page accounting, but in-
            # flight KV is gone) — fail loudly instead of feeding deleted
            # arrays to the next program
            raise PoolConsumedError(
                "KV pool was consumed by a failed donated device call; "
                "rebuild the ServingEngine and resubmit — queued requests "
                "were preserved by the admission unwind (ServingSupervisor "
                "automates the rebuild and replays in-flight work)")
        self._tick += 1
        with trace_span("serve.tick", tick=self._tick) as sp:
            maybe_fire(SITE_SERVE_TICK, tick=self._tick)
            if now is None:
                now = time.monotonic() - self._t0
            self._expire(now)
            if (self.probe_after_ticks is not None and not self._draining
                    and self._quarantined.any()):
                self._probe_quarantined()
            if not self._draining:
                self._admit(now)
            if self._active.any():
                rid_map = (self._slot_rid_map() if get_tracer().enabled
                           else None)
                if rid_map is not None:
                    # tick span carries the slot→rid map it decoded under
                    sp.set(slot_rids=rid_map)
                self._decode_tick(rid_map)
                # refill slots the decode just retired — the queue head
                # starts its prefill this tick instead of idling one
                # scheduler round
                if not self._draining:
                    self._admit(now)
                # SLO evaluation per working tick (monitor-independent —
                # alerts must fire even when no gauge backend is attached)
                if self._slo is not None:
                    self._slo.evaluate(monitor=self.monitor,
                                       tracer=get_tracer())
                # gauges only on working ticks: idle arrival-wait ticks
                # would otherwise dilute occupancy stats and spam csv
                # backends
                self._write_gauges()
                # windowed device capture (docs/OBSERVABILITY.md
                # "Device-time correlation"): one WORKING tick = one
                # capture unit — idle arrival-wait ticks must not burn the
                # window before any decode/prefill lands in the trace.
                # A global None check when no capture is armed.
                device_trace_unit()
        return (int(self._active.sum()) + len(self._queue)
                + len(self._pending))

    def run(self, requests: Optional[List[Request]] = None,
            max_ticks: Optional[int] = None,
            resume: bool = False) -> List[RequestResult]:
        """Serve ``requests`` (plus anything already submitted) to
        completion; returns results in completion order.  ``arrival_time``
        offsets gate admission against the wall clock measured from this
        call.  Results finished during a previous run() that raised (e.g.
        ``max_ticks``, an injected fault) are still in the completion log
        and are returned by the next run() alongside its own.

        ``resume=True`` continues a previous run() of THIS engine that was
        interrupted by a fault, WITHOUT re-anchoring the arrival/deadline
        clock or the tokens/sec accounting — the supervisor uses it so a
        continued stream's deadlines are not silently extended."""
        if not resume:
            self._t0 = time.monotonic()
            self._tokens_out = 0   # per-run: the tokens/sec gauge divides
                                   # by elapsed-since-_t0
        start_tick = self._tick    # max_ticks bounds THIS run on a reused engine
        for req in requests or []:
            self.submit(req)
        while True:
            pending = self.step()
            if pending == 0:
                break
            if max_ticks is not None and self._tick - start_tick >= max_ticks:
                raise ServeTimeout(
                    f"serve loop exceeded max_ticks={max_ticks} with "
                    f"{pending} request(s) outstanding")
            if not self._active.any():
                if self._draining:
                    # admission is closed: with no slot active this loop
                    # could never serve the waiters — without this guard a
                    # queued request would read as a bogus admission
                    # deadlock and pending-only work would spin forever
                    raise RuntimeError(
                        "engine is draining: admission is closed and "
                        f"{len(self._queue) + len(self._pending)} "
                        "request(s) remain unserved — call drain() to "
                        "finish in-flight work and hand them back")
                if self._pending and not self._queue:
                    # idle until the next arrival is due: the loop is
                    # single-threaded, nothing can change while we sleep
                    wait = (self._pending[0].arrival_time
                            - (time.monotonic() - self._t0))
                    if wait > 0:
                        time.sleep(wait)
                elif self._queue:
                    if self._usable_slots() == 0:
                        # every slot fenced: nothing can ever be admitted
                        # again on this engine — terminal for the engine,
                        # recoverable via a supervisor warm restart
                        raise RuntimeError(
                            f"all {self.b_slots} slots quarantined with "
                            f"{len(self._queue)} request(s) queued; rebuild "
                            "the engine (ServingSupervisor restarts + "
                            "replays automatically)")
                    # the step above ended with every usable slot free and
                    # STILL could not admit the head (after prefix-cache
                    # reclaim): the pool genuinely cannot hold it —
                    # quarantined slots leaked enough pages, or (a bug)
                    # references leaked silently
                    req = self._queue[0]
                    acct = self.page_accounting()
                    raise RuntimeError(
                        f"admission deadlock: request {req.rid!r} needs "
                        f"{self._pages_needed(req)} pages, "
                        f"{acct['free']} free ({acct['quarantined']} "
                        f"quarantined, {acct['referenced']} referenced) "
                        f"with no slot active")
        return self.take_results()

    def take_results(self) -> List[RequestResult]:
        """Claim every finished result (completion order) and release their
        rids for reuse.  :meth:`run` calls this on a clean drain; after a
        fault it lets a supervisor harvest what finished before the crash."""
        order, self._finished_order = self._finished_order, []
        self._live_rids.difference_update(order)
        return [self._results.pop(rid) for rid in order]

    # ------------------------------------------------------- health / drain

    def _oldest_age_s(self, now_abs: float) -> float:
        """Age of the oldest queued or in-flight request (0 when idle);
        pending requests that have not arrived yet clamp to 0.  O(b_slots),
        not O(backlog) — this runs every working tick for the gauge: the
        queue is FIFO (head oldest) and ``_pending`` is sorted by arrival."""
        arrivals = [st.arrival_s for st in self._slots if st is not None]
        if self._queue:
            arrivals.append(self._arrival_abs(self._queue[0]))
        if self._pending:
            arrivals.append(self._arrival_abs(self._pending[0]))
        return max(0.0, now_abs - min(arrivals)) if arrivals else 0.0

    def health(self) -> Dict[str, Any]:
        """One-call snapshot of loop health — what an external load
        balancer / readiness probe polls.  Mirrors the ``serve/*`` gauges
        plus the resilience counters and page accounting."""
        now = time.monotonic()
        acct = self.page_accounting()
        info = self._exec.mesh_info()
        pb = self._exec.pool_bytes
        return {
            "tick": self._tick,
            "pool_alive": self.pool_alive(),
            # multi-chip serving (docs/SERVING.md): the mesh this engine's
            # programs span, and the per-device KV-pool footprint — on a
            # tp-sharded mesh bytes_per_device is ~total/tp (heads over
            # 'model'), the number HBM capacity planning reads
            "mesh_devices": info["mesh_devices"],
            "mesh_axes": info["mesh_axes"],
            "kv_pool_bytes_total": pb["total"],
            "kv_pool_bytes_per_device": pb["per_device"],
            # at-rest pool storage dtype (docs/SERVING.md "Quantized KV
            # pages"): None = compute dtype; "int8" pools include their
            # scale rows in every byte figure above
            "kv_dtype": self.kv_dtype,
            "draft_pool_bytes_per_device": (
                self._spec.pool_bytes["per_device"]
                if self._spec is not None else 0),
            "draining": self._draining,
            "queue_depth": len(self._queue) + len(self._pending),
            "active_slots": int(self._active.sum()),
            "usable_slots": self._usable_slots(),
            "quarantined_slots": int(self._quarantined.sum()),
            "free_pages": acct["free"],
            "quarantined_pages": acct["quarantined"],
            # occupancy for capacity sizing: current referenced pages and
            # the high-water mark — operators size num_pages off these
            # (surfaced on /metrics via the serve/* gauges too)
            "referenced_pages": acct["referenced"],
            "cached_pages": acct["cached"],
            "pages_hwm": self._pages_hwm,
            "shed_total": self.shed_count,
            "deadline_expired_total": self.deadline_count,
            "probes_total": self.probe_count,
            "unfenced_total": self.unfence_count,
            "prefix_hits_total": self.prefix_hits,
            "prefix_misses_total": self.prefix_misses,
            "prefix_shared_tokens_total": self.prefix_shared_tokens,
            "prefix_pages_shared_total": self.prefix_pages_shared,
            "prefix_evictions_total": (self._prefix.evictions
                                       if self._prefix is not None else 0),
            "prefix_index_entries": (len(self._prefix)
                                     if self._prefix is not None else 0),
            "cow_copies_total": self.cow_copies,
            # KV-page tiering (docs/SERVING.md "KV-page tiering"): the
            # demoted ledger and host-tier footprint, plus the cumulative
            # movement counters — what capacity planning reads to size the
            # host tier against the prefix working set
            "demoted_pages": acct["demoted"],
            "host_tier_bytes": acct["host_tier_bytes"],
            "host_tier_capacity_pages": self.host_tier_pages or 0,
            "demotions_total": self.demotions,
            "promotions_total": self.promotions,
            "demoted_pages_hwm": self._demoted_hwm,
            # weight epochs (docs/HYBRID.md): the live-weight generation
            # being served plus the flush counters — a rollout controller
            # reads these to confirm the train↔serve flip landed and the
            # stale-KV flush balanced
            "weight_epoch": self._weight_epoch,
            "weight_updates_total": self.weight_updates,
            "kv_flushed_pages_total": self.kv_flushed_pages,
            "kv_flushed_slabs_total": self.kv_flushed_slabs,
            # sampling / speculative (docs/SERVING.md): non-greedy
            # admissions, and — with a draft configured — the verify-tick
            # economics operators size k from (mean accepted length > 1
            # means the draft pays for itself)
            "sampled_admissions_total": self.sampled_admissions,
            # multi-tenant adapter serving (docs/SERVING.md): the loaded
            # inventory a fleet member advertises for adapter-affinity
            # routing, the resolution counters, and the fused-view mode
            "adapters_loaded": (self.adapters.loaded()
                                if self.adapters is not None else []),
            "adapter_admissions_total": self.adapter_admissions,
            "adapter_resolve_total": (self.adapters.resolve_total
                                      if self.adapters is not None else 0),
            "adapter_resolve_miss_total": (
                self.adapters.resolve_miss_total
                if self.adapters is not None else 0),
            "adapter_bytes": (self.adapters.nbytes()
                              if self.adapters is not None else 0),
            "fused_adapter_id": self.fused_adapter_id,
            "speculative_k": self._spec.k if self._spec is not None else 0,
            "spec_verify_slot_ticks_total": (self._spec.verify_slot_ticks
                                             if self._spec is not None
                                             else 0),
            "spec_emitted_tokens_total": (self._spec.emitted_tokens
                                          if self._spec is not None else 0),
            "spec_drafted_tokens_total": (self._spec.drafted_tokens
                                          if self._spec is not None else 0),
            "spec_mean_accepted_len": round(
                self._spec.mean_accepted_len(), 4) if self._spec is not None
            else 0.0,
            "oldest_request_age_s": round(self._oldest_age_s(now), 4),
            "retry_after_hint_s": self._retry_after_hint(),
            "unclaimed_results": len(self._finished_order),
            # per-program device-time accounting + SLO firing states
            # (docs/OBSERVABILITY.md): the fleet advertisement carries
            # alerts so the router can roll up fleet/alerts_firing
            "program_stats": self.program_stats(),
            "alerts": (self._slo.firing() if self._slo is not None
                       else []),
            # the bound /metrics port (None = endpoint not enabled): with N
            # engines on one host each process binds its OWN port (ephemeral
            # fallback), so a scraper discovers endpoints from health/fleet
            # advertisements instead of assuming the configured port
            "metrics_port": self.metrics_port,
        }

    def drain(self, max_ticks: Optional[int] = None) -> List[Request]:
        """Stop admission, finish in-flight work, hand back the unserved
        queue (admission order) for hand-off to another engine.  Finished
        results stay claimable via :meth:`take_results`; later ``submit()``
        calls are shed.  Deadlines keep being enforced while draining."""
        self._draining = True
        start = self._tick
        while self._active.any():
            self.step()
            if max_ticks is not None and self._tick - start >= max_ticks:
                raise ServeTimeout(
                    f"drain exceeded max_ticks={max_ticks} with "
                    f"{int(self._active.sum())} slot(s) still decoding")
        unserved = list(self._queue)
        unserved.extend(self._pending)
        self._queue.clear()
        self._pending.clear()
        self._waiting_deadlines = 0
        self._live_rids.difference_update(r.rid for r in unserved)
        for r in unserved:
            # the hand-off target's submit() starts a fresh queued stamp;
            # keeping these would leak entries for requests we no longer own
            self._lifecycle_pending.pop(r.rid, None)
        log_dist(f"serve: drained — {len(unserved)} unserved request(s) "
                 f"handed back, {len(self._finished_order)} result(s) "
                 "claimable", ranks=[0])
        return unserved

    def _write_gauges(self) -> None:
        if self.monitor is None:
            return
        active = float(self._active.sum())
        elapsed = max(time.monotonic() - self._t0, 1e-9)
        self.monitor.write_events([
            ("serve/queue_depth",
             float(len(self._queue) + len(self._pending)), self._tick),
            ("serve/active_slots", active, self._tick),
            ("serve/slot_occupancy", active / self.b_slots, self._tick),
            ("serve/free_pages", float(len(self._free_pages)), self._tick),
            ("serve/tokens_per_sec", self._tokens_out / elapsed, self._tick),
            ("serve/shed_total", float(self.shed_count), self._tick),
            ("serve/deadline_expired_total", float(self.deadline_count),
             self._tick),
            ("serve/quarantined_slots", float(self._quarantined.sum()),
             self._tick),
            ("serve/quarantined_pages", float(len(self._quarantined_pages)),
             self._tick),
            ("serve/probes_total", float(self.probe_count), self._tick),
            ("serve/unfenced_total", float(self.unfence_count), self._tick),
            ("serve/referenced_pages",
             float((self._refcount[1:] > 0).sum()), self._tick),
            ("serve/pages_hwm", float(self._pages_hwm), self._tick),
            ("serve/prefix_hits_total", float(self.prefix_hits), self._tick),
            ("serve/prefix_misses_total", float(self.prefix_misses),
             self._tick),
            ("serve/prefix_shared_tokens_total",
             float(self.prefix_shared_tokens), self._tick),
            ("serve/prefix_index_entries",
             float(len(self._prefix) if self._prefix is not None else 0),
             self._tick),
            ("serve/prefix_evictions_total",
             float(self._prefix.evictions if self._prefix is not None
                   else 0), self._tick),
            ("serve/cow_copies_total", float(self.cow_copies), self._tick),
            ("serve/sampled_admissions_total",
             float(self.sampled_admissions), self._tick),
            ("serve/weight_epoch", float(self._weight_epoch), self._tick),
            ("serve/oldest_request_age_s",
             self._oldest_age_s(time.monotonic()), self._tick),
        ])
        if self._tier is not None:
            self.monitor.write_events([
                ("serve/tier_demoted_pages", float(self._prefix.demoted),
                 self._tick),
                ("serve/tier_host_bytes", float(self._tier.bytes()),
                 self._tick),
                ("serve/tier_demotions_total", float(self.demotions),
                 self._tick),
                ("serve/tier_promotions_total", float(self.promotions),
                 self._tick),
            ])
        if self._spec is not None:
            self.monitor.write_events([
                ("serve/spec_emitted_tokens_total",
                 float(self._spec.emitted_tokens), self._tick),
                ("serve/spec_mean_accepted_len",
                 self._spec.mean_accepted_len(), self._tick),
            ])
        if self.adapters is not None:
            # per-tenant accounting (docs/SERVING.md "Multi-tenant adapter
            # serving"): the {adapter=...} suffix rides the flat monitor
            # stream like the program gauges and renders as a real
            # Prometheus label — one admissions/tokens series per tenant
            ad_active = sum(
                1 for s in np.flatnonzero(self._active)
                if self._slots[s].request.adapter_id is not None)
            ad_events = [
                ("serve/adapter_loaded",
                 float(len(self.adapters.loaded())), self._tick),
                ("serve/adapter_active_slots", float(ad_active), self._tick),
                ("serve/adapter_resolve_miss_total",
                 float(self.adapters.resolve_miss_total), self._tick),
            ]
            for aid, n in self._adapter_admit_by_id.items():
                ad_events.append(
                    (f"serve/adapter_admissions_total{{adapter={aid}}}",
                     float(n), self._tick))
            for aid, n in self._adapter_tokens_by_id.items():
                ad_events.append(
                    (f"serve/adapter_tokens_total{{adapter={aid}}}",
                     float(n), self._tick))
            self.monitor.write_events(ad_events)
        # per-program accounting gauges (docs/OBSERVABILITY.md): the
        # {program=...} suffix rides the flat monitor stream and the
        # Prometheus exposition renders it as a real label
        # (dstpu_serve_program_flops{program="decode"}).
        # device_seconds_total is 0 until synced sampling is enabled.
        # gauge_rows() is the flat fast path — no table build per tick.
        prog_events = []
        for name, flops_total, device_s in self._catalog.gauge_rows():
            prog_events.append((f"serve/program_flops{{program={name}}}",
                                float(flops_total), self._tick))
            prog_events.append(
                (f"serve/device_seconds_total{{program={name}}}",
                 float(device_s), self._tick))
        if prog_events:
            self.monitor.write_events(prog_events)
        # SLO firing states as alert{rule=...} gauges -> dstpu_alert{...}
        if self._slo is not None:
            self.monitor.write_events(self._slo.gauge_events(self._tick))
