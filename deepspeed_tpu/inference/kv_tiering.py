"""KV-page tiering: a host-RAM offload tier behind the paged KV pool.

The serving pool (``models.transformer.init_paged_cache``) is HBM-only, so
under pool pressure ``ServingEngine._reclaim_cached`` used to permanently
EVICT cached prefix pages — the K/V of a hot system prompt was recomputed
from scratch the next time a request needed it.  This module is the
ZeRO-Infinity move applied to serving (the reference ships a whole
``runtime/swap_tensor`` offload tree for exactly this pattern, and this
repo already proved tiered placement on the training side — the
infinity_* artifacts): instead of evicting, a cold *full* (immutable)
prefix page is **demoted** — its ``[L, page, Hkv, hd]`` K/V slab is copied
to a pinned host buffer, the device page returns to the free list, and
the :class:`~.prefix_cache.PrefixIndex` entry stays resident with
``tier="host"``.  A later prefix hit on a demoted entry **promotes** the
page back: the host slab is ``device_put`` onto the pool's sharding and
injected into a freshly allocated device page by a fixed-shape program,
and admission maps it exactly like any other shared page.

Contracts this preserves (docs/SERVING.md "KV-page tiering"):

- **zero-recompile**: :func:`extract_page` / :func:`inject_page` take the
  page id as a TRACED int32 scalar — one compiled program each regardless
  of which page moves, pre-warmed at engine init like the COW snapshot.
  Promotion/demotion never introduces a program shape.
- **accounting**: the device-pool invariant
  ``free + quarantined + referenced == num_pages - 1`` is untouched (a
  demoted entry holds NO device page), extended with a *demoted ledger*:
  the index's demoted-entry count must equal the host tier's buffer count
  (``ServingEngine.page_accounting()["balanced"]`` checks both).
- **token exactness**: K/V at position ``t`` is a pure function of tokens
  ``0..t``, and the demote/promote round-trip is a bit-exact copy, so a
  promoted prefix decodes exactly as a never-demoted one (the tiered
  bench and the chaos soak assert it).
- **mesh correctness**: the :class:`~.execution.MeshExecutor` owns both
  directions of the move — on a tensor-sharded pool the extract gathers
  the head-sharded page to one host slab and the inject ``device_put``\\ s
  it back under the pool's own NamedSharding, so every shard receives its
  own head slice.

Only *full* chunks demote: a partial boundary page is mutable (its owner
may still be appending), so under pressure it is evicted exactly as
before.  With speculative decoding the draft pool is NOT tiered — a
promoted page's draft-side mirror is whatever currently occupies that
physical page, which can only cost draft acceptance rate, never
correctness (the verify pass reads the target pool).

:class:`HostTier` itself is deliberately dumb storage — an LRU
``OrderedDict`` of host slabs with a page-count cap; the engine
orchestrates demotion order, capacity eviction (a host-capacity eviction
is a REAL eviction: the entry dies with its only copy) and the ledger.
Buffers are plain host numpy, so they survive a supervisor warm restart
or ``recycle()`` even when the dead engine's device pool was consumed —
the replacement engine adopts them (``ServingEngine.adopt_host_tier``)
and serves promotions from the carried cache.

:func:`chain_keys` exposes the prefix index's content-derived chunk-key
schedule so a fleet router can compute a request's keys WITHOUT an index
and match them against per-engine residency digests
(``inference/fleet.py``; docs/FLEET.md "Prefix residency routing").
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from .prefix_cache import chain_keys  # noqa: F401  (digest key schedule)

__all__ = ["HostTier", "TIER_HBM", "TIER_HOST", "chain_keys",
           "extract_page", "inject_page", "extract_pool_page",
           "inject_pool_page"]

# digest tier codes (compact on-store encoding; docs/FLEET.md)
TIER_HBM = 0
TIER_HOST = 1


def extract_page(k, v, src):
    """Read one physical page out of the ``[L, P, page, Hkv, hd]`` pools:
    returns ``(k_page, v_page)`` slabs of shape ``[L, page, Hkv, hd]``.

    ``src`` is a traced int32 scalar — ONE program shape for every page,
    so demotion can never recompile.  Read-only: the pools are NOT donated
    (a demote must leave the pool alive even when the jit backend donates
    elsewhere).
    """
    import jax

    return (jax.lax.dynamic_index_in_dim(k, src, axis=1, keepdims=False),
            jax.lax.dynamic_index_in_dim(v, src, axis=1, keepdims=False))


def inject_page(k, v, hk, hv, dst):
    """Write the ``[L, page, Hkv, hd]`` slabs ``hk``/``hv`` into physical
    page ``dst`` of the pools (the promote half of the tier move).
    ``dst`` is a traced int32 scalar — one program shape; the pools are
    donated by the caller's jit exactly like the COW snapshot."""
    return k.at[:, dst].set(hk.astype(k.dtype)), \
        v.at[:, dst].set(hv.astype(v.dtype))


def extract_pool_page(pools, src):
    """:func:`extract_page` generalized over the canonical pool tuple: one
    slab per pool array — ``[L, page, Hkv, hd]`` for the k/v payload plus,
    on a quantized pool, the ``[L, page]`` scale rows.  An int8 page moves
    as raw int8 bytes + its scales (never re-expanded to float), which is
    what halves the host-tier slab (docs/SERVING.md "Quantized KV pages").
    """
    import jax

    return tuple(jax.lax.dynamic_index_in_dim(a, src, axis=1,
                                              keepdims=False) for a in pools)


def inject_pool_page(pools, slabs, dst):
    """:func:`inject_page` generalized over the canonical pool tuple (the
    promote half; pools donated by the caller's jit exactly like COW)."""
    return tuple(a.at[:, dst].set(s.astype(a.dtype))
                 for a, s in zip(pools, slabs))


class HostTier:
    """LRU store of demoted KV pages: index chain key -> host slab pair.

    Pure host-side storage (numpy buffers; on a TPU host these live in
    pinned RAM via the device_get path).  The serving engine owns the
    policy — what demotes, when capacity evicts, and the demoted ledger;
    the tier only holds buffers and their LRU order.  ``max_pages`` caps
    the buffer count; ``page_bytes`` (k+v bytes of one page, constant for
    the pool's lifetime) prices the ``host_tier_bytes`` gauge without
    touching the buffers.
    """

    def __init__(self, max_pages: int, page_bytes: int = 0):
        self.max_pages = int(max_pages)
        if self.max_pages < 1:
            raise ValueError(f"max_pages={max_pages} must be >= 1")
        self.page_bytes = int(page_bytes)
        # slab TUPLES in canonical pool order: (hk, hv) for a full-precision
        # pool, (hk, hv, hk_scale, hv_scale) for an int8 one — byte
        # accounting sums every member, so the scale planes are priced in
        self._buffers: "OrderedDict[object, Tuple[np.ndarray, ...]]" \
            = OrderedDict()
        # weight epoch each slab was extracted under (docs/HYBRID.md):
        # get(epoch=...) refuses a slab from any other epoch, so even a
        # stranded pre-update slab can never be injected after a live
        # param update — the engine's flush is the primary mechanism, the
        # stamp is the proof
        self._epochs: Dict[object, int] = {}
        self._bytes = 0

    def __len__(self) -> int:
        return len(self._buffers)

    def __contains__(self, key) -> bool:
        return key in self._buffers

    def bytes(self) -> int:
        """Host RAM currently held by demoted pages (actual buffer bytes)."""
        return self._bytes

    def full(self) -> bool:
        return len(self._buffers) >= self.max_pages

    def oldest_key(self):
        """LRU-most key (the capacity-eviction victim), or None."""
        return next(iter(self._buffers)) if self._buffers else None

    def keys(self) -> Iterable:
        return self._buffers.keys()

    def put(self, key, *slabs: np.ndarray, epoch: int = 0) -> None:
        """Store one demoted page's slab tuple (the caller made room
        first), stamped with the weight ``epoch`` it was extracted under.
        A re-demotion of a key replaces the old slabs (same content —
        chain keys are content-derived — so the bytes just re-account)."""
        old = self._buffers.pop(key, None)
        if old is not None:
            self._bytes -= sum(int(s.nbytes) for s in old)
        self._buffers[key] = tuple(slabs)
        self._epochs[key] = int(epoch)
        self._bytes += sum(int(s.nbytes) for s in slabs)

    def get(self, key, touch: bool = True, epoch: Optional[int] = None
            ) -> Optional[Tuple[np.ndarray, ...]]:
        """The slab for ``key`` — or ``None`` when absent, or when
        ``epoch`` is given and the slab was extracted under a DIFFERENT
        weight epoch (stale K/V must never be injected; docs/HYBRID.md)."""
        data = self._buffers.get(key)
        if data is None:
            return None
        if epoch is not None and self._epochs.get(key, 0) != int(epoch):
            return None
        if touch:
            self._buffers.move_to_end(key)
        return data

    def epoch_of(self, key) -> Optional[int]:
        """Weight epoch the stored slab was extracted under (None=absent)."""
        return self._epochs.get(key) if key in self._buffers else None

    def touch(self, key) -> None:
        if key in self._buffers:
            self._buffers.move_to_end(key)

    def pop(self, key) -> Optional[Tuple[np.ndarray, ...]]:
        data = self._buffers.pop(key, None)
        self._epochs.pop(key, None)
        if data is not None:
            self._bytes -= sum(int(s.nbytes) for s in data)
        return data

    def discard(self, key) -> None:
        """Idempotent removal — the ``PrefixIndex.on_drop_host`` hook, so
        an entry removed from the index (eviction, collision subtree,
        LRU cap) can never strand its host buffer."""
        self.pop(key)

    def adopt(self, other: "HostTier",
              keys: Optional[Iterable] = None) -> List:
        """Move buffers from a dead engine's tier into this one (LRU order
        preserved, capacity respected — oldest surplus dropped).  Returns
        the keys actually adopted; ``keys`` restricts the carry to entries
        the new prefix index re-registered."""
        wanted = set(keys) if keys is not None else None
        items = [(k, d) for k, d in other._buffers.items()
                 if wanted is None or k in wanted]
        free = self.max_pages - len(self._buffers)
        if free <= 0:
            return []
        adopted = []
        # slice BEFORE inserting so a pre-populated tier keeps the donor's
        # MRU-most surplus, not its LRU-most (order inside the keep is
        # still LRU→MRU, preserving recency here)
        for k, slabs in items[-free:]:
            self.put(k, *slabs, epoch=other._epochs.get(k, 0))
            adopted.append(k)
        return adopted
