"""InferenceEngine (reference ``deepspeed/inference/engine.py:89``).

First slice: tensor-parallel jitted forward with dtype conversion and
auto-sharded params (the auto-TP analogue — ``module_inject/auto_tp.py``
discovers linear layers to shard; here :func:`auto_tp_specs` shards every
matmul-shaped weight's largest free dim over the 'model' axis).  Generation
with a paged KV cache and Pallas-fused blocks lands with the kernel-injection
milestone (module_inject/), which plugs in through the same ``apply_fn``
contract.

The reference's CUDA-graph capture/replay (engine.py:532-560) has no TPU
analogue because jit AOT-compiles the whole forward — every call IS the
captured graph.
"""
from __future__ import annotations

import weakref
from collections import OrderedDict
from typing import Any, Callable, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .config import DeepSpeedInferenceConfig
from ..parallel.mesh import MeshLayout, initialize_mesh
from ..utils.logging import logger, log_dist


def auto_tp_specs(params: Any, mesh) -> Any:
    """Auto-TP for a param pytree (reference module_inject/auto_tp.py): shard
    each >=2D weight's largest dim over 'model'; replicate the rest."""
    tp = mesh.shape["model"]

    def spec_for(x):
        shape = getattr(x, "shape", ())
        if len(shape) < 2 or tp == 1:
            return P()
        dim = int(np.argmax(shape))
        if shape[dim] % tp != 0:
            return P()
        entries = [None] * len(shape)
        entries[dim] = "model"
        return P(*entries)

    return jax.tree_util.tree_map(spec_for, params)


class InferenceEngine:
    # bound LRU of compiled generate programs: distinct (model, shape,
    # sampling) tuples each hold a full jitted program — unbounded growth is
    # a memory leak on long-lived engines serving many shapes
    GEN_CACHE_MAX = 32
    _warned_uncached = False   # one-time fallback warning (class-wide)

    def __init__(self, model: Any = None, config: Optional[DeepSpeedInferenceConfig] = None,
                 apply_fn: Optional[Callable] = None, params: Any = None, mesh=None):
        self._config = config or DeepSpeedInferenceConfig()
        self._model = model if hasattr(model, "apply_cached") else None
        self._gen_cache: OrderedDict = OrderedDict()
        if self._config.use_flash_decode:
            logger.warning(
                "use_flash_decode: the Pallas decode kernel was RETIRED in "
                "round 5 — it lost 21/22 cells of the honest per-(B, T, "
                "head-mix) A/B (tools/artifacts/decode_r5.json); decode "
                "always uses the XLA einsum path now.  The knob is accepted "
                "for config compatibility and ignored.")
        if model is not None:
            apply_fn = apply_fn or getattr(model, "apply_fn", None) or getattr(
                model, "apply", None)
            params = params if params is not None else getattr(model, "params", None)
        if apply_fn is None:
            raise ValueError("InferenceEngine needs apply_fn(params, *args) "
                             "(directly or via a model adapter)")
        self.apply_fn = apply_fn

        tp = self._config.tensor_parallel.tp_size if self._config.tensor_parallel.enabled else 1
        if mesh is None:
            mesh = initialize_mesh(MeshLayout.from_world(jax.device_count(), tp=tp,
                                                         ep=self._config.moe.ep_size))
        self.mesh = mesh

        # Weight-only quantization (reference ZeRO-Inference int8 path:
        # init_inference(dtype=torch.int8)): weights stored int8/int4 at
        # rest, dequantized inside the jitted programs at use
        self._quant = self._config.weights_quantized
        if self._quant:
            if tp != 1:
                raise NotImplementedError(
                    "quantized inference is single-shard (tp=1) for "
                    "now: blockwise scales do not carry TP specs")
            if params is None:
                raise ValueError(
                    "weight quantization (dtype int8 / quant.enabled) needs "
                    "a param tree — a bare apply_fn engine has no weights "
                    "to quantize")
        if params is not None:
            if self._quant:
                from .quantization import quantize_params

                bits = self._config.quant.num_bits
                cdtype = self._config.compute_jnp_dtype
                # per-leaf quantization: peak device memory stays at the
                # loaded tree + ONE leaf's quantized copy, not the full
                # tree twice.  No donation — the caller owns `params`.
                # (Quantize-during-stream for models whose compute-dtype
                # form exceeds HBM is future loader work.)
                # one-shot init-time cast, discarded after this load —
                # never in the serving/steady path
                qleaf = jax.jit(lambda x: quantize_params(   # dslint: disable=recompile-hazard
                    x, bits=bits, compute_dtype=cdtype))
                self.params = jax.tree_util.tree_map(qleaf, params)
            else:
                dtype = self._config.jnp_dtype
                specs = auto_tp_specs(params, mesh)
                shardings = jax.tree_util.tree_map(
                    lambda s: NamedSharding(mesh, s), specs,
                    is_leaf=lambda x: isinstance(x, P))
                cast = lambda x: x.astype(dtype) if hasattr(x, "dtype") and jnp.issubdtype(  # noqa: E731
                    x.dtype, jnp.floating) else x
                # one-shot init-time cast+placement, discarded after load
                self.params = jax.jit(lambda p: jax.tree_util.tree_map(cast, p),   # dslint: disable=recompile-hazard
                                      out_shardings=shardings)(params)
        else:
            self.params = None
        if self._quant:
            from .quantization import dequantize_params

            inner_apply = self.apply_fn
            self.apply_fn = lambda p, *a, **k: inner_apply(
                dequantize_params(p), *a, **k)
            if self._model is not None and (
                    hasattr(self._model, "apply_cached")
                    or hasattr(self._model, "apply_paged")):
                # generate()'s decode programs call model.apply_cached —
                # shim it so the cache loop reads int8 weights every step.
                # The paged serving contract (apply_paged) gets the same
                # treatment: ServingEngine's prefill/decode programs then
                # dequantize at entry, so a quantized engine serves through
                # the ordinary paged path (init_paged_cache itself never
                # touches params — the pool stays compute-dtype).  Each shim
                # installs on its own hasattr: a model exposing only one of
                # the two contracts still gets that one dequantized.
                import copy

                shim = copy.copy(self._model)
                if hasattr(self._model, "apply_cached"):
                    inner_cached = self._model.apply_cached
                    shim.apply_cached = lambda p, *a, **k: inner_cached(
                        dequantize_params(p), *a, **k)
                if hasattr(self._model, "apply_paged"):
                    inner_paged = self._model.apply_paged
                    shim.apply_paged = lambda p, *a, **k: inner_paged(
                        dequantize_params(p), *a, **k)
                self._model = shim
        # the engine's ONE forward program: per-instance by design (one
        # inference engine per process; serving routes through the
        # MeshExecutor inventory, never this)
        self._forward = jax.jit(self.apply_fn)   # dslint: disable=recompile-hazard
        log_dist(f"inference engine ready: tp={tp} dtype={self._config.dtype}"
                 + (f" quant=int{self._config.quant.num_bits}"
                    if self._quant else ""), ranks=[0])

    @property
    def model(self):
        """The wrapped model adapter (reference InferenceEngine.module)."""
        return self._model

    def serving(self, **kwargs):
        """A continuous-batching :class:`~.serving.ServingEngine` sharing
        this engine's model and (cast/sharded) params, so serving numerics
        are identical to :meth:`generate`.  On a quantized engine the
        shimmed ``apply_paged`` dequantizes at program entry, so serving
        reads the same int8/int4 weights as quantized ``generate()`` and
        stays token-identical to it.  The ``dtype`` pin below governs only
        the pool's COMPUTE dtype; pass ``kv_dtype="int8"`` to additionally
        narrow the pool's at-rest storage (docs/SERVING.md "Quantized KV
        pages") — weight quantization and KV quantization are independent
        knobs that compose in one engine.  See docs/SERVING.md."""
        if self._model is None or not hasattr(self._model, "apply_paged"):
            raise ValueError(
                "serving() needs a model with the paged decode contract "
                "(apply_paged) — see models.CausalLM")
        from .serving import ServingEngine

        kwargs.setdefault("mesh", self.mesh)
        if self._quant and kwargs.get("dtype") is None:
            # the serving KV pool's COMPUTE dtype stays the compute dtype
            # regardless of weight quantization; pin it explicitly (also
            # over an explicit dtype=None) so the pool never allocates
            # pages in the weights' storage dtype.  An explicit
            # kv_dtype="int8" kwarg still narrows the at-rest storage on
            # top of this pin — the scale rows dequantize back into the
            # pinned compute dtype inside the gather
            kwargs["dtype"] = self._config.compute_jnp_dtype
        return ServingEngine(self._model, self.params, **kwargs)

    def supervised_serving(self, max_restarts: int = 5, **kwargs):
        """A :class:`~.serving_supervisor.ServingSupervisor` whose engine
        factory is :meth:`serving` with these kwargs: decode-tick faults
        warm-restart a fresh KV pool (compiled programs carried over) and
        replay queue + in-flight requests token-exactly.  See
        docs/SERVING.md "Failure handling"."""
        from .serving_supervisor import ServingSupervisor

        return ServingSupervisor(lambda: self.serving(**kwargs),
                                 max_restarts=max_restarts,
                                 monitor=kwargs.get("monitor"))

    def serving_fleet(self, n_engines: int = None, coord_dir: str = None,
                      store=None, router_id: str = "router0",
                      max_restarts: int = 5, lease_s: float = None,
                      miss_limit: int = None, max_fleet_queue: int = None,
                      fleet_monitor=None, metrics_port: int = None,
                      **kwargs):
        """A :class:`~.fleet.FleetRouter` over ``n_engines`` supervised
        serving engines (each a :meth:`supervised_serving` sharing this
        engine's model/params), leased on a coordination store (``store=``
        or a ``coord_dir`` for the file backend).  Engines register
        heartbeat leases + health advertisements; the router admits by
        least-loaded engine, sheds by fleet-wide queue depth
        (``max_fleet_queue``), fails requests over on lease lapse, and
        rolls restarts one engine at a time.  ``metrics_port=0`` gives
        every member its own ephemeral /metrics endpoint.

        ``n_engines`` / ``coord_dir`` / ``lease_s`` / ``miss_limit`` left
        unset fall back to the launcher's exported contract
        (``DS_TPU_FLEET_SIZE`` / ``_COORD_DIR`` / ``_LEASE`` /
        ``_MISS_LIMIT`` — `deepspeed-tpu --fleet N ...`), then to
        2 / 5.0s / 3.  An explicit argument always wins.  See
        docs/FLEET.md."""
        import os

        from ..elasticity.coordination import FileCoordinationStore
        from .fleet import FleetMember, FleetRouter

        env = os.environ
        if n_engines is None:
            n_engines = int(env.get("DS_TPU_FLEET_SIZE", 2))
        if lease_s is None:
            lease_s = float(env.get("DS_TPU_FLEET_LEASE", 5.0))
        if miss_limit is None:
            miss_limit = int(env.get("DS_TPU_FLEET_MISS_LIMIT", 3))
        if store is None:
            coord_dir = coord_dir or env.get("DS_TPU_FLEET_COORD_DIR")
            if not coord_dir:
                raise ValueError(
                    "serving_fleet needs store= or coord_dir= (the "
                    "coordination store engines lease on; the launcher's "
                    "--fleet flags export DS_TPU_FLEET_COORD_DIR)")
            store = FileCoordinationStore(coord_dir)
        members = [
            FleetMember(f"engine{i}",
                        self.supervised_serving(max_restarts=max_restarts,
                                                **kwargs),
                        store, lease_s=lease_s, metrics_port=metrics_port)
            for i in range(int(n_engines))]
        return FleetRouter(store, members, router_id=router_id,
                           lease_s=lease_s, miss_limit=miss_limit,
                           max_fleet_queue=max_fleet_queue,
                           monitor=fleet_monitor)

    def forward(self, *args, **kwargs):
        if self.params is not None:
            return self._forward(self.params, *args, **kwargs)
        return self._forward(*args, **kwargs)

    __call__ = forward

    # ------------------------------------------------------------------
    # Generation.  Reference: InferenceEngine._generate (engine.py:621) over
    # the KV-cache workspace (csrc/transformer/inference/inference_context.h).
    # TPU redesign: static-shape prefill + a lax.scan decode loop, so one
    # generate() call compiles exactly two programs (per prompt-length
    # bucket) instead of retracing a growing sequence every token.
    # ------------------------------------------------------------------

    @staticmethod
    def _bucket(n: int) -> int:
        """Prompt-length bucket (next power of two ≥ 16) to bound recompiles."""
        b = 16
        while b < n:
            b *= 2
        return b

    def _generate_program(self, model, B, S_pad, max_new, greedy,
                          top_k=0, top_p=1.0):
        cfg = model.config

        # KV-cache length rounded up to a 128 multiple: lane-aligned cache
        # tiles keep the decode einsum on clean XLA tilings (and the bucket
        # rounding below reuses the same granularity)
        T_cache = -(-(S_pad + max_new) // 128) * 128

        def prog(params, tokens, input_mask, positions, rng, eos_id, temperature):
            cache = model.init_cache(B, T_cache, dtype=cfg.dtype)
            logits, cache = model.apply_cached(params, tokens, cache, positions,
                                               input_mask)
            lengths = input_mask.sum(-1).astype(jnp.int32)           # [B]
            last = jnp.take_along_axis(
                logits, (lengths - 1)[:, None, None], axis=1)[:, 0]  # [B,V]

            def sample(lg, key):
                if greedy:
                    return jnp.argmax(lg, axis=-1).astype(jnp.int32)
                # the shared sampling subsystem (inference/sampling.py):
                # one full sort serves top-k and top-p, temperature <= 0
                # folds to argmax in-graph (never a division by zero), and
                # top_k >= vocab / top_k == 0 disable the k-filter — the
                # ISSUE 9 edge cases, fixed once for generate() and serving
                from .sampling import sample_tokens

                return sample_tokens(
                    lg, jnp.broadcast_to(temperature, (B,)),
                    jnp.full((B,), top_k, jnp.int32),
                    jnp.full((B,), top_p, jnp.float32),
                    jax.random.split(key, B))

            def step(carry, _):
                cache, lg, pos, done, key = carry
                key, sub = jax.random.split(key)
                tok = sample(lg, sub)
                # done rows repeat eos_id verbatim (never a clamped stand-in:
                # jnp.maximum(eos_id, 0) silently emitted token 0 for done
                # rows).  With eos_token_id=None the sentinel is -1, tokens
                # are >= 0, so `done` can never become True and the sentinel
                # is never emitted.
                tok = jnp.where(done, eos_id, tok)
                done = done | (tok == eos_id)
                lg2, cache = model.apply_cached(
                    params, tok[:, None], cache, pos[:, None], ~done[:, None])
                return (cache, lg2[:, 0], pos + 1, done, key), tok

            done0 = jnp.zeros((B,), jnp.bool_)
            (_, _, _, _, _), toks = jax.lax.scan(
                step, (cache, last, lengths, done0, rng), None, length=max_new)
            return toks.T  # [B, max_new]

        return jax.jit(prog, static_argnames=())

    def _generate_lanes_program(self, model, B, S_pad, max_new):
        """The per-row RNG-lane twin of :meth:`_generate_program`
        (``generate(sampling=...)``): temperature/top-k/top-p/seed are
        TRACED per-row vectors, greedy rows fold to argmax in-graph, and
        the key for the token at stream position ``p`` of row ``b`` is
        ``fold_in(PRNGKey(seed_b), p)`` — exactly the schedule the serving
        engine's per-slot lanes use, which is what makes serving output
        token-identical to this path under the same seed/params
        (docs/SERVING.md "Sampling").  One program per (B, S_pad, max_new)
        regardless of the parameter mix."""
        from .sampling import position_keys, sample_tokens

        cfg = model.config
        T_cache = -(-(S_pad + max_new) // 128) * 128

        def prog(params, tokens, input_mask, positions, eos_id,
                 temp, top_k, top_p, seeds):
            cache = model.init_cache(B, T_cache, dtype=cfg.dtype)
            logits, cache = model.apply_cached(params, tokens, cache,
                                               positions, input_mask)
            lengths = input_mask.sum(-1).astype(jnp.int32)           # [B]
            last = jnp.take_along_axis(
                logits, (lengths - 1)[:, None, None], axis=1)[:, 0]  # [B,V]

            def step(carry, _):
                cache, lg, pos, done = carry
                # `pos` is the stream position the sampled token will
                # occupy (starts at the prompt length) — the lane counter
                tok = sample_tokens(lg, temp, top_k, top_p,
                                    position_keys(seeds, pos))
                tok = jnp.where(done, eos_id, tok)
                done = done | (tok == eos_id)
                lg2, cache = model.apply_cached(
                    params, tok[:, None], cache, pos[:, None],
                    ~done[:, None])
                return (cache, lg2[:, 0], pos + 1, done), tok

            done0 = jnp.zeros((B,), jnp.bool_)
            (_, _, _, _), toks = jax.lax.scan(
                step, (cache, last, lengths, done0), None, length=max_new)
            return toks.T  # [B, max_new]

        return jax.jit(prog, static_argnames=())

    def generate(self, input_ids, max_new_tokens: int = 32, eos_token_id: Optional[int] = None,
                 greedy: bool = True, rng: Optional[jax.Array] = None, temperature: float = 1.0,
                 top_k: int = 0, top_p: float = 1.0,
                 attention_mask=None, model=None, params=None,
                 sampling=None):
        """KV-cached autoregressive generation under jit.

        Prompts may be right-padded ragged rows (pass ``attention_mask``); pad
        slots are written to the cache but masked from attention.  Returns the
        original ids with ``max_new_tokens`` generated tokens appended (rows
        that hit ``eos_token_id`` repeat it).

        ``sampling`` — a :class:`~.sampling.SamplingParams` (or one per
        row) switches to the per-row RNG-lane path: temperature/top-k/
        top-p/seed become TRACED vectors (any mix shares one program) and
        keys are counter-based (``fold_in(PRNGKey(seed), position)``), so
        the output is token-identical to a :class:`~.serving.ServingEngine`
        request carrying the same params — the sampled parity contract
        (docs/SERVING.md "Sampling").  Mutually exclusive with the legacy
        ``greedy``/``rng``/``temperature``/``top_k``/``top_p`` knobs.
        """
        if (model is not None and model is not self._model
                and self._quant and params is None):
            raise NotImplementedError(
                "generate(model=...) on a quantized engine needs explicit "
                "params: self.params is a QuantizedWeight tree the override "
                "model's apply_cached cannot consume (the engine's own "
                "model is shimmed to dequantize)")
        model = model or self._model
        if sampling is not None:
            if rng is not None:
                raise ValueError(
                    "generate(sampling=...) uses counter-based lane keys "
                    "derived from SamplingParams.seed — rng= would be "
                    "silently ignored; pass one or the other")
            if not greedy or temperature != 1.0 or top_k or top_p < 1.0:
                raise ValueError(
                    "generate(sampling=...) is mutually exclusive with the "
                    "legacy greedy/temperature/top_k/top_p knobs — they "
                    "would be silently ignored; put them in SamplingParams")
            if model is None or not hasattr(model, "apply_cached"):
                raise NotImplementedError(
                    "generate(sampling=...) requires a KV-cache-capable "
                    "model (apply_cached); the full-recompute fallback "
                    "has no lane path")
            return self._generate_lanes(model, input_ids, max_new_tokens,
                                        eos_token_id, sampling,
                                        attention_mask, params)
        if model is None or not hasattr(model, "apply_cached"):
            if attention_mask is not None:
                raise NotImplementedError(
                    "attention_mask requires a KV-cache-capable model "
                    "(apply_cached); the full-recompute fallback would "
                    "silently attend to pad tokens")
            if top_k or top_p < 1.0:
                raise NotImplementedError(
                    "top_k/top_p require a KV-cache-capable model "
                    "(apply_cached); the fallback would silently sample the "
                    "full distribution")
            return self._generate_uncached(input_ids, max_new_tokens, eos_token_id,
                                           greedy, rng, temperature, params=params)
        ids, toks, mpad, pos, B, S_pad = self._pad_prompt(input_ids,
                                                          attention_mask)
        prog = self._cached_program(
            model, (B, S_pad, max_new_tokens, greedy, top_k, top_p),
            lambda: self._generate_program(model, B, S_pad, max_new_tokens,
                                           greedy, top_k=top_k, top_p=top_p))
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        eos = jnp.int32(-1 if eos_token_id is None else eos_token_id)
        new = prog(
            self.params if params is None else params,
            jnp.asarray(toks), jnp.asarray(mpad), jnp.asarray(pos),
            rng, eos, jnp.float32(temperature))
        return jnp.concatenate([jnp.asarray(ids), new], axis=1)

    @staticmethod
    def _pad_prompt(input_ids, attention_mask):
        """Shared generate() host prep: right-pad the (possibly ragged)
        prompt to its pow2 bucket and derive the cumulative positions
        (pads repeat the last real index)."""
        ids = np.asarray(input_ids)
        if ids.ndim == 1:
            ids = ids[None, :]
        B, S = ids.shape
        mask = (np.ones_like(ids, dtype=bool) if attention_mask is None
                else np.asarray(attention_mask, dtype=bool))
        S_pad = InferenceEngine._bucket(S)
        toks = np.zeros((B, S_pad), ids.dtype)
        toks[:, :S] = ids
        mpad = np.zeros((B, S_pad), bool)
        mpad[:, :S] = mask
        pos = np.maximum(np.cumsum(mpad, axis=1) - 1, 0).astype(np.int32)
        return ids, toks, mpad, pos, B, S_pad

    def _cached_program(self, model, key_tail, builder):
        """LRU-cached generate program lookup.  Model identity is held by
        weakref: id(model) can be REUSED after GC and would then serve a
        stale program compiled for a different model; a weakref compares
        by referent identity while alive and can never equal a ref to a
        new object once dead — stale entries are inert and age out of the
        LRU.  (Either way the cached program's closure pins the model
        while its entry lives, so an id in a live key can never be
        recycled; eviction releases the pin.)"""
        try:
            mkey: Any = weakref.ref(model)
            hash(mkey)   # a ref hashes via its referent — an unhashable
        except TypeError:          # or weakref-less adapter falls back:
            mkey = (id(model),)    # id is safe while the entry (and its
                                   # closure pin on the model) lives
        key = (mkey,) + tuple(key_tail)
        prog = self._gen_cache.get(key)
        if prog is None:
            prog = self._gen_cache[key] = builder()
            while len(self._gen_cache) > self.GEN_CACHE_MAX:
                self._gen_cache.popitem(last=False)
        else:
            self._gen_cache.move_to_end(key)
        return prog

    def _generate_lanes(self, model, input_ids, max_new_tokens,
                        eos_token_id, sampling, attention_mask, params):
        """Host side of ``generate(sampling=...)``: normalize the per-row
        :class:`~.sampling.SamplingParams`, pad/bucket the prompt exactly
        like the legacy path, and run the lane program (cached per
        (model, B, S_pad, max_new) — the params are traced, so every
        parameter mix is a cache hit)."""
        from .sampling import SamplingParams

        ids, toks, mpad, pos, B, S_pad = self._pad_prompt(input_ids,
                                                          attention_mask)
        lanes = ([sampling] * B if isinstance(sampling, SamplingParams)
                 else list(sampling))
        if len(lanes) != B:
            raise ValueError(
                f"sampling: got {len(lanes)} SamplingParams for a batch "
                f"of {B} rows (pass one, or one per row)")
        for sp in lanes:
            sp.validate()
        prog = self._cached_program(
            model, (B, S_pad, max_new_tokens, "lanes"),
            lambda: self._generate_lanes_program(model, B, S_pad,
                                                 max_new_tokens))
        eos = jnp.int32(-1 if eos_token_id is None else eos_token_id)
        new = prog(
            self.params if params is None else params,
            jnp.asarray(toks), jnp.asarray(mpad), jnp.asarray(pos), eos,
            jnp.asarray([sp.temperature for sp in lanes], jnp.float32),
            jnp.asarray([sp.top_k for sp in lanes], jnp.int32),
            jnp.asarray([sp.top_p for sp in lanes], jnp.float32),
            jnp.asarray([sp.seed for sp in lanes], jnp.uint32))
        return jnp.concatenate([jnp.asarray(ids), new], axis=1)

    def _generate_uncached(self, input_ids, max_new_tokens: int = 32,
                           eos_token_id: Optional[int] = None, greedy: bool = True,
                           rng: Optional[jax.Array] = None, temperature: float = 1.0,
                           params=None):
        """Full-recompute fallback for arbitrary logits-returning apply_fns
        (and the parity reference for the cached path in tests).

        The forward runs on sequences RIGHT-PADDED to the ``_bucket``
        granularity, reading logits at the last real position — a growing
        ``ids`` would otherwise retrace/recompile the jitted forward EVERY
        step; padded, the whole generation compiles O(log) programs.  The
        bucketing requires a causal ``apply_fn`` (tail pads must not affect
        earlier positions' logits); the first call probes this with one
        padded-vs-unpadded logit comparison and a non-causal apply_fn drops
        back to the exact (per-step retracing) path with a warning."""
        if not InferenceEngine._warned_uncached:
            InferenceEngine._warned_uncached = True
            logger.warning(
                "generate() is using the full-recompute fallback (O(S) "
                "forward per token).  Give the model a KV cache "
                "(apply_cached — see models.CausalLM) for the single-"
                "program cached decode path.")
        ids = np.asarray(input_ids)
        if ids.ndim == 1:
            ids = ids[None, :]
        B = ids.shape[0]
        rng = rng if rng is not None else jax.random.PRNGKey(0)

        def fwd(tokens):
            logits = (self._forward(params, tokens) if params is not None
                      else self.forward(tokens))
            return logits[0] if isinstance(logits, tuple) else logits

        for _ in range(max_new_tokens):
            n = ids.shape[1]
            if getattr(self, "_uncached_causal", None) is False:
                next_logits = fwd(ids)[:, n - 1, :]
            else:
                padded = np.zeros((B, self._bucket(n)), ids.dtype)
                padded[:, :n] = ids
                next_logits = fwd(padded)[:, n - 1, :]
                if (getattr(self, "_uncached_causal", None) is None
                        and padded.shape[1] > n):
                    # one-time causality probe: tail pads must not reach
                    # position n-1 or the bucketed outputs would silently
                    # diverge from the exact ones (prefix-LM apply_fns).
                    # Only a genuinely padded step can probe — at n ==
                    # bucket(n) the two forwards would compare identical
                    # arrays and latch a vacuous True verdict
                    exact = fwd(ids)[:, n - 1, :]
                    self._uncached_causal = bool(jnp.allclose(
                        exact, next_logits, rtol=1e-4, atol=1e-5))
                    if not self._uncached_causal:
                        logger.warning(
                            "uncached generate: apply_fn is not causal "
                            "(pad tokens leak into earlier logits) — "
                            "using the exact per-step path, which "
                            "retraces every new length")
                        next_logits = exact
            if greedy or temperature <= 0:
                # temperature <= 0 folds to greedy (dividing logits by it
                # would be a silent NaN factory) — same guard the shared
                # sampling subsystem applies in-graph
                nxt = jnp.argmax(next_logits, axis=-1)
            else:
                rng, sub = jax.random.split(rng)
                nxt = jax.random.categorical(sub, next_logits / temperature, axis=-1)
            ids = np.concatenate([ids, np.asarray(nxt)[:, None].astype(ids.dtype)],
                                 axis=1)
            if eos_token_id is not None and bool((nxt == eos_token_id).all()):
                break
        return jnp.asarray(ids)
