"""InferenceEngine (reference ``deepspeed/inference/engine.py:89``).

First slice: tensor-parallel jitted forward with dtype conversion and
auto-sharded params (the auto-TP analogue — ``module_inject/auto_tp.py``
discovers linear layers to shard; here :func:`auto_tp_specs` shards every
matmul-shaped weight's largest free dim over the 'model' axis).  Generation
with a paged KV cache and Pallas-fused blocks lands with the kernel-injection
milestone (module_inject/), which plugs in through the same ``apply_fn``
contract.

The reference's CUDA-graph capture/replay (engine.py:532-560) has no TPU
analogue because jit AOT-compiles the whole forward — every call IS the
captured graph.
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .config import DeepSpeedInferenceConfig
from ..parallel.mesh import MeshLayout, initialize_mesh
from ..utils.logging import logger, log_dist


def auto_tp_specs(params: Any, mesh) -> Any:
    """Auto-TP for a param pytree (reference module_inject/auto_tp.py): shard
    each >=2D weight's largest dim over 'model'; replicate the rest."""
    tp = mesh.shape["model"]

    def spec_for(x):
        shape = getattr(x, "shape", ())
        if len(shape) < 2 or tp == 1:
            return P()
        dim = int(np.argmax(shape))
        if shape[dim] % tp != 0:
            return P()
        entries = [None] * len(shape)
        entries[dim] = "model"
        return P(*entries)

    return jax.tree_util.tree_map(spec_for, params)


class InferenceEngine:
    def __init__(self, model: Any = None, config: Optional[DeepSpeedInferenceConfig] = None,
                 apply_fn: Optional[Callable] = None, params: Any = None, mesh=None):
        self._config = config or DeepSpeedInferenceConfig()
        if model is not None:
            apply_fn = apply_fn or getattr(model, "apply_fn", None) or getattr(
                model, "apply", None)
            params = params if params is not None else getattr(model, "params", None)
        if apply_fn is None:
            raise ValueError("InferenceEngine needs apply_fn(params, *args) "
                             "(directly or via a model adapter)")
        self.apply_fn = apply_fn

        tp = self._config.tensor_parallel.tp_size if self._config.tensor_parallel.enabled else 1
        if mesh is None:
            mesh = initialize_mesh(MeshLayout.from_world(jax.device_count(), tp=tp,
                                                         ep=self._config.moe.ep_size))
        self.mesh = mesh

        if params is not None:
            dtype = self._config.jnp_dtype
            specs = auto_tp_specs(params, mesh)
            shardings = jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), specs,
                                               is_leaf=lambda x: isinstance(x, P))
            cast = lambda x: x.astype(dtype) if hasattr(x, "dtype") and jnp.issubdtype(  # noqa: E731
                x.dtype, jnp.floating) else x
            self.params = jax.jit(lambda p: jax.tree_util.tree_map(cast, p),
                                  out_shardings=shardings)(params)
        else:
            self.params = None
        self._forward = jax.jit(self.apply_fn)
        log_dist(f"inference engine ready: tp={tp} dtype={self._config.dtype}", ranks=[0])

    def forward(self, *args, **kwargs):
        if self.params is not None:
            return self._forward(self.params, *args, **kwargs)
        return self._forward(*args, **kwargs)

    __call__ = forward

    def generate(self, input_ids, max_new_tokens: int = 32, eos_token_id: Optional[int] = None,
                 greedy: bool = True, rng: Optional[jax.Array] = None, temperature: float = 1.0):
        """Greedy/sampled autoregressive generation by full-recompute forward.

        The KV-cached decode loop (reference softmax_context kernels with the
        inference_context workspace) arrives with models/ generation support;
        this path is correct for any logits-returning apply_fn."""
        ids = jnp.asarray(input_ids)
        if ids.ndim == 1:
            ids = ids[None, :]
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        for _ in range(max_new_tokens):
            logits = self.forward(ids)
            logits = logits[0] if isinstance(logits, tuple) else logits
            next_logits = logits[:, -1, :]
            if greedy:
                nxt = jnp.argmax(next_logits, axis=-1)
            else:
                rng, sub = jax.random.split(rng)
                nxt = jax.random.categorical(sub, next_logits / temperature, axis=-1)
            ids = jnp.concatenate([ids, nxt[:, None]], axis=1)
            if eos_token_id is not None and bool((nxt == eos_token_id).all()):
                break
        return ids
