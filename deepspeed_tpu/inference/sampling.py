"""Sampling subsystem: per-slot RNG lanes as fixed-shape traced ops.

The serving engine's decode program runs ONE fixed shape over all slots, so
per-request sampling (temperature / top-k / top-p / seed) must be expressed
as *traced per-slot parameter vectors*, never as program structure — a
request mix of greedy, hot-temperature and tight-nucleus slots has to share
the same compiled program or the zero-recompile admission contract
(docs/SERVING.md) dies the moment real traffic arrives.  This module is
that expression, shared by ``InferenceEngine.generate()`` and
``ServingEngine`` so the two paths are token-identical under the same
seed/params (the sampled analogue of the greedy parity contract):

- :class:`SamplingParams` — the per-request knobs.  ``temperature <= 0``
  means greedy and is folded IN-GRAPH (``jnp.where`` on the argmax), so
  "greedy" is just a lane value, not a different program (and the
  divide-by-zero of naive ``logits / temperature`` can never happen).
- :func:`filter_logits` / :func:`sample_tokens` / :func:`sampling_probs` —
  dynamic top-k *and* top-p from ONE full descending sort plus per-slot
  masks.  ``top_k <= 0`` or ``top_k >= vocab`` disables the k-filter,
  ``top_p >= 1`` disables the nucleus filter, all per row, all traced.
- **Counter-based keys** — the key for the token at absolute stream
  position ``p`` is ``fold_in(PRNGKey(seed), p)`` (:func:`position_keys`).
  No split-chain state: a replayed or failed-over stream that re-prefills
  ``prompt + generated`` re-derives the SAME key at every continuation
  position, which is what keeps ``ServingSupervisor`` replay and fleet
  mid-stream resume token-exact under sampling (docs/FLEET.md journals the
  lane seed + counter for exactly this).  Speculative decoding salts these
  keys per role (``inference/speculative.py``).
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["SamplingParams", "filter_logits", "position_keys",
           "sample_tokens", "sampling_probs"]


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling lane.  The defaults ARE greedy decoding — a
    request without sampling params behaves exactly as before this
    subsystem existed.

    ``temperature``: softmax temperature; ``<= 0`` folds to greedy
    in-graph.  ``top_k``: keep the k highest logits (``0`` or ``>= vocab``
    = no filter).  ``top_p``: keep the smallest prefix of the (top-k
    filtered) distribution with mass ``>= top_p`` (``1.0`` = no filter).
    ``seed``: the lane seed — the key for the token at stream position
    ``p`` is ``fold_in(PRNGKey(seed), p)``, so equal (seed, params, model)
    ⇒ equal tokens on any engine, any replay, any failover resume."""
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0

    @property
    def greedy(self) -> bool:
        return self.temperature <= 0.0

    def validate(self) -> "SamplingParams":
        if not (0.0 < self.top_p <= 1.0):
            raise ValueError(
                f"top_p={self.top_p} must be in (0, 1] (1.0 disables the "
                "nucleus filter; <= 0 would keep an empty support)")
        if self.top_k < 0:
            raise ValueError(
                f"top_k={self.top_k} must be >= 0 (0 disables the filter)")
        if self.seed < 0:
            raise ValueError(
                f"seed={self.seed} must be >= 0 (lane seeds are journaled "
                "as unsigned ints)")
        return self


GREEDY = SamplingParams()


def position_keys(seeds: jax.Array, positions: jax.Array,
                  salt: Optional[int] = None) -> jax.Array:
    """The counter-based lane schedule: key for the token at absolute
    position ``p`` of lane ``seed`` is ``fold_in(PRNGKey(seed), p)`` —
    with an optional role ``salt`` folded on top (speculative decoding
    derives draft/accept/resample randomness at one position).  Both
    array args ``[B]``; returns ``[B, 2]`` keys.  Pure function of
    (seed, position, salt) — replay/failover at any position re-derives
    it.  This is the ONE spelling of the schedule; every consumer must
    come through here or replay-exactness silently forks."""
    def one(s, p):
        k = jax.random.fold_in(jax.random.PRNGKey(s), p)
        return jax.random.fold_in(k, salt) if salt is not None else k

    return jax.vmap(one)(seeds, positions)


def filter_logits(logits: jax.Array, temperature: jax.Array,
                  top_k: jax.Array, top_p: jax.Array) -> jax.Array:
    """Temper + filter ``[B, V]`` logits with per-row params (all ``[B]``),
    returning float32 logits with ``-inf`` outside the kept support.

    ONE full descending sort serves both filters (dynamic per-row k/p make
    ``lax.top_k``'s static k unusable): the k-th sorted value thresholds
    top-k, and the nucleus cutoff is read off the cumulative softmax of the
    k-masked sorted row — the smallest prefix with mass ``>= top_p`` stays.
    ``top_k <= 0`` / ``>= V`` and ``top_p >= 1`` disable their filter per
    row; ``temperature <= 0`` rows pass through unscaled (the samplers fold
    them to argmax — never a division by zero)."""
    lg = logits.astype(jnp.float32)
    V = lg.shape[-1]
    greedy = temperature <= 0.0
    lg = lg / jnp.where(greedy, 1.0, temperature).astype(jnp.float32)[:, None]
    sorted_lg = jnp.sort(lg, axis=-1)[:, ::-1]
    k_eff = jnp.where((top_k <= 0) | (top_k >= V), V,
                      top_k).astype(jnp.int32)
    kth = jnp.take_along_axis(sorted_lg, (k_eff - 1)[:, None], axis=-1)
    keep = lg >= kth
    sorted_masked = jnp.where(
        jnp.arange(V, dtype=jnp.int32)[None, :] < k_eff[:, None],
        sorted_lg, -jnp.inf)
    probs = jax.nn.softmax(sorted_masked, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # keep the smallest prefix with mass >= top_p (same boundary the
    # pre-subsystem generate() used: the cutoff entry itself is kept)
    cutoff_idx = jnp.minimum(jnp.sum(cum < top_p[:, None], axis=-1), V - 1)
    cutoff = jnp.take_along_axis(sorted_masked, cutoff_idx[:, None], axis=-1)
    keep &= (lg >= cutoff) | (top_p >= 1.0)[:, None]
    return jnp.where(keep, lg, -jnp.inf)


def sample_tokens(logits: jax.Array, temperature: jax.Array,
                  top_k: jax.Array, top_p: jax.Array,
                  keys: jax.Array) -> jax.Array:
    """Sample one token per row: ``[B, V]`` logits, ``[B]`` param lanes,
    ``[B, 2]`` per-row keys -> ``[B]`` int32.  Greedy rows (``temperature
    <= 0``) take the raw argmax in-graph — one program serves any mix.

    An ALL-greedy call (the default serving workload: nobody passed
    SamplingParams) must not pay for the lane machinery: ``lax.cond``
    executes only the taken branch, so a tick with no sampled lane costs
    one argmax plus a scalar predicate — the pre-subsystem decode cost —
    while still being the same compiled program a mixed tick runs.
    ``keys`` may be a zero-arg callable returning the keys: it is invoked
    INSIDE the sampled branch, so per-row key derivation (threefry is not
    cheap) is also skipped on all-greedy ticks."""
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def drawn(_):
        k = keys() if callable(keys) else keys
        filtered = filter_logits(logits, temperature, top_k, top_p)
        sampled = jax.vmap(jax.random.categorical)(k, filtered)
        return jnp.where(temperature <= 0.0, greedy_tok,
                         sampled).astype(jnp.int32)

    return jax.lax.cond(jnp.any(temperature > 0.0), drawn,
                        lambda _: greedy_tok, None)


def sampling_probs(logits: jax.Array, temperature: jax.Array,
                   top_k: jax.Array, top_p: jax.Array) -> jax.Array:
    """The normalized distribution :func:`sample_tokens` draws from, as
    explicit ``[B, V]`` float32 probs (greedy rows are one-hot at the raw
    argmax).  Speculative decoding needs it on both sides of the
    accept test: draft proposal probs ``q`` and target probs ``p``
    (``inference/speculative.py``)."""
    filtered = filter_logits(logits, temperature, top_k, top_p)
    probs = jax.nn.softmax(filtered, axis=-1)
    onehot = jax.nn.one_hot(jnp.argmax(logits, axis=-1), logits.shape[-1],
                            dtype=jnp.float32)
    return jnp.where((temperature <= 0.0)[:, None], onehot, probs)


def as_lanes(sampling: Optional[SamplingParams]):
    """``(temperature, top_k, top_p, seed)`` scalar lane values for one
    request (``None`` = the greedy lane) — what the serving engine writes
    into its per-slot state arrays at admission."""
    sp = sampling if sampling is not None else GREEDY
    return (float(sp.temperature), int(sp.top_k), float(sp.top_p),
            int(sp.seed))
