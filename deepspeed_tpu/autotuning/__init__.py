"""Autotuning (reference ``deepspeed/autotuning/``): config-space search with
compile-time memory pruning + timed trials."""
from .autotuner import (  # noqa: F401
    Autotuner,
    AutotuningConfig,
    TrialRecord,
    autotune,
)
