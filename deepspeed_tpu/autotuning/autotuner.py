"""Autotuner: search the (zero stage × micro-batch × remat) config space.

Parity target: reference ``autotuning/autotuner.py:42`` (tuning spaces per
ZeRO stage, micro-batch sweep with an OOM-probe ceiling, gridsearch/random/
model-based tuners, fast-mode early exit, results records + best-config
emission — ``tune():404``, ``tune_space():523``).

TPU-native redesign: the reference launches a subprocess experiment per
candidate and watches for OOM.  XLA makes half of that unnecessary — a
candidate's memory footprint is known at COMPILE time: we ``jit.lower().
compile()`` the engine's train step and read ``memory_analysis()`` to reject
over-budget configs WITHOUT running them (the reference burns a full job
launch to learn the same bit).  Survivors get short timed trials on the real
chip; records and the best config are written like the reference's
``autotuning_results``.
"""
from __future__ import annotations

import dataclasses
import itertools
import json
import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils.logging import log_dist, logger

DEFAULT_HBM_BYTES = 16 * 1024 ** 3       # v5e chip
MEMORY_SAFETY_MARGIN = 0.92              # leave headroom for runtime buffers


@dataclasses.dataclass
class TrialRecord:
    config_overrides: Dict[str, Any]
    status: str                 # ok | compile_oom | compile_error | run_error
    metric_val: float = 0.0     # samples/sec (throughput) or -sec (latency)
    memory_bytes: int = 0
    compile_sec: float = 0.0
    error: str = ""

    def as_dict(self):
        return dataclasses.asdict(self)



def _memory_bytes(mem) -> int:
    """Compiled-program HBM estimate — ONE formula for prune and measure."""
    return int(getattr(mem, "temp_size_in_bytes", 0)
               + getattr(mem, "argument_size_in_bytes", 0)
               + getattr(mem, "output_size_in_bytes", 0)
               - getattr(mem, "alias_size_in_bytes", 0))


def _apply_budget(rec: TrialRecord, mem, hbm_bytes: int) -> bool:
    """Record the estimate; True if the config fits the budget."""
    if mem is None:
        return True
    rec.memory_bytes = _memory_bytes(mem)
    if rec.memory_bytes > hbm_bytes * MEMORY_SAFETY_MARGIN:
        rec.status = "compile_oom"
        rec.error = (f"predicted {rec.memory_bytes / 1e9:.2f} GB > "
                     f"budget {hbm_bytes / 1e9:.2f} GB")
        return False
    return True


@dataclasses.dataclass(frozen=True)
class AutotuningConfig:
    """``autotuning`` block (reference constants.py:41-70)."""
    enabled: bool = False
    metric: str = "throughput"            # throughput | latency
    results_dir: str = "autotuning_results"
    overwrite: bool = True
    fast: bool = True                     # stop a sweep on first regression
    tuner_type: str = "gridsearch"        # gridsearch | random | model_based
    max_trials: int = 50
    start_profile_step: int = 2
    end_profile_step: int = 6
    mbs_candidates: Optional[Sequence[int]] = None
    zero_stages: Optional[Sequence[int]] = None
    remat_policies: Optional[Sequence[str]] = None
    # flash-attention dispatch is part of the space (the kernel-vs-XLA
    # threshold is config, not a constant — VERDICT r2 item 8)
    attn_impls: Optional[Sequence[str]] = None
    # depth-2 dims (VERDICT r3 item 8): sequence length (model override),
    # gradient-accumulation, optimizer offload, pipeline degree
    seq_lens: Optional[Sequence[int]] = None
    gas_candidates: Optional[Sequence[int]] = None
    offload_devices: Optional[Sequence[Optional[str]]] = None  # None | "cpu"
    pp_sizes: Optional[Sequence[int]] = None
    # model_based: measured seed trials before the cost model takes over
    seed_trials: int = 3
    # compile-prune candidates concurrently (XLA compilation releases the
    # GIL; timing stays serial — one chip) — the TPU-shaped analogue of the
    # reference's multi-node experiment scheduler (autotuning/scheduler.py)
    parallel_compile: int = 4
    hbm_bytes: int = DEFAULT_HBM_BYTES

    @classmethod
    def from_dict(cls, d: Optional[Dict]) -> "AutotuningConfig":
        d = dict(d or {})
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown autotuning keys: {sorted(unknown)}")
        return cls(**d)


class Autotuner:
    """Grid/random search with compile-time memory pruning.

    ``make_engine(overrides) -> engine`` builds a fresh engine for a
    candidate; ``make_batch(engine) -> batch`` supplies a training batch.
    """

    def __init__(self, make_engine: Callable[[Dict[str, Any]], Any],
                 make_batch: Callable[[Any], Any],
                 config: Optional[AutotuningConfig] = None, model=None):
        self.make_engine = make_engine
        self.make_batch = make_batch
        self.config = config or AutotuningConfig(enabled=True)
        self.records: List[TrialRecord] = []
        # optional: enables profiler-informed cost-model feature scaling
        # (see _tune_model_based)
        self.model = model

    # -- candidate space (reference _generate_experiments / tune_space) --
    def sweeps(self) -> List[List[Dict[str, Any]]]:
        """One sweep per (stage, remat): micro-batches ascending, so fast
        mode can cut a sweep at the first regression/OOM (reference
        tune_space's prev_best early exit)."""
        c = self.config
        stages = list(c.zero_stages if c.zero_stages is not None else (0, 1, 2, 3))
        mbs = sorted(c.mbs_candidates if c.mbs_candidates is not None
                     else (1, 2, 4, 8, 16, 32))
        remats = list(c.remat_policies if c.remat_policies is not None else (None,))
        attns = list(c.attn_impls if c.attn_impls is not None else (None,))
        seqs = list(c.seq_lens if c.seq_lens is not None else (None,))
        gass = list(c.gas_candidates if c.gas_candidates is not None else (None,))
        offs = list(c.offload_devices if c.offload_devices is not None
                    else (None,))
        pps = list(c.pp_sizes if c.pp_sizes is not None else (None,))
        out = []
        for stage, remat, attn, seq, gas, off, pp in itertools.product(
                stages, remats, attns, seqs, gass, offs, pps):
            sweep = []
            for mb in mbs:
                ov: Dict[str, Any] = {
                    "zero_optimization": {"stage": stage},
                    "train_micro_batch_size_per_gpu": mb,
                }
                if remat is not None:
                    ov["_remat_policy"] = remat
                if attn is not None:
                    ov["_attn_impl"] = attn
                if seq is not None:
                    ov["_seq_len"] = seq
                if gas is not None:
                    ov["gradient_accumulation_steps"] = gas
                if off is not None:
                    ov["zero_optimization"]["offload_optimizer"] = \
                        {"device": off}
                if pp is not None:
                    ov["_pp"] = pp
                sweep.append(ov)
            out.append(sweep)
        if self.config.tuner_type == "random":
            rng = np.random.default_rng(0)
            rng.shuffle(out)
        return out

    # -- cost model (reference autotuning/tuner/model_based_tuner.py +
    #    cost_model.py — theirs is xgboost; ours is quadratic features under
    #    a ridge fit, which survives >100-point grids without a tree lib) --
    @staticmethod
    def _features(ov: Dict[str, Any], space: Dict[str, list]) -> np.ndarray:
        """Step-time features.  Continuous block: [1, mb, mb², S·mb, S²·mb,
        S, gas, gas·mb] — attention work scales mb·S² and matmul work mb·S,
        so per-step time is linear in these; the mb² term models batch-size
        curvature (cache/util effects) so throughput mb/t can peak interior.
        Categorical dims (stage/remat/attn/offload/pp) contribute a fixed
        overhead one-hot AND a per-sample slope one-hot (×mb): ZeRO stage or
        offload changes BOTH the per-step constant (collectives, host sync)
        and the per-sample cost."""
        mb = float(ov["train_micro_batch_size_per_gpu"])
        S = float(ov.get("_seq_len") or space.get("seq_default") or 1.0)
        Sn = S / max(space.get("seq_scale", 1.0), 1.0)   # normalized seq
        gas = float(ov.get("gradient_accumulation_steps", 1))
        if "dense_coeff" in space and "attn_coeff" in space:
            # profiler-informed: ONE physical model-flops column
            # (dc + ac·(S/S₀))·Sn·mb replaces the separate S·mb / S²·mb
            # terms — the per-module profile pins the dense:attention
            # ratio, so the ridge has one fewer free parameter to identify
            # from seed trials.  (Scaling the two columns separately would
            # be a no-op: the per-column max-abs normalization cancels
            # constant scales.)  The coefficients were MEASURED at
            # S₀ = seq_default, and attention flops/token scale linearly
            # in S, so the ratio term must be S/S₀ — normalizing by
            # seq_scale instead would mis-weight attention by
            # seq_scale/seq_default at the profiled point.
            dc = float(space["dense_coeff"])
            ac = float(space["attn_coeff"])
            r = S / max(float(space.get("seq_default", 1.0)), 1.0)
            x = [1.0, mb, mb * mb, (dc + ac * r) * Sn * mb, Sn, gas,
                 gas * mb]
        else:
            x = [1.0, mb, mb * mb, Sn * mb, Sn * Sn * mb, Sn, gas, gas * mb]
        off = (ov["zero_optimization"].get("offload_optimizer") or {}
               ).get("device")
        cats = [("stages", ov["zero_optimization"]["stage"]),
                ("remats", ov.get("_remat_policy")),
                ("attns", ov.get("_attn_impl")),
                ("offloads", off),
                ("pps", ov.get("_pp"))]
        for dim, val in cats:
            for v in space[dim]:
                hit = 1.0 if val == v else 0.0
                x.append(hit)          # fixed overhead
                x.append(hit * mb)     # per-sample slope
        return np.asarray(x, np.float64)

    @staticmethod
    def _ridge_fit(X: np.ndarray, t: np.ndarray, lam: float = 1e-6
                   ) -> np.ndarray:
        """Regularized least squares: stable when measured points are few
        relative to the feature count (the early rounds of a big grid)."""
        n = X.shape[1]
        return np.linalg.solve(X.T @ X + lam * np.eye(n), X.T @ t)

    def compile_prune(self, candidates: List[Dict[str, Any]]
                      ) -> List[TrialRecord]:
        """Parallel compile-time memory screening — the TPU-shaped analogue
        of the reference's multi-node experiment scheduler
        (``autotuning/scheduler.py`` runs candidate jobs concurrently; here
        the concurrency is in XLA compilation, which releases the GIL).

        Engine construction + lowering run serialized (global mesh / device
        state); ``.compile()`` of the lowered programs runs on a thread
        pool, ``parallel_compile`` at a time (each live engine holds params
        — keep the chunk small on real chips)."""
        from concurrent.futures import ThreadPoolExecutor

        out: List[TrialRecord] = []
        chunk = max(1, self.config.parallel_compile)
        for i in range(0, len(candidates), chunk):
            group = candidates[i:i + chunk]
            lowered: List[Tuple[TrialRecord, Any]] = []
            # construction + lowering stay on the main thread (global mesh /
            # device state); only the backend compile fans out below
            for ov in group:
                rec = TrialRecord(config_overrides=ov, status="ok")
                try:
                    engine = self.make_engine(dict(ov))
                    batch = self.make_batch(engine)
                    low = engine.lower_train_step(batch)
                    lowered.append((rec, low))
                except Exception as e:  # noqa: BLE001
                    rec.status = "compile_error"
                    rec.error = str(e)[:300]
                    out.append(rec)

            def compile_one(item):
                rec, low = item
                t0 = time.perf_counter()
                try:
                    compiled = low.compile()
                    rec.compile_sec = time.perf_counter() - t0
                    _apply_budget(rec, compiled.memory_analysis(),
                                  self.config.hbm_bytes)
                except Exception as e:  # noqa: BLE001
                    rec.status = ("compile_oom"
                                  if "resource_exhausted" in str(e).lower()
                                  else "compile_error")
                    rec.error = str(e)[:300]
                return rec

            with ThreadPoolExecutor(max_workers=chunk) as pool:
                out.extend(pool.map(compile_one, lowered))
        return out

    def _tune_model_based(self) -> Optional[TrialRecord]:
        """Fit step-time on measured trials, extrapolate over the untried
        grid, measure the predicted best, refit — until the model's argmax
        is already measured or the trial budget runs out."""
        c = self.config
        candidates = [ov for sweep in self.sweeps() for ov in sweep]
        seqs = sorted({ov.get("_seq_len") for ov in candidates
                       if ov.get("_seq_len")} or {1})
        space = {
            "stages": sorted({ov["zero_optimization"]["stage"]
                              for ov in candidates}),
            "remats": sorted({ov.get("_remat_policy") for ov in candidates},
                             key=str),
            "attns": sorted({ov.get("_attn_impl") for ov in candidates},
                            key=str),
            "offloads": sorted(
                {(ov["zero_optimization"].get("offload_optimizer") or {}
                  ).get("device") for ov in candidates}, key=str),
            "pps": sorted({ov.get("_pp") for ov in candidates}, key=str),
            "seq_default": float(seqs[0]),
            "seq_scale": float(max(seqs)),
        }
        # profiler-informed feature scaling: the S·mb (dense) and S²·mb
        # (attention) features carry the MODEL'S measured per-token flop
        # coefficients (flops_profiler per-module breakdown) instead of
        # unit weights — the ridge fit then starts from physically-scaled
        # regressors and needs fewer seed trials to separate the two terms
        try:
            from ..profiling.flops_profiler import get_detailed_profile

            det = get_detailed_profile(self.model, batch_size=1,
                                       seq_len=int(space["seq_default"]))
            tot = det["total"]["flops_per_token"] or 1.0
            space["dense_coeff"] = det["dense_flops_per_token"] / tot
            space["attn_coeff"] = det["attn_flops_per_token"] / tot
        except Exception:
            pass
        key = lambda ov: json.dumps(ov, sort_keys=True)  # noqa: E731
        measured: Dict[str, TrialRecord] = {}
        best: Optional[TrialRecord] = None

        # features that never vary over THIS grid carry no signal — prune
        # them so small grids stay well-determined under the rich set; then
        # normalize columns (unit scale over the grid) so the ridge fit and
        # the exploration geometry aren't dominated by mb² >> Sn-scale terms
        X_all = np.stack([self._features(ov, space) for ov in candidates])
        keep_cols = np.ptp(X_all, axis=0) > 0
        keep_cols[0] = True                     # intercept
        Xk = X_all[:, keep_cols]
        col_scale = np.maximum(np.abs(Xk).max(axis=0), 1e-12)
        feat_of = {key(ov): Xk[i] / col_scale
                   for i, ov in enumerate(candidates)}
        n_feat = int(keep_cols.sum())

        def measure(ov) -> TrialRecord:
            nonlocal best
            rec = self._measure(ov)
            self.records.append(rec)
            measured[key(ov)] = rec
            log_dist(f"autotuning[model] trial {ov}: {rec.status} "
                     f"metric={rec.metric_val:.2f}", ranks=[0])
            if rec.status == "ok" and (best is None
                                       or rec.metric_val > best.metric_val):
                best = rec
            return rec

        # seed: spread over the micro-batch range of the first sweep(s)
        seeds = candidates[:: max(1, len(candidates) // max(c.seed_trials, 1))]
        for ov in seeds[:c.seed_trials]:
            measure(ov)

        while len(self.records) < c.max_trials:
            ok = [r for r in measured.values() if r.status == "ok"]
            if len(ok) < 2:
                # not enough signal to fit — fall back to the next untried
                untried = [ov for ov in candidates if key(ov) not in measured]
                if not untried:
                    break
                measure(untried[0])
                continue
            X = np.stack([feat_of[key(r.config_overrides)] for r in ok])
            # fit per-sample step time: t = batch / throughput
            t = np.asarray([
                r.config_overrides["train_micro_batch_size_per_gpu"]
                * r.config_overrides.get("gradient_accumulation_steps", 1)
                / max(r.metric_val, 1e-9) if c.metric == "throughput"
                else -r.metric_val for r in ok])
            coef = self._ridge_fit(X, t)
            oom_keys = {key(r.config_overrides) for r in measured.values()
                        if r.status != "ok"}
            scored = []
            for ov in candidates:
                if key(ov) in oom_keys:
                    continue
                t_hat = float(feat_of[key(ov)] @ coef)
                samples = (ov["train_micro_batch_size_per_gpu"]
                           * ov.get("gradient_accumulation_steps", 1))
                if c.metric == "throughput":
                    score = samples / max(t_hat, 1e-9) if t_hat > 0 else 0.0
                else:  # latency: smallest predicted step time wins
                    score = -t_hat
                scored.append((score, ov))
            scored.sort(key=lambda p: -p[0])
            if not scored:
                break
            if key(scored[0][1]) not in measured:
                measure(scored[0][1])
                continue
            # the model's argmax is already measured: converged only when
            # the fit is determined; otherwise EXPLORE — measure the
            # unmeasured candidate whose feature vector lies furthest out of
            # the measured span (D-optimal-flavored), which buys the fit the
            # most new information per trial on a big grid
            if len(ok) >= n_feat:
                break
            Q, _ = np.linalg.qr(X.T)

            def novelty(ov):
                x = feat_of[key(ov)]
                r = x - Q @ (Q.T @ x)
                return float(np.dot(r, r))

            untried = [ov for _, ov in scored if key(ov) not in measured]
            if not untried:
                break
            measure(max(untried, key=novelty))
        return best

    # -- one trial --
    def _measure(self, overrides: Dict[str, Any]) -> TrialRecord:
        rec = TrialRecord(config_overrides=overrides, status="ok")
        try:
            engine = self.make_engine(dict(overrides))
            batch = self.make_batch(engine)
            t0 = time.perf_counter()
            step = engine.compile_train_step(batch)
            rec.compile_sec = time.perf_counter() - t0
            mem = step.memory_analysis() if hasattr(step, "memory_analysis") else None
            if not _apply_budget(rec, mem, self.config.hbm_bytes):
                return rec
            # timed steps (start/end_profile_step warmup convention)
            warm = self.config.start_profile_step
            steps = max(1, self.config.end_profile_step - warm)
            for _ in range(warm):
                loss = engine.train_batch(batch=batch)
            float(loss) if warm else None
            t0 = time.perf_counter()
            for _ in range(steps):
                loss = engine.train_batch(batch=batch)
            float(loss)
            dt = (time.perf_counter() - t0) / steps
            samples = engine.train_batch_size
            rec.metric_val = (samples / dt if self.config.metric == "throughput"
                              else -dt)
        except Exception as e:  # noqa: BLE001 — a failed trial is a record
            msg = str(e)
            low = msg.lower()
            rec.status = ("compile_oom" if "resource_exhausted" in low
                          or "out of memory" in low else "run_error")
            rec.error = msg[:300]
        return rec

    def tune(self) -> Tuple[Optional[Dict[str, Any]], List[TrialRecord]]:
        """Run the search; returns (best_overrides, records) and writes
        ``results_dir/`` like the reference (per-trial records + best)."""
        if not self.config.enabled:
            raise ValueError("autotuning is not enabled in the config")
        if self.config.tuner_type == "model_based":
            best = self._tune_model_based()
            self._write_results(best)
            return (best.config_overrides if best else None), self.records
        best: Optional[TrialRecord] = None
        trials = 0
        for sweep in self.sweeps():
            prev_val = -float("inf")
            for overrides in sweep:
                if trials >= self.config.max_trials:
                    break
                rec = self._measure(overrides)
                trials += 1
                self.records.append(rec)
                log_dist(f"autotuning trial {overrides}: {rec.status} "
                         f"metric={rec.metric_val:.2f} "
                         f"mem={rec.memory_bytes / 1e9:.2f}GB", ranks=[0])
                if rec.status == "ok" and (best is None
                                           or rec.metric_val > best.metric_val):
                    best = rec
                if self.config.fast:
                    if rec.status == "compile_oom":
                        break   # larger micro-batches in this sweep also OOM
                    if rec.status == "ok" and rec.metric_val < prev_val:
                        break   # past this sweep's throughput peak
                    if rec.status == "ok":
                        prev_val = rec.metric_val
            if trials >= self.config.max_trials:
                break
        self._write_results(best)
        return (best.config_overrides if best else None), self.records

    def _write_results(self, best: Optional[TrialRecord]) -> None:
        d = self.config.results_dir
        if os.path.isdir(d) and os.listdir(d) and not self.config.overwrite:
            raise FileExistsError(
                f"results_dir {d} exists and autotuning.overwrite is false")
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "records.json"), "w") as f:
            json.dump([r.as_dict() for r in self.records], f, indent=2)
        if best is not None:
            with open(os.path.join(d, "best_config.json"), "w") as f:
                json.dump({"overrides": best.config_overrides,
                           "metric": self.config.metric,
                           "metric_val": best.metric_val}, f, indent=2)
        logger.info(f"autotuning: {len(self.records)} trials -> {d}")


def autotune(model_factory: Callable[[], Any], base_config: Dict[str, Any],
             batch_factory: Callable[[Any], Any],
             autotuning_config: Optional[Dict] = None):
    """Convenience entry (reference ``deepspeed --autotuning run``): search
    around ``base_config`` and return (best_full_config, records)."""
    import deepspeed_tpu
    from ..parallel import mesh as mesh_mod

    at_cfg = AutotuningConfig.from_dict(
        autotuning_config or base_config.get("autotuning"))

    def make_engine(overrides):
        mesh_mod.reset_mesh()
        cfg = json.loads(json.dumps({k: v for k, v in base_config.items()
                                     if k != "autotuning"}))
        remat = overrides.pop("_remat_policy", None)
        attn = overrides.pop("_attn_impl", None)
        seq = overrides.pop("_seq_len", None)
        pp = overrides.pop("_pp", None)
        for k, v in overrides.items():
            if isinstance(v, dict):
                cfg.setdefault(k, {}).update(v)
            else:
                cfg[k] = v
        if pp is not None:
            cfg.setdefault("mesh", {})["pp"] = pp
        model = model_factory()
        model_over = {}
        if remat is not None:
            model_over["remat_policy"] = remat
        if seq is not None:
            # seq-len trials: the model's window shrinks/grows; the batch
            # factory reads engine.autotune_seq_len to size the batch
            model_over["max_seq_len"] = seq
        if pp is not None:
            model_over["pipeline_stages"] = pp
        if model_over and hasattr(model, "config"):
            model.config = dataclasses.replace(model.config, **model_over)
        if attn is not None and hasattr(model, "attn_impl"):
            model.attn_impl = attn
        engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=cfg)
        engine.autotune_seq_len = seq
        return engine

    try:
        profile_model = model_factory()
    except Exception:
        profile_model = None
    tuner = Autotuner(make_engine, batch_factory, at_cfg,
                      model=profile_model)
    best, records = tuner.tune()
    full = None
    if best is not None:
        full = json.loads(json.dumps({k: v for k, v in base_config.items()
                                      if k != "autotuning"}))
        for k, v in best.items():
            if isinstance(v, dict):
                full.setdefault(k, {}).update(v)
            elif k == "_pp":
                # a pipeline winner needs BOTH the engine mesh degree and
                # the model's pipeline_stages; mesh.pp is an engine key we
                # can set here, the model half rides along like _remat_policy
                full.setdefault("mesh", {})["pp"] = v
                full[k] = v
            else:
                # "_remat_policy"/"_seq_len" ride along: they are MODEL
                # overrides the caller must apply (TransformerConfig), not
                # engine-config keys — dropping them would return a config
                # that does not reproduce the measured winner
                full[k] = v
    return full, records
