"""Hang watchdog: convert a wedged step or checkpoint into a diagnosable
restart instead of a silent forever-hang.

Hung collectives are the nastiest TPU failure mode: one host drops out of an
all-reduce and every other host blocks inside XLA with no Python frame ever
returning — no exception, no exit, the supervisor sees a "healthy" process
making no progress.  The watchdog is a daemon thread armed around the two
places the runtime can block indefinitely (``train_batch`` and
async-checkpoint finalization).  If a guarded section overruns its deadline
the watchdog dumps every thread's stack through the monitor layer (so the
report lands next to the training metrics) and hard-exits with a dedicated
code — the supervisor treats it like any other failed round and relaunches
from the last committed checkpoint.

``os._exit`` is deliberate: the main thread is wedged in native code and
will never run ``sys.exit`` cleanup, and a daemon-thread ``raise`` cannot
cross into it.  Tests set ``on_hang`` to observe the report instead.
"""
from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from contextlib import contextmanager
from typing import Callable, Optional

from ..utils.logging import logger

# distinct from RC_INTERRUPT(130) and shell conventions; the supervisor
# relaunches on it like any failure exit
RC_HANG = 85


def format_stack_report(label: str, timeout_s: float) -> str:
    """All-thread stack dump, hung section first."""
    lines = [f"HANG WATCHDOG: {label!r} exceeded {timeout_s:.1f}s deadline",
             f"pid={os.getpid()} threads={threading.active_count()}", ""]
    frames = sys._current_frames()
    for t in threading.enumerate():
        frame = frames.get(t.ident)
        lines.append(f"--- thread {t.name} (ident={t.ident}, "
                     f"daemon={t.daemon}) ---")
        if frame is not None:
            lines.extend(l.rstrip() for l in traceback.format_stack(frame))
        else:
            lines.append("  <no frame>")
        lines.append("")
    return "\n".join(lines)


class HangWatchdog:
    """Deadline monitor for sections that may block in native code.

    ::

        wd = HangWatchdog(timeout_s=600)
        with wd.armed("train_batch step 42"):
            engine.train_batch(...)

    On expiry: stack report via ``monitor.write_report`` (or the logger),
    then ``os._exit(exit_code)`` — unless ``on_hang`` is set, in which case
    it is called with the report and the process lives (test hook)."""

    def __init__(self, timeout_s: float = 600.0, exit_code: int = RC_HANG,
                 monitor=None, on_hang: Optional[Callable[[str], None]] = None,
                 poll_s: float = 0.05):
        self.timeout_s = float(timeout_s)
        self.exit_code = exit_code
        self.monitor = monitor
        self.on_hang = on_hang
        self.poll_s = poll_s
        self.fired = False
        self._label: Optional[str] = None
        self._armed_timeout = self.timeout_s
        self._deadline: Optional[float] = None
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def arm(self, label: str, timeout_s: Optional[float] = None) -> None:
        with self._lock:
            self._label = label
            self._armed_timeout = timeout_s or self.timeout_s
            self._deadline = time.monotonic() + self._armed_timeout
            if self._thread is None or not self._thread.is_alive():
                self._stop.clear()
                self._thread = threading.Thread(
                    target=self._watch, name="hang-watchdog", daemon=True)
                self._thread.start()

    def disarm(self) -> None:
        with self._lock:
            self._label = None
            self._deadline = None

    @contextmanager
    def armed(self, label: str, timeout_s: Optional[float] = None):
        self.arm(label, timeout_s)
        try:
            yield
        finally:
            self.disarm()

    def stop(self) -> None:
        """Shut the monitor thread down (tests / engine teardown)."""
        self.disarm()
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def _watch(self) -> None:
        while not self._stop.wait(self.poll_s):
            with self._lock:
                deadline, label = self._deadline, self._label
                timeout = self._armed_timeout
            if deadline is None or time.monotonic() < deadline:
                continue
            self.fired = True
            report = format_stack_report(label or "<unlabelled>", timeout)
            # append the flight recorder (when tracing is on): the stack
            # says WHERE the hang is, the span history says what the last
            # N seconds were doing — and the hung section itself shows up
            # as an open span (docs/OBSERVABILITY.md)
            try:
                from ..observability.trace import (dump_window_s,
                                                   flight_dump)

                fr = flight_dump(f"watchdog {label or '<unlabelled>'}",
                                 last_s=dump_window_s())
            except Exception as e:
                logger.warning("watchdog: flight dump failed (%s: %s)",
                               type(e).__name__, e)
                fr = None
            if fr:
                report = report + "\n" + fr
            logger.error(report)
            try:
                if self.monitor is not None:
                    self.monitor.write_report("watchdog/hang", report)
            except Exception as e:   # the report must not mask the exit
                logger.error("watchdog: monitor report failed: %s", e)
            if self.on_hang is not None:
                self.disarm()   # test hook observed the hang; stand down
                self.on_hang(report)
                continue
            logger.error("watchdog: exiting %d so the supervisor can "
                         "relaunch from the last committed checkpoint",
                         self.exit_code)
            os._exit(self.exit_code)
