"""Checkpoint integrity: per-tag manifest written at save, verified at load.

A committed tag directory looks like::

    <tag>/
      state/                # orbax sharded pytree
      client_state.json     # engine counters + user client_state
      ds_config.json        # config snapshot
      manifest.json         # written LAST (before `latest` is published)

``manifest.json`` records the logical tree structure (leaf paths, global
shapes, dtypes), content checksums of the small JSON sidecars, a size
listing of the orbax payload, and the writer world size.  Because it is
written after every other file and *before* the ``latest`` pointer, its
presence marks the commit point: a torn save is a tag directory without a
manifest, and a bit-rotted sidecar fails its checksum.

Verification failures raise :class:`CheckpointIntegrityError`; the elastic
agent responds by quarantining the tag (rename to ``<tag>.corrupt``) and
falling back one generation (``elastic_agent.restore_if_present``).
Legacy tags without a manifest verify as "unverified" (warn, accept) so
pre-manifest checkpoints keep loading.
"""
from __future__ import annotations

import hashlib
import json
import os
import re
import time
from typing import Dict, List, Optional

from ..utils.logging import logger

MANIFEST_FILE = "manifest.json"
MANIFEST_VERSION = 1
CORRUPT_SUFFIX = ".corrupt"
# ---- pod-scope commit (docs/POD.md): each host of a generation writes its
# shard plus a per-host manifest under host_manifests/; the pod manifest is
# published only after every host reported — ITS presence is the pod-level
# commit marker, exactly as manifest.json is the per-host one
POD_MANIFEST_FILE = "pod_manifest.json"
HOST_MANIFEST_DIR = "host_manifests"
# the newest-committed-tag pointer (single source; orbax_engine re-exports)
LATEST_FILE = "latest"
# dropped at the start of a save, removed when the manifest lands: its
# presence distinguishes a TORN save (crash mid-write) from a LEGACY
# pre-manifest tag — both lack a manifest, only the former must be rejected
INCOMPLETE_MARKER = ".incomplete"
# small sidecars cheap enough to checksum on every save/load
_CHECKSUMMED = ("client_state.json", "ds_config.json")
# payload subtrees listed (path -> size) in the manifest
_PAYLOAD_DIRS = ("state", "offload_optimizer")


class CheckpointIntegrityError(RuntimeError):
    """A checkpoint tag failed verification (torn write, corruption, or a
    manifest/content mismatch)."""


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _tree_summary(state) -> Dict[str, Dict]:
    """Leaf path -> {shape, dtype} for the saved pytree (global shapes, so
    the summary is topology-invariant — a dp8 save verifies on tp2×dp4)."""
    import jax

    out = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        if hasattr(leaf, "shape"):
            out[jax.tree_util.keystr(path)] = {
                "shape": [int(d) for d in leaf.shape],
                "dtype": str(getattr(leaf, "dtype", "")),
            }
    return out


def _payload_listing(ckpt_dir: str) -> Dict[str, int]:
    """Relative path -> size for the payload subtrees (orbax ``state/`` and
    host-stepped ``offload_optimizer/`` files).  Catches truncated/missing
    array files without checksumming gigabytes."""
    listing = {}
    for sub in _PAYLOAD_DIRS:
        for root, _dirs, files in os.walk(os.path.join(ckpt_dir, sub)):
            for name in files:
                p = os.path.join(root, name)
                listing[os.path.relpath(p, ckpt_dir)] = os.path.getsize(p)
    return listing


def mark_incomplete(ckpt_dir: str) -> None:
    """Drop the torn-save marker; removed by :func:`write_manifest` once the
    tag commits.  Call before writing any other file of the tag."""
    with open(os.path.join(ckpt_dir, INCOMPLETE_MARKER), "w") as f:
        f.write("save in progress; a crash before manifest.json removes "
                "this tag from the restore path\n")


def build_manifest(engine, tag: str) -> Dict:
    """The save-time half that needs the live engine; file checksums and the
    payload listing are added by :func:`write_manifest` once the payload is
    durable (sync: immediately; async: in the commit finalizer)."""
    import jax

    manifest: Dict = {
        "manifest_version": MANIFEST_VERSION,
        "tag": str(tag),
        "global_steps": int(engine.global_steps),
        "writer_world_size": int(jax.process_count()),
    }
    if engine.state is not None:
        manifest["tree"] = _tree_summary(engine.state)
    return manifest


def write_manifest(ckpt_dir: str, manifest: Dict) -> str:
    """Checksum the sidecars, list the payload, write ``manifest.json``.
    Must run after every other file of the tag is durable and before the
    ``latest`` pointer moves — the manifest IS the commit marker."""
    manifest = dict(manifest)
    files = {}
    for name in _CHECKSUMMED:
        p = os.path.join(ckpt_dir, name)
        if os.path.exists(p):
            files[name] = {"sha256": _sha256(p), "size": os.path.getsize(p)}
    manifest["files"] = files
    manifest["payload"] = _payload_listing(ckpt_dir)
    # the manifest itself must never be torn
    path = _atomic_write_json(os.path.join(ckpt_dir, MANIFEST_FILE), manifest)
    marker = os.path.join(ckpt_dir, INCOMPLETE_MARKER)
    if os.path.exists(marker):
        os.remove(marker)   # commit: the tag is now complete AND marked so
    return path


def read_manifest(ckpt_dir: str) -> Optional[Dict]:
    path = os.path.join(ckpt_dir, MANIFEST_FILE)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError) as e:
        # ValueError covers JSONDecodeError AND UnicodeDecodeError — a
        # bit-flipped manifest is frequently not even valid UTF-8
        raise CheckpointIntegrityError(
            f"unreadable manifest {path}: {e}") from e


def verify_checkpoint_dir(ckpt_dir: str) -> Optional[Dict]:
    """Verify a tag directory against its manifest.

    Returns the manifest (or ``None`` for legacy pre-manifest tags, which
    are accepted with a warning).  Raises :class:`CheckpointIntegrityError`
    on any mismatch: missing/short payload file, sidecar checksum drift,
    or an unreadable manifest.
    """
    if not os.path.isdir(ckpt_dir):
        raise CheckpointIntegrityError(f"checkpoint dir missing: {ckpt_dir}")
    if os.path.exists(os.path.join(ckpt_dir, INCOMPLETE_MARKER)):
        # the save died between first write and manifest commit — without
        # this marker a torn tag would be indistinguishable from a legacy
        # pre-manifest tag and sail through the `manifest is None` branch
        raise CheckpointIntegrityError(
            f"checkpoint {ckpt_dir} is a torn save ({INCOMPLETE_MARKER} "
            "present: the writer died before committing the manifest)")
    manifest = read_manifest(ckpt_dir)
    if manifest is None:
        logger.warning("checkpoint %s has no manifest (pre-manifest save); "
                       "loading unverified", ckpt_dir)
        return None
    problems: List[str] = []
    for name, meta in manifest.get("files", {}).items():
        p = os.path.join(ckpt_dir, name)
        if not os.path.exists(p):
            problems.append(f"{name}: missing")
        elif os.path.getsize(p) != meta["size"]:
            problems.append(f"{name}: size {os.path.getsize(p)} != "
                            f"{meta['size']}")
        elif _sha256(p) != meta["sha256"]:
            problems.append(f"{name}: checksum mismatch")
    for rel, size in manifest.get("payload", {}).items():
        p = os.path.join(ckpt_dir, rel)
        if not os.path.exists(p):
            problems.append(f"{rel}: missing")
        elif os.path.getsize(p) != size:
            problems.append(f"{rel}: size {os.path.getsize(p)} != {size}")
    if problems:
        raise CheckpointIntegrityError(
            f"checkpoint {ckpt_dir} failed verification: "
            + "; ".join(problems[:8])
            + (f" (+{len(problems) - 8} more)" if len(problems) > 8 else ""))
    return manifest


def read_tag_step(ckpt_dir: str) -> int:
    """Best-effort global step of a tag (manifest first, then the sidecar);
    -1 when unreadable — sorts such tags last."""
    try:
        m = read_manifest(ckpt_dir)
        if m is not None:
            return int(m.get("global_steps", -1))
    except CheckpointIntegrityError:
        return -1
    p = os.path.join(ckpt_dir, "client_state.json")
    try:
        with open(p) as f:
            return int(json.load(f).get("global_steps", -1))
    except (OSError, ValueError, json.JSONDecodeError):
        return -1


def candidate_tags(save_dir: str) -> List[str]:
    """Restore candidates newest-to-oldest: the ``latest`` pointer's tag
    first, then every other non-quarantined tag by descending step."""
    if not os.path.isdir(save_dir):
        return []
    tags = [d for d in os.listdir(save_dir)
            if os.path.isdir(os.path.join(save_dir, d))
            and CORRUPT_SUFFIX not in d]
    tags.sort(key=lambda t: (read_tag_step(os.path.join(save_dir, t)), t),
              reverse=True)
    latest_path = os.path.join(save_dir, LATEST_FILE)
    if os.path.exists(latest_path):
        with open(latest_path) as f:
            latest = f.read().strip()
        if latest in tags:
            tags.remove(latest)
            tags.insert(0, latest)
    return tags


# ------------------------------------------------------- pod-scope commit
#
# A POD checkpoint is committed only when every host of the writing
# generation has durably landed its shard: host k writes its files, then
# host_manifests/host<k>.json (listing them with sizes + sha256); the
# coordinator waits for all expected host manifests and only then publishes
# pod_manifest.json (atomic).  A pod tag without pod_manifest.json is TORN
# (some host never reported) and must never be restored from — the
# pod-aware restore walk quarantines it and falls back a generation, the
# same contract verify_checkpoint_dir enforces per host.

# path-component shapes that attribute a payload file to one process of a
# multi-host save: orbax OCDBT's `ocdbt.process_<k>`, plus `process_<k>` /
# `process<k>` variants other layouts use
_PROCESS_COMPONENT = re.compile(r"(?:^|[._-])process[._-]?(\d+)(?:$|[._-])")


def host_payload_files(ckpt_dir: str, process_index: int = 0) -> List[str]:
    """The payload files (``state/``, ``offload_optimizer/``) attributable
    to one process of a multi-host save — what that host's shard manifest
    attests so :func:`verify_pod_checkpoint_dir` can detect a MISSING shard
    file, not just a missing manifest.

    Attribution: a path component naming a process (orbax OCDBT writes
    ``ocdbt.process_<k>/``; other layouts use ``process_<k>`` or
    ``process<k>``) assigns the file to that process; every file no
    process component claims (single-process saves, shared metadata like
    ``_METADATA``/zarray sidecars) is attested by process 0, so the union
    over all processes covers the ENTIRE payload listing and any file lost
    in transit fails the pod commit/restore verification.
    """
    mine: List[str] = []
    for rel in sorted(_payload_listing(ckpt_dir)):
        owner = _path_process_owner(rel)
        if owner == int(process_index) or (owner is None
                                           and int(process_index) == 0):
            mine.append(rel)
    return mine


def write_host_manifest(ckpt_dir: str, host_id: str, generation: int,
                        global_steps: int,
                        files: Optional[List[str]] = None,
                        owner: Optional[int] = None) -> str:
    """Land one host's shard manifest: relative ``files`` (the shard files
    THIS host wrote, already durable) with size + sha256.  Fires the
    ``ckpt.shard_commit`` fault site before writing — the commit unit chaos
    tests kill to produce torn pod checkpoints.

    ``owner`` stamps the manifest with the process index whose payload
    files it attests (the same index :func:`host_payload_files` partitions
    by).  Verification then cross-checks every listed path's path-derived
    process component against the stamp: a file whose path names process
    ``k`` attested under a manifest stamped ``j != k`` fails LOUDLY at
    commit/verify time instead of silently mis-attributing (the path-based
    attribution window the ROADMAP carried)."""
    from .fault_injection import SITE_SHARD_COMMIT, maybe_fire

    maybe_fire(SITE_SHARD_COMMIT, path=ckpt_dir, host=host_id,
               generation=generation)
    listing = {}
    for rel in files or []:
        p = os.path.join(ckpt_dir, rel)
        listing[rel] = {"size": os.path.getsize(p), "sha256": _sha256(p)}
    doc = {"host_id": str(host_id), "generation": int(generation),
           "global_steps": int(global_steps), "files": listing}
    if owner is not None:
        doc["owner"] = int(owner)
    mdir = os.path.join(ckpt_dir, HOST_MANIFEST_DIR)
    os.makedirs(mdir, exist_ok=True)
    return _atomic_write_json(os.path.join(mdir, f"host{host_id}.json"), doc)


def _path_process_owner(rel: str) -> Optional[int]:
    """The process index a payload path claims (``ocdbt.process_<k>`` et
    al.), or ``None`` for unmarked paths — ONE spelling of the
    attribution, shared by partitioning and verification."""
    for comp in rel.replace(os.sep, "/").split("/"):
        m = _PROCESS_COMPONENT.search(comp)
        if m is not None:
            return int(m.group(1))
    return None


def _owner_attribution_problems(host: str, manifest: Dict) -> List[str]:
    """Cross-check a manifest's explicit ``owner`` stamp against the
    path-derived attribution of every file it attests.  Manifests without
    the stamp (pre-stamp writers, simulated-host shard files) skip the
    check — the stamp is what closes the window, not a retroactive
    requirement."""
    owner = manifest.get("owner")
    if owner is None:
        return []
    problems = []
    for rel in manifest.get("files", {}):
        claimed = _path_process_owner(rel)
        if claimed is not None and claimed != int(owner):
            problems.append(
                f"host{host}:{rel}: path names process {claimed} but the "
                f"manifest is stamped owner={int(owner)} — silent "
                "shard misattribution")
    return problems


def _atomic_write_json(path: str, doc: Dict) -> str:
    """The one atomic-JSON-commit idiom every manifest writer shares: dump
    to a tmp sibling, ``os.replace`` into place — a reader never observes a
    torn document.  The tmp name carries pid + thread id so concurrent
    writers (simulated pod hosts are threads) never collide on it."""
    import threading

    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2)
    os.replace(tmp, path)
    return path


def read_host_manifests(ckpt_dir: str, strict: bool = True) -> Dict[str, Dict]:
    """host_id -> per-host manifest currently present under the tag.
    ``strict=False`` (the commit poll loop) treats an unreadable manifest as
    not-yet-present — a peer's ``os.replace`` may be mid-visibility on
    network storage and the poller will simply see it next round; at
    verify time unreadable means corrupt and raises."""
    mdir = os.path.join(ckpt_dir, HOST_MANIFEST_DIR)
    out: Dict[str, Dict] = {}
    if not os.path.isdir(mdir):
        return out
    for name in sorted(os.listdir(mdir)):
        if not name.endswith(".json") or ".tmp." in name:
            continue
        try:
            with open(os.path.join(mdir, name)) as f:
                doc = json.load(f)
            out[str(doc["host_id"])] = doc
        except (OSError, ValueError, KeyError) as e:
            if strict:
                raise CheckpointIntegrityError(
                    f"unreadable host manifest {os.path.join(mdir, name)}: "
                    f"{e}") from e
            logger.warning("pod commit: host manifest %s unreadable (%s); "
                           "treating as not yet present", name, e)
    return out


class PodCommitTimeout(RuntimeError):
    """Not every expected host reported its shard manifest in time: the pod
    checkpoint stays UNcommitted (torn) and the round should fail so the
    supervisor can re-form.  Deliberately not a CheckpointIntegrityError —
    nothing on disk is corrupt, a writer is missing."""

    def __init__(self, msg: str, missing: List[str]):
        super().__init__(msg)
        self.missing = missing


def commit_pod_manifest(ckpt_dir: str, generation: int,
                        expected_hosts: List[str], timeout_s: float = 120.0,
                        poll_s: float = 0.25) -> str:
    """Coordinator half of the pod commit: wait until every expected host's
    manifest (of THIS generation) is present and its listed files verify,
    then atomically publish ``pod_manifest.json``.  Raises
    :class:`PodCommitTimeout` when a host never reports — the tag is left
    torn (no pod manifest) and the pod-aware restore path will quarantine
    it.  Call BEFORE the ``latest`` pointer moves."""
    expected = sorted(set(str(h) for h in expected_hosts))
    deadline = time.monotonic() + timeout_s
    while True:
        manifests = read_host_manifests(ckpt_dir, strict=False)
        present = [h for h in expected
                   if manifests.get(h, {}).get("generation") == generation]
        if len(present) == len(expected):
            break
        if time.monotonic() >= deadline:
            missing = sorted(set(expected) - set(present))
            raise PodCommitTimeout(
                f"pod commit of {ckpt_dir} (generation {generation}) timed "
                f"out after {timeout_s:.1f}s: host(s) {missing} never "
                "reported a shard manifest — the tag stays uncommitted",
                missing)
        time.sleep(poll_s)
    # verify every reported shard before declaring the pod commit: a host
    # that reported but whose file tore is a torn pod checkpoint NOW, not
    # at restore time generations later
    problems: List[str] = []
    for host in expected:
        # owner-stamp cross-check: a misattributed shard (path names one
        # process, manifest stamped another) fails the COMMIT, the same
        # discipline as a torn checksum
        problems.extend(_owner_attribution_problems(host, manifests[host]))
        for rel, meta in manifests[host].get("files", {}).items():
            p = os.path.join(ckpt_dir, rel)
            if not os.path.exists(p):
                problems.append(f"host{host}:{rel}: missing")
            elif os.path.getsize(p) != meta["size"]:
                problems.append(f"host{host}:{rel}: size mismatch")
            elif _sha256(p) != meta["sha256"]:
                # same-size in-place corruption must fail the COMMIT, not
                # surface generations later at restore when the fallback
                # may already be pruned
                problems.append(f"host{host}:{rel}: checksum mismatch")
    if problems:
        raise CheckpointIntegrityError(
            f"pod commit of {ckpt_dir} refused: " + "; ".join(problems[:8]))
    doc = {"manifest_version": MANIFEST_VERSION, "generation": int(generation),
           "hosts": expected,
           "global_steps": max((int(m.get("global_steps", -1))
                                for m in manifests.values()), default=-1)}
    # the pod commit marker must never be torn
    return _atomic_write_json(os.path.join(ckpt_dir, POD_MANIFEST_FILE), doc)


def read_pod_manifest(ckpt_dir: str) -> Optional[Dict]:
    path = os.path.join(ckpt_dir, POD_MANIFEST_FILE)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointIntegrityError(
            f"unreadable pod manifest {path}: {e}") from e


def verify_pod_checkpoint_dir(ckpt_dir: str) -> Dict:
    """Verify a tag as a POD checkpoint: the pod manifest must be present
    (else the tag is torn/uncommitted), every host it names must have a
    matching per-host manifest, and every listed shard file must exist with
    the recorded size and checksum.  Per-host engine-state verification
    (``verify_checkpoint_dir``) is separate and still runs on load."""
    pod = read_pod_manifest(ckpt_dir)
    if pod is None:
        raise CheckpointIntegrityError(
            f"checkpoint {ckpt_dir} has no {POD_MANIFEST_FILE}: the pod "
            "commit never completed (a host died before reporting its "
            "shard) — torn pod checkpoint")
    manifests = read_host_manifests(ckpt_dir)
    problems: List[str] = []
    for host in pod.get("hosts", []):
        m = manifests.get(str(host))
        if m is None:
            problems.append(f"host{host}: manifest missing")
            continue
        if int(m.get("generation", -1)) != int(pod["generation"]):
            problems.append(f"host{host}: generation "
                            f"{m.get('generation')} != {pod['generation']}")
        problems.extend(_owner_attribution_problems(host, m))
        for rel, meta in m.get("files", {}).items():
            p = os.path.join(ckpt_dir, rel)
            if not os.path.exists(p):
                problems.append(f"host{host}:{rel}: missing")
            elif os.path.getsize(p) != meta["size"]:
                problems.append(
                    f"host{host}:{rel}: size {os.path.getsize(p)} != "
                    f"{meta['size']}")
            elif _sha256(p) != meta["sha256"]:
                problems.append(f"host{host}:{rel}: checksum mismatch")
    if problems:
        raise CheckpointIntegrityError(
            f"pod checkpoint {ckpt_dir} failed verification: "
            + "; ".join(problems[:8])
            + (f" (+{len(problems) - 8} more)" if len(problems) > 8 else ""))
    return pod


def pod_committed(ckpt_dir: str) -> bool:
    return os.path.exists(os.path.join(ckpt_dir, POD_MANIFEST_FILE))


def pod_checkpoint_progress_fn(ckpt_dir: str):
    """Pod analogue of ``checkpoint_progress_fn``: the newest POD-committed
    global step (-1 while nothing is pod-committed).  Tags that are only
    host-committed (manifest.json but no pod manifest) do not count — the
    pod restore path rejects them, so counting them would refresh the
    restart budget off unreachable state."""
    def progress() -> int:
        if not os.path.isdir(ckpt_dir):
            return -1
        best = -1
        for tag in candidate_tags(ckpt_dir):
            tag_dir = os.path.join(ckpt_dir, tag)
            if not pod_committed(tag_dir):
                continue
            best = max(best, read_tag_step(tag_dir))
        return best

    return progress


def quarantine_tag(save_dir: str, tag: str) -> str:
    """Rename a failed tag to ``<tag>.corrupt`` (numbered on collision) so
    the fallback walk never re-reads it; drop a ``latest`` pointing at it."""
    src = os.path.join(save_dir, tag)
    dst = src + CORRUPT_SUFFIX
    n = 0
    while os.path.exists(dst):
        n += 1
        dst = f"{src}{CORRUPT_SUFFIX}.{n}"
    os.replace(src, dst)
    latest_path = os.path.join(save_dir, LATEST_FILE)
    if os.path.exists(latest_path):
        with open(latest_path) as f:
            if f.read().strip() == str(tag):
                os.remove(latest_path)
    logger.error("quarantined corrupt checkpoint %s -> %s", src, dst)
    return dst
