"""Checkpoint integrity: per-tag manifest written at save, verified at load.

A committed tag directory looks like::

    <tag>/
      state/                # orbax sharded pytree
      client_state.json     # engine counters + user client_state
      ds_config.json        # config snapshot
      manifest.json         # written LAST (before `latest` is published)

``manifest.json`` records the logical tree structure (leaf paths, global
shapes, dtypes), content checksums of the small JSON sidecars, a size
listing of the orbax payload, and the writer world size.  Because it is
written after every other file and *before* the ``latest`` pointer, its
presence marks the commit point: a torn save is a tag directory without a
manifest, and a bit-rotted sidecar fails its checksum.

Verification failures raise :class:`CheckpointIntegrityError`; the elastic
agent responds by quarantining the tag (rename to ``<tag>.corrupt``) and
falling back one generation (``elastic_agent.restore_if_present``).
Legacy tags without a manifest verify as "unverified" (warn, accept) so
pre-manifest checkpoints keep loading.
"""
from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, List, Optional

from ..utils.logging import logger

MANIFEST_FILE = "manifest.json"
MANIFEST_VERSION = 1
CORRUPT_SUFFIX = ".corrupt"
# the newest-committed-tag pointer (single source; orbax_engine re-exports)
LATEST_FILE = "latest"
# dropped at the start of a save, removed when the manifest lands: its
# presence distinguishes a TORN save (crash mid-write) from a LEGACY
# pre-manifest tag — both lack a manifest, only the former must be rejected
INCOMPLETE_MARKER = ".incomplete"
# small sidecars cheap enough to checksum on every save/load
_CHECKSUMMED = ("client_state.json", "ds_config.json")
# payload subtrees listed (path -> size) in the manifest
_PAYLOAD_DIRS = ("state", "offload_optimizer")


class CheckpointIntegrityError(RuntimeError):
    """A checkpoint tag failed verification (torn write, corruption, or a
    manifest/content mismatch)."""


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _tree_summary(state) -> Dict[str, Dict]:
    """Leaf path -> {shape, dtype} for the saved pytree (global shapes, so
    the summary is topology-invariant — a dp8 save verifies on tp2×dp4)."""
    import jax

    out = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        if hasattr(leaf, "shape"):
            out[jax.tree_util.keystr(path)] = {
                "shape": [int(d) for d in leaf.shape],
                "dtype": str(getattr(leaf, "dtype", "")),
            }
    return out


def _payload_listing(ckpt_dir: str) -> Dict[str, int]:
    """Relative path -> size for the payload subtrees (orbax ``state/`` and
    host-stepped ``offload_optimizer/`` files).  Catches truncated/missing
    array files without checksumming gigabytes."""
    listing = {}
    for sub in _PAYLOAD_DIRS:
        for root, _dirs, files in os.walk(os.path.join(ckpt_dir, sub)):
            for name in files:
                p = os.path.join(root, name)
                listing[os.path.relpath(p, ckpt_dir)] = os.path.getsize(p)
    return listing


def mark_incomplete(ckpt_dir: str) -> None:
    """Drop the torn-save marker; removed by :func:`write_manifest` once the
    tag commits.  Call before writing any other file of the tag."""
    with open(os.path.join(ckpt_dir, INCOMPLETE_MARKER), "w") as f:
        f.write("save in progress; a crash before manifest.json removes "
                "this tag from the restore path\n")


def build_manifest(engine, tag: str) -> Dict:
    """The save-time half that needs the live engine; file checksums and the
    payload listing are added by :func:`write_manifest` once the payload is
    durable (sync: immediately; async: in the commit finalizer)."""
    import jax

    manifest: Dict = {
        "manifest_version": MANIFEST_VERSION,
        "tag": str(tag),
        "global_steps": int(engine.global_steps),
        "writer_world_size": int(jax.process_count()),
    }
    if engine.state is not None:
        manifest["tree"] = _tree_summary(engine.state)
    return manifest


def write_manifest(ckpt_dir: str, manifest: Dict) -> str:
    """Checksum the sidecars, list the payload, write ``manifest.json``.
    Must run after every other file of the tag is durable and before the
    ``latest`` pointer moves — the manifest IS the commit marker."""
    manifest = dict(manifest)
    files = {}
    for name in _CHECKSUMMED:
        p = os.path.join(ckpt_dir, name)
        if os.path.exists(p):
            files[name] = {"sha256": _sha256(p), "size": os.path.getsize(p)}
    manifest["files"] = files
    manifest["payload"] = _payload_listing(ckpt_dir)
    path = os.path.join(ckpt_dir, MANIFEST_FILE)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=2)
    os.replace(tmp, path)   # the manifest itself must never be torn
    marker = os.path.join(ckpt_dir, INCOMPLETE_MARKER)
    if os.path.exists(marker):
        os.remove(marker)   # commit: the tag is now complete AND marked so
    return path


def read_manifest(ckpt_dir: str) -> Optional[Dict]:
    path = os.path.join(ckpt_dir, MANIFEST_FILE)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError) as e:
        # ValueError covers JSONDecodeError AND UnicodeDecodeError — a
        # bit-flipped manifest is frequently not even valid UTF-8
        raise CheckpointIntegrityError(
            f"unreadable manifest {path}: {e}") from e


def verify_checkpoint_dir(ckpt_dir: str) -> Optional[Dict]:
    """Verify a tag directory against its manifest.

    Returns the manifest (or ``None`` for legacy pre-manifest tags, which
    are accepted with a warning).  Raises :class:`CheckpointIntegrityError`
    on any mismatch: missing/short payload file, sidecar checksum drift,
    or an unreadable manifest.
    """
    if not os.path.isdir(ckpt_dir):
        raise CheckpointIntegrityError(f"checkpoint dir missing: {ckpt_dir}")
    if os.path.exists(os.path.join(ckpt_dir, INCOMPLETE_MARKER)):
        # the save died between first write and manifest commit — without
        # this marker a torn tag would be indistinguishable from a legacy
        # pre-manifest tag and sail through the `manifest is None` branch
        raise CheckpointIntegrityError(
            f"checkpoint {ckpt_dir} is a torn save ({INCOMPLETE_MARKER} "
            "present: the writer died before committing the manifest)")
    manifest = read_manifest(ckpt_dir)
    if manifest is None:
        logger.warning("checkpoint %s has no manifest (pre-manifest save); "
                       "loading unverified", ckpt_dir)
        return None
    problems: List[str] = []
    for name, meta in manifest.get("files", {}).items():
        p = os.path.join(ckpt_dir, name)
        if not os.path.exists(p):
            problems.append(f"{name}: missing")
        elif os.path.getsize(p) != meta["size"]:
            problems.append(f"{name}: size {os.path.getsize(p)} != "
                            f"{meta['size']}")
        elif _sha256(p) != meta["sha256"]:
            problems.append(f"{name}: checksum mismatch")
    for rel, size in manifest.get("payload", {}).items():
        p = os.path.join(ckpt_dir, rel)
        if not os.path.exists(p):
            problems.append(f"{rel}: missing")
        elif os.path.getsize(p) != size:
            problems.append(f"{rel}: size {os.path.getsize(p)} != {size}")
    if problems:
        raise CheckpointIntegrityError(
            f"checkpoint {ckpt_dir} failed verification: "
            + "; ".join(problems[:8])
            + (f" (+{len(problems) - 8} more)" if len(problems) > 8 else ""))
    return manifest


def read_tag_step(ckpt_dir: str) -> int:
    """Best-effort global step of a tag (manifest first, then the sidecar);
    -1 when unreadable — sorts such tags last."""
    try:
        m = read_manifest(ckpt_dir)
        if m is not None:
            return int(m.get("global_steps", -1))
    except CheckpointIntegrityError:
        return -1
    p = os.path.join(ckpt_dir, "client_state.json")
    try:
        with open(p) as f:
            return int(json.load(f).get("global_steps", -1))
    except (OSError, ValueError, json.JSONDecodeError):
        return -1


def candidate_tags(save_dir: str) -> List[str]:
    """Restore candidates newest-to-oldest: the ``latest`` pointer's tag
    first, then every other non-quarantined tag by descending step."""
    if not os.path.isdir(save_dir):
        return []
    tags = [d for d in os.listdir(save_dir)
            if os.path.isdir(os.path.join(save_dir, d))
            and CORRUPT_SUFFIX not in d]
    tags.sort(key=lambda t: (read_tag_step(os.path.join(save_dir, t)), t),
              reverse=True)
    latest_path = os.path.join(save_dir, LATEST_FILE)
    if os.path.exists(latest_path):
        with open(latest_path) as f:
            latest = f.read().strip()
        if latest in tags:
            tags.remove(latest)
            tags.insert(0, latest)
    return tags


def quarantine_tag(save_dir: str, tag: str) -> str:
    """Rename a failed tag to ``<tag>.corrupt`` (numbered on collision) so
    the fallback walk never re-reads it; drop a ``latest`` pointing at it."""
    src = os.path.join(save_dir, tag)
    dst = src + CORRUPT_SUFFIX
    n = 0
    while os.path.exists(dst):
        n += 1
        dst = f"{src}{CORRUPT_SUFFIX}.{n}"
    os.replace(src, dst)
    latest_path = os.path.join(save_dir, LATEST_FILE)
    if os.path.exists(latest_path):
        with open(latest_path) as f:
            if f.read().strip() == str(tag):
                os.remove(latest_path)
    logger.error("quarantined corrupt checkpoint %s -> %s", src, dst)
    return dst
