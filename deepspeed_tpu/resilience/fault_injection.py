"""Deterministic fault injection for the resilience test harness.

A :class:`FaultInjector` holds an ordered list of :class:`FaultRule`s.  The
production code calls :func:`maybe_fire` at a handful of **named hook
points** (sites); when no injector is installed the call is a dict lookup
and a ``None`` check — cheap enough to sit on the train step.

Sites wired into the tree:

========================  ====================================================
``ckpt.save``             entry of ``save_engine_checkpoint`` (before any file
                          is written); ``path`` = the tag directory
``ckpt.publish_latest``   immediately before the ``latest`` pointer is
                          written (sync and async commit paths)
``ckpt.load``             entry of ``load_engine_checkpoint``; ``path`` = the
                          tag directory about to be read
``train.step``            entry of ``DeepSpeedEngine.train_batch``
``supervisor.attempt``    inside ``Supervisor.run`` before each attempt
``serve.tick``            top of every ``ServingEngine.step`` scheduler tick
``serve.admit``           inside ``ServingEngine`` admission, after a queued
                          request is popped and before its prefill runs
``serve.prefill``         inside ``ServingEngine._prefill``, immediately
                          before the prefill device call (slot-attributable)
``serve.decode``          inside ``ServingEngine._decode_tick``, immediately
                          before the decode device call (fleet-wide)
``serve.replay``          inside ``ServingSupervisor`` warm restart, before
                          each in-flight request is re-submitted for replay
``pod.heartbeat``         inside ``coordination.beat`` before a host's lease
                          is renewed in the coordination store
``pod.rendezvous``        entry of ``coordination.rendezvous`` (before the
                          host registers itself for the generation)
``ckpt.shard_commit``     inside ``write_host_manifest`` before a host's
                          shard manifest lands (the pod-commit unit)
========================  ====================================================

Fault kinds: ``raise`` (raise :class:`InjectedFault`), ``delay`` (sleep
``delay_s`` — pairs with the hang watchdog), ``corrupt`` (flip bytes in
``target``, resolved against the site's ``path``), ``sigterm`` (deliver
``signum`` to this process — latched by ``PreemptionGuard`` exactly like a
real TPU preemption notice).

Rules fire deterministically: ``at_call`` counts matching invocations of the
site (1-based), ``every`` fires periodically, ``probability`` draws from the
rule's own seeded PRNG.  Each rule fires at most ``max_fires`` times.

Configuration is programmatic (:func:`install_injector`) or via the
``DS_TPU_FAULTS`` env var holding a JSON list of rule dicts, e.g.::

    DS_TPU_FAULTS='[{"site": "train.step", "kind": "sigterm", "at_call": 3},
                    {"site": "ckpt.save", "kind": "raise", "at_call": 2}]'
"""
from __future__ import annotations

import json
import os
import signal
import time
from dataclasses import dataclass, field
from random import Random
from typing import Dict, List, Optional

from ..utils.logging import logger

SITE_CKPT_SAVE = "ckpt.save"
SITE_CKPT_LOAD = "ckpt.load"
SITE_LATEST_PUBLISH = "ckpt.publish_latest"
SITE_TRAIN_STEP = "train.step"
SITE_SUPERVISOR_ATTEMPT = "supervisor.attempt"
SITE_SERVE_TICK = "serve.tick"
SITE_SERVE_ADMIT = "serve.admit"
SITE_SERVE_PREFILL = "serve.prefill"
SITE_SERVE_DECODE = "serve.decode"
SITE_SERVE_REPLAY = "serve.replay"
SITE_POD_HEARTBEAT = "pod.heartbeat"
SITE_POD_RENDEZVOUS = "pod.rendezvous"
SITE_SHARD_COMMIT = "ckpt.shard_commit"
SITE_FLEET_CHANNEL = "fleet.channel_append"
SITE_REPLICA_SEAL = "pod.replica_seal"
SITE_POD_ADOPT = "pod.adopt"

SITES = (SITE_CKPT_SAVE, SITE_CKPT_LOAD, SITE_LATEST_PUBLISH,
         SITE_TRAIN_STEP, SITE_SUPERVISOR_ATTEMPT, SITE_SERVE_TICK,
         SITE_SERVE_ADMIT, SITE_SERVE_PREFILL, SITE_SERVE_DECODE,
         SITE_SERVE_REPLAY, SITE_POD_HEARTBEAT, SITE_POD_RENDEZVOUS,
         SITE_SHARD_COMMIT, SITE_FLEET_CHANNEL, SITE_REPLICA_SEAL,
         SITE_POD_ADOPT,
         # coordination-store op sites, fired by the FaultyStore proxy
         # on every proxied op (elasticity/store_faults.py; canonical
         # SITE_STORE_* spellings live there to keep this module free of
         # an elasticity import)
         "store.get", "store.put", "store.cas", "store.delete",
         "store.compare_delete", "store.list")
KINDS = ("raise", "delay", "corrupt", "sigterm")

FAULTS_ENV = "DS_TPU_FAULTS"


class InjectedFault(RuntimeError):
    """Raised by a ``raise`` rule — distinguishable from organic failures so
    tests can assert the recovery path, not the fault itself."""


@dataclass
class FaultRule:
    site: str
    kind: str
    at_call: Optional[int] = None   # fire on the Nth matching call (1-based)
    every: Optional[int] = None     # fire on every Nth call
    probability: float = 1.0        # drawn from this rule's seeded PRNG
    max_fires: int = 1              # 0 = unlimited
    delay_s: float = 0.0            # kind == delay
    signum: int = int(signal.SIGTERM)  # kind == sigterm
    target: Optional[str] = None    # kind == corrupt: file, relative to the
                                    # site's `path` context when present
    seed: int = 0
    calls: int = field(default=0, compare=False)
    fires: int = field(default=0, compare=False)

    def __post_init__(self):
        if self.site not in SITES:
            raise ValueError(f"unknown fault site {self.site!r}; one of {SITES}")
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; one of {KINDS}")
        if self.kind == "corrupt" and not self.target:
            raise ValueError("corrupt rule needs a `target` file")
        self._rng = Random(self.seed)

    def should_fire(self) -> bool:
        self.calls += 1
        if self.max_fires and self.fires >= self.max_fires:
            return False
        if self.at_call is not None and self.calls != self.at_call:
            return False
        if self.every is not None and self.calls % self.every != 0:
            return False
        if self.probability < 1.0 and self._rng.random() >= self.probability:
            return False
        return True


def corrupt_file(path: str, seed: int = 0, nbytes: int = 16) -> None:
    """Flip ``nbytes`` bytes at deterministic offsets — a torn/bit-rotted
    write.  Zero-length or missing files are truncated-torn already."""
    size = os.path.getsize(path)
    if size == 0:
        return
    rng = Random(seed)
    with open(path, "r+b") as f:
        for _ in range(min(nbytes, size)):
            off = rng.randrange(size)
            f.seek(off)
            b = f.read(1)
            f.seek(off)
            f.write(bytes([b[0] ^ 0xFF]))


class FaultInjector:
    """Ordered rule set + per-site dispatch.  Deterministic given the rule
    seeds and the (deterministic) sequence of site calls."""

    def __init__(self, rules: Optional[List[FaultRule]] = None):
        self.rules: List[FaultRule] = list(rules or [])
        self.log: List[Dict] = []   # (site, kind, call#) of every fired rule

    @classmethod
    def from_specs(cls, specs: List[Dict]) -> "FaultInjector":
        return cls([FaultRule(**spec) for spec in specs])

    def add(self, **spec) -> FaultRule:
        rule = FaultRule(**spec)
        self.rules.append(rule)
        return rule

    def fire(self, site: str, path: Optional[str] = None, **ctx) -> None:
        for rule in self.rules:
            if rule.site != site or not rule.should_fire():
                continue
            rule.fires += 1
            self.log.append({"site": site, "kind": rule.kind,
                             "call": rule.calls, **ctx})
            logger.warning("fault injection: %s at %s (call %d) ctx=%s",
                           rule.kind, site, rule.calls, ctx)
            if rule.kind == "raise":
                raise InjectedFault(f"injected fault at {site} "
                                    f"(call {rule.calls})")
            if rule.kind == "delay":
                time.sleep(rule.delay_s)
            elif rule.kind == "sigterm":
                os.kill(os.getpid(), rule.signum)
            elif rule.kind == "corrupt":
                tgt = (os.path.join(path, rule.target)
                       if path and not os.path.isabs(rule.target)
                       else rule.target)
                if os.path.exists(tgt):
                    corrupt_file(tgt, seed=rule.seed)
                else:
                    logger.warning("fault injection: corrupt target %s "
                                   "missing; skipped", tgt)


# ---------------------------------------------------------------- global hook
_ACTIVE: Optional[FaultInjector] = None
_ENV_CHECKED = False


def install_injector(injector: FaultInjector) -> FaultInjector:
    global _ACTIVE
    _ACTIVE = injector
    return injector


def clear_injector() -> None:
    global _ACTIVE, _ENV_CHECKED
    _ACTIVE = None
    _ENV_CHECKED = False


def get_injector() -> Optional[FaultInjector]:
    """The installed injector, lazily configured from ``DS_TPU_FAULTS``."""
    global _ACTIVE, _ENV_CHECKED
    if _ACTIVE is None and not _ENV_CHECKED:
        _ENV_CHECKED = True
        spec = os.environ.get(FAULTS_ENV)
        if spec:
            try:
                _ACTIVE = FaultInjector.from_specs(json.loads(spec))
                logger.warning("fault injection: %d rule(s) loaded from $%s",
                               len(_ACTIVE.rules), FAULTS_ENV)
            except (json.JSONDecodeError, TypeError, ValueError) as e:
                raise ValueError(f"bad ${FAULTS_ENV}: {e}") from e
    return _ACTIVE


def maybe_fire(site: str, path: Optional[str] = None, **ctx) -> None:
    """Production-side hook: no-op unless an injector is installed."""
    inj = get_injector()
    if inj is not None:
        inj.fire(site, path=path, **ctx)
