"""Resilience subsystem: make the restart loop trustworthy end to end.

Four pieces (see docs/RESILIENCE.md for the failure model):

- ``fault_injection``: config/env-driven :class:`FaultInjector` with named
  hook points in the checkpoint, train-step and supervisor paths, so every
  recovery path is exercised by deterministic tests rather than hope;
- ``integrity``: per-tag ``manifest.json`` written at save, verified at load;
  corrupt generations are quarantined (``<tag>.corrupt``) and the elastic
  agent falls back to the previous committed generation;
- ``watchdog``: :class:`HangWatchdog` armed around ``train_batch`` and
  async-checkpoint finalization — a hang becomes a stack report plus a
  nonzero exit the supervisor can recycle;
- supervisor hardening lives in ``elasticity/supervisor.py`` (jittered
  exponential backoff, progress-aware restart budget, circuit breaker);
  :func:`checkpoint_progress_fn` here supplies the progress signal.
"""
from .fault_injection import (  # noqa: F401
    FaultInjector,
    FaultRule,
    InjectedFault,
    SITE_CKPT_LOAD,
    SITE_CKPT_SAVE,
    SITE_LATEST_PUBLISH,
    SITE_POD_HEARTBEAT,
    SITE_POD_RENDEZVOUS,
    SITE_SERVE_ADMIT,
    SITE_SERVE_DECODE,
    SITE_SERVE_PREFILL,
    SITE_SERVE_REPLAY,
    SITE_SERVE_TICK,
    SITE_SHARD_COMMIT,
    SITE_SUPERVISOR_ATTEMPT,
    SITE_TRAIN_STEP,
    clear_injector,
    get_injector,
    install_injector,
    maybe_fire,
)
from .integrity import (  # noqa: F401
    CheckpointIntegrityError,
    MANIFEST_FILE,
    POD_MANIFEST_FILE,
    PodCommitTimeout,
    build_manifest,
    candidate_tags,
    commit_pod_manifest,
    host_payload_files,
    pod_checkpoint_progress_fn,
    pod_committed,
    quarantine_tag,
    read_host_manifests,
    read_pod_manifest,
    verify_checkpoint_dir,
    verify_pod_checkpoint_dir,
    write_host_manifest,
    write_manifest,
)
from .watchdog import HangWatchdog  # noqa: F401


def checkpoint_progress_fn(ckpt_dir: str):
    """Progress signal for the supervisor's restart budget: the newest
    committed global step under ``ckpt_dir`` (-1 while nothing is
    committed).  A restart that advanced this number made real progress and
    refreshes the budget; K restarts that did not trip the breaker."""
    import os

    from .integrity import MANIFEST_FILE, candidate_tags, read_tag_step

    def progress() -> int:
        if not os.path.isdir(ckpt_dir):
            return -1
        best = -1
        for tag in candidate_tags(ckpt_dir):
            tag_dir = os.path.join(ckpt_dir, tag)
            # only manifest-bearing (committed) tags count — the same
            # filter as ElasticAgent._prune_generations.  A torn save has
            # no manifest, but read_tag_step would still surface its step
            # through the client_state.json fallback, and counting it
            # would refresh the restart budget off a tag the restore path
            # rejects — defeating the circuit breaker on exactly the
            # crash loops it exists to diagnose.
            if not os.path.exists(os.path.join(tag_dir, MANIFEST_FILE)):
                continue
            best = max(best, read_tag_step(tag_dir))
        return best

    return progress
