"""``ds_report`` equivalent (reference ``deepspeed/env_report.py``).

Prints the software stack, device inventory, and op/kernel availability so a
bug report carries the whole environment.  Run as
``python -m deepspeed_tpu.env_report`` (add ``--hide_operator_status`` /
``--hide_errors_and_warnings`` for terser output, flag parity with the
reference CLI).
"""
from __future__ import annotations

import argparse
import importlib
import sys

GREEN_OK = "\033[92m[OKAY]\033[0m"
RED_NO = "\033[93m[NO]\033[0m"


def _ver(mod: str) -> str:
    try:
        m = importlib.import_module(mod)
        return getattr(m, "__version__", "unknown")
    except Exception:
        return "not installed"


def op_status():
    """kernel/op availability: (name, importable, functional)."""
    rows = []

    def probe(name, fn):
        try:
            fn()
            rows.append((name, True, True))
        except ImportError:
            rows.append((name, False, False))
        except Exception:
            rows.append((name, True, False))

    probe("pallas.flash_attention",
          lambda: importlib.import_module(
              "deepspeed_tpu.ops.pallas.flash_attention"))
    probe("ring_attention",
          lambda: importlib.import_module("deepspeed_tpu.ops.ring_attention"))
    probe("quantizer (int8/int4 collectives)",
          lambda: importlib.import_module("deepspeed_tpu.ops.quantizer"))
    try:
        from deepspeed_tpu.ops.op_builder import ALL_OPS

        for name, builder in ALL_OPS.items():
            b = builder()
            rows.append((f"native.{name}", b.is_compatible(), b.is_built()))
    except ImportError:
        pass
    return rows


def devices_report(timeout_s: float = 60.0):
    """Device inventory; a report tool must DEGRADE, not hang, when the
    device backend is unreachable (remote/tunneled backends can block
    jax.devices() indefinitely), so the probe runs under a timeout."""
    import jax

    from .utils.debug import probe_device_count

    n, err = probe_device_count(timeout_s)
    if n is None and err is None:
        return [f"device probe timed out after {timeout_s:.0f}s — backend "
                "unreachable (tunnel/libtpu down?); host report above is "
                "still valid"]
    if err is not None:
        return [f"device probe failed: {err}"]
    devs = jax.devices()   # backend proven responsive; returns immediately
    lines = []
    lines.append(f"platform ............. {devs[0].platform}")
    lines.append(f"local devices ........ {jax.local_device_count()}")
    lines.append(f"global devices ....... {jax.device_count()}")
    lines.append(f"process index ........ {jax.process_index()}/{jax.process_count()}")
    for d in devs[:8]:
        kind = getattr(d, "device_kind", "?")
        lines.append(f"  [{d.id}] {kind}")
    if len(devs) > 8:
        lines.append(f"  ... and {len(devs) - 8} more")
    return lines


def main(args=None) -> int:
    ap = argparse.ArgumentParser(prog="ds_report")
    ap.add_argument("--hide_operator_status", action="store_true")
    ap.add_argument("--hide_errors_and_warnings", action="store_true")
    opts = ap.parse_args(args)

    import deepspeed_tpu

    print("-" * 66)
    print("DeepSpeed-TPU C++/Pallas op report")
    print("-" * 66)
    if not opts.hide_operator_status:
        print(f"{'op name':<40}{'compatible':<14}{'built/functional'}")
        print("-" * 66)
        for name, compat, built in op_status():
            print(f"{name:<40}"
                  f"{GREEN_OK if compat else RED_NO:<23}"
                  f"{GREEN_OK if built else RED_NO}")
    print("-" * 66)
    print("General environment:")
    print(f"deepspeed_tpu ........ {deepspeed_tpu.__version__} "
          f"({deepspeed_tpu.__path__[0]})")
    for mod in ("jax", "jaxlib", "flax", "optax", "orbax.checkpoint", "numpy"):
        print(f"{mod:<21}{_ver(mod)}")
    print(f"python ............... {sys.version.split()[0]}")
    print("-" * 66)
    print("Device inventory:")
    for line in devices_report():
        print(line)
    print("-" * 66)
    return 0


if __name__ == "__main__":
    sys.exit(main())
