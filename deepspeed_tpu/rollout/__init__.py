"""Hybrid rollout subsystem: RLHF-shaped generation through the paged
serving engine over LIVE training weights (docs/HYBRID.md).

The reference's third engine is ``DeepSpeedHybridEngine`` (training and
inference sharing one weight set for DeepSpeed-Chat actors).  This package
is the TPU-native production form of that workload: a
:class:`~.engine.RolloutEngine` serves batched, sampled rollouts through
the continuous-batching :class:`~..inference.serving.ServingEngine` —
paged KV pool, per-slot RNG lanes, zero-recompile admission, warm-restart
supervision — reading the training engine's live compute-precision params
between train steps, with the **weight epoch** contract guaranteeing a
post-update prefix lookup can never serve pre-update K/V.
"""
from .engine import RolloutEngine, RolloutRound

__all__ = ["RolloutEngine", "RolloutRound"]
