"""RolloutEngine: the RLHF actor loop over the paged serving engine.

Parity target: the reference's ``DeepSpeedHybridEngine`` actor loop
(``runtime/hybrid_engine.py:32`` — DeepSpeed-Chat's generate→score→train
cycle over one shared weight set).  The seed
:class:`~..runtime.hybrid_engine.DeepSpeedHybridEngine` already hands the
live training view to sequential ``InferenceEngine.generate()``; this
module routes the same weights through the **continuous-batching serving
stack** instead — slot-based decode over the paged KV pool, per-slot RNG
lanes, prefix caching, warm-restart supervision — so rollout generation
gets the same throughput, resilience and observability machinery
production serving has (docs/SERVING.md), while training keeps owning the
weights.

The three contracts (docs/HYBRID.md):

- **zero-recompile weight handoff** — serving programs take params as
  arguments, so publishing a train step's weights is
  :meth:`~..inference.serving.ServingEngine.update_params`: the live tree
  is resharded through the shared ``place_params``/``auto_tp_specs`` path
  and committed to the exact shardings the programs compiled against —
  a cache hit, never a recompile.  The LoRA fuse-once-per-flip cache from
  the seed hybrid engine is kept: :meth:`publish_weights` reads
  ``DeepSpeedHybridEngine._generation_params()``, which re-fuses
  base + A@B·scale only when ``global_steps`` moved.
- **weight epochs** — a param update makes every cached K/V page, prefix
  index entry and demoted host-tier slab stale; ``update_params`` flushes
  them (ledger-balanced) and stamps everything with the new epoch, so a
  post-update prefix lookup can never serve pre-update K/V.
- **round resilience** — rollouts run under
  :class:`~..inference.serving_supervisor.ServingSupervisor`: a kill
  mid-rollout warm-restarts with the adopted program inventory and
  replays token-exactly under the same RNG lane AND the same weight epoch
  (the factory rebuilds from the published params; the supervisor's epoch
  carry covers every other path).  The round loop itself is resumable, so
  it rides an :class:`~..elasticity.Supervisor` (or the pod tier's
  ``PodSupervisor`` rounds) for train-side kills —
  ``tools/chaos_soak.py --mode hybrid`` is the seeded proof.

Typical actor loop::

    rollout = RolloutEngine(train_engine, b_slots=8, max_model_len=512)
    for r in range(rounds):
        round = rollout.run_round(
            prompts, train_batches=ppo_batches(r),
            max_new_tokens=128, sampling=SamplingParams(temperature=0.8,
                                                        seed=r))
        ppo_batches = score(round.results, round.train_batch)
"""
from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Sequence, Union

import numpy as np

from ..inference.sampling import SamplingParams
from ..inference.serving import Request, RequestResult, ServingEngine
from ..inference.serving_supervisor import ServingSupervisor
from ..observability.trace import trace_span, trace_tags
from ..utils.logging import log_dist

__all__ = ["RolloutEngine", "RolloutRound"]

Prompts = Union[np.ndarray, Sequence[np.ndarray]]
Sampling = Union[None, SamplingParams, Sequence[Optional[SamplingParams]]]


@dataclasses.dataclass
class RolloutRound:
    """One completed actor round: train K steps → publish the weight epoch
    → collect rollouts → hand back a training batch."""
    round: int                       # 1-based round index
    weight_epoch: int                # epoch the rollouts decoded under
    losses: List[float]              # per-train-step losses (K entries)
    results: List[RequestResult]     # rollouts, completion order
    train_batch: Optional[Dict[str, np.ndarray]]  # {"input_ids": [B, S]}
    rollout_tokens: int              # tokens generated this round
    rollout_s: float                 # wall time of the collect phase
    refresh_s: float                 # update_params wall time
    flushed_pages: int               # stale HBM pages flushed by the flip
    flushed_slabs: int               # stale host-tier slabs flushed


class RolloutEngine:
    """Batched, sampled rollouts through the paged serving engine over the
    live training weights.

    ``engine`` is a training :class:`~..runtime.engine.DeepSpeedEngine`
    (or an existing
    :class:`~..runtime.hybrid_engine.DeepSpeedHybridEngine` wrapping one —
    its LoRA fuse cache and sequential ``generate()`` are reused as-is).
    Remaining kwargs configure the underlying
    :class:`~..inference.serving.ServingEngine` (``b_slots``,
    ``page_size``, ``max_model_len``, ``host_tier_pages``, ...); the mesh
    defaults to the training engine's, so on a pod the rollout programs
    span the same devices training does.
    """

    def __init__(self, engine, model: Any = None, monitor=None,
                 max_restarts: int = 5, rollout_seq_len: Optional[int] = None,
                 pad_token_id: int = 0, **serving_kwargs):
        from ..runtime.hybrid_engine import DeepSpeedHybridEngine

        self.hybrid = (engine if isinstance(engine, DeepSpeedHybridEngine)
                       else DeepSpeedHybridEngine(engine, model=model))
        self.engine = self.hybrid.engine
        self.model = self.hybrid._gen_model
        if not hasattr(self.model, "apply_paged"):
            raise ValueError(
                "RolloutEngine needs a model with the paged decode "
                "contract (apply_paged) — see models.CausalLM")
        self.monitor = monitor
        self.rollout_seq_len = (int(rollout_seq_len)
                                if rollout_seq_len is not None else None)
        self.pad_token_id = int(pad_token_id)
        self._serving_kwargs = dict(serving_kwargs)
        self._serving_kwargs.setdefault("mesh", self.engine.mesh)
        self._serving_kwargs.setdefault("monitor", monitor)
        # the weight view rollouts decode under: pinned at the last
        # publish_weights() so a warm-restart replacement mid-rollout
        # rebuilds at the SAME epoch even if someone trained in between
        # (params are immutable jax arrays — pinning is one reference)
        self._published_params = None
        self._rid_seq = 0
        self.rounds_completed = 0
        self.rollout_tokens = 0
        self._round_tok_s: Deque[float] = deque(maxlen=256)
        self._sup = ServingSupervisor(self._build_serving,
                                      max_restarts=max_restarts,
                                      monitor=monitor)
        self._published_params = self._sup.engine.params
        log_dist(
            f"rollout engine ready: b_slots={self._sup.engine.b_slots} "
            f"weight_epoch={self.weight_epoch} "
            f"(serving the live training view)", ranks=[0])

    # ------------------------------------------------------------ plumbing

    @property
    def supervisor(self) -> ServingSupervisor:
        return self._sup

    @property
    def serving(self) -> ServingEngine:
        """The live serving incarnation (replaced by warm restarts)."""
        return self._sup.engine

    @property
    def weight_epoch(self) -> int:
        return self._sup.engine.weight_epoch

    def _build_serving(self) -> ServingEngine:
        """ServingSupervisor factory: a fresh engine over the PUBLISHED
        weight view at the published epoch — a mid-rollout warm restart
        replays under the exact weights the interrupted streams started
        with (docs/HYBRID.md)."""
        params = self._published_params
        epoch = 0
        if params is None:           # first build (supervisor ctor)
            params = self.hybrid._generation_params()
        else:
            epoch = self._sup.engine.weight_epoch
        eng = ServingEngine(self.model, params, **self._serving_kwargs)
        if epoch:
            eng.weight_epoch = epoch
        return eng

    # ------------------------------------------------------------- publish

    def publish_weights(self) -> Dict[str, Any]:
        """Flip the serving side to the CURRENT training weights: one
        zero-recompile param swap + the weight-epoch flush
        (:meth:`~..inference.serving.ServingEngine.update_params`).  LoRA
        actors fuse base + adapters once per flip via the hybrid engine's
        cache — repeated publishes without a train step reuse the fused
        tree.  Returns the update stats (epoch, flushed pages/slabs,
        refresh wall time)."""
        params = self.hybrid._generation_params()
        with trace_span("rollout.publish", epoch=self.weight_epoch + 1):
            stats = self._sup.engine.update_params(params)
        self._published_params = self._sup.engine.params
        if self.monitor is not None:
            self.monitor.write_events([
                ("rollout/weight_epoch", float(stats["weight_epoch"]), 0),
                ("rollout/refresh_s", stats["refresh_s"], 0),
                ("rollout/flushed_pages_total",
                 float(self._sup.health()["kv_flushed_pages_total"]), 0),
            ])
        return stats

    def publish_weights_fleet(self, router, max_ticks: int = 500,
                              on_tick=None) -> int:
        """Fleet-capable publish (docs/FLEET.md "Weight-epoch barrier";
        closes the docs/HYBRID.md single-engine limitation): flip EVERY
        member of ``router``'s fleet to the current training weights
        through the store-mediated two-phase barrier — the router holds
        admission while members drain and prepare, then commits, so no
        rollout request is ever admitted against stale weights on any
        member.  Store-proxied member daemons re-derive their weight
        material from their own ``params_provider`` (the epoch number is
        what crosses the store).  Returns the committed fleet epoch."""
        params = self.hybrid._generation_params()
        target = max(self.weight_epoch, router.fleet_epoch) + 1
        with trace_span("rollout.publish", epoch=target):
            epoch = router.flip_weight_epoch(params, epoch=target,
                                             max_ticks=max_ticks,
                                             on_tick=on_tick)
        self._published_params = params
        if self.monitor is not None:
            self.monitor.write_events([
                ("rollout/weight_epoch", float(epoch), 0),
                ("rollout/refresh_s", 0.0, 0),
            ])
        return epoch

    # ------------------------------------------------------------- rollout

    def _normalize_prompts(self, prompts: Prompts) -> List[np.ndarray]:
        if isinstance(prompts, np.ndarray) and prompts.ndim == 2:
            return [np.asarray(row, np.int32) for row in prompts]
        return [np.asarray(p, np.int32).reshape(-1) for p in prompts]

    @staticmethod
    def _normalize_sampling(sampling: Sampling,
                            n: int) -> List[Optional[SamplingParams]]:
        if sampling is None or isinstance(sampling, SamplingParams):
            return [sampling] * n
        lanes = list(sampling)
        if len(lanes) != n:
            raise ValueError(
                f"sampling: got {len(lanes)} SamplingParams for {n} "
                "prompt(s) (pass one, one per prompt, or None)")
        return lanes

    def rollout(self, prompts: Prompts, max_new_tokens: int = 32,
                sampling: Sampling = None,
                eos_token_id: Optional[int] = None,
                max_ticks: Optional[int] = None) -> List[RequestResult]:
        """Serve one prompt batch through the supervised serving engine at
        the current weight epoch; returns per-request results in
        completion order (``rid`` is ``(batch_seq, prompt_index)``).
        Per-prompt ``sampling`` lanes ride the serving engine's traced
        per-slot RNG lanes, so the output is token-identical to
        ``hybrid.generate(prompt, sampling=lane)`` on the same weights —
        and a mid-rollout warm restart replays token-exactly under the
        same lane and epoch (docs/HYBRID.md)."""
        rows = self._normalize_prompts(prompts)
        lanes = self._normalize_sampling(sampling, len(rows))
        self._rid_seq += 1
        reqs = [Request(rid=(self._rid_seq, i), input_ids=ids,
                        max_new_tokens=int(max_new_tokens),
                        eos_token_id=eos_token_id, sampling=lanes[i])
                for i, ids in enumerate(rows)]
        t0 = time.monotonic()
        # ambient rollout tag (docs/OBSERVABILITY.md "Distributed
        # tracing"): every serving span this batch opens — admissions,
        # ticks, replays after a mid-rollout kill — carries the rollout
        # sequence id, so one round is one filterable unit in Perfetto
        with trace_span("rollout.collect", n=len(reqs),
                        epoch=self.weight_epoch), \
                trace_tags(rollout_seq=self._rid_seq):
            results = self._sup.run(reqs, max_ticks=max_ticks)
        dt = max(time.monotonic() - t0, 1e-9)
        tokens = sum(len(r.output_ids) for r in results)
        self.rollout_tokens += tokens
        self._round_tok_s.append(tokens / dt)
        if self.monitor is not None:
            self.monitor.write_events([
                ("rollout/tokens_total", float(self.rollout_tokens), 0),
                ("rollout/tokens_per_sec", tokens / dt, 0),
            ])
        return results

    # --------------------------------------------------------- round loop

    def run_round(self, prompts: Prompts, train_batches: Sequence = (),
                  max_new_tokens: int = 32, sampling: Sampling = None,
                  eos_token_id: Optional[int] = None,
                  max_ticks: Optional[int] = None,
                  build_train_batch: bool = True) -> RolloutRound:
        """One actor round: train K steps on ``train_batches`` → publish
        the new weight epoch → admit the prompt batch with its sampling
        lanes → collect rollouts → hand back a fixed-shape training batch
        (``{"input_ids": [B, S]}``, prompt + rollout right-padded) the
        caller scores and feeds into the next round's ``train_batches``.

        The loop is restart-friendly by construction: each phase is
        idempotent from the outside (a supervisor retrying a raised round
        re-runs only the phase that failed — ``train_batch`` mutates state
        only on success, ``publish_weights`` is a pure flip, and the
        serving supervisor already replays interrupted rollouts
        internally)."""
        idx = self.rounds_completed + 1
        with trace_span("rollout.round", round=idx):
            losses: List[float] = []
            if train_batches:
                with trace_span("rollout.train", steps=len(train_batches)):
                    for b in train_batches:
                        losses.append(float(self.hybrid.train_batch(batch=b)))
            pub = self.publish_weights()
            t0 = time.monotonic()
            results = self.rollout(prompts, max_new_tokens=max_new_tokens,
                                   sampling=sampling,
                                   eos_token_id=eos_token_id,
                                   max_ticks=max_ticks)
            rollout_s = time.monotonic() - t0
            batch = (self.training_batch(results)
                     if build_train_batch else None)
        self.rounds_completed += 1
        if self.monitor is not None:
            self.monitor.write_events([
                ("rollout/rounds_total", float(self.rounds_completed), 0)])
        return RolloutRound(
            round=idx, weight_epoch=pub["weight_epoch"], losses=losses,
            results=results, train_batch=batch,
            rollout_tokens=sum(len(r.output_ids) for r in results),
            rollout_s=rollout_s, refresh_s=pub["refresh_s"],
            flushed_pages=pub["flushed_hbm_pages"],
            flushed_slabs=pub["flushed_host_slabs"])

    def training_batch(self, results: Sequence[RequestResult],
                       seq_len: Optional[int] = None
                       ) -> Dict[str, np.ndarray]:
        """Assemble rollouts into one fixed-shape training batch:
        ``input_ids [B, S]`` int32, row ``i`` = prompt ``i`` + its
        generated tokens, right-padded with ``pad_token_id`` (truncated at
        ``S``).  ``S`` defaults to ``rollout_seq_len`` (ctor) or the
        longest row — pin ``rollout_seq_len`` in production so the train
        step never sees a new shape across rounds."""
        rows = sorted(results, key=lambda r: r.rid)
        seqs = [np.concatenate([np.asarray(r.input_ids, np.int32),
                                np.asarray(r.output_ids, np.int32)])
                for r in rows]
        S = int(seq_len or self.rollout_seq_len
                or max(len(s) for s in seqs))
        batch = np.full((len(seqs), S), self.pad_token_id, np.int32)
        for i, s in enumerate(seqs):
            batch[i, :min(len(s), S)] = s[:S]
        return {"input_ids": batch}

    # ------------------------------------------------------------- health

    def health(self) -> Dict[str, Any]:
        """Serving health (through the supervisor, cumulative across warm
        restarts) plus the rollout-loop counters."""
        h = self._sup.health()
        lat = sorted(self.serving.refresh_latencies())
        h["rollout_rounds_total"] = self.rounds_completed
        h["rollout_tokens_total"] = self.rollout_tokens
        h["rollout_tokens_per_sec_last"] = (round(self._round_tok_s[-1], 2)
                                            if self._round_tok_s else 0.0)
        h["rollout_refresh_p50_s"] = (lat[len(lat) // 2] if lat else 0.0)
        return h

    def drain(self, max_ticks: Optional[int] = None) -> List[Request]:
        """Hand back any unserved rollout requests (see
        :meth:`~..inference.serving_supervisor.ServingSupervisor.drain`)."""
        return self._sup.drain(max_ticks=max_ticks)
