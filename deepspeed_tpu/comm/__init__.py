from .comm import *  # noqa: F401,F403 - torch.distributed-shaped facade
from .comm import (init_distributed, is_initialized, get_rank, get_world_size,
                   get_local_rank, barrier, broadcast_object, all_reduce, all_gather,
                   reduce_scatter, all_to_all, ppermute, axis_index, get_axis_size,
                   ReduceOp, configure, log_summary)
