"""Collective micro-benchmarks (reference ``benchmarks/communication/run_all.py``).

Times each collective as its own jitted shard_map program over the active
mesh's devices and reports latency, algorithm bandwidth, and bus bandwidth.
Bus-bandwidth factors follow the standard ring-algorithm accounting (the same
convention the reference's busbw column uses, communication/utils.py):

  all_reduce      busbw = algbw * 2(n-1)/n
  all_gather      busbw = algbw *  (n-1)/n
  reduce_scatter  busbw = algbw *  (n-1)/n
  all_to_all      busbw = algbw *  (n-1)/n
  broadcast       busbw = algbw *  (n-1)/n   (modeled by its ring equivalent:
                                              every rank must END with the full
                                              payload, which moves the same
                                              (n-1)/n * S per link as all_gather)

where algbw = payload_bytes / time.  Payload is the GLOBAL tensor size, so
numbers are comparable with the reference's tables.

Run: ``python -m deepspeed_tpu.comm.benchmark [--op all] [--maxsize 27]``
(sizes are powers of two in bytes, 2^15..2^maxsize). Works on the real chip
pool or the virtual CPU mesh alike.
"""
from __future__ import annotations

import argparse
import time
from typing import Callable, Dict

BUSBW_FACTOR: Dict[str, Callable[[int], float]] = {
    "all_reduce": lambda n: 2 * (n - 1) / n,
    "all_gather": lambda n: (n - 1) / n,
    "reduce_scatter": lambda n: (n - 1) / n,
    "all_to_all": lambda n: (n - 1) / n,
    "broadcast": lambda n: (n - 1) / n,
    "ppermute": lambda n: 1.0,
}


def _mesh_and_axis():
    import jax
    from jax.sharding import Mesh
    import numpy as np

    devs = jax.devices()
    return Mesh(np.array(devs), ("x",)), "x", len(devs)


def _programs(axis):
    import jax
    from jax import lax

    return {
        "all_reduce": lambda x: lax.psum(x, axis),
        "all_gather": lambda x: lax.all_gather(x, axis, tiled=True),
        "reduce_scatter": lambda x: lax.psum_scatter(x, axis, tiled=True),
        "all_to_all": lambda x: lax.all_to_all(
            x.reshape(jax.device_count(), -1), axis, 0, 0, tiled=True),
        # ring-equivalent broadcast: every rank ends holding the full payload
        "broadcast": lambda x: lax.all_gather(x, axis, tiled=True),
        "ppermute": lambda x: lax.ppermute(
            x, axis, [(i, (i + 1) % jax.device_count())
                      for i in range(jax.device_count())]),
    }


def run_op(op: str, global_bytes: int, trials: int = 20, warmups: int = 3,
           dtype=None):
    """Time one collective at one size; returns a result dict."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    mesh, axis, n = _mesh_and_axis()
    dtype = dtype or jnp.float32
    elem = jnp.dtype(dtype).itemsize
    # round the per-device count up to a multiple of n so all_to_all's
    # n-way re-split is always exact
    per_dev = max(global_bytes // (n * elem), 1)
    per_dev = -(-per_dev // n) * n
    body = _programs(axis)[op]
    specs = dict(mesh=mesh, in_specs=P("x"),
                 out_specs=P("x") if op != "broadcast" else P())
    if op == "broadcast":
        # tiled all_gather output IS replicated, but shard_map's varying-axes
        # check can't see through it; the flag is check_vma on jax>=0.8,
        # check_rep before
        try:
            fn = jax.jit(shard_map(body, check_vma=False, **specs))
        except TypeError:
            fn = jax.jit(shard_map(body, check_rep=False, **specs))
    else:
        fn = jax.jit(shard_map(body, **specs))
    x = jax.device_put(
        jnp.ones((n * per_dev,), dtype),
        NamedSharding(mesh, P("x")))
    out = x
    for _ in range(warmups):
        out = fn(x)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(trials):
        out = fn(x)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / trials
    payload = n * per_dev * elem
    algbw = payload / dt
    return {
        "op": op, "size_bytes": payload, "n_devices": n,
        "latency_us": dt * 1e6, "algbw_gbps": algbw / 1e9,
        "busbw_gbps": algbw * BUSBW_FACTOR[op](n) / 1e9,
    }


def run_sweep(ops=None, min_pow: int = 15, max_pow: int = 27, trials: int = 20,
              print_table: bool = True):
    ops = ops or list(BUSBW_FACTOR)
    rows = []
    for op in ops:
        for p in range(min_pow, max_pow + 1, 3):
            rows.append(run_op(op, 1 << p, trials=trials))
    if print_table:
        hdr = (f"{'op':<16}{'size':>12}{'lat(us)':>12}{'algbw GB/s':>12}"
               f"{'busbw GB/s':>12}")
        print(hdr)
        print("-" * len(hdr))
        for r in rows:
            print(f"{r['op']:<16}{r['size_bytes']:>12}{r['latency_us']:>12.1f}"
                  f"{r['algbw_gbps']:>12.2f}{r['busbw_gbps']:>12.2f}")
    return rows


def main(argv=None):
    ap = argparse.ArgumentParser(prog="deepspeed_tpu.comm.benchmark")
    ap.add_argument("--op", default="all",
                    choices=["all"] + list(BUSBW_FACTOR))
    ap.add_argument("--minsize", type=int, default=15, help="log2 min bytes")
    ap.add_argument("--maxsize", type=int, default=27, help="log2 max bytes")
    ap.add_argument("--trials", type=int, default=20)
    args = ap.parse_args(argv)
    ops = list(BUSBW_FACTOR) if args.op == "all" else [args.op]
    run_sweep(ops, args.minsize, args.maxsize, args.trials)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
