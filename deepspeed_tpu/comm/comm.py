"""``deepspeed_tpu.comm`` — the comm facade (reference ``comm/comm.py``).

The reference exposes a torch.distributed-shaped API (broadcast / all_gather /
reduce_scatter_tensor / all_to_all_single / barrier / init_distributed,
comm.py:214-497,578) over a pluggable Backend.  The TPU-native split:

- **Traced data plane** — functions here named after the reference ops that,
  when called inside a jit/shard_map region, emit XLA collectives on a mesh
  axis (the analogue of a process group).  This is the hot path: ZeRO
  reduce-scatter/all-gather, MoE all-to-all, pipeline ppermute all ride ICI.
- **Eager control plane** — ``init_distributed`` (jax.distributed rendezvous,
  the analogue of init_process_group + MPI/env discovery, comm.py:578-745),
  ``barrier``, and host-object broadcast via multihost utils.

Every data-plane op is wrapped by :func:`timed_op` feeding the comms logger
(reference ``@timed_op`` comm.py:100-133).  Under XLA, per-op wall timing at
call-site is meaningless (ops are compiled and scheduled by XLA), so the
logger records message sizes/op counts at trace time and defers latency to the
profiler — an honest TPU translation of the busbw log.
"""
from __future__ import annotations

import functools
import os
from typing import Any, Optional, Sequence, Union

import numpy as np

from .backend import XLABackend, AxisName
from ..parallel.mesh import BATCH_AXES
from ..utils.logging import logger, log_dist

class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    AVG = "avg"
    PRODUCT = "prod"


# module-level aliases of the canonical ReduceOp vocabulary
SUM = ReduceOp.SUM
MAX = ReduceOp.MAX
MIN = ReduceOp.MIN
AVG = ReduceOp.AVG

_backend = XLABackend()
_comms_logger = None  # lazily attached by configure()


def configure(comms_config=None) -> None:
    """Attach the comms logger (reference comm.py dist.configure)."""
    global _comms_logger
    if comms_config is not None and getattr(comms_config, "enabled", False):
        from ..utils.comms_logging import CommsLogger

        _comms_logger = CommsLogger(comms_config)


def get_comms_logger():
    return _comms_logger


def _nbytes(tree: Any) -> int:
    import jax

    leaves = jax.tree_util.tree_leaves(tree)
    total = 0
    for leaf in leaves:
        size = getattr(leaf, "size", None)
        dtype = getattr(leaf, "dtype", None)
        if size is not None and dtype is not None:
            total += int(size) * np.dtype(dtype).itemsize
    return total


def timed_op(fn):
    """Record op name + message size at trace time (reference comm.py:100)."""

    @functools.wraps(fn)
    def wrapper(tensor, *args, **kwargs):
        if _comms_logger is not None:
            _comms_logger.append(fn.__name__, _nbytes(tensor))
        return fn(tensor, *args, **kwargs)

    return wrapper


# ---------------------------------------------------------------------------
# Traced data-plane collectives (call inside shard_map / with mesh axes bound)
# ---------------------------------------------------------------------------

@timed_op
def all_reduce(tensor, op: str = SUM, axis: AxisName = BATCH_AXES):
    return _backend.all_reduce(tensor, op, axis)


@timed_op
def inference_all_reduce(tensor, axis: AxisName = "model"):
    return _backend.all_reduce(tensor, SUM, axis)


@timed_op
def all_gather(tensor, axis: AxisName, gather_dim: int = 0):
    """Tiled all-gather: concat shards along gather_dim (reference
    all_gather_into_tensor, comm.py:300)."""
    return _backend.all_gather(tensor, axis, tiled=True, gather_dim=gather_dim)


@timed_op
def reduce_scatter(tensor, axis: AxisName, scatter_dim: int = 0):
    """Tiled reduce-scatter (reference reduce_scatter_tensor, comm.py:257)."""
    return _backend.reduce_scatter(tensor, axis, scatter_dim)


@timed_op
def all_to_all(tensor, axis: AxisName, split_dim: int = 0, concat_dim: int = 0):
    """Tiled all-to-all (reference all_to_all_single, comm.py:361)."""
    return _backend.all_to_all(tensor, axis, split_dim, concat_dim)


@timed_op
def ppermute(tensor, axis: str, perm):
    """collective_permute; the TPU analogue of pipeline send/recv pairs
    (reference runtime/pipe/p2p.py:50-99)."""
    return _backend.permute(tensor, axis, perm)


def send_recv_next(tensor, axis: str):
    """Shift +1 along a mesh axis ring (stage i -> i+1); last wraps to 0 but
    pipeline schedules never read the wrapped value."""
    n = _backend.axis_size(axis)
    return _backend.permute(tensor, axis, [(i, (i + 1) % n) for i in range(n)])


def send_recv_prev(tensor, axis: str):
    n = _backend.axis_size(axis)
    return _backend.permute(tensor, axis, [((i + 1) % n, i) for i in range(n)])


def axis_index(axis: AxisName):
    return _backend.axis_index(axis)


def get_axis_size(axis: AxisName) -> int:
    return _backend.axis_size(axis)


# ---------------------------------------------------------------------------
# Eager / control plane
# ---------------------------------------------------------------------------
_initialized = False

# the pod supervisor exports the membership epoch it (re-)formed the job
# under (elasticity/pod_agent.py, launcher --pod_coord_dir); control-plane
# sync points scope their rendezvous names by it so a stale host from a
# previous incarnation can never complete a barrier with the new round
POD_GENERATION_ENV = "DS_TPU_POD_GENERATION"


def get_pod_generation() -> int:
    """The pod membership generation this process was launched under
    (0 when not running under a pod supervisor / malformed env)."""
    raw = os.environ.get(POD_GENERATION_ENV, "").strip()
    try:
        return int(raw) if raw else 0
    except ValueError:
        logger.warning("ignoring malformed $%s=%r (want an int)",
                       POD_GENERATION_ENV, raw)
        return 0


def is_initialized() -> bool:
    return _initialized


def init_distributed(dist_backend: str = "xla", auto_mpi_discovery: bool = True,
                     verbose: bool = True, timeout=None, init_method=None,
                     rank: int = -1, world_size: int = -1) -> None:
    """Multi-host rendezvous (reference init_distributed, comm.py:578-745).

    Single-controller JAX: each *host* runs one process driving its local TPU
    chips.  Discovery order: explicit args > DS_TPU_* / JAX standard env vars
    (COORDINATOR_ADDRESS, NUM_PROCESSES, PROCESS_ID) > TPU-pod metadata
    (jax.distributed auto-detect) > single-process (no-op).
    """
    global _initialized
    if _initialized:
        return
    import jax

    if verbose and get_pod_generation():
        log_dist(f"init_distributed: pod generation {get_pod_generation()}",
                 [0])
    coord = os.environ.get("COORDINATOR_ADDRESS") or os.environ.get("MASTER_ADDR")
    nprocs = world_size if world_size > 0 else int(
        os.environ.get("NUM_PROCESSES", os.environ.get("WORLD_SIZE", "0")) or 0)
    pid = rank if rank >= 0 else int(
        os.environ.get("PROCESS_ID", os.environ.get("RANK", "-1")) or -1)

    if coord and nprocs > 1 and pid >= 0:
        port = os.environ.get("COORDINATOR_PORT", os.environ.get("MASTER_PORT", "8476"))
        addr = coord if ":" in coord else f"{coord}:{port}"
        if verbose:
            log_dist(f"init_distributed: coordinator={addr} nprocs={nprocs} pid={pid}", [0])
        jax.distributed.initialize(coordinator_address=addr, num_processes=nprocs,
                                   process_id=pid)
    elif (len((os.environ.get("TPU_WORKER_HOSTNAMES") or "").split(",")) > 1
          or os.environ.get("MEGASCALE_COORDINATOR_ADDRESS")):
        # TPU pod slice: jax.distributed can auto-detect from metadata
        if verbose:
            log_dist("init_distributed: auto-detecting TPU pod topology", [0])
        jax.distributed.initialize()
    else:
        if verbose:
            log_dist("init_distributed: single-process mode", [0])
    _initialized = True


def get_rank() -> int:
    """Process rank (host index). Device-level 'rank' is a mesh coordinate."""
    import jax

    return jax.process_index()


def get_world_size() -> int:
    """Number of processes (hosts)."""
    import jax

    return jax.process_count()


def get_local_rank() -> int:
    return int(os.environ.get("LOCAL_RANK", "0"))


def barrier() -> None:
    """Cross-host sync barrier (reference comm.py:398 monitored_barrier).
    The sync name is scoped by the pod generation: a host left over from a
    previous membership epoch blocks on a DIFFERENT name and times out in
    the runtime instead of silently pairing with the re-formed job."""
    import jax

    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(
            f"deepspeed_tpu_barrier/gen{get_pod_generation()}")
    else:
        jax.block_until_ready(jax.numpy.zeros(()))


def broadcast_object(obj: Any, src_process: int = 0) -> Any:
    """Host-side object broadcast (reference pickled-object send, p2p.py:100)."""
    import jax

    if jax.process_count() <= 1:
        return obj
    from jax.experimental import multihost_utils

    return multihost_utils.broadcast_one_to_all(obj)


def log_summary(show_bandwidth: bool = False, print_log: bool = True):
    """Print (and return) the comms table; ``show_bandwidth`` re-times each
    (op, size) as a standalone microbench for algbw/busbw columns (the TPU
    analogue of the reference's latency-derived columns, comm.py:408)."""
    if _comms_logger is None:
        return ""
    return _comms_logger.log_all(print_log=print_log,
                                 show_bandwidth=show_bandwidth)


# -- capability probing (reference comm.py:300 has_all_gather_into_tensor,
#    torch.py:39 has_coalescing_manager).  The reference gates fast paths on
#    backend feature flags; on XLA every collective below is native, so the
#    probes exist for API parity and for user code written against the
#    reference's feature-detection idiom.
def has_all_gather_into_tensor() -> bool:
    """XLA all_gather always lands in one tensor — no Python-list fallback."""
    return True


def has_reduce_scatter_tensor() -> bool:
    return True


def has_coalescing_manager() -> bool:
    """XLA fuses/coalesces collectives during compilation; there is no
    eager-mode coalescing manager to expose (the compiler IS the manager)."""
    return False


def has_all_to_all_single() -> bool:
    return True
