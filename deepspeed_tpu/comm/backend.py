"""Communication backend base (reference ``comm/backend.py:25``).

In the reference a Backend wraps an out-of-band collective library
(NCCL/oneCCL/HCCL).  On TPU the data plane is *compiled into the program*: XLA
emits collectives (psum / all-gather / reduce-scatter / all-to-all /
collective-permute) over ICI/DCN from sharding annotations or explicit ``lax``
ops inside ``shard_map``.  The Backend abstraction therefore splits into:

- a **data-plane** object (:class:`XLABackend`) whose ops are traced-context
  collectives keyed by mesh axis name (the analogue of a process group), and
- a **control-plane** (``jax.distributed`` + multihost utils) for rendezvous,
  barriers, and host-side object broadcast — see ``comm.init_distributed``.
"""
from __future__ import annotations

from typing import Any, Optional, Sequence, Union

from ..parallel.mesh import BATCH_AXES

AxisName = Union[str, Sequence[str]]


class Backend:
    def __init__(self, name: str = "backend", rank: int = 0, size: int = 1):
        self.name = name
        self.initialized = False

    def is_initialized(self) -> bool:
        return self.initialized

    def init_process_group(self) -> None:
        self.initialized = True

    def destroy_process_group(self) -> None:
        self.initialized = False


class XLABackend(Backend):
    """Data-plane collectives as traced ``lax`` ops over mesh axes.

    These must be called inside a ``shard_map``(manual) region — the engine's
    hot loops run there.  For eager/control-plane variants see ``comm.comm``.
    """

    def __init__(self):
        super().__init__(name="xla")

    # Each op returns the result (functional, jax-style) instead of mutating.
    def all_reduce(self, tensor: Any, op: str = "sum", axis: AxisName = BATCH_AXES):
        import jax.lax as lax

        if op == "sum":
            return lax.psum(tensor, axis)
        if op == "max":
            return lax.pmax(tensor, axis)
        if op == "min":
            return lax.pmin(tensor, axis)
        if op in ("mean", "avg"):
            return lax.pmean(tensor, axis)
        if op == "prod":
            # XLA has no product collective; gather then reduce locally.
            import jax.numpy as jnp

            gathered = lax.all_gather(tensor, axis, axis=0, tiled=False)
            return jnp.prod(gathered, axis=0)
        raise ValueError(f"unsupported reduce op {op}")

    def all_gather(self, tensor: Any, axis: AxisName, tiled: bool = True, gather_dim: int = 0):
        import jax.lax as lax

        return lax.all_gather(tensor, axis, axis=gather_dim, tiled=tiled)

    def reduce_scatter(self, tensor: Any, axis: AxisName, scatter_dim: int = 0):
        import jax.lax as lax

        return lax.psum_scatter(tensor, axis, scatter_dimension=scatter_dim, tiled=True)

    def all_to_all(self, tensor: Any, axis: AxisName, split_dim: int = 0, concat_dim: int = 0):
        import jax.lax as lax

        return lax.all_to_all(tensor, axis, split_axis=split_dim, concat_axis=concat_dim,
                              tiled=True)

    def permute(self, tensor: Any, axis: str, perm):
        import jax.lax as lax

        return lax.ppermute(tensor, axis, perm)

    def axis_index(self, axis: AxisName):
        import jax.lax as lax

        return lax.axis_index(axis)

    def axis_size(self, axis: AxisName) -> int:
        import jax.lax as lax
        import numpy as np

        # lax.axis_size is newer-jax only; psum(1, axis) is static at
        # trace time on every version the graft supports
        size_of = getattr(lax, "axis_size", None) or (
            lambda a: lax.psum(1, a))
        if isinstance(axis, (tuple, list)):
            return int(np.prod([size_of(a) for a in axis]))
        return size_of(axis)
