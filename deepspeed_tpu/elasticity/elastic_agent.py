"""Elastic run loop: survive preemption / slice-shape changes via checkpoints.

Parity target: reference ``deepspeed/elasticity/elastic_agent.py:28``
(``DSElasticAgent`` — a torch-elastic agent that restarts worker groups when
membership changes).  TPUs have no in-job membership change: a slice is
immutable while allocated, and "elasticity" means the JOB is stopped
(preemption, resize) and restarted on a possibly different slice.  So the TPU
agent is checkpoint-centric rather than rendezvous-centric:

- a signal handler converts SIGTERM (the TPU preemption notice) into a
  save-and-exit at the next step boundary;
- on start, the agent resolves the elastic plan for the CURRENT device count
  (``compute_elastic_config``) and restores the latest checkpoint — the orbax
  checkpoint layer already reshards across topologies, so a job that left on
  32 chips resumes on 8 with the same effective batch size.
"""
from __future__ import annotations

import os
import signal
import time
from typing import Callable, Optional

from .elasticity import ElasticPlan, compute_elastic_config
from ..utils.logging import log_dist, logger


class PreemptionGuard:
    """Latches termination signals so training can exit at a step boundary.

    Usage::

        guard = PreemptionGuard.install()
        while training:
            engine.train_batch(...)
            if guard.should_stop:
                engine.save_checkpoint(ckpt_dir)
                break
    """

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self._signals = signals
        self._previous = {}
        self.should_stop = False
        self.received: Optional[int] = None

    def _handler(self, signum, frame):
        self.should_stop = True
        self.received = signum
        logger.warning(f"preemption signal {signum} latched; will checkpoint "
                       "and exit at the next step boundary")

    @classmethod
    def install(cls, signals=(signal.SIGTERM, signal.SIGINT)) -> "PreemptionGuard":
        guard = cls(signals)
        for s in signals:
            guard._previous[s] = signal.signal(s, guard._handler)
        return guard

    def uninstall(self) -> None:
        for s, prev in self._previous.items():
            signal.signal(s, prev)
        self._previous = {}


class ElasticAgent:
    """Drives an elastic training session across restarts.

    ``train_step_fn(engine, step) -> loss`` supplies one training step; the
    agent owns plan resolution, checkpoint restore on entry, periodic +
    preemption checkpointing, and the stop decision.
    """

    def __init__(self, engine, ckpt_dir: str, ckpt_every: int = 0,
                 tag: str = "elastic"):
        self.engine = engine
        self.ckpt_dir = ckpt_dir
        self.ckpt_every = ckpt_every
        self.tag = tag
        self.guard = PreemptionGuard.install()
        self.resumed_step = 0

    def restore_if_present(self) -> int:
        """Load the newest checkpoint (any prior topology); returns the step
        training should resume from."""
        if os.path.isdir(self.ckpt_dir) and os.listdir(self.ckpt_dir):
            try:
                self.engine.load_checkpoint(self.ckpt_dir)
                self.resumed_step = int(self.engine.global_steps)
                log_dist(f"elastic resume from step {self.resumed_step} "
                         f"on {self.engine.dp_world} DP devices", ranks=[0])
            except FileNotFoundError:
                pass
        return self.resumed_step

    def run(self, train_step_fn: Callable, total_steps: int) -> int:
        """Run to ``total_steps`` or preemption; returns the last global step
        completed.  Exit code contract: exit nonzero while the returned step
        < total_steps — the IN-TREE supervisor (``elasticity/supervisor.py``,
        ``deepspeed_tpu.launcher --elastic_restarts N``) relaunches on any
        failure exit, re-discovering resources so a resized slice resumes at
        its new world size from the last committed checkpoint."""
        start = self.restore_if_present()
        saved_at = -1
        for step in range(start, total_steps):
            train_step_fn(self.engine, step)
            at_interval = self.ckpt_every and (step + 1) % self.ckpt_every == 0
            if at_interval or self.guard.should_stop:
                self.engine.save_checkpoint(self.ckpt_dir, tag=self.tag)
                saved_at = step + 1
            if self.guard.should_stop:
                log_dist(f"elastic exit at step {step + 1} "
                         f"(signal {self.guard.received})", ranks=[0])
                return step + 1
        if saved_at != total_steps:
            self.engine.save_checkpoint(self.ckpt_dir, tag=self.tag)
        return total_steps


def resolve_plan_for_current_world(config, dp_world_size: int,
                                   node_size: int = 1,
                                   model_parallel_size: int = 1) -> ElasticPlan:
    """Helper the runtime config calls: elastic plan bound to this restart's
    world size."""
    plan = compute_elastic_config(config, dp_world_size, node_size,
                                  model_parallel_size)
    log_dist(
        f"elasticity: batch={plan.train_batch_size} micro="
        f"{plan.micro_batch_per_device} gas={plan.gradient_accumulation_steps} "
        f"valid device counts={list(plan.valid_device_counts)}", ranks=[0])
    return plan
