"""Elastic run loop: survive preemption / slice-shape changes via checkpoints.

Parity target: reference ``deepspeed/elasticity/elastic_agent.py:28``
(``DSElasticAgent`` — a torch-elastic agent that restarts worker groups when
membership changes).  TPUs have no in-job membership change: a slice is
immutable while allocated, and "elasticity" means the JOB is stopped
(preemption, resize) and restarted on a possibly different slice.  So the TPU
agent is checkpoint-centric rather than rendezvous-centric:

- a signal handler converts SIGTERM (the TPU preemption notice) into a
  save-and-exit at the next step boundary;
- on start, the agent resolves the elastic plan for the CURRENT device count
  (``compute_elastic_config``) and restores the latest checkpoint — the orbax
  checkpoint layer already reshards across topologies, so a job that left on
  32 chips resumes on 8 with the same effective batch size.
"""
from __future__ import annotations

import os
import signal
import time
from typing import Callable, Optional

from .elasticity import ElasticPlan, compute_elastic_config
from ..resilience.integrity import (LATEST_FILE, MANIFEST_FILE,
                                    candidate_tags, quarantine_tag)
from ..utils.logging import log_dist, logger


class PreemptionGuard:
    """Latches termination signals so training can exit at a step boundary.

    Usage::

        guard = PreemptionGuard.install()
        while training:
            engine.train_batch(...)
            if guard.should_stop:
                engine.save_checkpoint(ckpt_dir)
                break
    """

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self._signals = signals
        self._previous = {}
        self.should_stop = False
        self.received: Optional[int] = None

    def _handler(self, signum, frame):
        self.should_stop = True
        self.received = signum
        logger.warning(f"preemption signal {signum} latched; will checkpoint "
                       "and exit at the next step boundary")

    @classmethod
    def install(cls, signals=(signal.SIGTERM, signal.SIGINT)) -> "PreemptionGuard":
        guard = cls(signals)
        for s in signals:
            guard._previous[s] = signal.signal(s, guard._handler)
        return guard

    def uninstall(self) -> None:
        for s, prev in self._previous.items():
            signal.signal(s, prev)
        self._previous = {}


class ElasticAgent:
    """Drives an elastic training session across restarts.

    ``train_step_fn(engine, step) -> loss`` supplies one training step; the
    agent owns plan resolution, checkpoint restore on entry, periodic +
    preemption checkpointing, and the stop decision.
    """

    def __init__(self, engine, ckpt_dir: str, ckpt_every: int = 0,
                 tag: Optional[str] = None, keep: int = 3):
        self.engine = engine
        self.ckpt_dir = ckpt_dir
        self.ckpt_every = ckpt_every
        # tag=None -> per-step generation tags (global_stepN): corruption of
        # the newest generation can fall back to the previous one.  A fixed
        # tag keeps the old single-slot behaviour (no fallback depth).
        self.tag = tag
        self.keep = keep
        self.guard = PreemptionGuard.install()
        self.resumed_step = 0

    def restore_if_present(self) -> int:
        """Load the newest *verified* checkpoint (any prior topology);
        returns the step training should resume from.

        Walks committed tags newest-to-oldest.  A tag that fails manifest
        verification (``load_checkpoint`` verifies before mutating state)
        or errors during restore — torn write, bit rot, incompatible
        payload, flaky storage — is quarantined (renamed ``<tag>.corrupt``)
        and the walk falls back one generation, instead of letting the
        error escape and permanently crash-loop the supervisor on the same
        poisoned tag.

        Multi-host caveat: each host walks and verifies independently
        against shared storage; the quarantine rename and the ``latest``
        re-point run on process 0 only.  A host-local read flake can still
        diverge hosts onto different generations — the next collective then
        fails and the supervisor recycles the round, which is the designed
        backstop rather than a coordinated election."""
        import jax

        if not (os.path.isdir(self.ckpt_dir) and os.listdir(self.ckpt_dir)):
            return self.resumed_step
        from ..resilience.integrity import CheckpointIntegrityError

        for tag in candidate_tags(self.ckpt_dir):
            tag_dir = os.path.join(self.ckpt_dir, tag)
            try:
                # subclass hook (pod agent): extra commit-scope verification
                # BEFORE any engine state is touched — a failure here
                # quarantines and falls back exactly like a load failure
                self._pre_load_verify(tag_dir)
                try:
                    self.engine.load_checkpoint(self.ckpt_dir, tag=tag)
                except KeyboardInterrupt:
                    raise
                except CheckpointIntegrityError:
                    raise   # proven corruption: no point retrying
                except Exception as e:
                    # could be a transient storage blip, not corruption —
                    # one retry before the IRREVERSIBLE quarantine rename
                    logger.warning(
                        "elastic restore: load of %s raised %s: %s; "
                        "retrying once before quarantining",
                        tag_dir, type(e).__name__, e)
                    self.engine.load_checkpoint(self.ckpt_dir, tag=tag)
            except KeyboardInterrupt:
                raise
            except Exception as e:
                logger.error(
                    "elastic restore: checkpoint %s unusable (%s: %s); "
                    "quarantining and falling back one generation",
                    tag_dir, type(e).__name__, e)
                if jax.process_index() == 0:
                    try:
                        quarantine_tag(self.ckpt_dir, tag)
                    except OSError as qe:   # storage flaking mid-quarantine:
                        logger.error("elastic restore: quarantine of %s "
                                     "failed (%s); skipping tag", tag_dir, qe)
                continue
            self.resumed_step = int(self.engine.global_steps)
            # re-point `latest` at the generation that actually loaded so
            # the next writer/reader agree on the committed frontier
            if jax.process_index() == 0:
                with open(os.path.join(self.ckpt_dir, LATEST_FILE), "w") as f:
                    f.write(str(tag))
            log_dist(f"elastic resume from step {self.resumed_step} "
                     f"(tag {tag}) on {self.engine.dp_world} DP devices",
                     ranks=[0])
            break
        else:
            logger.warning("elastic restore: no usable checkpoint under %s; "
                           "starting fresh", self.ckpt_dir)
        return self.resumed_step

    def run(self, train_step_fn: Callable, total_steps: int) -> int:
        """Run to ``total_steps`` or preemption; returns the last global step
        completed.  Exit code contract: exit nonzero while the returned step
        < total_steps — the IN-TREE supervisor (``elasticity/supervisor.py``,
        ``deepspeed_tpu.launcher --elastic_restarts N``) relaunches on any
        failure exit, re-discovering resources so a resized slice resumes at
        its new world size from the last committed checkpoint."""
        start = self.restore_if_present()
        saved_at = -1
        for step in range(start, total_steps):
            train_step_fn(self.engine, step)
            at_interval = self.ckpt_every and (step + 1) % self.ckpt_every == 0
            if at_interval or self.guard.should_stop:
                try:
                    self._save()
                    if self.guard.should_stop:
                        # about to exit: an async save's commit runs on a
                        # daemon thread that dies with the process — join it
                        # or the preemption checkpoint is torn and lost
                        self._join_pending_save()
                    saved_at = step + 1
                    self._prune_generations()
                except Exception as e:
                    if not self.guard.should_stop:
                        raise
                    # preemption is latched: the save failed but the logged
                    # exit contract below must still run so the supervisor
                    # sees a failure exit and relaunches from the last
                    # COMMITTED generation — raising here would skip it
                    logger.error(
                        "elastic exit: preemption-path checkpoint save "
                        "failed (%s: %s); exiting without a new generation "
                        "— restart resumes from the previous committed tag",
                        type(e).__name__, e)
            if self.guard.should_stop:
                log_dist(f"elastic exit at step {step + 1} "
                         f"(signal {self.guard.received})", ranks=[0])
                return step + 1
        if saved_at != total_steps:
            self._save()
            self._join_pending_save()
            self._prune_generations()
        else:
            self._join_pending_save()
        return total_steps

    def _save(self) -> None:
        """One checkpoint save at the agent's tag policy; the pod agent
        overrides this with the pod-scope commit protocol."""
        self.engine.save_checkpoint(self.ckpt_dir, tag=self.tag)

    def _pre_load_verify(self, tag_dir: str) -> None:
        """Commit-scope verification hook run before a tag is loaded (the
        base agent relies on the engine's per-host manifest check)."""

    def _join_pending_save(self) -> None:
        """Commit barrier before the process may exit (no-op for sync
        saves): wait_for_checkpoint joins the async finalize thread with
        the engine's bounded timeout and re-raises a failed save."""
        wait = getattr(self.engine, "wait_for_checkpoint", None)
        if wait is not None:
            wait()

    def _prune_generations(self) -> None:
        """Bound disk: keep the newest ``keep`` COMMITTED generations.
        Only manifest-bearing tags are prune candidates: an in-flight async
        save has no manifest yet and must never be rmtree'd under its
        writer; torn tags are left for quarantine, quarantined ``*.corrupt``
        dirs for the operator.  With a fixed tag there is a single
        overwritten generation and nothing to prune."""
        if self.tag is not None or self.keep <= 0:
            return
        import jax

        if jax.process_index() != 0:
            return
        import shutil

        committed = [t for t in candidate_tags(self.ckpt_dir)
                     if self._tag_committed(os.path.join(self.ckpt_dir, t))]
        for old in committed[self.keep:]:
            shutil.rmtree(os.path.join(self.ckpt_dir, old),
                          ignore_errors=True)

    def _tag_committed(self, tag_dir: str) -> bool:
        """Commit test used for prune candidacy AND the keep-newest count;
        the pod agent tightens it to pod-committed so a torn pod tag can
        neither be deleted under a late writer nor crowd a real fallback
        generation out of the keep window."""
        return os.path.exists(os.path.join(tag_dir, MANIFEST_FILE))


def resolve_plan_for_current_world(config, dp_world_size: int,
                                   node_size: int = 1,
                                   model_parallel_size: int = 1) -> ElasticPlan:
    """Helper the runtime config calls: elastic plan bound to this restart's
    world size."""
    plan = compute_elastic_config(config, dp_world_size, node_size,
                                  model_parallel_size)
    log_dist(
        f"elasticity: batch={plan.train_batch_size} micro="
        f"{plan.micro_batch_per_device} gas={plan.gradient_accumulation_steps} "
        f"valid device counts={list(plan.valid_device_counts)}", ranks=[0])
    return plan
