"""Checkpoint-free pod recovery: buddy-replicated host state (ISSUE 20).

Pod failover so far is checkpoint-grained: when a host dies the supervisor
re-forms at the healthy slice and `PodElasticAgent.restore_if_present`
rolls back to the last *pod-committed* checkpoint, throwing away every
step since the last durable save (docs/POD.md "Limitations").  This
module closes that gap with an in-memory redundancy layer:

- every ``replica_every_k`` steps each host snapshots its param/optimizer
  shards to host RAM (a device→host copy on the step path; checksum +
  publish run on a background worker, off it) and **seals** the result
  into a size-capped CAS document under ``pod/replica/<host>`` — the
  store-coupled stand-in for pushing the slab to the host's ring
  **buddy** (the next host in sorted order), who is responsible for
  serving it during recovery;
- on a peer death the next round's survivors run a **live-adoption**
  path instead of the checkpoint walk: pick the newest step at which
  *every* previous member holds a sealed, checksum-verified, generation-
  fenced slab (the consistent cut), CAS-claim ``pod/adopt/gen<g>/<v>``
  (at most one adopter per victim per round), re-ingest the state, and
  resume at the cut + 1;
- any missing slab, dead buddy, failed checksum or generation-fence
  violation aborts adoption LOUDLY (:class:`ReplicaAdoptionError`) and
  the caller falls back to today's checkpoint restart — the replica
  layer is an optimization over, never a replacement for, the durable
  commit protocol.

Slabs keep the newest :data:`REPLICA_KEEP` entries so a host killed
mid-seal (snapshot taken, publish never landed — or landed torn with a
bad checksum) falls back to its *previous* replica instead of dragging
the whole pod to the durable checkpoint.

Store-only coupling, like PR 11's host-tier slabs and PR 16's channels:
no new transport, every document moves through ``CoordinationStore``
CAS under :func:`~.coordination.default_retry_policy`.  Fault sites
``pod.replica_seal`` / ``pod.adopt`` plug into the standard injector
(docs/RESILIENCE.md); the protocol history is checkable by
``tools/store_check.py``'s replica rules.
"""
from __future__ import annotations

import base64
import hashlib
import threading
from typing import Callable, Dict, List, Optional, Sequence

from .coordination import (CoordinationStore, StoreRetryPolicy,
                           StoreUnavailable, default_retry_policy)
from ..observability.trace import trace_span
from ..resilience.fault_injection import (SITE_POD_ADOPT, SITE_REPLICA_SEAL,
                                          maybe_fire)
from ..utils.logging import log_dist, logger

POD_REPLICA_PREFIX = "pod/replica"
POD_REPLICA_ROUND_PREFIX = "pod/replica_round"
POD_ADOPT_PREFIX = "pod/adopt"

# newest-first entries kept per host slab.  Sizing: adoption needs a cut
# COMMON to every previous member, and a silent death surfaces at the
# next pod-commit barrier (the commit timeout names every missing host
# at once — lease expiry may lag it).  Between a victim's last landed
# seal and that barrier the survivors can seal every boundary of the
# checkpoint interval — ceil(ckpt_every / k) of them, plus the one a
# mid-seal kill tears off the victim's slab.  4 keeps the shared cut
# adoptable through both at the shipped cadences (k=2, ckpt_every=5).
REPLICA_KEEP = 4
# size cap per slab document (the file store moves whole JSON docs; a
# state too big for the cap must replicate through a real object store,
# not the coordination tier)
REPLICA_MAX_BYTES = 64 << 20


class ReplicaIntegrityError(RuntimeError):
    """A sealed slab entry failed its checksum — the payload is torn."""


class ReplicaAdoptionError(RuntimeError):
    """Live-state adoption cannot proceed (missing slab, dead buddy,
    generation fence, no verifiable consistent cut).  The caller must
    fall back to checkpoint restart — loudly."""


# module counters surfaced as pod/replica_* gauges by the supervisor
_TOTALS_LOCK = threading.Lock()
_ADOPTIONS_TOTAL = 0
_FALLBACKS_TOTAL = 0


def replica_adoptions_total() -> int:
    with _TOTALS_LOCK:
        return _ADOPTIONS_TOTAL


def replica_fallbacks_total() -> int:
    with _TOTALS_LOCK:
        return _FALLBACKS_TOTAL


def note_adoption_fallback() -> None:
    """Count a loud adoption→checkpoint fallback (the agent calls this
    right before re-entering the durable restore walk)."""
    global _FALLBACKS_TOTAL
    with _TOTALS_LOCK:
        _FALLBACKS_TOTAL += 1


# ------------------------------------------------------------- buddy ring

def buddy_ring(hosts: Sequence[str]) -> Dict[str, str]:
    """Ring buddy assignment over the (healthy) membership: each host's
    buddy is the next host in sorted order, wrapping.  A single-host pod
    has nobody to replicate to ({})."""
    ring = sorted(hosts)
    if len(ring) < 2:
        return {}
    return {h: ring[(i + 1) % len(ring)] for i, h in enumerate(ring)}


# ---------------------------------------------------------- seal / verify

def seal_entry(payload: bytes, step: int, generation: int) -> Dict:
    """One sealed slab entry: step-stamped, generation-fenced,
    checksummed, payload carried base64 (store docs are JSON)."""
    return {
        "step": int(step),
        "generation": int(generation),
        "sha256": hashlib.sha256(payload).hexdigest(),
        "bytes": len(payload),
        "payload": base64.b64encode(payload).decode("ascii"),
    }


def verify_entry(entry: Dict) -> bytes:
    """Decode + checksum-verify one entry; returns the payload bytes."""
    try:
        payload = base64.b64decode(entry["payload"])
    except Exception as e:
        raise ReplicaIntegrityError(
            f"replica entry for step {entry.get('step')} is not decodable: "
            f"{e}") from e
    digest = hashlib.sha256(payload).hexdigest()
    if digest != entry.get("sha256"):
        raise ReplicaIntegrityError(
            f"replica entry for step {entry.get('step')} failed its "
            f"checksum ({digest[:12]}… != {str(entry.get('sha256'))[:12]}…)")
    if len(payload) != int(entry.get("bytes", -1)):
        raise ReplicaIntegrityError(
            f"replica entry for step {entry.get('step')} is truncated: "
            f"{len(payload)} bytes, sealed as {entry.get('bytes')}")
    return payload


# -------------------------------------------------------- publish / read

def publish_replica(store: CoordinationStore, host: str, entry: Dict,
                    buddy: Optional[str] = None,
                    keep: int = REPLICA_KEEP) -> Dict:
    """CAS-append ``entry`` (newest first) onto ``pod/replica/<host>``,
    keeping the newest ``keep`` entries — the same size-capped CAS-doc
    idiom as the fleet channels.  Returns the document as written."""
    if int(entry.get("bytes", 0)) > REPLICA_MAX_BYTES:
        raise ValueError(
            f"replica slab for {host!r} is {entry['bytes']} bytes, over "
            f"the {REPLICA_MAX_BYTES}-byte coordination-store cap")
    key = f"{POD_REPLICA_PREFIX}/{host}"
    maybe_fire(SITE_REPLICA_SEAL, host=host, step=entry.get("step"))
    out: Dict = {}

    def attempt():
        cur = store.get(key)
        entries = [e for e in (cur or {}).get("entries", ())
                   if int(e.get("step", -1)) != int(entry["step"])]
        entries.insert(0, entry)
        doc = {
            "host": host,
            "buddy": buddy,
            "generation": int(entry["generation"]),
            "seq": int((cur or {}).get("seq", 0)) + 1,
            "entries": entries[:keep],
            "t": store.now(),
        }
        if store.compare_and_swap(key, cur, doc):
            out.update(doc)
            return doc
        return StoreRetryPolicy.RETRY

    return default_retry_policy().run(f"publish_replica({host!r})", attempt)


def read_replica(store: CoordinationStore, host: str) -> Optional[Dict]:
    return store.get(f"{POD_REPLICA_PREFIX}/{host}")


def announce_replica_round(store: CoordinationStore, generation: int,
                           step: int) -> None:
    """Coordinator-side announcement that the pod seals at ``step``:
    hosts that do not drive the step loop themselves (simulated peers,
    protocol-only processes) poll this instead of guessing boundaries —
    the replica analogue of :func:`~.pod_agent.pending_commit`."""
    store.put(f"{POD_REPLICA_ROUND_PREFIX}/gen{int(generation)}",
              {"step": int(step), "t": store.now()})


def pending_replica_round(store: CoordinationStore,
                          generation: int) -> Optional[int]:
    doc = store.get(f"{POD_REPLICA_ROUND_PREFIX}/gen{int(generation)}")
    return int(doc["step"]) if doc else None


# ---------------------------------------------------------- host replicator

class HostReplicator:
    """Per-host replica pump: snapshot on the step path (device→host copy
    only), seal + publish on a background worker thread — the same
    off-step-path shape as the async-checkpoint finalize thread
    (runtime/checkpoint_engine/async_engine.py), coalescing so a slow
    store never queues more than the newest pending slab.

    ``snapshot_fn() -> bytes`` produces this host's shard payload (the
    engine host uses ``engine.replica_snapshot()``; simulated peers
    return synthetic shard bytes).  ``replica_every_k == 0`` disables the
    layer entirely: :meth:`maybe_replicate` is a single compare-and-return
    (the zero-step-time-regression contract).
    """

    def __init__(self, store: CoordinationStore, host_id: str,
                 generation: int, hosts: Sequence[str],
                 snapshot_fn: Callable[[], bytes],
                 replica_every_k: int = 0, monitor=None,
                 on_sealed: Optional[Callable[[int], None]] = None):
        self.store = store
        self.host_id = host_id
        self.generation = int(generation)
        self.buddy = buddy_ring(hosts).get(host_id)
        self.snapshot_fn = snapshot_fn
        self.replica_every_k = int(replica_every_k)
        self.monitor = monitor
        self.on_sealed = on_sealed
        self.seals_total = 0
        self.bytes_published = 0
        self.last_step = -1
        self.publish_failures = 0
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._pending: Optional[Dict] = None
        self._stopping = False
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ step path

    def maybe_replicate(self, step: int) -> bool:
        """Called once per completed step.  Off-boundary (and disabled)
        calls return immediately; on a boundary the snapshot runs here
        (the device→host copy must see the step's state before the next
        step mutates it) and the seal/publish is handed to the worker."""
        if self.replica_every_k <= 0:
            return False
        if step % self.replica_every_k != 0:
            return False
        entry = seal_entry(self.snapshot_fn(), step, self.generation)
        with self._cv:
            # coalesce: a publish still in flight is superseded — the
            # newest slab is the only one adoption will ever want
            self._pending = entry
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._worker, daemon=True,
                    name=f"pod-replica-{self.host_id}")
                self._thread.start()
            self._cv.notify()
        return True

    def seal_now(self, step: int) -> bool:
        """Synchronous best-effort seal + publish — the preemption path
        (SIGTERM latched): a planned preemption must never cost more than
        the in-flight step, so the exiting host pushes its state to its
        buddy before the save/exit sequence runs.  Failures are logged,
        never raised (the durable preemption checkpoint still runs)."""
        if self.replica_every_k <= 0:
            return False
        try:
            entry = seal_entry(self.snapshot_fn(), step, self.generation)
            self._publish(entry)
            return True
        except Exception as e:   # best-effort by contract
            with self._lock:
                self.publish_failures += 1
            logger.error(
                "pod replicate: preemption-path seal of %s at step %d "
                "failed (%s: %s); the durable checkpoint is the fallback",
                self.host_id, step, type(e).__name__, e)
            return False

    # ---------------------------------------------------------- worker side

    def _worker(self) -> None:
        while True:
            with self._cv:
                while self._pending is None and not self._stopping:
                    self._cv.wait()
                if self._pending is None and self._stopping:
                    return
                entry, self._pending = self._pending, None
            try:
                self._publish(entry)
            except Exception as e:
                with self._lock:
                    self.publish_failures += 1
                logger.warning(
                    "pod replicate: publish of %s step %s failed "
                    "(%s: %s); the slab stays at its previous seal",
                    self.host_id, entry.get("step"), type(e).__name__, e)

    def _publish(self, entry: Dict) -> None:
        with trace_span("pod.replicate", host=self.host_id,
                        step=entry["step"], bytes=entry["bytes"]):
            publish_replica(self.store, self.host_id, entry,
                            buddy=self.buddy)
        with self._lock:   # _publish runs on the worker AND seal_now paths
            self.seals_total += 1
            self.bytes_published += int(entry["bytes"])
            self.last_step = int(entry["step"])
            seals, published, last = (self.seals_total,
                                      self.bytes_published, self.last_step)
        if self.monitor is not None:
            self.monitor.write_events([
                ("pod/replica_seals_total", float(seals), entry["step"]),
                ("pod/replica_bytes_total", float(published),
                 entry["step"]),
                ("pod/replica_last_step", float(last), entry["step"]),
            ])
        if self.on_sealed is not None:
            self.on_sealed(int(entry["step"]))

    def stop(self, timeout_s: float = 30.0) -> None:
        """Drain the pending publish (bounded) and stop the worker —
        called at round exit so the final slab is durable-on-store before
        the next round plans its adoption cut."""
        with self._cv:
            self._stopping = True
            self._cv.notify()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout_s)
        self._thread = None


# --------------------------------------------------------------- adoption

def plan_adoption(store: CoordinationStore, prev_hosts: Sequence[str],
                  dead: Sequence[str],
                  dead_prefix: str = "dead") -> Dict:
    """The consistent cut: the newest step at which EVERY previous member
    holds a sealed, checksum-verified slab entry of one generation, with
    every victim's entry generation-fenced against its dead marker and
    every victim's ring buddy still alive (the buddy is the host
    answering for the replica; buddy-and-victim double-kill therefore
    falls back to the durable checkpoint by design).

    Returns ``{"step", "generation", "victims": {victim: buddy},
    "entries": {host: entry}}``; raises :class:`ReplicaAdoptionError` on
    any reason adoption must not proceed."""
    prev = sorted(prev_hosts)
    victims = sorted(set(dead) & set(prev))
    if not victims:
        raise ReplicaAdoptionError("no victim among the previous "
                                   "membership — nothing to adopt")
    survivors = [h for h in prev if h not in set(dead)]
    ring = buddy_ring(prev)
    fence = 0
    buddies: Dict[str, str] = {}
    for v in victims:
        buddy = ring.get(v)
        if buddy is None or buddy in set(dead):
            raise ReplicaAdoptionError(
                f"victim {v!r}'s ring buddy {buddy!r} is dead too — its "
                "replica RAM died with it (double-kill)")
        buddies[v] = buddy
        marker = store.get(f"{dead_prefix}/{v}")
        if marker is not None:
            fence = max(fence, int(marker.get("generation", 0)))
    if not survivors:
        raise ReplicaAdoptionError("no survivor remains to adopt")
    docs: Dict[str, Dict] = {}
    for h in prev:
        doc = read_replica(store, h)
        if doc is None or not doc.get("entries"):
            raise ReplicaAdoptionError(
                f"host {h!r} has no published replica slab — the pod "
                "never sealed (or the store lost the doc)")
        docs[h] = doc
    # verified (step, generation) candidates per host, fence applied
    verified: Dict[str, Dict[int, Dict]] = {}
    for h, doc in docs.items():
        ok: Dict[int, Dict] = {}
        for e in doc.get("entries", ()):
            if int(e.get("generation", -1)) < fence:
                continue   # slab of a pre-death incarnation: fenced out
            try:
                verify_entry(e)
            except ReplicaIntegrityError as ie:
                logger.warning(
                    "pod adopt: %s slab entry at step %s fails "
                    "verification (%s); trying an older seal", h,
                    e.get("step"), ie)
                continue
            ok[int(e["step"])] = e
        verified[h] = ok
    common = set.intersection(*(set(v) for v in verified.values())) \
        if verified else set()
    cuts = sorted(common, reverse=True)
    for step in cuts:
        gens = {int(verified[h][step]["generation"]) for h in prev}
        if len(gens) == 1:
            return {"step": step, "generation": gens.pop(),
                    "victims": buddies,
                    "entries": {h: verified[h][step] for h in prev}}
    raise ReplicaAdoptionError(
        "no consistent cut: no step is sealed+verified by every previous "
        f"member within the generation fence (fence {fence}; per-host "
        f"steps: { {h: sorted(v) for h, v in verified.items()} })")


def claim_adoption(store: CoordinationStore, generation: int, victim: str,
                   adopter: str, step: int, slab_generation: int,
                   dead_prefix: str = "dead") -> bool:
    """CAS-create ``pod/adopt/gen<g>/<victim>`` — the at-most-one-adopter
    fence: exactly one survivor wins the right to reconstruct a victim's
    shards in a round (checked after the fact by tools/store_check.py's
    replica rules).  Returns False when another adopter already holds
    the claim for this round."""
    marker = store.get(f"{dead_prefix}/{victim}")
    key = f"{POD_ADOPT_PREFIX}/gen{int(generation)}/{victim}"
    doc = {
        "victim": victim,
        "adopter": adopter,
        "step": int(step),
        "slab_generation": int(slab_generation),
        "dead_generation": int((marker or {}).get("generation", 0)),
        "t": store.now(),
    }
    maybe_fire(SITE_POD_ADOPT, victim=victim, adopter=adopter, step=step)

    def attempt():
        cur = store.get(key)
        if cur is not None:
            return bool(cur.get("adopter") == adopter)
        if store.compare_and_swap(key, None, doc):
            return True
        return StoreRetryPolicy.RETRY

    return bool(default_retry_policy().run(
        f"claim_adoption({victim!r})", attempt))


def adopt_replicas(store: CoordinationStore, engine,
                   prev_hosts: Sequence[str], dead: Sequence[str],
                   generation: int, host_id: str,
                   dead_prefix: str = "dead") -> int:
    """The live-adoption path, end to end: plan the consistent cut, claim
    every victim for its buddy, re-ingest this host's own slab into the
    engine, and return the step training resumes FROM (the cut; the next
    trained step is cut+1).  Raises :class:`ReplicaAdoptionError` when
    any stage says the replicas cannot carry the round — the caller falls
    back loudly to the checkpoint walk."""
    global _ADOPTIONS_TOTAL
    with trace_span("pod.adopt", host=host_id, generation=int(generation)):
        try:
            plan = plan_adoption(store, prev_hosts, dead,
                                 dead_prefix=dead_prefix)
        except (StoreUnavailable, OSError) as e:
            raise ReplicaAdoptionError(
                f"store unreachable while planning adoption: {e}") from e
        for victim, buddy in sorted(plan["victims"].items()):
            try:
                claimed = claim_adoption(store, generation, victim, buddy,
                                         plan["step"], plan["generation"],
                                         dead_prefix=dead_prefix)
            except (StoreUnavailable, OSError) as e:
                raise ReplicaAdoptionError(
                    f"store unreachable while claiming {victim!r}: "
                    f"{e}") from e
            if not claimed:
                raise ReplicaAdoptionError(
                    f"victim {victim!r} is already claimed by another "
                    f"adopter this round (generation {generation})")
        own = plan["entries"].get(host_id)
        if own is None:
            raise ReplicaAdoptionError(
                f"host {host_id!r} holds no slab at the cut step "
                f"{plan['step']}")
        payload = verify_entry(own)
        try:
            resumed = int(engine.replica_ingest(payload))
        except Exception as e:
            raise ReplicaAdoptionError(
                f"re-ingest of the step-{plan['step']} slab failed "
                f"({type(e).__name__}: {e})") from e
        if resumed != int(plan["step"]):
            raise ReplicaAdoptionError(
                f"slab claimed step {plan['step']} but ingested state is "
                f"at step {resumed}")
        with _TOTALS_LOCK:
            _ADOPTIONS_TOTAL += 1
        log_dist(
            f"pod adopt: live-state adoption at step {plan['step']} "
            f"(generation {generation}; victims "
            f"{sorted(plan['victims'])}, buddies serve the replicas) — "
            "zero checkpoint rollback", ranks=[0])
        return resumed
