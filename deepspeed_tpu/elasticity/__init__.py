"""Elastic training (reference ``deepspeed/elasticity/``): batch-size plans
that stay valid across device-count changes, plus a preemption-aware agent."""
from .elasticity import (  # noqa: F401
    DEEPSPEED_ELASTICITY_CONFIG,
    ElasticityError,
    ElasticityConfigError,
    ElasticityIncompatibleWorldSize,
    ElasticPlan,
    compute_elastic_config,
    elasticity_enabled,
    ensure_immutable_elastic_config,
    pick_micro_batch,
    plan_elastic_batch,
    valid_device_counts,
)
from .elastic_agent import (  # noqa: F401
    ElasticAgent,
    PreemptionGuard,
    resolve_plan_for_current_world,
)
from .supervisor import RC_COMPLETE, RC_INTERRUPT, Supervisor  # noqa: F401
from .coordination import (  # noqa: F401
    CoordinationStore,
    CoordinatorLease,
    FileCoordinationStore,
    HeartbeatWatchdog,
    HostLease,
    PodCoordinationError,
    PodRendezvousTimeout,
    RC_POD_PEER_LOST,
    beat,
    bump_generation,
    clear_dead,
    dead_hosts,
    dead_set,
    elect_coordinator,
    lease_table,
    read_coordinator,
    read_generation,
    record_dead,
    rendezvous,
    resign_coordinator,
)
from .pod_agent import (  # noqa: F401
    PodContext,
    PodElasticAgent,
    PodPeerLost,
    PodRound,
    PodSupervisor,
    RC_POD_UNRECOVERABLE,
    pending_commit,
    save_pod_checkpoint,
    shrink_to_healthy,
)
