"""Elastic training: batch-size planning that survives device-count changes.

Parity target: reference ``deepspeed/elasticity/elasticity.py:27-233``
(``compute_elastic_config`` and friends).  The goal is identical — pick ONE
global batch size that (a) stays under a ceiling, (b) is reachable from an
allowed micro-batch size at as many different device counts as possible, so a
job can be stopped and resumed on a different slice without changing its
effective hyperparameters.

The algorithm here is NOT the reference's: the reference scales LCM/micro-batch
bases by a table of highly-composite numbers and brute-forces the winners.  We
do an exact search instead — every feasible global batch size is ``mb * k`` for
an allowed ``mb``, so the candidate set is small (≤ sum(max_batch/mb)) and each
candidate can be scored exactly by counting the device counts it admits
(divisors of its slot count).  NOTE: like the reference, raw divisor-count
scoring favors highly-composite batches; on TPU, where real slice shapes are
powers of two (8, 16, 32, …), set ``min_gpus`` to the smallest slice you will
actually run so the score only counts reachable device counts.

Runtime entanglement mirrors the reference: ``DeepSpeedConfig`` calls
``compute_elastic_config`` when ``elasticity.enabled`` and derives the batch
triad from the CURRENT world size; ``ensure_immutable_elastic_config`` guards
against the resource scheduler and the runtime disagreeing about the elastic
envelope (reference :204-224, env var ``DEEPSPEED_ELASTICITY_CONFIG``).
"""
from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

DEEPSPEED_ELASTICITY_CONFIG = "DEEPSPEED_ELASTICITY_CONFIG"


class ElasticityError(Exception):
    """Base error for the elasticity subsystem."""


class ElasticityConfigError(ElasticityError):
    """Elastic config is malformed or inconsistent with the scheduler's."""


class ElasticityIncompatibleWorldSize(ElasticityError):
    """The current device count cannot run the planned elastic batch."""


def _divisors(n: int) -> List[int]:
    small, large = [], []
    d = 1
    while d * d <= n:
        if n % d == 0:
            small.append(d)
            if d != n // d:
                large.append(n // d)
        d += 1
    return small + large[::-1]


def valid_device_counts(batch_size: int, micro_batches: Sequence[int],
                        min_devices: int = 1,
                        max_devices: Optional[int] = None) -> List[int]:
    """All device counts that can train ``batch_size`` exactly.

    A count ``d`` works if some allowed micro-batch ``mb`` divides the batch
    and ``d`` divides the slot count ``batch_size // mb`` (the leftover factor
    becomes gradient accumulation).  Mirrors reference ``get_valid_gpus``
    semantics with an exact divisor enumeration instead of a factor scan.
    """
    max_devices = max_devices or batch_size
    counts = set()
    for mb in micro_batches:
        if mb <= 0 or batch_size % mb:
            continue
        slots = batch_size // mb  # = devices × gradient_accumulation_steps
        for d in _divisors(slots):
            if min_devices <= d <= max_devices:
                counts.add(d)
    return sorted(counts)


def plan_elastic_batch(micro_batches: Sequence[int],
                       max_batch_size: int,
                       min_devices: int = 1,
                       max_devices: Optional[int] = None,
                       prefer_larger: bool = True) -> Tuple[int, List[int]]:
    """Choose the global batch size with the most compatible device counts.

    Exact search over every feasible batch (multiples of each allowed
    micro-batch up to the ceiling); ties break toward the larger (or smaller,
    per ``prefer_larger``) batch.  Returns (batch_size, sorted device counts).
    """
    micro_batches = sorted(set(int(m) for m in micro_batches))
    if not micro_batches:
        raise ElasticityConfigError("micro_batch_sizes must be non-empty")
    if any(m <= 0 for m in micro_batches):
        raise ElasticityConfigError(
            f"micro_batch_sizes must be positive, got {micro_batches}")
    if micro_batches[0] > max_batch_size:
        raise ElasticityConfigError(
            f"smallest micro-batch {micro_batches[0]} exceeds "
            f"max_train_batch_size {max_batch_size}")
    candidates = set()
    for mb in micro_batches:
        candidates.update(mb * k for k in range(1, max_batch_size // mb + 1))

    best: Tuple[int, int, List[int]] = (-1, 0, [])
    for batch in candidates:
        counts = valid_device_counts(batch, micro_batches, min_devices,
                                     max_devices)
        if not counts:
            continue
        key = (len(counts), batch if prefer_larger else -batch)
        if key > (best[0], best[1]):
            best = (len(counts), batch if prefer_larger else -batch, counts)
    if best[0] < 0:
        raise ElasticityConfigError(
            f"no batch size ≤ {max_batch_size} admits a device count in "
            f"[{min_devices}, {max_devices}] with micro-batches {micro_batches}")
    batch = best[1] if prefer_larger else -best[1]
    return batch, best[2]


def pick_micro_batch(batch_size: int, micro_batches: Sequence[int],
                     dp_world_size: int, prefer_larger: bool = True) -> int:
    """Micro-batch for the CURRENT world size: the per-device slot count
    ``batch_size / dp`` must be a multiple of the chosen micro-batch (the
    remainder is gradient accumulation)."""
    if batch_size % dp_world_size:
        raise ElasticityIncompatibleWorldSize(
            f"world size {dp_world_size} does not divide the elastic batch "
            f"size {batch_size}")
    per_device = batch_size // dp_world_size
    fits = [mb for mb in micro_batches if per_device % mb == 0]
    if not fits:
        raise ElasticityIncompatibleWorldSize(
            f"no allowed micro-batch divides batch/world = {per_device} "
            f"(micro_batches={list(micro_batches)})")
    return max(fits) if prefer_larger else min(fits)


@dataclasses.dataclass(frozen=True)
class ElasticPlan:
    """The resolved elastic schedule for one (config, world size) pair."""
    train_batch_size: int
    micro_batch_per_device: int
    gradient_accumulation_steps: int
    valid_device_counts: Tuple[int, ...]

    def as_triad(self) -> Tuple[int, int, int]:
        return (self.train_batch_size, self.micro_batch_per_device,
                self.gradient_accumulation_steps)


def compute_elastic_config(elastic_config, dp_world_size: int = 0,
                           node_size: int = 1,
                           model_parallel_size: int = 1) -> ElasticPlan:
    """Resolve the elastic plan (reference ``compute_elastic_config``:233).

    ``elastic_config`` is the pydantic ``ElasticityConfig`` block.  With
    ``version >= 0.2`` the plan is computed at node granularity: device counts
    step by whole hosts of ``node_size`` chips and the data-parallel degree
    per node is ``node_size / model_parallel_size`` (reference
    ``_get_compatible_gpus_v02``).  ``dp_world_size == 0`` plans without
    binding to a world size (scheduler-side use).
    """
    ec = elastic_config
    if not ec.enabled:
        raise ElasticityConfigError("elasticity is not enabled in the config")
    if ec.max_gpus < ec.min_gpus or ec.min_gpus < 1:
        raise ElasticityConfigError(
            f"bad device range [{ec.min_gpus}, {ec.max_gpus}]")

    if ec.version >= 0.2:
        if node_size % model_parallel_size:
            raise ElasticityConfigError(
                f"node size {node_size} must be divisible by model-parallel "
                f"size {model_parallel_size}")
        dp_per_node = node_size // model_parallel_size
        per_node_batch, node_counts = plan_elastic_batch(
            ec.micro_batch_sizes,
            max(1, ec.max_train_batch_size // dp_per_node),
            max(1, -(-ec.min_gpus // node_size)),  # ceil: never under the floor
            max(1, ec.max_gpus // node_size),
            ec.prefer_larger_batch)
        batch = per_node_batch * dp_per_node
        counts = [c * dp_per_node for c in node_counts]
    else:
        batch, counts = plan_elastic_batch(
            ec.micro_batch_sizes, ec.max_train_batch_size,
            ec.min_gpus, ec.max_gpus, ec.prefer_larger_batch)

    if dp_world_size <= 0:
        return ElasticPlan(batch, 0, 0, tuple(counts))

    if dp_world_size not in counts:
        raise ElasticityIncompatibleWorldSize(
            f"current data-parallel world size {dp_world_size} is not among "
            f"the elastic-compatible counts {counts} for batch {batch}")
    micro = pick_micro_batch(batch, ec.micro_batch_sizes, dp_world_size,
                             ec.prefer_larger_batch)
    gas = batch // (micro * dp_world_size)
    return ElasticPlan(batch, micro, gas, tuple(counts))


def elasticity_enabled(ds_config: Dict) -> bool:
    return bool(ds_config.get("elasticity", {}).get("enabled", False))


def ensure_immutable_elastic_config(runtime_elastic_config_dict: Dict) -> None:
    """The resource scheduler snapshots the elastic envelope into
    ``DEEPSPEED_ELASTICITY_CONFIG``; the runtime's copy must agree on the
    fields that determine the batch plan, else resumed jobs silently train
    with a different effective batch (reference :204-224)."""
    raw = os.environ.get(DEEPSPEED_ELASTICITY_CONFIG)
    if raw is None:
        return
    sched = json.loads(raw)
    for key in ("max_train_batch_size", "micro_batch_sizes", "version"):
        if key in sched and key in runtime_elastic_config_dict and \
                sched[key] != runtime_elastic_config_dict[key]:
            raise ElasticityConfigError(
                f"elastic config mismatch between scheduler and runtime on "
                f"'{key}': {sched[key]} != {runtime_elastic_config_dict[key]}")
