"""Pod coordination: heartbeat leases, generation counter, rendezvous.

Multi-host TPU training has no failure story without a side channel: a host
that dies mid-collective leaves its peers wedged in native code with no
exception, and the launcher's supervisor cannot tell a transient crash from
a permanently lost host.  This module is that side channel — a tiny
coordination layer in the spirit of Bamboo (NSDI '23) and Oobleck
(SOSP '23)-style elastic recovery, built on one deliberately small
abstraction:

:class:`CoordinationStore`
    A namespaced key -> JSON-document store with atomic replace and an
    atomic :meth:`~CoordinationStore.compare_and_swap`.  The production
    deployment backs it with storage every host already shares
    (the checkpoint filesystem / a coordinator-host export); tests and
    single-node soaks use the same :class:`FileCoordinationStore` on a
    tmpdir.  Nothing here imports jax — the layer must stay usable from
    the launcher before any device runtime exists.

On top of it, four protocols:

- **Heartbeats with leases** (:func:`beat` / :func:`lease_table` /
  :func:`dead_hosts`): each host renews a lease document stamped with the
  store clock; a host whose newest beat is older than ``miss_limit``
  lease periods is *dead by lease*.  :class:`HeartbeatWatchdog` runs the
  renew/scan loop on a daemon thread and reports the first dead peer so
  the training process can exit with :data:`RC_POD_PEER_LOST` instead of
  hanging in the next collective.
- **Pod generation** (:func:`read_generation` / :func:`bump_generation`):
  a monotonically increasing integer identifying one membership epoch.
  Every relaunch round bumps it; heartbeats, rendezvous records, dead-host
  markers and pod checkpoint manifests all carry it, so state from a
  previous incarnation can never be mistaken for the current round's.
  The bump is a compare-and-swap loop: concurrent bumpers (two supervisor
  rounds racing, a deposed coordinator racing its successor) each win a
  distinct round — no lost update, no torn document.
- **Rendezvous** (:func:`rendezvous`): hosts of a generation register and
  wait until the expected membership is present (or a timeout raises
  :class:`PodRendezvousTimeout`) — the barrier the pod supervisor uses to
  re-form the job after a shrink.
- **Coordinator election** (:func:`elect_coordinator` /
  :func:`read_coordinator`): a lease-based leader lock, CAS on one
  coordinator key.  A candidate acquires a vacant or LAPSED lease with a
  bumped ``term``, and the incumbent renews by CAS-ing its own document —
  so exactly one leader holds any term, and losing the coordinator only
  costs one lease worth of time before a standby takes over.  This is
  what removes the "coordinator host is never failed over" gap: the pod
  supervisor round and the serving fleet router
  (``inference/fleet.py``) both run under it.

The lease/dead-marker helpers take a ``prefix`` so independent tiers share
one implementation without sharing a namespace: pods lease under
``heartbeat/`` + ``dead/`` (the defaults), serving-fleet engines under
``fleet/heartbeat`` + ``fleet/dead``.

Fault sites ``pod.heartbeat`` and ``pod.rendezvous`` hook the two live
paths so chaos tests can kill leases and wedge rendezvous deterministically
(resilience/fault_injection.py).  See docs/POD.md.
"""
from __future__ import annotations

import json
import os
import random
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from ..resilience.fault_injection import (SITE_FLEET_CHANNEL,
                                          SITE_POD_HEARTBEAT,
                                          SITE_POD_RENDEZVOUS, maybe_fire)
from ..utils.logging import logger

# exit code a host uses when the heartbeat watchdog declares a peer dead:
# distinct from RC_HANG (85, watchdog) so the supervisor can tell "my own
# step wedged" from "a peer's lease expired and I exited to re-form"
RC_POD_PEER_LOST = 87


class PodCoordinationError(RuntimeError):
    """Base error for the pod coordination layer."""


class PodRendezvousTimeout(PodCoordinationError):
    """Rendezvous did not reach the expected membership in time."""


class StoreUnavailable(PodCoordinationError):
    """The coordination store is unreachable for THIS client — a
    blackout/partition, or a retry discipline that exhausted its
    deadline.  The graceful-degradation signal, not a retry signal:
    clients catch it and degrade (a member daemon buffers its outbox
    and keeps decoding, a router parks admission, a watchdog counts a
    failed scan instead of declaring peers dead) rather than spinning
    against a store that is gone.  :class:`StoreRetryPolicy` NEVER
    retries it — transient flakiness is ``OSError``; this is "stop
    asking" (docs/FLEET.md "Store brownouts and partitions")."""


class CoordinationStore:
    """Namespaced key -> JSON document store with atomic replace.

    Keys are ``/``-separated paths (``heartbeat/host3``,
    ``rendezvous/gen2/host0``).  Semantics the protocols rely on:

    - :meth:`put` replaces atomically — a reader never observes a torn
      document;
    - :meth:`compare_and_swap` replaces atomically ONLY when the current
      document equals ``expected`` (``None`` = key absent) — the primitive
      the generation bump, dead markers and coordinator election build on;
    - :meth:`list` returns the child names directly under a prefix;
    - there is no watch/subscribe: every consumer polls, which keeps the
      file backend honest and the test clock injectable.
    """

    def put(self, key: str, value: Dict) -> None:
        raise NotImplementedError

    def get(self, key: str) -> Optional[Dict]:
        raise NotImplementedError

    def compare_and_swap(self, key: str, expected: Optional[Dict],
                         new: Dict) -> bool:
        """Write ``new`` iff the current document equals ``expected``
        (``None`` = the key must be absent); returns whether the swap won.
        This base implementation is a plain read-compare-write — correct
        only under a single writer.  Real backends MUST override it with
        an atomic version (``FileCoordinationStore`` locks per key); it
        exists so a minimal duck-typed store still runs the protocols."""
        if self.get(key) != expected:
            return False
        self.put(key, new)
        return True

    def delete(self, key: str) -> None:
        raise NotImplementedError

    def compare_and_delete(self, key: str, expected: Dict) -> bool:
        """Delete ``key`` iff the current document equals ``expected``,
        leaving a TOMBSTONE that blocks a later create
        (``compare_and_swap(key, None, ...)``) until it is cleared or
        expires — the fenced GC primitive (docs/FLEET.md "Journal GC"):
        a leader stalled past its election lease holds a stale
        ``expected`` and can never delete an entry its successor
        re-stamped, and its stale appends cannot resurrect an entry the
        live owner already collected.  ``expected`` must not be ``None``
        (deleting an absent key is a plain :meth:`delete`).

        This base implementation is read-compare-delete with no lock and
        no tombstone — correct only under a single writer, exactly like
        the base :meth:`compare_and_swap` it mirrors.  Real backends MUST
        override it atomically (``FileCoordinationStore`` serializes
        through the same per-key lock file its CAS uses)."""
        if expected is None:
            raise ValueError(
                "compare_and_delete: expected must be a document, not None")
        if self.get(key) != expected:
            return False
        self.delete(key)
        return True

    def clear_tombstone(self, key: str) -> None:
        """Drop the tombstone a :meth:`compare_and_delete` left on
        ``key`` so a create can land again — the escape hatch for a
        caller that KNOWS the key's next writer is legitimate (e.g. a
        fresh submission reusing a collected rid).  Base stores keep no
        tombstones; this is a no-op there."""

    def list(self, prefix: str) -> List[str]:
        raise NotImplementedError

    def now(self) -> float:
        """The store clock — ``time.time`` by default so stamps are
        comparable across hosts sharing the backend; tests inject a fake
        clock for deterministic lease-expiry coverage."""
        return time.time()


class FileCoordinationStore(CoordinationStore):
    """File-backed store: one JSON file per key under ``root``.

    Deployment target is storage all hosts of the pod already mount (the
    checkpoint filesystem or a coordinator-host export); tests point it at
    a tmpdir.  Atomicity is write-to-tmp + ``os.replace`` — the same
    discipline as the checkpoint manifests.  The tmp name carries pid and
    thread id so concurrent writers (simulated hosts are threads) never
    collide on it.

    :meth:`compare_and_swap` serializes writers through a per-key
    ``<key>.lock`` file created ``O_CREAT|O_EXCL`` — atomic on every
    filesystem the store targets, across threads AND processes.  A lock
    orphaned by a writer that died mid-CAS is broken after
    ``lock_stale_s`` (the readers-never-block property is preserved:
    ``get``/``list`` ignore locks entirely).
    """

    def __init__(self, root: str, clock: Optional[Callable[[], float]] = None,
                 cas_timeout_s: float = 10.0, lock_stale_s: float = 5.0,
                 tombstone_ttl_s: float = 300.0):
        self.root = str(root)
        os.makedirs(self.root, exist_ok=True)
        self._clock = clock
        # the CAS wait must be able to OUTLIVE the stale-lock window, or a
        # lock orphaned by a SIGKILLed writer turns every later CAS on the
        # key into a timeout error instead of one stolen lock (the breaker
        # would be unreachable within a single call)
        self.cas_timeout_s = max(float(cas_timeout_s),
                                 float(lock_stale_s) + 1.0)
        self.lock_stale_s = float(lock_stale_s)
        # tombstones left by compare_and_delete expire after this long
        # (wall clock, like the stale-lock breaker): fencing windows are
        # election-lease-sized, so a tombstone old enough to outlive every
        # deposed writer is pure debris
        self.tombstone_ttl_s = float(tombstone_ttl_s)
        # CAS acquisitions that found the per-key lock held at least once
        # (the fleet/store_cas_contended_total gauge): N routers racing
        # one key show up here long before latency does
        self.cas_contended_total = 0
        # torn/corrupt documents quarantined aside by get() (the
        # store/corrupt_docs_total gauge): every one of these is a
        # writer that bypassed the tmp+rename discipline (or storage
        # corruption) — it must be visible, never silently "absent"
        self.corrupt_docs_total = 0

    def _path(self, key: str) -> str:
        key = key.strip("/")
        if not key or ".." in key.split("/"):
            raise ValueError(f"bad coordination key {key!r}")
        return os.path.join(self.root, *key.split("/"))

    def put(self, key: str, value: Dict) -> None:
        from ..resilience.integrity import _atomic_write_json

        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        _atomic_write_json(path, value)

    def get(self, key: str) -> Optional[Dict]:
        path = self._path(key)   # key validation errors must not be eaten
        try:
            with open(path) as f:
                return json.load(f)
        except FileNotFoundError:
            return None
        except ValueError as e:
            # TORN/CORRUPT document (our own writes are tmp+atomic-rename,
            # so this is a foreign writer that skipped the discipline, or
            # real storage corruption).  Silently reading it as "absent"
            # used to let a CAS create clobber whatever the key held and
            # made torn-write-recovered indistinguishable from lost —
            # quarantine the bytes aside (numbered, never clobbering an
            # earlier quarantine), count it, and ONLY then report absent:
            # the checker and the gauge can now tell the two apart.
            self.corrupt_docs_total += 1
            quarantined = self._quarantine_corrupt(path)
            logger.error(
                "coordination store: corrupt document at key %s (%s); "
                "quarantined to %s", key, e, quarantined or "<unmovable>")
            return None
        except OSError as e:
            # the backend itself failed (not "no such key"): this client
            # cannot tell what the key holds, and "absent" would be a
            # LIE that cascades — a lease scan would declare live peers
            # dead, a CAS create would fence-break.  Degrade typed.
            raise StoreUnavailable(
                f"coordination store: backend read of {key!r} failed "
                f"({e})") from e

    @staticmethod
    def _quarantine_corrupt(path: str) -> Optional[str]:
        """Move a corrupt document aside as ``<path>.corrupt[.N]`` (the
        numbered-collision discipline of ``integrity.quarantine_tag``);
        returns the quarantine path, or None when the rename failed."""
        dst = path + ".corrupt"
        n = 0
        while os.path.exists(dst):
            n += 1
            dst = f"{path}.corrupt.{n}"
        try:
            os.replace(path, dst)
            return dst
        except OSError:   # pragma: no cover - racing quarantines
            return None

    def _acquire_lock(self, key: str, path: str,
                      what: str) -> Tuple[int, int, str]:
        """Take the per-key ``<key>.lock`` (O_CREAT|O_EXCL — atomic across
        threads AND processes), spinning with jittered exponential backoff
        under contention: N routers racing one hot key (the admission
        partition table, the election key) must degrade into staggered
        retries, not a synchronized hot-spin that keeps re-colliding at
        the same instants.  Returns ``(fd, inode, lock_path)``; the caller
        must release via :meth:`_release_lock`."""
        lock = path + ".lock"
        deadline = time.monotonic() + self.cas_timeout_s
        attempt = 0
        while True:
            try:
                fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                return fd, os.fstat(fd).st_ino, lock
            except FileExistsError:
                if attempt == 0:
                    # counted once per contended ACQUISITION, not per spin:
                    # the gauge answers "how often do writers collide", not
                    # "how long did they wait"
                    self.cas_contended_total += 1
                attempt += 1
                try:
                    # break a lock orphaned by a writer that died holding
                    # it (wall-clock mtime: the injectable store clock must
                    # not make a live lock look ancient).  The steal is an
                    # atomic RENAME to a waiter-unique name: of N waiters
                    # that all observed the same stale lock, exactly one
                    # rename succeeds — a bare remove here would let a
                    # second waiter delete the FIRST waiter's freshly
                    # re-created lock and put two writers inside the
                    # critical section (split-brain CAS).
                    if time.time() - os.path.getmtime(lock) > self.lock_stale_s:
                        stolen = (f"{lock}.stale.{os.getpid()}"
                                  f".{threading.get_ident()}")
                        os.rename(lock, stolen)
                        os.remove(stolen)
                        continue
                except OSError:
                    pass   # the holder released it (or another waiter
                           # stole it) between the two calls
                if time.monotonic() >= deadline:
                    raise PodCoordinationError(
                        f"{what}({key!r}): lock {lock} held for "
                        f"over {self.cas_timeout_s:.1f}s — a writer is "
                        "wedged or the stale-lock breaker is disabled")
                # full jitter on an exponentially growing ceiling (capped
                # well under the lease scale): waiters desynchronize, and
                # the first retry stays ~instant for the common
                # two-writers-once case
                cap = min(0.02, 0.0005 * (1 << min(attempt, 6)))
                time.sleep(random.uniform(0.0001, cap))

    @staticmethod
    def _release_lock(fd: int, my_ino: int, lock: str) -> None:
        os.close(fd)
        try:
            # ownership-checked release: if a waiter stale-stole OUR
            # lock (we stalled past lock_stale_s inside this critical
            # section), the file at `lock` is now the stealer's —
            # removing it blindly would admit yet another writer.  The
            # stale threshold (seconds) vs the ms-long critical section
            # makes a steal-from-live vanishingly rare, but the release
            # must not widen it into a cascade.
            if os.stat(lock).st_ino == my_ino:
                os.remove(lock)
        except OSError:   # pragma: no cover - breaker raced us
            pass

    def _tomb_path(self, path: str) -> str:
        return path + ".tomb"

    def _tombstone_blocks(self, path: str) -> bool:
        """Whether a LIVE tombstone sits on ``path`` (expired ones are
        reaped in passing — debris, not a fence; the TTL is wall-clock
        like the stale-lock breaker, and far beyond any election lease)."""
        tomb = self._tomb_path(path)
        try:
            if time.time() - os.path.getmtime(tomb) <= self.tombstone_ttl_s:
                return True
            os.remove(tomb)
        except OSError:
            pass
        return False

    def compare_and_swap(self, key: str, expected: Optional[Dict],
                         new: Dict) -> bool:
        from ..resilience.integrity import _atomic_write_json

        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fd, my_ino, lock = self._acquire_lock(key, path, "compare_and_swap")
        try:
            if self.get(key) != expected:
                return False
            if expected is None and self._tombstone_blocks(path):
                # a compare_and_delete fenced this key: a create here is by
                # definition a writer that did not see the delete (the
                # deposed leader's stale append / create retry) — blocked
                # until clear_tombstone or the TTL says no deposed writer
                # can still be alive
                return False
            _atomic_write_json(path, new)
            return True
        finally:
            self._release_lock(fd, my_ino, lock)

    def compare_and_delete(self, key: str, expected: Dict) -> bool:
        if expected is None:
            raise ValueError(
                "compare_and_delete: expected must be a document, not None")
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fd, my_ino, lock = self._acquire_lock(key, path, "compare_and_delete")
        try:
            if self.get(key) != expected:
                return False
            # tombstone FIRST, then remove: a crash between the two leaves
            # the key both present and fenced — the next compare_and_delete
            # with the same expected finishes the job, and no create can
            # slip into the gap
            with open(self._tomb_path(path), "w", encoding="utf-8") as fh:
                json.dump({"t": self.now()}, fh)
            try:
                os.remove(path)
            except FileNotFoundError:   # pragma: no cover - defensive
                pass
            return True
        finally:
            self._release_lock(fd, my_ino, lock)

    def clear_tombstone(self, key: str) -> None:
        try:
            os.remove(self._tomb_path(self._path(key)))
        except OSError:
            pass

    def list(self, prefix: str) -> List[str]:
        try:
            names = os.listdir(self._path(prefix))
        except (FileNotFoundError, NotADirectoryError):
            return []
        # tmp siblings, CAS lock files (incl. `<key>.lock.stale.*`
        # rename-steal remnants of a waiter that died mid-steal),
        # compare-delete tombstones and quarantined corrupt documents
        # (`<key>.corrupt[.N]`) are write-protocol artifacts, never
        # documents.  Match the exact artifact shapes, not a bare ".lock"
        # substring — a legitimate id like "db.lockhart-3" must stay
        # visible to lease/dead scans.
        return sorted(n for n in names
                      if ".tmp." not in n and not n.endswith(".lock")
                      and ".lock.stale." not in n
                      and not n.endswith(".tomb")
                      and not n.endswith(".corrupt")
                      and ".corrupt." not in n)

    def delete(self, key: str) -> None:
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass

    def now(self) -> float:
        return self._clock() if self._clock is not None else time.time()


# ------------------------------------------------------------- retry policy

# process-wide count of store-op retries taken through StoreRetryPolicy
# (CAS losses re-attempted + transient errors absorbed) — the single
# number behind the fleet/store_retries_total gauge, whatever mix of
# policy instances a process runs
_STORE_RETRIES_LOCK = threading.Lock()
_STORE_RETRIES_TOTAL = 0


def store_retries_total() -> int:
    """Process-wide retries taken by every :class:`StoreRetryPolicy`
    (the ``fleet/store_retries_total`` gauge reads this)."""
    return _STORE_RETRIES_TOTAL


class StoreRetryPolicy:
    """The one retry discipline for store protocol loops: jittered
    exponential backoff under a wall-clock deadline, store-agnostic.

    Replaces the ad-hoc bare ``while True`` CAS loops that used to live
    in :func:`bump_generation`, :func:`channel_append`, the journal
    flush and the partition claims — each of which would spin forever
    (and hot) against a store that stopped answering.  Two retryable
    outcomes, one terminal one:

    - the attempt returns :data:`RETRY` (a lost CAS: re-read, try
      again) — retried with backoff;
    - the attempt raises ``OSError`` (transient backend flakiness,
      injected or real) — retried with backoff;
    - the attempt raises :class:`StoreUnavailable` (blackout/partition)
      — **propagated immediately**: a dark store must fail FAST into
      the caller's degradation path (outbox, parked admission), not
      stall it for the full deadline.

    Past ``deadline_s`` of wall time the policy raises
    :class:`StoreUnavailable` itself — the per-op deadline wrapper the
    degradation contracts are written against.  Every retry counts into
    :func:`store_retries_total` and the instance's ``retries_total``.
    """

    RETRY = object()   # sentinel an attempt returns to request another try

    def __init__(self, deadline_s: float = 10.0, base_s: float = 0.0005,
                 cap_s: float = 0.02, seed: Optional[int] = None,
                 sleep: Callable[[float], None] = time.sleep):
        self.deadline_s = float(deadline_s)
        self.base_s = float(base_s)
        self.cap_s = float(cap_s)
        self.retries_total = 0
        self._rng = random.Random(seed)
        self._sleep = sleep

    def run(self, what: str, attempt: Callable[[], object]):
        """Call ``attempt()`` until it returns a non-:data:`RETRY` value
        (returned), raising :class:`StoreUnavailable` at the deadline."""
        global _STORE_RETRIES_TOTAL
        deadline = time.monotonic() + self.deadline_s
        attempts = 0
        err: Optional[BaseException] = None
        while True:
            try:
                out = attempt()
                if out is not StoreRetryPolicy.RETRY:
                    return out
            except StoreUnavailable:
                raise
            except OSError as e:
                err = e
            attempts += 1
            self.retries_total += 1
            with _STORE_RETRIES_LOCK:
                _STORE_RETRIES_TOTAL += 1
            if time.monotonic() >= deadline:
                raise StoreUnavailable(
                    f"{what}: no successful store op within "
                    f"{self.deadline_s:.1f}s ({attempts} attempt(s); "
                    f"last error: {err!r})") from err
            # full jitter on an exponentially growing ceiling — the same
            # shape as the file store's lock spin, but store-agnostic
            cap = min(self.cap_s, self.base_s * (1 << min(attempts, 6)))
            self._sleep(self._rng.uniform(0.0, cap))


_DEFAULT_RETRY: Optional[StoreRetryPolicy] = None


def default_retry_policy() -> StoreRetryPolicy:
    """The module-shared policy behind :func:`bump_generation`,
    :func:`record_dead`, :func:`channel_append` and friends — one
    instance, so the protocol helpers stay zero-config."""
    global _DEFAULT_RETRY
    if _DEFAULT_RETRY is None:
        _DEFAULT_RETRY = StoreRetryPolicy()
    return _DEFAULT_RETRY


# --------------------------------------------------------------- heartbeats

@dataclass(frozen=True)
class HostLease:
    """One host's newest heartbeat as seen through the store."""
    host_id: str
    generation: int
    beat_t: float          # store-clock stamp of the newest beat
    lease_s: float         # the period the host promised to renew within
    attrs: Dict

    def age(self, now: float) -> float:
        return max(0.0, now - self.beat_t)

    def missed(self, now: float) -> float:
        """Lease periods elapsed since the newest beat (0.0 = fresh)."""
        return self.age(now) / self.lease_s if self.lease_s > 0 else 0.0


def beat(store: CoordinationStore, host_id: str, generation: int,
         lease_s: float, prefix: str = "heartbeat", **attrs) -> None:
    """Renew ``host_id``'s lease for ``generation``.  ``attrs`` ride along
    (e.g. ``step=`` so peers and the supervisor can observe progress).
    ``prefix`` namespaces the lease tier (pods default to ``heartbeat``;
    serving-fleet engines lease under ``fleet/heartbeat``)."""
    maybe_fire(SITE_POD_HEARTBEAT, host=host_id, generation=generation)
    store.put(f"{prefix}/{host_id}", {
        "host_id": host_id, "generation": int(generation),
        "beat_t": store.now(), "lease_s": float(lease_s), "attrs": attrs})


def lease_table(store: CoordinationStore,
                prefix: str = "heartbeat") -> Dict[str, HostLease]:
    """Every host's newest lease, regardless of generation or freshness."""
    out: Dict[str, HostLease] = {}
    for name in store.list(prefix):
        doc = store.get(f"{prefix}/{name}")
        if doc is None:
            continue
        out[doc["host_id"]] = HostLease(
            host_id=doc["host_id"], generation=int(doc["generation"]),
            beat_t=float(doc["beat_t"]), lease_s=float(doc["lease_s"]),
            attrs=doc.get("attrs", {}))
    return out


def dead_hosts(store: CoordinationStore, generation: int, miss_limit: int,
               expected: Optional[List[str]] = None,
               prefix: str = "heartbeat") -> List[str]:
    """Hosts of ``generation`` whose lease has lapsed ``miss_limit`` times
    — plus, when ``expected`` is given, hosts that never reached this
    generation at all (no lease, or one stuck at an OLDER generation: a
    host that died before its first renewal is just as dead).  A lease
    from a NEWER generation is proof of life, never death — a stale
    watchdog still scanning for its old generation must not dead-mark the
    healthy hosts that re-formed without it."""
    now = store.now()
    table = lease_table(store, prefix=prefix)
    dead = []
    for host, lease in table.items():
        if lease.generation == generation and lease.missed(now) >= miss_limit:
            dead.append(host)
    for host in expected or []:
        lease = table.get(host)
        if lease is None or lease.generation < generation:
            dead.append(host)
    return sorted(set(dead))


def record_dead(store: CoordinationStore, host_id: str, generation: int,
                reported_by: str, prefix: str = "dead") -> None:
    """Durable dead-host marker: once ANY peer declares a host dead for a
    generation, every later supervisor round excludes it until an operator
    (or a re-registering host) clears the marker.  CAS-written so racing
    reporters commit exactly one marker per generation — the FIRST
    reporter wins, and a marker from an equal-or-newer generation is never
    clobbered by a stale scanner still looking at an old epoch."""
    key = f"{prefix}/{host_id}"
    doc = {"host_id": host_id, "generation": int(generation),
           "reported_by": reported_by, "t": store.now()}

    def attempt():
        cur = store.get(key)
        if cur is not None \
                and int(cur.get("generation", -1)) >= int(generation):
            return None
        if store.compare_and_swap(key, cur, doc):
            return None
        return StoreRetryPolicy.RETRY

    default_retry_policy().run(f"record_dead({host_id!r})", attempt)


def dead_set(store: CoordinationStore, prefix: str = "dead") -> List[str]:
    return [name for name in store.list(prefix)
            if store.get(f"{prefix}/{name}") is not None]


def clear_dead(store: CoordinationStore, host_id: str,
               prefix: str = "dead") -> None:
    """A replaced/recovered host re-admits itself by clearing its marker
    (the next supervisor round then counts it healthy again)."""
    store.delete(f"{prefix}/{host_id}")


# ------------------------------------------------------ host advertisements

# pod-side analogue of the serving fleet's ``fleet/engines`` advertisements
# (inference/fleet.py): each host publishes its per-process observability
# counters so any host (or an external scraper) gets ONE cross-host view
# through the store instead of N per-process /metrics endpoints
POD_HOSTS_PREFIX = "pod/hosts"


_PROCESS_SRC: Optional[str] = None


def process_src() -> str:
    """Machine-unique PROCESS identity for advertisement dedup keys.  A
    bare pid is not unique across the machines of a real pod (containers
    commonly all run as pid 1, which would silently merge distinct hosts'
    counters in a rollup), so the hostname rides along; simulated hosts
    (threads of one process) still share one src and dedup to a single
    count, which is the point of the key.  Cached — it sits on every
    heartbeat/advertisement path and cannot change within a process."""
    global _PROCESS_SRC
    if _PROCESS_SRC is None:
        import socket

        _PROCESS_SRC = f"{socket.gethostname()}.{os.getpid()}"
    return _PROCESS_SRC


def advertise_host(store: CoordinationStore, host_id: str, generation: int,
                   monitor=None, prefix: str = POD_HOSTS_PREFIX,
                   **attrs) -> Dict:
    """Publish this host's observability snapshot under
    ``pod/hosts/<host_id>``: the flight-recorder ring's drop counter and
    the monitor ring's drop counter PR 4 left per-process, plus caller
    attrs (``step=`` etc.).  The ``*_src`` ids scope each counter to its
    process-level object — the tracer ring is a process singleton and
    simulated hosts (threads) share a process, so a rollup summing N
    identical advertisements would overcount N-fold without them (same
    contract as the fleet advertisements)."""
    from ..observability.trace import get_tracer

    src = process_src()
    ad = {
        "host_id": str(host_id),
        "generation": int(generation),
        "t": store.now(),
        "flight_dropped": int(get_tracer().recorder.dropped),
        "flight_src": src,
        "monitor_dropped": int(getattr(monitor, "dropped_events", 0) or 0),
        "monitor_src": f"{src}.{id(monitor)}",
        "attrs": attrs,
    }
    store.put(f"{prefix}/{host_id}", ad)
    return ad


def host_advertisements(store: CoordinationStore,
                        prefix: str = POD_HOSTS_PREFIX) -> Dict[str, Dict]:
    """host_id -> newest advertisement (the cross-host /metrics view)."""
    out: Dict[str, Dict] = {}
    for name in store.list(prefix):
        doc = store.get(f"{prefix}/{name}")
        if doc is not None:
            out[str(doc.get("host_id", name))] = doc
    return out


def dedup_drop_totals(ads: Dict[str, Dict]) -> Tuple[int, int]:
    """Fold advertisements into (flight_dropped, monitor_dropped) totals,
    deduplicated by source id: advertisers sharing a process ring (the
    ``*_src`` keys) are counted once, not once per advertisement.  The ONE
    implementation of the fold — the pod watchdog rollup and the fleet
    router's gauge rollup (inference/fleet.py) both call it, so the dedup
    contract cannot drift between tiers."""
    flight_by_src: Dict[str, int] = {}
    monitor_by_src: Dict[str, int] = {}
    for key, ad in ads.items():
        # max, not last-iterated: advertisers sharing a src write on
        # independent cadences, and the counters are monotonic — a stale
        # advertisement must never mask a fresher, higher count (listing
        # order is arbitrary)
        fsrc = str(ad.get("flight_src", key))
        flight_by_src[fsrc] = max(flight_by_src.get(fsrc, 0),
                                  int(ad.get("flight_dropped", 0)))
        msrc = str(ad.get("monitor_src", key))
        monitor_by_src[msrc] = max(monitor_by_src.get(msrc, 0),
                                   int(ad.get("monitor_dropped", 0)))
    return sum(flight_by_src.values()), sum(monitor_by_src.values())


def rollup_host_gauges(store: CoordinationStore, monitor, tick: int = 0,
                       prefix: str = POD_HOSTS_PREFIX,
                       max_age_s: Optional[float] = None) -> Dict[str, float]:
    """Fold every host's advertisement into pod-scope monitor gauges
    (``pod/flight_dropped_total``, ``pod/monitor_dropped_total``,
    ``pod/hosts_advertised``) — deduplicated by source id, so hosts
    sharing a process ring are counted once.  ``max_age_s`` drops
    advertisements older than that on the store clock (advertisements are
    never deleted, so without an age bound a permanently dead host's last
    snapshot would inflate the gauges forever — the watchdog passes its
    own dead-by-lease threshold).  Returns the gauge values; writes them
    when ``monitor`` is not None (they then reach the Prometheus
    exposition like every other gauge)."""
    ads = host_advertisements(store, prefix=prefix)
    if max_age_s is not None:
        now = store.now()
        ads = {h: ad for h, ad in ads.items()
               if now - float(ad.get("t", 0.0)) <= max_age_s}
    flight, monitor_drops = dedup_drop_totals(ads)
    gauges = {
        "pod/flight_dropped_total": float(flight),
        "pod/monitor_dropped_total": float(monitor_drops),
        "pod/hosts_advertised": float(len(ads)),
    }
    if monitor is not None:
        monitor.write_events([(name, val, tick)
                              for name, val in sorted(gauges.items())])
    return gauges


# ----------------------------------------------------- residency digests

def publish_residency(store: CoordinationStore, owner_id: str, digest,
                      prefix: str = "residency", **attrs) -> Dict:
    """Publish a compact prefix-residency digest under
    ``<prefix>/<owner_id>``: ``[[chain_key, tier], ...]`` pairs (tier 0 =
    device-resident/hot, 1 = host-tier/demoted), MRU first.  Chain keys
    are content-derived (``inference/prefix_cache.chain_keys``), so any
    reader that can hash the same token chunks can match against the
    digest without sharing Python objects with the owner — the serving
    fleet router uses this (prefix ``fleet/residency``) to route
    shared-prefix requests to the engine already holding the prefix
    (docs/FLEET.md "Prefix residency routing")."""
    doc = {"owner_id": str(owner_id), "t": store.now(),
           "digest": [[int(k), int(t)] for k, t in digest],
           "attrs": attrs}
    store.put(f"{prefix}/{owner_id}", doc)
    return doc


def read_residency(store: CoordinationStore,
                   prefix: str = "residency") -> Dict[str, Dict]:
    """owner_id -> newest residency digest document under ``prefix``."""
    out: Dict[str, Dict] = {}
    for name in store.list(prefix):
        doc = store.get(f"{prefix}/{name}")
        if doc is not None:
            out[str(doc.get("owner_id", name))] = doc
    return out


# ------------------------------------------------------------ trace segments

def append_trace_segment(store: CoordinationStore, owner_id: str,
                         spans: List[Dict], prefix: str = "trace",
                         max_spans: int = 2048,
                         attrs: Optional[Dict] = None) -> Dict:
    """CAS-append completed-span records under ``<prefix>/<owner_id>``
    (the serving fleet uses ``fleet/trace/<engine>`` — docs/FLEET.md
    keyspace table).  The document is size-capped like the request
    journal: past ``max_spans`` the OLDEST records drop and the ``dropped``
    counter grows, so one chatty process can never grow its store document
    unboundedly — truncation is visible, never silent.

    Every append stamps a fresh **clock anchor** pairing the writing
    process's ``time.monotonic()`` with ``time.time()``: span records
    stamp monotonic t0s (immune to wall steps but process-local), and the
    anchor is what lets ``observability/trace_assembly.py`` place every
    process's spans on ONE shared epoch timeline with per-process skew
    correction.  The write retries through :class:`StoreRetryPolicy`
    (single writer per owner in practice — contention can only be a
    dying predecessor's last append), mirroring
    ``record_dead``/``bump_generation``."""
    key = f"{prefix}/{owner_id}"

    def attempt():
        cur = store.get(key)
        merged = list((cur or {}).get("spans") or ())
        merged.extend(spans)
        dropped = int((cur or {}).get("dropped") or 0)
        if len(merged) > int(max_spans):
            dropped += len(merged) - int(max_spans)
            merged = merged[-int(max_spans):]
        doc = {"owner_id": str(owner_id),
               "anchor": {"mono": time.monotonic(), "epoch": time.time()},
               "spans": merged,
               "dropped": dropped,
               "attrs": dict(attrs or {}),
               "t": store.now()}
        if store.compare_and_swap(key, cur, doc):
            return doc
        return StoreRetryPolicy.RETRY

    return default_retry_policy().run(
        f"append_trace_segment({owner_id!r})", attempt)


def read_trace_segments(store: CoordinationStore,
                        prefix: str = "trace") -> Dict[str, Dict]:
    """owner_id -> newest trace-segment document under ``prefix`` — the
    input ``trace_assembly.assemble_fleet_trace`` merges."""
    out: Dict[str, Dict] = {}
    for name in store.list(prefix):
        doc = store.get(f"{prefix}/{name}")
        if doc is not None:
            out[str(doc.get("owner_id", name))] = doc
    return out


# ----------------------------------------------------------------- channels
#
# Store-mediated message channels: how a fleet router and a MEMBER DAEMON
# in another OS process exchange assignments, results and control verbs
# with no coupling beyond the store (docs/FLEET.md "Member daemons").  One
# channel is one size-capped document; every payload gets a CAS-assigned,
# strictly increasing sequence number, so a consumer detects capped-out
# drops as sequence gaps (truncation is visible, never silent — the same
# contract as the trace segments and the request journal).  Consumption is
# a CAS truncation: of N racing consumers (a deposed router and its
# successor both draining a results channel), exactly one claims each item.

def channel_append(store: CoordinationStore, key: str, payload: Dict,
                   owner_id: str, max_items: int = 256,
                   max_bytes: int = 262144) -> int:
    """Append ``payload`` to the channel at ``key`` and return its
    sequence number.  Past ``max_items`` entries (or ``max_bytes`` of
    serialized items) the OLDEST entries drop and the ``dropped`` counter
    grows — one wedged consumer can never grow a producer's document
    unboundedly.  Retries through :class:`StoreRetryPolicy`, mirroring
    :func:`append_trace_segment`."""
    maybe_fire(SITE_FLEET_CHANNEL, key=key)

    def attempt():
        cur = store.get(key)
        items = [list(e) for e in ((cur or {}).get("items") or ())]
        seq = int((cur or {}).get("seq") or 0) + 1
        items.append([seq, payload])
        dropped = int((cur or {}).get("dropped") or 0)
        if len(items) > int(max_items):
            dropped += len(items) - int(max_items)
            items = items[-int(max_items):]
        while len(items) > 1 and len(json.dumps(items)) > int(max_bytes):
            items.pop(0)
            dropped += 1
        doc = {"owner": str(owner_id), "seq": seq, "items": items,
               "dropped": dropped, "t": store.now()}
        if store.compare_and_swap(key, cur, doc):
            return seq
        return StoreRetryPolicy.RETRY

    return default_retry_policy().run(f"channel_append({key!r})", attempt)


def channel_consume(store: CoordinationStore, key: str,
                    consumer_id: str) -> List[Tuple[int, Dict]]:
    """Claim every pending ``(seq, payload)`` on the channel at ``key``
    (ascending seq) and truncate it — atomically, via CAS: a concurrent
    producer append or a RACING CONSUMER makes the truncation lose, and
    the loop re-reads.  Each item is claimed by exactly one consumer;
    ``consumer_id`` is stamped on the truncated document so an operator
    can see who drained it last."""
    def attempt():
        cur = store.get(key)
        if cur is None or not cur.get("items"):
            return []
        new = {"owner": cur.get("owner"), "seq": int(cur.get("seq") or 0),
               "items": [], "dropped": int(cur.get("dropped") or 0),
               "consumer": str(consumer_id), "t": store.now()}
        if store.compare_and_swap(key, cur, new):
            return [(int(s), p) for s, p in cur["items"]]
        return StoreRetryPolicy.RETRY

    return default_retry_policy().run(f"channel_consume({key!r})", attempt)


def channel_stats(store: CoordinationStore, key: str) -> Dict[str, int]:
    """``{"seq", "pending", "dropped"}`` for the channel at ``key`` —
    the drop accounting the fleet gauges roll up (all zero when the
    channel was never written)."""
    doc = store.get(key) or {}
    return {"seq": int(doc.get("seq") or 0),
            "pending": len(doc.get("items") or ()),
            "dropped": int(doc.get("dropped") or 0)}


# --------------------------------------------------------------- generation

def read_generation(store: CoordinationStore, key: str = "generation") -> int:
    doc = store.get(key)
    return int(doc["generation"]) if doc else 0


def bump_generation(store: CoordinationStore, key: str = "generation") -> int:
    """Advance the generation and return the value THIS caller committed.
    A retried CAS (:class:`StoreRetryPolicy`): each concurrent bumper
    wins exactly one distinct round — two supervisor processes racing
    (or a deposed coordinator racing its successor) can no longer lose
    an update or tear the counter.  The returned value is strictly
    monotonic across all winners."""
    def attempt():
        doc = store.get(key)
        gen = int(doc["generation"]) if doc else 0
        if store.compare_and_swap(key, doc,
                                  {"generation": gen + 1,
                                   "t": store.now()}):
            return gen + 1
        return StoreRetryPolicy.RETRY

    return default_retry_policy().run(f"bump_generation({key!r})", attempt)


# ----------------------------------------------------- coordinator election

@dataclass(frozen=True)
class CoordinatorLease:
    """The coordinator lock document: who leads, under which term, renewed
    when.  ``term`` increments on every leadership CHANGE (never on a
    renewal), so any two leaders are ordered and a fenced-out old leader
    can recognize its own deposition."""
    leader_id: str
    term: int
    t: float               # store-clock stamp of the newest acquire/renewal
    lease_s: float

    def age(self, now: float) -> float:
        return max(0.0, now - self.t)

    def expired(self, now: float) -> bool:
        return self.age(now) >= self.lease_s


def _coordinator_doc(doc: Optional[Dict]) -> Optional[CoordinatorLease]:
    if doc is None:
        return None
    return CoordinatorLease(
        leader_id=doc["leader_id"], term=int(doc["term"]),
        t=float(doc["t"]), lease_s=float(doc["lease_s"]))


def read_coordinator(store: CoordinationStore,
                     key: str = "coordinator") -> Optional[CoordinatorLease]:
    return _coordinator_doc(store.get(key))


def elect_coordinator(store: CoordinationStore, candidate_id: str,
                      lease_s: float,
                      key: str = "coordinator") -> Optional[CoordinatorLease]:
    """One election round for ``candidate_id``: returns the lease it holds
    after this call, or ``None`` when someone else leads.

    Exactly one CAS attempt — callers poll this every scheduler round, so
    a lost race just retries on the next poll:

    - vacant key  -> acquire at term 1;
    - own lease   -> renew (same term, fresh stamp);
    - LAPSED peer -> take over at ``term + 1`` (re-elect on lease lapse);
    - live peer   -> ``None`` (a healthy leader is never stolen from).

    The CAS is what makes a split-brain impossible: two standbys seeing
    the same lapsed lease both attempt ``term + 1``, and the store admits
    exactly one — the loser observes the new document and stands down.
    """
    doc = store.get(key)
    now = store.now()
    if doc is None:
        new = {"leader_id": candidate_id, "term": 1, "t": now,
               "lease_s": float(lease_s)}
        return _coordinator_doc(new) \
            if store.compare_and_swap(key, None, new) else None
    cur = _coordinator_doc(doc)
    if cur.leader_id == candidate_id:
        new = {"leader_id": candidate_id, "term": cur.term, "t": now,
               "lease_s": float(lease_s)}
        # a failed renewal means a standby deposed us between our beats —
        # report not-leader so the caller stops driving immediately
        return _coordinator_doc(new) \
            if store.compare_and_swap(key, doc, new) else None
    if cur.expired(now):
        new = {"leader_id": candidate_id, "term": cur.term + 1, "t": now,
               "lease_s": float(lease_s)}
        if store.compare_and_swap(key, doc, new):
            logger.info("coordinator election: %r takes term %d from "
                        "lapsed %r (lease age %.3fs)", candidate_id,
                        cur.term + 1, cur.leader_id, cur.age(now))
            return _coordinator_doc(new)
    return None


def resign_coordinator(store: CoordinationStore, candidate_id: str,
                       key: str = "coordinator") -> bool:
    """Voluntarily lapse the candidate's own lease (planned hand-off: the
    next ``elect_coordinator`` poll by any standby wins immediately
    instead of waiting out the lease).  CAS-guarded so resigning can never
    clobber a successor that already took over."""
    doc = store.get(key)
    cur = _coordinator_doc(doc)
    if cur is None or cur.leader_id != candidate_id:
        return False
    lapsed = {"leader_id": candidate_id, "term": cur.term,
              "t": cur.t - cur.lease_s, "lease_s": cur.lease_s}
    return store.compare_and_swap(key, doc, lapsed)


# --------------------------------------------------------------- rendezvous

def rendezvous(store: CoordinationStore, host_id: str, generation: int,
               expected_hosts: List[str], timeout_s: float = 60.0,
               poll_s: float = 0.02) -> List[str]:
    """Register for ``generation`` and wait until every expected host has.

    Returns the sorted member list (rank = index of ``host_id`` in it).
    Registration is idempotent; a stale registration from a previous
    generation is invisible (records are keyed by generation).  Raises
    :class:`PodRendezvousTimeout` with the missing hosts after
    ``timeout_s`` — the supervisor treats that as a failed round and
    re-plans against the hosts that did show up.
    """
    maybe_fire(SITE_POD_RENDEZVOUS, host=host_id, generation=generation)
    store.put(f"rendezvous/gen{generation}/{host_id}",
              {"host_id": host_id, "t": store.now()})
    expected = sorted(set(expected_hosts))
    deadline = time.monotonic() + timeout_s
    while True:
        present = set(store.list(f"rendezvous/gen{generation}"))
        if all(h in present for h in expected):
            return expected
        if time.monotonic() >= deadline:
            missing = sorted(set(expected) - present)
            raise PodRendezvousTimeout(
                f"rendezvous gen{generation}: host {host_id!r} waited "
                f"{timeout_s:.1f}s; missing {missing} "
                f"(present: {sorted(present)})")
        time.sleep(poll_s)


# ----------------------------------------------------------- the watchdog

class HeartbeatWatchdog:
    """Daemon thread that renews this host's lease and scans its peers.

    The first peer whose lease lapses ``miss_limit`` periods (or that never
    beat at all once ``grace_beats`` of our own renewals have happened) is
    recorded in the store (:func:`record_dead`) and reported through
    ``on_peer_dead(host_id)``.  The default action exits the process with
    :data:`RC_POD_PEER_LOST` via ``os._exit`` — the same rationale as the
    hang watchdog: this thread may be the only one NOT wedged inside a
    native collective, so a clean exception cannot be relied on to
    propagate.  Tests pass an ``on_peer_dead`` observer instead.

    One watchdog per host per generation; scanning stops after the first
    detection (``dead`` keeps the list) so a cascade of expiring peers —
    everyone else exiting after the same detection — produces one exit
    cause, not ``n`` races.
    """

    def __init__(self, store: CoordinationStore, host_id: str,
                 generation: int, peers: List[str], lease_s: float = 5.0,
                 miss_limit: int = 3,
                 on_peer_dead: Optional[Callable[[str], None]] = None,
                 monitor=None, grace_beats: int = 3,
                 renew_s: Optional[float] = None, advertise: bool = True,
                 store_fail_grace: int = 3):
        self.store = store
        self.host_id = host_id
        self.generation = int(generation)
        self.peers = [p for p in peers if p != host_id]
        self.lease_s = float(lease_s)
        self.miss_limit = int(miss_limit)
        self.on_peer_dead = on_peer_dead
        self.monitor = monitor
        self.grace_beats = int(grace_beats)
        # wall-clock renew cadence; defaults to a third of the lease.  Kept
        # separate so stores with an injected (test) clock can renew on real
        # time while lease expiry is judged on the store clock.
        self.renew_s = (float(renew_s) if renew_s is not None
                        else max(self.lease_s / 3.0, 1e-3))
        # publish a pod/hosts/<host> observability advertisement with every
        # renewal (flight-recorder + monitor drop counters; see
        # advertise_host) so the pod has one cross-host /metrics view
        self.advertise = bool(advertise)
        self._last_rollup_t: Optional[float] = None   # store clock
        self._last_advert_t: Optional[float] = None   # store clock
        self.dead: List[str] = []
        self.beats = 0
        # store-failure escalation (docs/FLEET.md "Store brownouts and
        # partitions"): consecutive renew/scan rounds that failed on the
        # STORE (not on a peer).  Below `store_fail_grace` it is a logged
        # brownout; at the grace it escalates to the
        # pod/store_unreachable gauge + a flight-recorder note.  Peers
        # are NEVER declared dead from a failed scan — "my store view is
        # broken" and "that host stopped beating" are different facts —
        # and after a heal one clean scan runs declaration-free (the
        # peers' beats may have been dark through the same partition).
        self.store_fail_grace = int(store_fail_grace)
        self.store_fail_streak = 0
        self.store_failures_total = 0
        self.store_unreachable = False
        self._attrs: Dict = {}
        self._started_at: Optional[float] = None   # store clock, at start()
        # beat_once() runs on BOTH the renew daemon and the training step
        # loop (piggybacked attrs); `beats += 1` and the advert rate-limit
        # check-then-set are read-modify-write, so without the lock two
        # concurrent renewals lose a beat — and `beats` gates the _scan
        # grace window, so lost beats extend the dead-host grace period
        self._beat_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def set_attrs(self, **attrs) -> None:
        """Attach attributes to the next beats (e.g. ``step=N`` so peers
        and the supervisor can watch progress through the store)."""
        self._attrs.update(attrs)

    def start(self) -> "HeartbeatWatchdog":
        beat(self.store, self.host_id, self.generation, self.lease_s,
             **self._attrs)   # first lease lands before start() returns
        with self._beat_lock:
            self.beats = 1
        self._started_at = self.store.now()
        self._thread = threading.Thread(
            target=self._loop, name=f"pod-heartbeat[{self.host_id}]",
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def beat_once(self) -> None:
        """Renew synchronously (the thread also renews on its own cadence;
        call this from the step loop to piggyback fresh attrs)."""
        beat(self.store, self.host_id, self.generation, self.lease_s,
             **self._attrs)
        should_advertise = False
        with self._beat_lock:
            self.beats += 1
            if self.advertise:
                # once per lease, not per renewal: the advertisement's only
                # consumer (rollup_host_gauges) is itself rate-limited to
                # once per lease, so renewing it 3x as often just doubles
                # the store's write volume for an identical cross-host view
                now = self.store.now()
                if self._last_advert_t is None \
                        or now - self._last_advert_t >= self.lease_s:
                    self._last_advert_t = now
                    should_advertise = True
        if should_advertise:   # store write outside the lock
            advertise_host(self.store, self.host_id, self.generation,
                           monitor=self.monitor, **self._attrs)

    def _loop(self) -> None:
        # renew well inside the lease so one slow write never costs it
        while not self._stop.wait(self.renew_s):
            self.tick_once()

    def tick_once(self) -> None:
        """One renew+scan round with the store-failure escalation —
        factored off the daemon loop so deterministic tests (and
        cooperative harnesses) can drive it without threads."""
        try:
            healed = self.store_fail_streak > 0
            self.beat_once()
            if not self.dead:
                # the first clean scan after a heal observes but does
                # not declare: peers whose beats were dark through the
                # same partition get one round to land a fresh lease
                self._scan(declare=not healed)
            self._note_store_ok(healed)
        except (StoreUnavailable, OSError, PodCoordinationError) as e:
            # the STORE failed this round, not a peer: count toward the
            # escalation grace, never toward any dead declaration
            self._note_store_failure(e)
        except Exception as e:   # the watchdog must outlive flaky storage
            logger.warning("pod heartbeat: %s: %s", type(e).__name__, e)

    def _note_store_ok(self, healed: bool) -> None:
        if not healed and not self.store_unreachable:
            return
        if healed:
            logger.info(
                "pod heartbeat[%s]: store reachable again after %d "
                "failed round(s)", self.host_id, self.store_fail_streak)
        self.store_fail_streak = 0
        if self.store_unreachable:
            self.store_unreachable = False
            if self.monitor is not None:
                self.monitor.write_events([
                    ("pod/store_unreachable", 0.0, self.beats)])

    def _note_store_failure(self, err: BaseException) -> None:
        self.store_fail_streak += 1
        self.store_failures_total += 1
        logger.warning(
            "pod heartbeat[%s]: store op failed (%s: %s) — streak %d/%d; "
            "no peer is declared dead from a failed scan", self.host_id,
            type(err).__name__, err, self.store_fail_streak,
            self.store_fail_grace)
        if self.store_fail_streak < self.store_fail_grace \
                or self.store_unreachable:
            return
        self.store_unreachable = True
        if self.monitor is not None:
            self.monitor.write_events([
                ("pod/store_unreachable", 1.0, self.beats)])
        from ..observability.trace import trace_count

        # flight-recorder note: the escalation shows up in crash dumps
        # and trace exports even when no scraper watches the gauge
        trace_count("pod.store_unreachable", 1.0, host=self.host_id,
                    streak=self.store_fail_streak)
        logger.error(
            "pod heartbeat[%s]: %d consecutive store failures — this "
            "host's STORE VIEW is unreachable (escalating the "
            "pod/store_unreachable gauge); peer liveness is unknown, "
            "not absent", self.host_id, self.store_fail_streak)

    def _scan(self, declare: bool = True) -> None:
        # the "never beat at all" check needs BOTH grace gates: our own
        # renewal count AND miss_limit lease periods of STORE-CLOCK time
        # since start() — a peer still inside device init (its watchdog not
        # started yet) must get the same allowance a lease expiry would,
        # or a fast starter would durably dead-mark a healthy slow one
        elapsed = (self.store.now() - self._started_at
                   if self._started_at is not None else 0.0)
        expected = (self.peers
                    if (self.beats >= self.grace_beats
                        and elapsed >= self.miss_limit * self.lease_s)
                    else None)
        dead = dead_hosts(self.store, self.generation, self.miss_limit,
                          expected=expected)
        dead = [h for h in dead if h in self.peers]
        if self.monitor is not None:
            # emitted on the detection scan too: the drop from full
            # membership is exactly the transition this gauge exists for
            self.monitor.write_events([
                ("pod/live_hosts",
                 float(len(self.peers) + 1 - len(dead)), self.beats),
                ("pod/generation", float(self.generation), self.beats)])
            if self.advertise:
                # fold every host's pod/hosts advertisement into pod-scope
                # gauges so THIS host's /metrics shows the cross-host view
                # (staleness bound = our own dead-by-lease threshold, so a
                # lost host ages out of the rollup when it ages out of the
                # pod).  Rate-limited to once per lease on the store clock:
                # the rollup reads every host's advertisement, and N hosts
                # doing that every scan would put O(N^2) reads per renew
                # interval on the store for byte-identical gauge values.
                now = self.store.now()
                if self._last_rollup_t is None \
                        or now - self._last_rollup_t >= self.lease_s:
                    self._last_rollup_t = now
                    rollup_host_gauges(
                        self.store, self.monitor, tick=self.beats,
                        max_age_s=self.miss_limit * self.lease_s)
        if not dead:
            return
        if not declare:
            # post-heal observation round: the peers' beats may have been
            # dark through the SAME partition we just recovered from, so
            # what looks lapsed gets one renew interval to land a fresh
            # lease before any durable declaration
            logger.warning(
                "pod heartbeat[%s]: host(s) %s look lapsed on the first "
                "scan after a store heal — withholding declaration for "
                "one round", self.host_id, dead)
            return
        self.dead = dead
        for host in dead:
            record_dead(self.store, host, self.generation, self.host_id)
        logger.error(
            "pod heartbeat: host(s) %s missed %d lease(s) of %.3fs in "
            "generation %d — declaring dead; peers should exit %d and let "
            "the pod supervisor re-form at the healthy slice",
            dead, self.miss_limit, self.lease_s, self.generation,
            RC_POD_PEER_LOST)
        if self.monitor is not None:
            self.monitor.write_events([
                ("pod/dead_hosts", float(len(dead)), self.beats)])
        if self.on_peer_dead is not None:
            self.on_peer_dead(dead[0])
        else:   # pragma: no cover - exercised only in real pod deployments
            os._exit(RC_POD_PEER_LOST)
