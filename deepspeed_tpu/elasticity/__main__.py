"""``ds_elastic`` CLI parity (reference bin/ds_elastic): inspect a config's
elastic plan — the chosen batch size and compatible device counts."""
import argparse
import json
import sys

from ..runtime.config import ElasticityConfig
from .elasticity import ElasticityError, compute_elastic_config


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="ds_elastic",
        description="Show the elastic batch plan for a deepspeed_tpu config")
    ap.add_argument("-c", "--config", required=True,
                    help="path to the deepspeed_tpu JSON config")
    def positive(v):
        n = int(v)
        if n < 0:
            raise argparse.ArgumentTypeError("world size must be >= 0")
        return n

    ap.add_argument("-w", "--world-size", type=positive, default=0,
                    help="bind the plan to this data-parallel world size")
    args = ap.parse_args(argv)

    try:
        with open(args.config) as f:
            cfg = json.load(f)
        ec = ElasticityConfig(**cfg.get("elasticity", {}))
        if not ec.enabled:
            print("elasticity is not enabled in this config")
            return 1
        plan = compute_elastic_config(
            ec, dp_world_size=args.world_size,
            node_size=ec.num_gpus_per_node,
            model_parallel_size=ec.model_parallel_size)
    except (OSError, json.JSONDecodeError, ValueError, ElasticityError) as e:
        # expected user errors (bad path/JSON, incompatible world size,
        # malformed elastic block) get a clean message, not a traceback
        print(f"error: {e}")
        return 1
    print(f"train_batch_size      : {plan.train_batch_size}")
    print(f"valid device counts   : {list(plan.valid_device_counts)}")
    if args.world_size > 0:
        print(f"micro batch @ dp={args.world_size:<5}: "
              f"{plan.micro_batch_per_device}")
        print(f"grad accumulation     : {plan.gradient_accumulation_steps}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
