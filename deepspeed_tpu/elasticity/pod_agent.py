"""Pod-level fault tolerance: all-hosts checkpoint commit + shrink-to-healthy.

This wires the pieces PRs 1-4 left disconnected into one recovery path
(docs/POD.md):

- :func:`save_pod_checkpoint` extends the per-host manifest commit (PR 1)
  to pod scope: every host lands its shard and a per-host manifest, the
  coordinator publishes ``pod_manifest.json`` only after *all* hosts of the
  generation reported, and only then does the ``latest`` pointer move.  A
  crash anywhere in between leaves a TORN pod tag that the restore walk
  quarantines.
- :class:`PodElasticAgent` is :class:`~.elastic_agent.ElasticAgent` with
  pod-scope commit on save and pod-scope verification on restore; its
  restore walk falls back by generation across *pod sizes* — orbax restores
  global arrays onto whatever mesh the resumed world builds, so a pod
  checkpoint written at 4 hosts restores at 2 (the reshard/``sharded_load``
  path does the same for inference checkpoints).
- :class:`PodSupervisor` is the round driver: each round it reads the
  coordination store's dead-host markers, shrinks the job to the largest
  healthy slice :func:`~.elasticity.compute_elastic_config` admits, bumps
  the pod generation, and hands the resulting :class:`PodRound` (hosts +
  batch triad) to the caller's attempt.  A round that exits
  :data:`~.coordination.RC_POD_PEER_LOST` (a peer's lease expired) is the
  expected shrink signal, not a crash loop.

Simulated pods (tests, ``tools/chaos_soak.py --mode pod``) drive hosts as
threads against a :class:`~.coordination.FileCoordinationStore`; the
coordinator host owns the real engine (a single CPU process owns the whole
virtual mesh) and peers exercise the protocol half: heartbeats, shard
writes, host manifests, rendezvous.
"""
from __future__ import annotations

import dataclasses
import os
import time
from typing import Callable, List, Optional, Sequence, Tuple

from .coordination import (CoordinationStore, HeartbeatWatchdog,
                           RC_POD_PEER_LOST, bump_generation, dead_set,
                           elect_coordinator, read_generation)
from .elastic_agent import ElasticAgent
from .elasticity import (ElasticPlan, ElasticityIncompatibleWorldSize,
                         compute_elastic_config)
from .replication import (HostReplicator, ReplicaAdoptionError,
                          adopt_replicas, note_adoption_fallback,
                          replica_adoptions_total, replica_fallbacks_total)
from .supervisor import Supervisor, SupervisorStandDown
from ..observability.trace import trace_span
from ..resilience.fault_injection import SITE_LATEST_PUBLISH, maybe_fire
from ..resilience.integrity import (LATEST_FILE, commit_pod_manifest,
                                    verify_pod_checkpoint_dir,
                                    write_host_manifest)
from ..utils.logging import log_dist, logger

# a healthy slice below the elastic plan's floor cannot run the job at the
# planned batch — permanent until hosts come back; distinct from
# RC_POD_PEER_LOST (87, transient membership loss) and RC_HANG (85)
RC_POD_UNRECOVERABLE = 86

# the training-pod coordinator election key (the serving fleet elects under
# fleet/coordinator on the same store — namespaced so the tiers never race
# each other's leases)
POD_COORDINATOR_KEY = "pod/coordinator"


class PodPeerLost(RuntimeError):
    """Raised inside the step loop when the heartbeat watchdog declared a
    peer dead: the round must exit (code :data:`RC_POD_PEER_LOST`) so the
    supervisor can re-form at the healthy slice."""

    def __init__(self, host: str):
        super().__init__(f"pod peer {host!r} declared dead by lease; "
                         f"exiting round for re-formation")
        self.host = host


# --------------------------------------------------------- pod-scope commit

def save_pod_checkpoint(engine, save_dir: str, ctx: "PodContext",
                        tag: Optional[str] = None,
                        client_state: Optional[dict] = None) -> str:
    """One pod-scope checkpoint from this host's perspective.

    In a real multi-host job every host calls this collectively (the orbax
    save inside ``engine.save_checkpoint`` already coordinates shard
    writes); on a simulated pod only the coordinator holds an engine and
    peers pass ``engine=None``, exercising just the commit protocol.

    Order per host: engine save (``save_latest=False`` — the pointer must
    not move before the POD commit) -> this host's extra shard files
    (``ctx.shard_writer``) -> per-host manifest (the ``ckpt.shard_commit``
    fault unit).  Coordinator then: wait for every host manifest of this
    generation, publish ``pod_manifest.json``, and only then ``latest``.
    """
    if tag is None:
        if engine is None:
            raise ValueError("peers without an engine must be given the tag")
        tag = f"global_step{engine.global_steps}"
    ckpt_dir = os.path.join(save_dir, str(tag))
    with trace_span("ckpt.pod_save", tag=str(tag), host=ctx.host_id,
                    generation=ctx.generation):
        if engine is not None:
            engine.save_checkpoint(save_dir, tag=tag,
                                   client_state=client_state,
                                   save_latest=False)
            wait = getattr(engine, "wait_for_checkpoint", None)
            if wait is not None:
                wait()   # the host manifest must list DURABLE files
        os.makedirs(ckpt_dir, exist_ok=True)
        if ctx.is_coordinator:
            # announce the pending commit through the store, scoped by
            # generation: host-side shard writers key on THIS record (never
            # on directory names, which recur across rounds — a re-saved
            # step after a torn tag's quarantine reuses the tag name)
            ctx.store.put(f"commit/gen{ctx.generation}",
                          {"tag": str(tag), "t": ctx.store.now()})
        shard_files: List[str] = []
        owner: Optional[int] = None
        if ctx.shard_writer is not None:
            shard_files = list(ctx.shard_writer(ckpt_dir, ctx.host_id))
        if engine is not None:
            # attest the REAL payload files this process wrote (orbax
            # shards + sidecars): the host manifest lists them with sizes
            # and checksums, so verify_pod_checkpoint_dir catches a
            # missing/torn shard FILE, not just a missing manifest.  The
            # attribution index is the JAX process index — the one that
            # names ocdbt.process_<k> payload paths — NOT ctx.rank, whose
            # lexicographic host ordering diverges from it past 10 hosts
            # (attesting another process's still-being-written files would
            # record torn checksums and quarantine good checkpoints).
            from ..resilience.integrity import host_payload_files

            try:
                import jax

                proc = int(jax.process_index())
            except Exception:   # pragma: no cover - no device runtime
                proc = ctx.rank
            owner = proc
            shard_files.extend(
                f for f in host_payload_files(ckpt_dir, process_index=proc)
                if f not in shard_files)
        step = int(engine.global_steps) if engine is not None else -1
        # the explicit owner stamp lets commit/verify cross-check the
        # path-derived attribution (integrity._owner_attribution_problems)
        write_host_manifest(ckpt_dir, ctx.host_id, ctx.generation, step,
                            files=shard_files, owner=owner)
        if ctx.is_coordinator:
            commit_pod_manifest(ckpt_dir, ctx.generation,
                                expected_hosts=ctx.hosts,
                                timeout_s=ctx.commit_timeout_s)
            maybe_fire(SITE_LATEST_PUBLISH, path=save_dir, tag=str(tag))
            with open(os.path.join(save_dir, LATEST_FILE), "w") as f:
                f.write(str(tag))
            log_dist(f"pod checkpoint {tag} committed by all "
                     f"{len(ctx.hosts)} host(s) of generation "
                     f"{ctx.generation} -> {ckpt_dir}", ranks=[0])
    return ckpt_dir


def pending_commit(store: CoordinationStore,
                   generation: int) -> Optional[str]:
    """The tag the coordinator most recently announced for commit under
    ``generation`` (None before the first save of the round).  Host-side
    shard writers poll this instead of scanning tag directories."""
    doc = store.get(f"commit/gen{generation}")
    return str(doc["tag"]) if doc else None


@dataclasses.dataclass
class PodContext:
    """One host's view of the pod for one generation."""
    store: CoordinationStore
    host_id: str
    hosts: List[str]                  # sorted membership of this generation
    generation: int
    lease_s: float = 5.0
    miss_limit: int = 3
    commit_timeout_s: float = 120.0
    # optional extra shard files a host contributes to the tag before its
    # manifest lands: fn(ckpt_dir, host_id) -> [relative paths].  Real jobs
    # leave it None (orbax wrote the shards inside the engine save);
    # simulated pods use it so torn-checkpoint coverage has real files.
    shard_writer: Optional[Callable[[str, str], Sequence[str]]] = None
    # in-RAM replica cadence for checkpoint-free recovery (0 = disabled):
    # every k completed steps each host seals its shard slab to its ring
    # buddy through the store (elasticity/replication.py)
    replica_every_k: int = 0

    @property
    def is_coordinator(self) -> bool:
        return bool(self.hosts) and self.host_id == self.hosts[0]

    @property
    def rank(self) -> int:
        return self.hosts.index(self.host_id)


class PodElasticAgent(ElasticAgent):
    """Elastic agent whose commit and restore are pod-scope.

    Saves run the all-hosts commit protocol; the restore walk additionally
    requires :func:`~..resilience.integrity.verify_pod_checkpoint_dir` to
    pass, so a torn pod tag (one host's shard/manifest missing) is
    quarantined and the walk falls back a generation — across pod sizes,
    since nothing in the tag binds it to a world size (global-array orbax
    payloads plus per-host attestations).

    With a ``watchdog`` (:class:`~.coordination.HeartbeatWatchdog`), the
    step loop raises :class:`PodPeerLost` as soon as a peer is declared
    dead, so this host exits the round at a step boundary instead of
    wedging in the next collective.

    **Live-state adoption** (ISSUE 20): when the supervisor hands the
    agent the previous round's membership + dead set
    (``adopt_prev_hosts`` / ``adopt_dead``), the restore walk first tries
    :func:`~.replication.adopt_replicas` — reconstruct the dead host's
    shards from its buddy's in-RAM replica and resume at the sealed step
    — and only on a loud :class:`~.replication.ReplicaAdoptionError`
    (missing slab, dead buddy, checksum, generation fence) falls back to
    the durable-checkpoint walk.  A ``replicator``
    (:class:`~.replication.HostReplicator`) seals this host's slab every
    ``ctx.replica_every_k`` steps from the step loop, plus a synchronous
    best-effort seal when a preemption signal is latched (the planned
    preemption never costs more than the in-flight step).
    """

    def __init__(self, engine, ckpt_dir: str, ctx: PodContext,
                 watchdog: Optional[HeartbeatWatchdog] = None,
                 replicator: Optional["HostReplicator"] = None,
                 adopt_prev_hosts: Optional[Sequence[str]] = None,
                 adopt_dead: Optional[Sequence[str]] = None, **kw):
        super().__init__(engine, ckpt_dir, **kw)
        self.ctx = ctx
        self.watchdog = watchdog
        self.replicator = replicator
        self.adopt_prev_hosts = tuple(adopt_prev_hosts or ())
        self.adopt_dead = tuple(adopt_dead or ())
        self.adopted_step: Optional[int] = None

    def _save(self) -> None:
        save_pod_checkpoint(self.engine, self.ckpt_dir, self.ctx,
                            tag=self.tag)

    def _pre_load_verify(self, tag_dir: str) -> None:
        verify_pod_checkpoint_dir(tag_dir)

    def _tag_committed(self, tag_dir: str) -> bool:
        from ..resilience.integrity import pod_committed

        return super()._tag_committed(tag_dir) and pod_committed(tag_dir)

    def restore_if_present(self) -> int:
        self._sweep_torn_pod_tags()
        if (self.adopt_prev_hosts and self.adopt_dead
                and self.engine is not None):
            try:
                resumed = adopt_replicas(
                    self.ctx.store, self.engine, self.adopt_prev_hosts,
                    self.adopt_dead, self.ctx.generation, self.ctx.host_id)
            except ReplicaAdoptionError as e:
                # LOUD fallback by contract: the replica layer is an
                # optimization over the durable commit protocol, never a
                # replacement — any doubt sends us down the checkpoint walk
                note_adoption_fallback()
                logger.error(
                    "pod restore: live-state adoption failed (%s); falling "
                    "back to checkpoint restart", e)
            else:
                self.adopted_step = self.resumed_step = int(resumed)
                log_dist(
                    f"pod resume via live adoption at step {resumed} "
                    f"(generation {self.ctx.generation}; rollback 0 steps "
                    "past the last sealed replica)", ranks=[0])
                return self.resumed_step
        return super().restore_if_present()

    def _sweep_torn_pod_tags(self) -> None:
        """Quarantine every tag that never pod-committed BEFORE the walk.
        The base walk only quarantines tags it visits, and a torn pod tag
        can sit AHEAD of ``latest`` (its writer died before the pointer
        moved) where the walk never reaches it — but a later save of the
        same step would silently mix generations into it.  Coordinator
        only: one renamer per pod, same as the base agent's process-0
        rule.  No pod save is in flight at restore time (pod saves join
        their commit before returning), so every uncommitted tag here is
        genuinely torn."""
        if not self.ctx.is_coordinator or not os.path.isdir(self.ckpt_dir):
            return
        from ..resilience.integrity import (candidate_tags, pod_committed,
                                            quarantine_tag)

        for tag in candidate_tags(self.ckpt_dir):
            tag_dir = os.path.join(self.ckpt_dir, tag)
            if pod_committed(tag_dir):
                continue
            logger.error(
                "pod restore: tag %s has no pod manifest (a host died "
                "before its shard committed); quarantining the torn pod "
                "checkpoint", tag_dir)
            try:
                quarantine_tag(self.ckpt_dir, tag)
            except OSError as e:
                logger.error("pod restore: quarantine of %s failed (%s); "
                             "skipping", tag_dir, e)

    def run(self, train_step_fn: Callable, total_steps: int) -> int:
        def stepped(engine, step):
            if self.watchdog is not None and self.watchdog.dead:
                raise PodPeerLost(self.watchdog.dead[0])
            out = train_step_fn(engine, step)
            if self.watchdog is not None:
                # progress rides the lease so peers + supervisor can watch
                self.watchdog.set_attrs(step=step + 1)
            if self.replicator is not None:
                if self.guard.should_stop:
                    # preemption latched (SIGTERM): synchronous best-effort
                    # seal BEFORE the save/exit sequence, so the planned
                    # preemption never costs more than the in-flight step
                    self.replicator.seal_now(step + 1)
                else:
                    self.replicator.maybe_replicate(step + 1)
            return out

        try:
            return super().run(stepped, total_steps)
        finally:
            if self.replicator is not None:
                # drain the in-flight publish: the final slab must be on
                # the store before the next round plans its adoption cut
                self.replicator.stop()


# ------------------------------------------------------- shrink-to-healthy

def shrink_to_healthy(elastic_config, healthy_hosts: Sequence[str],
                      chips_per_host: int = 1,
                      model_parallel_size: int = 1
                      ) -> Tuple[List[str], ElasticPlan]:
    """The largest slice the elastic plan admits within the healthy hosts.

    Device counts come from the same :func:`compute_elastic_config` plan
    the runtime binds to, so the shrunken job trains the SAME global batch
    with a re-derived (micro, gradient-accumulation) pair.  Raises
    :class:`ElasticityIncompatibleWorldSize` when even the smallest valid
    count needs more hosts than are healthy.
    """
    healthy = sorted(healthy_hosts)
    plan0 = compute_elastic_config(elastic_config, 0, chips_per_host,
                                   model_parallel_size)
    avail_devices = len(healthy) * chips_per_host
    fits = [c for c in plan0.valid_device_counts if c <= avail_devices]
    if not fits:
        raise ElasticityIncompatibleWorldSize(
            f"{len(healthy)} healthy host(s) x {chips_per_host} chip(s) = "
            f"{avail_devices} devices cannot run any elastic-compatible "
            f"count {list(plan0.valid_device_counts)}")
    best = max(fits)
    n_hosts = -(-best // chips_per_host)   # ceil
    plan = compute_elastic_config(elastic_config, best, chips_per_host,
                                  model_parallel_size)
    return healthy[:n_hosts], plan


@dataclasses.dataclass(frozen=True)
class PodRound:
    """What one supervisor round hands the attempt: the generation it must
    heartbeat/rendezvous/commit under, the member hosts (coordinator
    first), the batch triad the shrunken world trains with, plus — for the
    live-adoption path — the PREVIOUS round's membership and the dead set
    this round shrank away from (both empty on the first round)."""
    generation: int
    hosts: Tuple[str, ...]
    plan: ElasticPlan
    prev_hosts: Tuple[str, ...] = ()
    dead: Tuple[str, ...] = ()

    @property
    def n_hosts(self) -> int:
        return len(self.hosts)


class PodSupervisor(Supervisor):
    """Round-based pod re-formation on top of the hardened Supervisor.

    ``attempt(round: PodRound) -> int`` runs one full training round at the
    round's membership (launch/fan-out, rendezvous, heartbeats, pod
    checkpoints) and returns the job's exit code.  Before every round the
    supervisor re-reads the coordination store's dead-host markers (written
    by whichever peer's :class:`~.coordination.HeartbeatWatchdog` detected
    the miss), re-plans via :func:`shrink_to_healthy`, and bumps the pod
    generation — so a round after a host loss automatically re-forms at
    the largest healthy slice with the plan's batch triad, and a stale
    host from the previous incarnation can never rendezvous into it
    (records are generation-keyed).

    **Standby takeover** (``supervisor_id=``): the round loop runs under
    :func:`~.coordination.elect_coordinator` on ``pod/coordinator`` — the
    SAME lease protocol the serving-fleet router uses, with the same
    exactly-one-winner CAS proof under racing standbys.  A supervisor that
    does not hold the lease stands by (polls, drives nothing); when the
    leader's lease lapses, exactly one standby takes the next term, adopts
    the CURRENT pod generation and dead-host set from the store (both
    already live there — :func:`bump_generation` continues the monotonic
    counter, :meth:`healthy_hosts` re-reads the markers), and continues
    rounds where the dead leader stopped.  Long rounds must renew via
    :meth:`renew_coordinator` from the step loop (the runbook in
    docs/POD.md); a renewal returning False means a standby deposed us —
    stop driving.  ``supervisor_id=None`` (default) keeps the PR 5
    single-supervisor behavior: no election, rounds drive unconditionally.

    Exit semantics: :data:`RC_POD_PEER_LOST` is an ordinary failed round
    (the designed shrink path — backoff, budget, progress accounting all
    apply); an unshrinkable pod returns :data:`RC_POD_UNRECOVERABLE`,
    which is terminal; a standby that never wins within
    ``standby_max_wait_s`` stands down cleanly (no budget burned).
    """

    def __init__(self, store: CoordinationStore, elastic_config,
                 attempt: Callable[[PodRound], int], hosts: Sequence[str],
                 chips_per_host: int = 1, model_parallel_size: int = 1,
                 monitor=None, supervisor_id: Optional[str] = None,
                 election_key: str = POD_COORDINATOR_KEY,
                 coordinator_lease_s: float = 5.0,
                 standby_poll_s: float = 0.05,
                 standby_max_wait_s: Optional[float] = None,
                 **supervisor_kw):
        self.store = store
        self.elastic_config = elastic_config
        self.pod_attempt = attempt
        self.all_hosts = sorted(hosts)
        self.chips_per_host = int(chips_per_host)
        self.model_parallel_size = int(model_parallel_size)
        self.rounds: List[PodRound] = []
        self.supervisor_id = (str(supervisor_id)
                              if supervisor_id is not None else None)
        self.election_key = election_key
        self.coordinator_lease_s = float(coordinator_lease_s)
        self.standby_poll_s = float(standby_poll_s)
        self.standby_max_wait_s = (float(standby_max_wait_s)
                                   if standby_max_wait_s is not None
                                   else None)
        self.is_coordinator = self.supervisor_id is None
        self.term = 0
        self.elections_total = 0
        supervisor_kw.setdefault("terminal_rcs", (RC_POD_UNRECOVERABLE,))
        super().__init__(self._pod_round, monitor=monitor, **supervisor_kw)

    def healthy_hosts(self) -> List[str]:
        dead = set(dead_set(self.store))
        return [h for h in self.all_hosts if h not in dead]

    # ------------------------------------------------------------- election

    def renew_coordinator(self) -> bool:
        """Renew (or re-confirm) this supervisor's coordinator lease.
        Long training rounds call this from their step loop so the lease
        never lapses under a healthy driver; ``False`` means a standby
        deposed us — the caller must stop driving the round (the deposer
        adopted the store state and is re-driving).  Always ``True`` when
        elections are disabled (``supervisor_id=None``)."""
        if self.supervisor_id is None:
            return True
        lease = elect_coordinator(self.store, self.supervisor_id,
                                  self.coordinator_lease_s,
                                  key=self.election_key)
        self.is_coordinator = lease is not None
        if lease is not None:
            self.term = lease.term
        return lease is not None

    def _await_leadership(self) -> None:
        """Block until this supervisor holds the coordinator lease: the
        standby loop.  Exactly one of N racing candidates wins each term
        (the election CAS); a winner that TAKES OVER a lapsed term adopts
        the store's current pod generation and dead-host set — both are
        re-read from the store every round anyway, so adoption is just
        logging what the next round will naturally see."""
        if self.supervisor_id is None:
            return
        deadline = (time.monotonic() + self.standby_max_wait_s
                    if self.standby_max_wait_s is not None else None)
        while True:
            lease = elect_coordinator(self.store, self.supervisor_id,
                                      self.coordinator_lease_s,
                                      key=self.election_key)
            if lease is not None:
                if lease.term != self.term or not self.is_coordinator:
                    self.elections_total += 1
                    gen = read_generation(self.store)
                    dead = dead_set(self.store)
                    with trace_span("pod.election",
                                    supervisor=self.supervisor_id,
                                    term=lease.term):
                        log_dist(
                            f"pod supervisor {self.supervisor_id!r} leads "
                            f"term {lease.term} (adopting pod generation "
                            f"{gen}, {len(dead)} dead-host marker(s))",
                            ranks=[0])
                self.is_coordinator = True
                self.term = lease.term
                return
            self.is_coordinator = False
            if deadline is not None and time.monotonic() >= deadline:
                raise SupervisorStandDown(
                    f"pod supervisor {self.supervisor_id!r} stood by "
                    f"{self.standby_max_wait_s:.1f}s without the leader's "
                    "lease lapsing — the pod has a healthy driver")
            time.sleep(self.standby_poll_s)

    def _pod_round(self, _restarts: int) -> int:
        self._await_leadership()
        healthy = self.healthy_hosts()
        try:
            members, plan = shrink_to_healthy(
                self.elastic_config, healthy, self.chips_per_host,
                self.model_parallel_size)
        except ElasticityIncompatibleWorldSize as e:
            self.diagnosis = (
                f"pod unrecoverable: {e} — waiting for replacement hosts "
                "will not help this supervisor; clear the dead-host markers "
                "once capacity returns and relaunch")
            logger.error("pod supervisor: %s", self.diagnosis)
            return RC_POD_UNRECOVERABLE
        gen = bump_generation(self.store)
        prev = tuple(self.rounds[-1].hosts) if self.rounds else ()
        dead_now = tuple(sorted(set(self.all_hosts) - set(healthy)))
        rnd = PodRound(generation=gen, hosts=tuple(members), plan=plan,
                       prev_hosts=prev, dead=dead_now)
        self.rounds.append(rnd)
        if len(members) < len(self.all_hosts):
            logger.warning(
                "pod supervisor: generation %d re-forms at %d/%d host(s) "
                "(dead: %s) with batch triad %s", gen, len(members),
                len(self.all_hosts),
                sorted(set(self.all_hosts) - set(members)), plan.as_triad())
        if self.monitor is not None:
            self.monitor.write_events([
                ("pod/generation", float(gen), gen),
                ("pod/round_hosts", float(len(members)), gen),
                ("pod/dead_hosts",
                 float(len(self.all_hosts) - len(healthy)), gen),
                ("pod/coordinator_term", float(self.term), gen),
                ("pod/replica_adoptions_total",
                 float(replica_adoptions_total()), gen),
                ("pod/replica_fallbacks_total",
                 float(replica_fallbacks_total()), gen)])
        with trace_span("pod.round", generation=gen, hosts=len(members)):
            return self.pod_attempt(rnd)
