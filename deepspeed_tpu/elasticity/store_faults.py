"""Store fault injection: deterministic brownouts, partitions, torn writes.

Every fleet protocol (leases, election, sharded admission, the token
journal, channels, weight-epoch barriers) rides on a
:class:`~.coordination.CoordinationStore`, yet process-kill chaos leaves
the store itself perfectly healthy and instant.  This module closes that
gap with a :class:`FaultyStore` proxy that wraps ANY store with seeded,
per-op-class fault programs:

- **latency** — a real ``time.sleep`` before the op (the serve_bench
  store-latency sweep drives this);
- **error** — raise :class:`InjectedStoreFault` (an ``OSError``:
  transient, retryable — exactly what
  :class:`~.coordination.StoreRetryPolicy` absorbs);
- **timeout** — optional delay, then :class:`InjectedStoreTimeout`;
- **stale_read** — serve a PREVIOUSLY-observed document for the key
  instead of reading the backend (a lagging replica / cache);
- **torn_write** — leave a truncated document at the key by writing the
  file DIRECTLY (bypassing the store's tmp+rename discipline — the
  "crash between lock and rename" shape), then raise: the committed
  value is lost and a half-visible one is readable, which is what
  ``FileCoordinationStore.get``'s quarantine path recovers from;
- **blackout** — raise :class:`~.coordination.StoreUnavailable` for a
  store-clock window (``from_t``/``until_t``), or for as long as
  :attr:`FaultyStore.partitioned` is set.

Faults are PER CLIENT: each process (or simulated client) wraps the
shared backend in its own proxy, so member A can be dark while router B
sees a healthy store — the asymmetric partition no process-kill chaos
can express.  Rules carry their own seeded PRNG (mirroring
``resilience/fault_injection.FaultRule``), so a given seed + op sequence
fires identically on every run.

Env arming mirrors ``DS_TPU_FAULTS``: :func:`maybe_faulty` wraps a store
when :data:`STORE_FAULTS_ENV` holds a JSON rule list, which is how
``tools/fleet_member.py`` daemons join a fault schedule without code
changes.  Every proxied op additionally fires the generic
:func:`~..resilience.fault_injection.maybe_fire` at a ``store.*`` site,
so existing ``DS_TPU_FAULTS`` rules can target store traffic too.

See docs/RESILIENCE.md ("Store faults") and docs/FLEET.md ("Store
brownouts and partitions") for the client-side degradation contracts
these faults exercise.
"""
from __future__ import annotations

import json
import os
import random
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..resilience.fault_injection import maybe_fire
from ..utils.logging import logger
from .coordination import CoordinationStore, StoreUnavailable

__all__ = ["FaultyStore", "InjectedStoreFault", "InjectedStoreTimeout",
           "OP_CLASSES", "STORE_FAULTS_ENV", "SITE_STORE_CAS",
           "SITE_STORE_COMPARE_DELETE", "SITE_STORE_DELETE",
           "SITE_STORE_GET", "SITE_STORE_LIST", "SITE_STORE_PUT",
           "StoreFaultRule", "maybe_faulty", "rules_from_env"]

# env var holding a JSON list of rule specs (see StoreFaultRule.from_spec)
# — the store-op analogue of resilience/fault_injection.FAULTS_ENV
STORE_FAULTS_ENV = "DS_TPU_STORE_FAULTS"

# generic-injector sites (docs/RESILIENCE.md registry): every proxied op
# class fires one, so DS_TPU_FAULTS rules can hit store traffic without
# a FaultyStore in the stack
SITE_STORE_GET = "store.get"
SITE_STORE_PUT = "store.put"
SITE_STORE_CAS = "store.cas"
SITE_STORE_DELETE = "store.delete"
SITE_STORE_COMPARE_DELETE = "store.compare_delete"
SITE_STORE_LIST = "store.list"

# op classes a rule can target.  compare_and_swap is "cas" and
# compare_and_delete is "compare_delete"; clear_tombstone rides the
# "delete" class (it is a removal on the same write path).
OP_CLASSES = ("get", "put", "cas", "delete", "compare_delete", "list")

_OP_SITES = {
    "get": SITE_STORE_GET,
    "put": SITE_STORE_PUT,
    "cas": SITE_STORE_CAS,
    "delete": SITE_STORE_DELETE,
    "compare_delete": SITE_STORE_COMPARE_DELETE,
    "list": SITE_STORE_LIST,
}

KINDS = ("latency", "error", "timeout", "stale_read", "torn_write",
         "blackout")


class InjectedStoreFault(OSError):
    """A deterministic injected store failure.  An ``OSError`` on
    purpose: it is TRANSIENT by contract — the same class of failure a
    real flaky backend raises — and every client-side retry discipline
    (:class:`~.coordination.StoreRetryPolicy`) absorbs it.  Contrast
    :class:`~.coordination.StoreUnavailable`, which means "stop
    retrying and degrade"."""


class InjectedStoreTimeout(InjectedStoreFault):
    """An injected operation timeout (optionally after a real delay)."""


@dataclass
class StoreFaultRule:
    """One seeded fault program over an op class (see module docstring
    for the kinds).  Trigger selection mirrors
    ``resilience/fault_injection.FaultRule``: ``at_call`` (1-based Nth
    MATCHING call), ``every`` (every Nth), ``probability`` (per-rule
    seeded PRNG), or — with none of those — every matching call, which
    is what windowed blackouts and flat latency programs want.
    ``max_fires`` caps total fires; ``key_prefix`` scopes to a key
    namespace; ``client`` scopes to one proxy's client id;
    ``from_t``/``until_t`` gate on the STORE clock (injectable in
    soaks, so windows land at exact rounds)."""
    ops: Tuple[str, ...] = OP_CLASSES
    kind: str = "error"
    key_prefix: Optional[str] = None
    client: Optional[str] = None
    at_call: Optional[int] = None
    every: Optional[int] = None
    probability: Optional[float] = None
    max_fires: Optional[int] = None
    delay_s: float = 0.0
    from_t: Optional[float] = None
    until_t: Optional[float] = None
    seed: int = 0
    calls: int = field(default=0, init=False)
    fires: int = field(default=0, init=False)

    def __post_init__(self):
        if isinstance(self.ops, str):
            self.ops = OP_CLASSES if self.ops == "*" else (self.ops,)
        self.ops = tuple(self.ops)
        for op in self.ops:
            if op not in OP_CLASSES:
                raise ValueError(
                    f"store fault rule: unknown op {op!r} "
                    f"(one of {OP_CLASSES})")
        if self.kind not in KINDS:
            raise ValueError(
                f"store fault rule: unknown kind {self.kind!r} "
                f"(one of {KINDS})")
        self._rng = random.Random(self.seed)

    @classmethod
    def from_spec(cls, spec: Dict[str, Any]) -> "StoreFaultRule":
        """Build a rule from one JSON spec dict (the DS_TPU_STORE_FAULTS
        payload is a list of these)."""
        known = {"ops", "kind", "key_prefix", "client", "at_call", "every",
                 "probability", "max_fires", "delay_s", "from_t", "until_t",
                 "seed"}
        unknown = set(spec) - known
        if unknown:
            raise ValueError(
                f"store fault rule: unknown field(s) {sorted(unknown)}")
        return cls(**spec)

    def matches(self, op: str, key: str, client: str, now: float) -> bool:
        if op not in self.ops:
            return False
        if self.key_prefix is not None \
                and not key.startswith(self.key_prefix):
            return False
        if self.client is not None and self.client != client:
            return False
        if self.from_t is not None and now < self.from_t:
            return False
        if self.until_t is not None and now >= self.until_t:
            return False
        return True

    def triggers(self) -> bool:
        """Count one matching call and decide whether this rule fires on
        it — deterministic per (seed, call sequence)."""
        self.calls += 1
        if self.max_fires is not None and self.fires >= self.max_fires:
            return False
        if self.at_call is not None:
            fire = self.calls == int(self.at_call)
        elif self.every is not None:
            fire = self.calls % int(self.every) == 0
        elif self.probability is not None:
            fire = self._rng.random() < float(self.probability)
        else:
            fire = True
        if fire:
            self.fires += 1
        return fire


class _Stale:
    """Sentinel carrying a stale document past the real read."""
    __slots__ = ("doc",)

    def __init__(self, doc):
        self.doc = doc


class FaultyStore(CoordinationStore):
    """Per-client fault-injecting proxy over any coordination store.

    Delegates every op to ``inner`` after running the fault program
    (see the module docstring).  Unknown attributes delegate too, so
    backend surface like ``cas_contended_total``, ``corrupt_docs_total``
    or ``_path`` stays reachable through the proxy.  Per-op wall
    latencies are recorded in bounded windows
    (:meth:`op_latency_percentiles`) — the measurement surface of
    ``serve_bench --store_latency_ms``."""

    def __init__(self, inner: CoordinationStore, client: str = "client",
                 rules: Optional[List[StoreFaultRule]] = None,
                 latency_window: int = 4096):
        self.inner = inner
        self.client = str(client)
        self.rules: List[StoreFaultRule] = list(rules or ())
        # manual asymmetric-partition toggle: while set, EVERY op raises
        # StoreUnavailable for this client only — the soak's scheduled
        # partitions flip it at exact rounds
        self.partitioned = False
        self.ops_total = 0
        self.faults_total = 0
        self.faults_by_kind: Dict[str, int] = {}
        self._lat: Dict[str, deque] = {
            op: deque(maxlen=int(latency_window)) for op in OP_CLASSES}
        # key -> up to the last two DISTINCT observed documents (oldest
        # first): what a stale read serves
        self._seen: Dict[str, List[Optional[Dict]]] = {}
        self._lock = threading.Lock()

    # ------------------------------------------------------ fault program

    def _count(self, kind: str) -> None:
        with self._lock:
            self.faults_total += 1
            self.faults_by_kind[kind] = self.faults_by_kind.get(kind, 0) + 1

    def _apply(self, op: str, key: str,
               value: Optional[Dict] = None) -> Optional[_Stale]:
        """Run the fault program for one op.  Returns a :class:`_Stale`
        sentinel (get only) when a stale read replaces the real one;
        raises for error/timeout/blackout kinds; sleeps for latency."""
        maybe_fire(_OP_SITES[op], key=key, client=self.client)
        with self._lock:
            self.ops_total += 1
        if self.partitioned:
            self._count("blackout")
            raise StoreUnavailable(
                f"store blackout: client {self.client!r} is partitioned "
                f"from the store ({op} {key!r})")
        now = self.inner.now()
        stale: Optional[_Stale] = None
        for rule in self.rules:
            if not rule.matches(op, key, self.client, now):
                continue
            if not rule.triggers():
                continue
            kind = rule.kind
            if kind == "latency":
                if rule.delay_s > 0:
                    time.sleep(rule.delay_s)
                continue   # latency composes with any later rule
            self._count(kind)
            if kind == "error":
                raise InjectedStoreFault(
                    f"injected store fault: {op} {key!r} "
                    f"(client {self.client!r})")
            if kind == "timeout":
                if rule.delay_s > 0:
                    time.sleep(rule.delay_s)
                raise InjectedStoreTimeout(
                    f"injected store timeout: {op} {key!r} "
                    f"(client {self.client!r})")
            if kind == "blackout":
                raise StoreUnavailable(
                    f"store blackout window: {op} {key!r} "
                    f"(client {self.client!r}, t={now:.3f})")
            if kind == "stale_read" and op == "get":
                hist = self._seen.get(key) or []
                stale = _Stale(hist[0] if hist else None)
            if kind == "torn_write" and op in ("put", "cas") \
                    and value is not None:
                self._tear(key, value)
                raise InjectedStoreFault(
                    f"injected torn write: {op} {key!r} crashed between "
                    f"lock and rename (client {self.client!r})")
        return stale

    def _tear(self, key: str, value: Dict) -> None:
        """Leave a truncated document at ``key`` by writing the backing
        file DIRECTLY — no tmp, no atomic rename: the torn state a
        writer crash mid-write leaves on storage without the
        write-to-tmp discipline.  File backends only (a backend without
        ``_path`` just gets the transient error)."""
        path_fn = getattr(self.inner, "_path", None)
        if path_fn is None:
            return
        try:
            path = path_fn(key)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            data = json.dumps(value)
            with open(path, "w", encoding="utf-8") as fh:
                fh.write(data[:max(1, len(data) // 2)])
        except OSError:   # pragma: no cover - defensive
            pass

    def _remember(self, key: str, doc: Optional[Dict]) -> None:
        hist = self._seen.setdefault(key, [])
        if not hist or hist[-1] != doc:
            hist.append(doc)
            del hist[:-2]

    def _timed(self, op: str, fn):
        t0 = time.perf_counter()
        try:
            return fn()
        finally:
            self._lat[op].append(time.perf_counter() - t0)

    # -------------------------------------------------------- the surface

    # _timed wraps the WHOLE op — fault application (where latency rules
    # sleep) plus the inner call — so op_latency_percentiles() reports
    # what a caller actually waited, injected delay included

    def get(self, key: str) -> Optional[Dict]:
        def _op():
            stale = self._apply("get", key)
            if stale is not None:
                return stale.doc
            doc = self.inner.get(key)
            self._remember(key, doc)
            return doc
        return self._timed("get", _op)

    def put(self, key: str, value: Dict) -> None:
        def _op():
            self._apply("put", key, value=value)
            self.inner.put(key, value)
            self._remember(key, value)
        self._timed("put", _op)

    def compare_and_swap(self, key: str, expected: Optional[Dict],
                         new: Dict) -> bool:
        def _op():
            self._apply("cas", key, value=new)
            won = self.inner.compare_and_swap(key, expected, new)
            if won:
                self._remember(key, new)
            return won
        return self._timed("cas", _op)

    def delete(self, key: str) -> None:
        def _op():
            self._apply("delete", key)
            self.inner.delete(key)
            self._remember(key, None)
        self._timed("delete", _op)

    def compare_and_delete(self, key: str, expected: Dict) -> bool:
        def _op():
            self._apply("compare_delete", key)
            won = self.inner.compare_and_delete(key, expected)
            if won:
                self._remember(key, None)
            return won
        return self._timed("compare_delete", _op)

    def clear_tombstone(self, key: str) -> None:
        def _op():
            self._apply("delete", key)
            self.inner.clear_tombstone(key)
        self._timed("delete", _op)

    def list(self, prefix: str) -> List[str]:
        def _op():
            self._apply("list", prefix)
            return self.inner.list(prefix)
        return self._timed("list", _op)

    def now(self) -> float:
        # never faulted: the clock is process-local state, not a store
        # round trip — and blacking it out would freeze lease math on
        # exactly the client whose lease is supposed to LAPSE
        return self.inner.now()

    def __getattr__(self, name: str):
        # backend surface (cas_contended_total, corrupt_docs_total,
        # _path, root, ...) stays reachable through the proxy
        return getattr(self.inner, name)

    # ------------------------------------------------------- measurement

    def op_latencies(self, op: str) -> List[float]:
        """Recent wall seconds per ``op`` (bounded window), injected
        latency included — what the bench computes percentiles over."""
        return list(self._lat[op])

    def op_latency_percentiles(self) -> Dict[str, Dict[str, float]]:
        """Per-op-class ``{"p50", "p99", "n"}`` over the recorded
        windows (ops with no samples are omitted)."""
        out: Dict[str, Dict[str, float]] = {}
        for op, window in self._lat.items():
            if not window:
                continue
            lat = sorted(window)
            out[op] = {
                "p50": lat[len(lat) // 2],
                "p99": lat[min(len(lat) - 1, int(len(lat) * 0.99))],
                "n": float(len(lat)),
            }
        return out


def rules_from_env(env: Optional[str] = None) -> List[StoreFaultRule]:
    """Parse the :data:`STORE_FAULTS_ENV` JSON rule list (``env``
    overrides the environment for tests).  Returns ``[]`` when unset.
    A malformed spec raises — a chaos schedule that silently parses to
    nothing would report a clean soak that injected no faults."""
    raw = (env if env is not None
           else os.environ.get(STORE_FAULTS_ENV, "")).strip()
    if not raw:
        return []
    specs = json.loads(raw)
    if not isinstance(specs, list):
        raise ValueError(
            f"{STORE_FAULTS_ENV} must hold a JSON LIST of rule specs, "
            f"got {type(specs).__name__}")
    return [StoreFaultRule.from_spec(s) for s in specs]


def maybe_faulty(store: CoordinationStore, client: str,
                 env: Optional[str] = None) -> CoordinationStore:
    """Wrap ``store`` in a :class:`FaultyStore` when
    :data:`STORE_FAULTS_ENV` is armed (else return it unchanged) — the
    one hook every store-building entrypoint calls so daemons join a
    fault schedule by environment alone (``tools/fleet_member.py``)."""
    rules = rules_from_env(env)
    if not rules:
        return store
    logger.warning("store faults armed for client %r: %d rule(s) from %s",
                   client, len(rules), STORE_FAULTS_ENV)
    return FaultyStore(store, client=client, rules=rules)
