"""In-tree elastic restart supervisor (reference
``deepspeed/elasticity/elastic_agent.py:28`` ``DSElasticAgent`` +
``launcher/launch.py:255-313`` — torch-elastic restarts worker groups on
membership change; the TPU equivalent relaunches the JOB at the current
resource shape and lets checkpoint resharding absorb the topology change).

This implements the wrapper contract ``ElasticAgent.run`` documents: the
training process checkpoints on preemption and exits nonzero while work
remains; the supervisor re-discovers resources and relaunches until the job
exits 0 (complete) or the restart budget is exhausted.  Because discovery
runs again on every round, a restart after a resize naturally launches at
the NEW world size — ``ElasticAgent.restore_if_present`` +
``compute_elastic_config`` rebuild the schedule there, and orbax restores
the last committed checkpoint onto the new mesh.

Hardening (resilience subsystem):

- **jittered exponential backoff**: retry delay grows ``backoff_s *
  backoff_mult**(n-1)`` capped at ``backoff_max_s``, with a deterministic
  seeded jitter so a preempted pod's hosts don't stampede storage in
  lockstep;
- **progress-aware restart budget**: with a ``progress_fn`` (see
  ``resilience.checkpoint_progress_fn``), a failed round that still
  advanced the committed checkpoint refreshes the budget — long jobs on
  preemptible capacity survive unbounded *productive* restarts — while
  ``zero_progress_limit`` consecutive rounds with no progress trip a
  circuit breaker with a terminal diagnosis instead of crash-looping
  forever on a poisoned state.
"""
from __future__ import annotations

import time
from random import Random
from typing import Callable, Optional, Sequence

from ..resilience.fault_injection import SITE_SUPERVISOR_ATTEMPT, maybe_fire
from ..utils.logging import logger

# exit codes that must NOT trigger a relaunch
RC_COMPLETE = 0          # training finished
RC_INTERRUPT = 130       # operator ^C through the launcher


class SupervisorStandDown(Exception):
    """An attempt determined this supervisor should stop cleanly WITHOUT
    consuming restart budget or backoff: it is not the driver and never
    will be within its wait bound (e.g. an elected standby pod supervisor
    whose leader stayed healthy past ``standby_max_wait_s``).  ``rc`` is
    what :meth:`Supervisor.run` returns — standing down is not a failed
    round, so the default is success."""

    def __init__(self, reason: str, rc: int = RC_COMPLETE):
        super().__init__(reason)
        self.reason = reason
        self.rc = int(rc)


class Supervisor:
    """Relaunch loop around a launch attempt.

    ``attempt(round_idx) -> int`` performs one full discovery + launch and
    returns the job's exit code.  The supervisor relaunches on any failure
    exit until ``max_restarts`` is spent; interrupts are terminal.

    ``progress_fn() -> int`` (optional) reports monotonically comparable
    progress (newest committed checkpoint step); ``zero_progress_limit`` of
    K > 0 trips the circuit breaker after K consecutive failed rounds that
    made no progress.  After the run, ``breaker_tripped`` / ``diagnosis``
    describe a terminal failure.

    ``monitor`` (optional): every failed round ships a flight-recorder
    dump through ``monitor.write_report`` when tracing is enabled, so a
    crash-looping job's restart log carries the spans of each failed
    attempt (docs/OBSERVABILITY.md); the most recent dump also stays
    readable on ``last_flight_dump``.
    """

    def __init__(self, attempt: Callable[[int], int], max_restarts: int = 10,
                 backoff_s: float = 3.0,
                 on_round: Optional[Callable[[int, int], None]] = None,
                 backoff_mult: float = 2.0, backoff_max_s: float = 60.0,
                 jitter: float = 0.25,
                 progress_fn: Optional[Callable[[], int]] = None,
                 zero_progress_limit: int = 0, seed: int = 0, monitor=None,
                 terminal_rcs: Sequence[int] = ()):
        self.attempt = attempt
        # exit codes that are PERMANENT no matter the budget (e.g. the pod
        # supervisor's "healthy slice below the elastic floor") — relaunching
        # cannot change them, so retrying only burns the backoff schedule
        self.terminal_rcs = frozenset(terminal_rcs)
        self.max_restarts = max_restarts
        self.backoff_s = backoff_s
        self.on_round = on_round
        self.backoff_mult = backoff_mult
        self.backoff_max_s = backoff_max_s
        self.jitter = jitter
        self.progress_fn = progress_fn
        self.zero_progress_limit = zero_progress_limit
        self._rng = Random(seed)
        self.breaker_tripped = False
        self.diagnosis: Optional[str] = None
        self.monitor = monitor
        self.last_flight_dump: Optional[str] = None

    def backoff_delay(self, consecutive_failures: int) -> float:
        """Exponential in the *consecutive* failure count (a productive
        restart resets it), capped, with ±jitter."""
        base = self.backoff_s * self.backoff_mult ** max(
            0, consecutive_failures - 1)
        base = min(base, self.backoff_max_s)
        if self.jitter > 0 and base > 0:
            base *= 1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
        return base

    def run(self) -> int:
        restarts = 0          # spent against max_restarts; refreshed on progress
        consecutive = 0       # consecutive failures, drives backoff + breaker
        rounds = 0
        last_progress = self.progress_fn() if self.progress_fn else None
        while True:
            try:
                maybe_fire(SITE_SUPERVISOR_ATTEMPT, round=rounds)
                rc = self.attempt(restarts)
            except KeyboardInterrupt:
                raise
            except SupervisorStandDown as e:
                # not a failed round: another supervisor is (and stays) the
                # driver — exit without burning budget or backoff
                self.diagnosis = f"stand-down: {e.reason}"
                logger.info("elastic supervisor: %s", self.diagnosis)
                return e.rc
            except Exception as e:
                # a transient discovery failure (e.g. pod metadata absent
                # WHILE the preempted slice is being recreated) must consume
                # a restart, not crash the supervisor in exactly the window
                # elastic restarts exist to survive
                logger.warning("elastic supervisor: attempt raised %s: %s; "
                               "treating as failed round", type(e).__name__, e)
                rc = 1
            rounds += 1
            if self.on_round is not None:
                self.on_round(restarts, rc)
            if rc == RC_COMPLETE:
                if restarts or rounds > 1:
                    logger.info("elastic supervisor: job complete after "
                                "%d round(s)", rounds)
                return 0
            if rc == RC_INTERRUPT:
                logger.info("elastic supervisor: interrupted; not restarting")
                return rc
            if rc in self.terminal_rcs:
                if self.diagnosis is None:
                    self.diagnosis = (f"terminal exit code {rc}: the failure "
                                      "is permanent by contract; not "
                                      "relaunching")
                logger.error("elastic supervisor: %s", self.diagnosis)
                return rc
            consecutive += 1
            # failed round: capture the attempt's span history before the
            # next attempt overwrites the ring (None when tracing is off)
            try:
                from ..observability.trace import (dump_window_s,
                                                   flight_dump)

                self.last_flight_dump = flight_dump(
                    f"supervisor.round[{rounds}] rc={rc}",
                    monitor=self.monitor, last_s=dump_window_s())
            except Exception as e:
                logger.warning("elastic supervisor: flight dump failed "
                               "(%s: %s)", type(e).__name__, e)
                self.last_flight_dump = None
            if self.progress_fn is not None:
                cur = self.progress_fn()
                if last_progress is None or cur > last_progress:
                    # the failed round still committed new checkpoints —
                    # productive preemption churn, not a crash loop
                    logger.info(
                        "elastic supervisor: round failed (rc=%d) but "
                        "progress advanced %s -> %s; refreshing restart "
                        "budget", rc, last_progress, cur)
                    last_progress = cur
                    restarts = 0
                    # the productive round itself must not count toward the
                    # zero-progress streak: consecutive resets to 0, so the
                    # breaker needs zero_progress_limit FURTHER barren
                    # rounds (1 here tripped it one round early)
                    consecutive = 0
                elif cur < last_progress:
                    # the committed frontier REGRESSED (newest generation
                    # quarantined on restore): re-anchor, or genuine forward
                    # progress from the fallback generation would keep
                    # comparing against the dead high-water mark and read
                    # as a crash loop
                    logger.warning(
                        "elastic supervisor: committed progress regressed "
                        "%s -> %s (generation quarantined?); re-anchoring",
                        last_progress, cur)
                    last_progress = cur
                elif self.zero_progress_limit and \
                        consecutive >= self.zero_progress_limit:
                    self.breaker_tripped = True
                    self.diagnosis = (
                        f"circuit breaker: {consecutive} consecutive "
                        f"failed rounds with no checkpoint progress "
                        f"(stuck at step {cur}, last rc={rc}) — the job is "
                        "crash-looping on a non-transient fault (poisoned "
                        "state, incompatible config, or unrecoverable "
                        "corruption); NOT relaunching. Inspect the newest "
                        "*.corrupt quarantine dirs and the last failure "
                        "log before restarting manually.")
                    logger.error("elastic supervisor: %s", self.diagnosis)
                    return rc
            if restarts >= self.max_restarts:
                logger.error(
                    "elastic supervisor: rc=%d with restart budget exhausted "
                    "(%d); giving up", rc, self.max_restarts)
                return rc
            restarts += 1
            delay = self.backoff_delay(consecutive)
            logger.warning(
                "elastic supervisor: job exited rc=%d; relaunching "
                "(restart %d/%d) after %.1fs — resources are re-discovered, "
                "so a resized slice relaunches at the new world size",
                rc, restarts, self.max_restarts, delay)
            if delay > 0:
                time.sleep(delay)
