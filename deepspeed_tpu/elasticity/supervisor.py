"""In-tree elastic restart supervisor (reference
``deepspeed/elasticity/elastic_agent.py:28`` ``DSElasticAgent`` +
``launcher/launch.py:255-313`` — torch-elastic restarts worker groups on
membership change; the TPU equivalent relaunches the JOB at the current
resource shape and lets checkpoint resharding absorb the topology change).

This implements the wrapper contract ``ElasticAgent.run`` documents: the
training process checkpoints on preemption and exits nonzero while work
remains; the supervisor re-discovers resources and relaunches until the job
exits 0 (complete) or the restart budget is exhausted.  Because discovery
runs again on every round, a restart after a resize naturally launches at
the NEW world size — ``ElasticAgent.restore_if_present`` +
``compute_elastic_config`` rebuild the schedule there, and orbax restores
the last committed checkpoint onto the new mesh.
"""
from __future__ import annotations

import time
from typing import Callable, Optional

from ..utils.logging import logger

# exit codes that must NOT trigger a relaunch
RC_COMPLETE = 0          # training finished
RC_INTERRUPT = 130       # operator ^C through the launcher


class Supervisor:
    """Relaunch loop around a launch attempt.

    ``attempt(round_idx) -> int`` performs one full discovery + launch and
    returns the job's exit code.  The supervisor relaunches on any failure
    exit until ``max_restarts`` is spent; interrupts are terminal.
    """

    def __init__(self, attempt: Callable[[int], int], max_restarts: int = 10,
                 backoff_s: float = 3.0,
                 on_round: Optional[Callable[[int, int], None]] = None):
        self.attempt = attempt
        self.max_restarts = max_restarts
        self.backoff_s = backoff_s
        self.on_round = on_round

    def run(self) -> int:
        restarts = 0
        while True:
            try:
                rc = self.attempt(restarts)
            except KeyboardInterrupt:
                raise
            except Exception as e:
                # a transient discovery failure (e.g. pod metadata absent
                # WHILE the preempted slice is being recreated) must consume
                # a restart, not crash the supervisor in exactly the window
                # elastic restarts exist to survive
                logger.warning("elastic supervisor: attempt raised %s: %s; "
                               "treating as failed round", type(e).__name__, e)
                rc = 1
            if self.on_round is not None:
                self.on_round(restarts, rc)
            if rc == RC_COMPLETE:
                if restarts:
                    logger.info("elastic supervisor: job complete after "
                                "%d restart(s)", restarts)
                return 0
            if rc == RC_INTERRUPT:
                logger.info("elastic supervisor: interrupted; not restarting")
                return rc
            if restarts >= self.max_restarts:
                logger.error(
                    "elastic supervisor: rc=%d with restart budget exhausted "
                    "(%d); giving up", rc, self.max_restarts)
                return rc
            restarts += 1
            logger.warning(
                "elastic supervisor: job exited rc=%d; relaunching "
                "(restart %d/%d) after %.1fs — resources are re-discovered, "
                "so a resized slice relaunches at the new world size",
                rc, restarts, self.max_restarts, self.backoff_s)
            if self.backoff_s > 0:
                time.sleep(self.backoff_s)
