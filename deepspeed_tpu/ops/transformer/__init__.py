"""Fused BERT-style transformer layer API (reference
``ops/transformer/transformer.py:296`` ``DeepSpeedTransformerLayer`` +
``DeepSpeedTransformerConfig``).

The reference builds this layer from hand-fused CUDA kernels (softmax,
layernorm, dropout, gemm scheduling — csrc/transformer/*.cu); on TPU the
fusion is XLA's job and the flash-attention Pallas kernel covers the one
fusion XLA cannot do.  This module keeps the reference's *API* so BERT-style
training code ports verbatim: a per-layer config, a layer object with
``init``/``apply``, pre-LN or post-LN selection, and the reference's knobs —
where a knob only selects a CUDA implementation detail (``stochastic_mode``,
``normalize_invertible``, ``attn_dropout_checkpoint``, ``gelu_checkpoint``)
it is accepted and recorded, because under XLA the deterministic and
"stochastic" schedules compile to the same program and invertible-LN /
checkpoint tricks are what ``jax.checkpoint`` policies already do.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from ...models.transformer import (TransformerConfig, _block)

__all__ = ["DeepSpeedTransformerConfig", "DeepSpeedTransformerLayer"]


@dataclasses.dataclass
class DeepSpeedTransformerConfig:
    """Reference-shaped config (transformer.py:34)."""

    batch_size: int = -1
    hidden_size: int = -1
    intermediate_size: int = -1
    heads: int = -1
    attn_dropout_ratio: float = 0.0
    hidden_dropout_ratio: float = 0.0
    num_hidden_layers: int = -1
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12
    seed: int = -1
    fp16: bool = False
    pre_layer_norm: bool = True
    # CUDA-implementation knobs, accepted for API parity (see module doc):
    normalize_invertible: bool = False
    gelu_checkpoint: bool = False
    adjust_init_range: bool = True
    attn_dropout_checkpoint: bool = False
    stochastic_mode: bool = False
    return_tuple: bool = False
    training: bool = True

    def to_native(self) -> TransformerConfig:
        if self.intermediate_size <= 0:
            raise ValueError("intermediate_size must be set")
        if self.attn_dropout_ratio != self.hidden_dropout_ratio:
            raise NotImplementedError(
                "separate attention/hidden dropout ratios are not supported "
                "(one dropout knob drives both sites)")
        return TransformerConfig(
            vocab_size=1,  # layer-only: no embedding/head
            hidden_size=self.hidden_size,
            intermediate_size=self.intermediate_size,
            num_layers=self.num_hidden_layers,
            num_heads=self.heads,
            max_seq_len=1 << 16,
            norm="layernorm", activation="gelu_exact",
            # BERT-style layer: positions live in the embedding, not the
            # block ("learned" => the block applies no rope/alibi), and
            # attention is bidirectional
            position="learned", causal=False,
            post_layernorm=not self.pre_layer_norm,
            attn_bias=True, mlp_bias=True,
            dropout=self.hidden_dropout_ratio,
            norm_eps=self.layer_norm_eps,
            initializer_range=self.initializer_range,
            dtype=jnp.bfloat16 if self.fp16 else jnp.float32,
            remat=self.gelu_checkpoint or self.attn_dropout_checkpoint,
            scan_layers=False)


class DeepSpeedTransformerLayer:
    """One transformer layer with the reference's object surface:
    ``layer = DeepSpeedTransformerLayer(config)``, ``params = layer.init(rng)``,
    ``out = layer.apply(params, hidden_states[, input_mask])``.

    Functional (params are explicit), so the same layer object serves every
    depth — the reference's per-layer ``layer_id`` bookkeeping is not needed.
    """

    def __init__(self, config: DeepSpeedTransformerConfig,
                 initial_weights: Optional[Dict[str, Any]] = None,
                 initial_biases: Optional[Dict[str, Any]] = None):
        self.config = config
        self.native = config.to_native()
        self._initial = (initial_weights, initial_biases)

    def init(self, rng: jax.Array) -> Dict[str, Any]:
        d, f = self.native.hidden_size, self.native.intermediate_size
        hd, nh = self.native.dims_per_head, self.native.num_heads
        std = self.config.initializer_range
        if self.config.adjust_init_range:
            # reference output_std = std / sqrt(2*L) on the residual path
            out_std = std / (2.0 * max(self.config.num_hidden_layers, 1)) ** .5
        else:
            out_std = std
        k = jax.random.split(rng, 8)

        def dense(key, shape, scale=std):
            return jax.random.normal(key, shape, jnp.float32) * scale

        lp = {
            "attn_norm_scale": jnp.ones((d,)),
            "attn_norm_bias": jnp.zeros((d,)),
            "mlp_norm_scale": jnp.ones((d,)),
            "mlp_norm_bias": jnp.zeros((d,)),
            "wq": dense(k[0], (d, nh * hd)), "bq": jnp.zeros((nh * hd,)),
            "wk": dense(k[1], (d, nh * hd)), "bk": jnp.zeros((nh * hd,)),
            "wv": dense(k[2], (d, nh * hd)), "bv": jnp.zeros((nh * hd,)),
            "wo": dense(k[3], (nh * hd, d), out_std), "bo": jnp.zeros((d,)),
            "w_in": dense(k[4], (d, f)), "b_in": jnp.zeros((f,)),
            "w_down": dense(k[5], (f, d), out_std), "b_down": jnp.zeros((d,)),
        }
        iw, ib = self._initial
        if iw:
            lp.update({key: jnp.asarray(v) for key, v in iw.items()})
        if ib:
            lp.update({key: jnp.asarray(v) for key, v in ib.items()})
        return lp

    def apply(self, params: Dict[str, Any], hidden_states: jax.Array,
              input_mask: Optional[jax.Array] = None,
              rng: Optional[jax.Array] = None,
              deterministic: Optional[bool] = None) -> jax.Array:
        if input_mask is not None:
            # reject tracers structurally (concretizing one would surface as
            # a confusing TracerBoolConversionError under jit/vmap); concrete
            # arrays keep the device-side reduce — one scalar transfer
            if isinstance(input_mask, jax.core.Tracer):
                raise NotImplementedError(
                    "input_mask cannot be a traced value: per-token masks "
                    "are not wired into the layer-level API (the BERT "
                    "injection path handles padding); pass None")
            if not bool(jnp.all(input_mask)):
                raise NotImplementedError(
                    "per-token input masks are not wired into the layer-level "
                    "API (the BERT injection path handles padding); pass an "
                    "all-ones mask or None")
        B, S, _ = hidden_states.shape
        positions = jnp.broadcast_to(
            jnp.arange(S, dtype=jnp.int32)[None, :], (B, S))
        det = (not self.config.training if deterministic is None
               else deterministic)
        out, _aux = _block(
            self.native, params, hidden_states.astype(self.native.dtype),
            positions, rng if rng is not None else jax.random.PRNGKey(
                max(self.config.seed, 0)),
            attn_impl="auto", deterministic=det)
        return (out,) if self.config.return_tuple else out
