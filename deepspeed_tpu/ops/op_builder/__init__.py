"""Native op build system (reference ``op_builder/builder.py``).

JIT-compiles the C++ sources in ``ops/csrc/`` into shared libraries with the
system toolchain on first use and binds them via ctypes (this image has no
pybind11; the ops export a C ABI).  Mirrors the reference's contract:

  builder = CPUAdamBuilder()
  builder.is_compatible()   -> toolchain + CPU feature probe
  builder.is_built()        -> cached .so exists
  builder.load()            -> ctypes.CDLL with typed signatures (compiles
                               on demand, like the reference's JIT path)

``ALL_OPS`` is the registry ``ds_report`` walks (env_report.py).
Build artifacts live under ``ops/csrc/build/`` (env override
``DS_TPU_OPS_BUILD_DIR``).
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import shutil
import subprocess
from typing import Dict, List, Optional, Type

from ...utils.logging import logger

_CSRC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "csrc")


def _build_dir() -> str:
    d = os.environ.get("DS_TPU_OPS_BUILD_DIR") or os.path.join(_CSRC, "build")
    os.makedirs(d, exist_ok=True)
    return d


class OpBuilder:
    NAME = "base"
    SOURCES: List[str] = []

    def __init__(self):
        self._lib: Optional[ctypes.CDLL] = None

    # -- probes ----------------------------------------------------------
    def compiler(self) -> Optional[str]:
        return shutil.which("g++")

    def extra_flags(self) -> List[str]:
        return []

    def is_compatible(self) -> bool:
        return self.compiler() is not None

    def _source_paths(self) -> List[str]:
        return [os.path.join(_CSRC, s) for s in self.SOURCES]

    SO_NAME: Optional[str] = None  # builders sharing a translation unit share it

    def _so_path(self) -> str:
        # content-hash the sources + flags so edits trigger rebuilds
        h = hashlib.sha1()
        for p in self._source_paths():
            with open(p, "rb") as f:
                h.update(f.read())
        h.update(" ".join(self.extra_flags()).encode())
        return os.path.join(_build_dir(),
                            f"{self.SO_NAME or self.NAME}_{h.hexdigest()[:12]}.so")

    def is_built(self) -> bool:
        return os.path.exists(self._so_path())

    # -- build + load ----------------------------------------------------
    def build(self) -> str:
        so = self._so_path()
        if os.path.exists(so):
            return so
        cxx = self.compiler()
        if cxx is None:
            raise RuntimeError(f"{self.NAME}: no C++ compiler on PATH")
        # per-process temp name: concurrent first-use builds (pytest workers,
        # multi-process launch) must not clobber each other's half-written
        # object before the atomic publish
        tmp = f"{so}.{os.getpid()}.tmp"
        cmd = [cxx, "-O3", "-shared", "-fPIC", "-std=c++17",
               *self.extra_flags(), *self._source_paths(), "-o", tmp]
        logger.info("building native op %s: %s", self.NAME, " ".join(cmd))
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"{self.NAME} build failed (rc={proc.returncode}):\n"
                f"{proc.stderr[-4000:]}")
        os.replace(tmp, so)
        return so

    def bind(self, lib: ctypes.CDLL) -> None:
        """Subclasses declare argtypes/restype here."""

    def load(self) -> ctypes.CDLL:
        if self._lib is None:
            lib = ctypes.CDLL(self.build())
            self.bind(lib)
            self._lib = lib
        return self._lib


def _march_native_ok() -> bool:
    """Cached probe: does -march=native compile here?"""
    global _MARCH_OK
    if _MARCH_OK is None:
        try:
            src = os.path.join(_build_dir(), "_probe.cpp")
            with open(src, "w") as f:
                f.write("int main(){return 0;}\n")
            rc = subprocess.run(
                ["g++", "-march=native", src, "-o", src + ".out"],
                capture_output=True).returncode
            _MARCH_OK = rc == 0
        except Exception:
            _MARCH_OK = False
    return _MARCH_OK


_MARCH_OK: Optional[bool] = None

_F = ctypes.POINTER(ctypes.c_float)
_U16 = ctypes.POINTER(ctypes.c_uint16)


class CPUAdamBuilder(OpBuilder):
    """Reference ``op_builder/cpu_adam.py`` (csrc/adam/cpu_adam.cpp)."""

    NAME = "cpu_adam"
    SOURCES = ["cpu_adam.cpp"]

    def extra_flags(self):
        flags = ["-fopenmp"]
        if _march_native_ok():
            flags.append("-march=native")
        return flags

    def bind(self, lib):
        lib.cpu_adam_step.argtypes = [
            _F, _F, _F, _F, ctypes.c_int64, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, _U16]
        lib.cpu_adam_step.restype = None
        lib.cpu_adagrad_step.argtypes = [
            _F, _F, _F, ctypes.c_int64, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, _U16]
        lib.cpu_adagrad_step.restype = None
        lib.cpu_l2_norm.argtypes = [_F, ctypes.c_int64]
        lib.cpu_l2_norm.restype = ctypes.c_double


class CPUAdagradBuilder(CPUAdamBuilder):
    """Reference ``op_builder/cpu_adagrad.py`` — same translation unit, so it
    shares cpu_adam's cached .so instead of compiling a duplicate."""

    NAME = "cpu_adagrad"
    SO_NAME = "cpu_adam"


class AsyncIOBuilder(OpBuilder):
    """Reference ``op_builder/async_io.py`` (csrc/aio/)."""

    NAME = "async_io"
    SOURCES = ["aio.cpp"]

    def extra_flags(self):
        return ["-pthread"]

    def bind(self, lib):
        lib.ds_aio_submit_write.argtypes = [ctypes.c_char_p, ctypes.c_void_p,
                                            ctypes.c_int64, ctypes.c_int]
        lib.ds_aio_submit_write.restype = ctypes.c_int64
        lib.ds_aio_submit_read.argtypes = [ctypes.c_char_p, ctypes.c_void_p,
                                           ctypes.c_int64, ctypes.c_int]
        lib.ds_aio_submit_read.restype = ctypes.c_int64
        lib.ds_aio_wait.argtypes = [ctypes.c_int64]
        lib.ds_aio_wait.restype = ctypes.c_int
        lib.ds_aio_write.argtypes = [ctypes.c_char_p, ctypes.c_void_p,
                                     ctypes.c_int64, ctypes.c_int]
        lib.ds_aio_write.restype = ctypes.c_int
        lib.ds_aio_read.argtypes = [ctypes.c_char_p, ctypes.c_void_p,
                                    ctypes.c_int64, ctypes.c_int]
        lib.ds_aio_read.restype = ctypes.c_int
        # persistent-fd API (open once per swap file; optional O_DIRECT)
        lib.ds_aio_open.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                    ctypes.c_int]
        lib.ds_aio_open.restype = ctypes.c_int64
        lib.ds_aio_is_direct.argtypes = [ctypes.c_int64]
        lib.ds_aio_is_direct.restype = ctypes.c_int
        lib.ds_aio_close.argtypes = [ctypes.c_int64]
        lib.ds_aio_close.restype = ctypes.c_int
        for name in ("ds_aio_submit_pwrite", "ds_aio_submit_pread"):
            fn = getattr(lib, name)
            fn.argtypes = [ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64,
                           ctypes.c_int64, ctypes.c_int]
            fn.restype = ctypes.c_int64
        for name in ("ds_aio_pwrite", "ds_aio_pread"):
            fn = getattr(lib, name)
            fn.argtypes = [ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64,
                           ctypes.c_int64, ctypes.c_int]
            fn.restype = ctypes.c_int


ALL_OPS: Dict[str, Type[OpBuilder]] = {
    CPUAdamBuilder.NAME: CPUAdamBuilder,
    CPUAdagradBuilder.NAME: CPUAdagradBuilder,
    AsyncIOBuilder.NAME: AsyncIOBuilder,
}

__all__ = ["OpBuilder", "CPUAdamBuilder", "CPUAdagradBuilder",
           "AsyncIOBuilder", "ALL_OPS"]
