"""Shared Pallas kernel utilities (reference ``csrc/includes/``: the common
kernel layer every CUDA op includes — ``reduction_utils.h``,
``memory_access_utils.h``, ``conversion_utils.h``).

The TPU analogue is small because Mosaic handles tiling/layout, but the
conventions that DO repeat across kernels live here so they stay aligned:

  - ``NEG_INF`` — the masking constant (finite: ``-inf`` breaks the online
    softmax's ``exp(m_prev - m_new)`` rescale when a whole block is masked).
  - ``interpret_default()`` — interpret mode on CPU hosts so the unit suite
    runs kernels without hardware.
  - ``pick_block()`` — largest power-of-two tile that divides the axis.
  - ``mask_to_i32()`` — masks cross the pallas_call boundary as int32 and
    are compared ``!= 0`` in-kernel: bool memref tiling is a Mosaic
    lowering hazard.
  - ``parallel_semantics()`` — CompilerParams with the leading grid axes
    'parallel' and the innermost (accumulator-carrying) axis 'arbitrary'.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def interpret_default() -> bool:
    """Kernels run in interpret mode when no TPU is attached."""
    return jax.devices()[0].platform == "cpu"


def pick_block(n: int, want: int, floor: int = 8) -> int:
    """Largest power-of-two block <= ``want`` dividing ``n`` (>= ``floor``).

    Raises NotImplementedError when no such block exists — callers fall back
    to their XLA path rather than running a ragged final tile (padded rows
    would leak through index-based masks).
    """
    b = min(want, n)
    while b > floor and n % b:
        b //= 2
    # a full-axis tile (b == n) is legal at any size (tile == array dim);
    # otherwise the tile must divide n and respect the floor
    if n % b or (b < floor and b != n):
        raise NotImplementedError(
            f"axis length {n} has no power-of-two block divisor >= {floor}; "
            "use the XLA path")
    return b


def mask_to_i32(mask) -> jax.Array:
    """Boolean mask -> int32 for crossing the pallas_call boundary."""
    return jnp.asarray(mask).astype(jnp.int32)


def parallel_semantics(n_parallel: int, n_arbitrary: int = 1):
    """CompilerParams for an n-axis grid: leading axes independent, the
    trailing axes carrying accumulator state across iterations."""
    # jax renamed TPUCompilerParams -> CompilerParams; support both
    params_cls = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams")
    return params_cls(
        dimension_semantics=("parallel",) * n_parallel
        + ("arbitrary",) * n_arbitrary)
