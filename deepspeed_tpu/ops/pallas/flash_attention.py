"""Flash attention for TPU (Pallas, MXU-tiled, online softmax).

The TPU-native replacement for the reference's fused attention kernels
(csrc/transformer/softmax_kernels.cu, csrc/transformer/inference/csrc/
softmax.cu "softmax_context") and the block-sparse path
(deepspeed/ops/sparse_attention/): one kernel covers dense causal attention
with O(S) memory; block-sparse patterns reduce to the same kernel with block
skipping (causal is the special case the trainer uses).

Layout: q [B, Hq, S, hd], k/v [B, Hkv, S, hd] (grouped-query: Hq % Hkv == 0 —
the kernel indexes the KV head directly, no materialized repeat).
Forward saves the log-sum-exp rows; backward runs two kernels (dq sweep over
KV blocks; dkv sweep over Q blocks) with the standard delta = rowsum(dO*O).
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Measured on v5e for hd=128-class shapes (best-of-3, causal B8 H14 S2048):
# 1024-tiles beat 256 by 1.9x fwd / 1.7x bwd; the FORWARD gains another ~25%
# with a full-row K block (bk=2048: the online-softmax carry disappears),
# while backward is fastest at 1024 — so fwd defaults to bk=2048 and the
# wrapper caps the bwd tiles at 1024.  _pick_block shrinks for short S.
# Tile choice is measured in the FULL remat train step, not in kernel
# isolation: an isolated fwd+bwd sweep preferred fwd block_q=512 by 11-25%,
# but the same tiles cost ~2.5% end-to-end (S=8192 llama bench, same
# thermal state) — the rematerialized fwd inside the backward schedules
# differently than a standalone chain.  Keep (1024, 2048) fwd + 1024 bwd.
import os as _os

def _env_block(name: str, default: int) -> int:
    """Tile override via env (read at import — trace-time semantics like
    DS_TPU_FLASH_DECODE): lets tools/tune_flash.py A/B tile choices in the
    FULL remat train step via subprocess env, the only measurement that
    predicts end-to-end cost (see note above: isolated sweeps mislead)."""
    v = _os.environ.get(name, "").strip()
    if not v:
        return default
    try:
        iv = int(v)
    except ValueError as e:
        raise ValueError(f"{name}={v!r} is not an integer") from e
    if iv < 128 or iv % 128:
        raise ValueError(f"{name}={iv} must be a positive multiple of 128 "
                         "(MXU tile granularity)")
    return iv


DEFAULT_BLOCK_Q = _env_block("DS_TPU_FLASH_BLOCK_Q", 1024)
DEFAULT_BLOCK_K = _env_block("DS_TPU_FLASH_BLOCK_K", 2048)
# backward tiles: min(fwd tile, this) — the bwd kernels compile reliably at 1024
DEFAULT_BWD_BLOCK = _env_block("DS_TPU_FLASH_BWD_BLOCK", 1024)

from .common import (NEG_INF, interpret_default as _interpret_default,  # noqa: E402
                     parallel_semantics, pick_block as _pick_block)

# The first three grid axes are independent in every kernel here; only the
# INNERMOST axis carries accumulator state (the K sweep in _fwd/_bwd_dq, the
# Q-and-group sweep in _bwd_dkv) and must stay 'arbitrary'.
_COMPILER_PARAMS = parallel_semantics(3, 1)


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, *rest, sm_scale: float, causal: bool,
                block_q: int, block_k: int, num_k: int, masked: bool = False):
    if masked:
        mask_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref = rest
    else:
        o_ref, lse_ref, acc_ref, m_ref, l_ref = rest
    qi, ki = pl.program_id(2), pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    should_run = True
    if causal:
        should_run = ki * block_k <= qi * block_q + block_q - 1
    if masked:
        live = mask_ref[qi, ki] != 0
        should_run = jnp.logical_and(should_run, live) if causal else live

    @pl.when(should_run)
    def _body():
        q, k, v = q_ref[:], k_ref[:], v_ref[:]    # native dtype into the MXU
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * sm_scale                              # [bq, bk] fp32
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)

        m_prev = m_ref[:, :1]                         # [bq, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)     # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)               # [bq, 1]
        p = jnp.exp(s - m_new)                        # [bq, bk]
        l_new = alpha * l_ref[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == num_k - 1)
    def _finish():
        l = l_ref[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[:] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        lse = m_ref[:, :1] + jnp.log(l_safe)
        lse_ref[:] = lse[:, 0][None, :]


def _mask_array(block_mask):
    """Hashable tuple-of-tuples (custom_vjp static arg) -> int32 array."""
    import numpy as _np

    return jnp.asarray(_np.asarray(block_mask, _np.int32))


def _fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret,
         block_mask=None):
    B, Hq, S, hd = q.shape
    Hkv = k.shape[1]
    group = Hq // Hkv
    block_q = min(block_q, S)
    block_k = min(block_k, S)
    num_q, num_k = pl.cdiv(S, block_q), pl.cdiv(S, block_k)
    grid = (B, Hq, num_q, num_k)
    masked = block_mask is not None

    kernel = functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                               block_q=block_q, block_k=block_k, num_k=num_k,
                               masked=masked)
    in_specs = [
            pl.BlockSpec((None, None, block_q, hd), lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec((None, None, block_k, hd),
                         lambda b, h, qi, ki: (b, h // group, ki, 0)),
            pl.BlockSpec((None, None, block_k, hd),
                         lambda b, h, qi, ki: (b, h // group, ki, 0)),
    ]
    operands = [q, k, v]
    if masked:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        operands.append(_mask_array(block_mask))
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((None, None, block_q, hd), lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec((None, None, 1, block_q),
                         lambda b, h, qi, ki: (b, h, 0, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((B, Hq, 1, S), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, hd), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        compiler_params=_COMPILER_PARAMS,
        interpret=interpret,
    )(*operands)
    return out, lse


# ---------------------------------------------------------------------------
# Backward
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
                   sm_scale, causal, block_q, block_k, num_k,
                   masked: bool = False):
    if masked:
        mask_ref, dq_ref, acc_ref = rest
    else:
        dq_ref, acc_ref = rest
    qi, ki = pl.program_id(2), pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    should_run = True
    if causal:
        should_run = ki * block_k <= qi * block_q + block_q - 1
    if masked:
        live = mask_ref[qi, ki] != 0
        should_run = jnp.logical_and(should_run, live) if causal else live

    @pl.when(should_run)
    def _body():
        q, k, v, do = q_ref[:], k_ref[:], v_ref[:], do_ref[:]
        lse = lse_ref[0, :][:, None]               # [bq, 1]
        delta = delta_ref[0, :][:, None]           # [bq, 1]

        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)                          # [bq, bk]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale              # [bq, bk]
        acc_ref[:] += jax.lax.dot_general(ds.astype(k.dtype), k,
                                          (((1,), (0,)), ((), ())),
                                          preferred_element_type=jnp.float32)

    @pl.when(ki == num_k - 1)
    def _finish():
        dq_ref[:] = acc_ref[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
                    sm_scale, causal, block_q, block_k, num_q, group,
                    masked: bool = False):
    # Grid head axis is the KV head; the innermost axis walks every
    # (q-head-in-group, q-block) pair so dk/dv accumulate in VMEM at
    # [B, Hkv, S, hd] — no group-times-larger HBM intermediate.
    if masked:
        mask_ref, dk_ref, dv_ref, dk_acc, dv_acc = rest
    else:
        dk_ref, dv_ref, dk_acc, dv_acc = rest
    ki, j = pl.program_id(2), pl.program_id(3)
    qi = j % num_q

    @pl.when(j == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    should_run = True
    if causal:
        should_run = qi * block_q + block_q - 1 >= ki * block_k
    if masked:
        live = mask_ref[qi, ki] != 0
        should_run = jnp.logical_and(should_run, live) if causal else live

    @pl.when(should_run)
    def _body():
        q, k, v, do = q_ref[:], k_ref[:], v_ref[:], do_ref[:]
        lse = lse_ref[0, :][:, None]
        delta = delta_ref[0, :][:, None]

        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)                          # [bq, bk]
        dv_acc[:] += jax.lax.dot_general(p.astype(do.dtype), do,
                                         (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dk_acc[:] += jax.lax.dot_general(ds.astype(q.dtype), q,
                                         (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    @pl.when(j == num_q * group - 1)
    def _finish():
        dk_ref[:] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[:] = dv_acc[:].astype(dv_ref.dtype)


def _bwd(sm_scale, causal, block_q, block_k, interpret, res, g,
         block_mask=None, dlse=None):
    q, k, v, out, lse = res
    do = g
    B, Hq, S, hd = q.shape
    Hkv = k.shape[1]
    group = Hq // Hkv
    block_q = min(block_q, S)
    block_k = min(block_k, S)
    num_q, num_k = pl.cdiv(S, block_q), pl.cdiv(S, block_k)
    masked = block_mask is not None
    mask_ops = [_mask_array(block_mask)] if masked else []
    mask_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)] if masked else []

    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)[:, :, None, :]
    if dlse is not None:
        # lse cotangent folds into delta: d s_ij = p_ij (dp_ij - delta_i)
        # + p_ij dlse_i  ==  p_ij (dp_ij - (delta_i - dlse_i)) — so the
        # kernels run unchanged with a shifted delta (the ring-attention
        # merge differentiates through lse, unlike the plain path whose
        # lse is consumed only by checkpoint_name)
        delta = delta - dlse.astype(jnp.float32)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, num_k=num_k,
                          masked=masked),
        grid=(B, Hq, num_q, num_k),
        in_specs=[
            pl.BlockSpec((None, None, block_q, hd), lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec((None, None, block_k, hd),
                         lambda b, h, qi, ki: (b, h // group, ki, 0)),
            pl.BlockSpec((None, None, block_k, hd),
                         lambda b, h, qi, ki: (b, h // group, ki, 0)),
            pl.BlockSpec((None, None, block_q, hd), lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec((None, None, 1, block_q),
                         lambda b, h, qi, ki: (b, h, 0, qi)),
            pl.BlockSpec((None, None, 1, block_q),
                         lambda b, h, qi, ki: (b, h, 0, qi)),
        ] + mask_specs,
        out_specs=pl.BlockSpec((None, None, block_q, hd),
                               lambda b, h, qi, ki: (b, h, qi, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, hd), jnp.float32)],
        compiler_params=_COMPILER_PARAMS,
        interpret=interpret,
    )(q, k, v, do, lse, delta, *mask_ops)

    # dk/dv accumulate per (kv-head, kv-block); the inner grid axis sweeps
    # all group*num_q (q-head, q-block) pairs so the group reduction happens
    # in the VMEM accumulator, not in an [B, Hq, S, hd] HBM intermediate.
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, num_q=num_q,
                          group=group, masked=masked),
        grid=(B, Hkv, num_k, num_q * group),
        in_specs=[
            pl.BlockSpec((None, None, block_q, hd),
                         lambda b, h, ki, j: (b, h * group + j // num_q, j % num_q, 0)),
            pl.BlockSpec((None, None, block_k, hd),
                         lambda b, h, ki, j: (b, h, ki, 0)),
            pl.BlockSpec((None, None, block_k, hd),
                         lambda b, h, ki, j: (b, h, ki, 0)),
            pl.BlockSpec((None, None, block_q, hd),
                         lambda b, h, ki, j: (b, h * group + j // num_q, j % num_q, 0)),
            pl.BlockSpec((None, None, 1, block_q),
                         lambda b, h, ki, j: (b, h * group + j // num_q, 0, j % num_q)),
            pl.BlockSpec((None, None, 1, block_q),
                         lambda b, h, ki, j: (b, h * group + j // num_q, 0, j % num_q)),
        ] + mask_specs,
        out_specs=[
            pl.BlockSpec((None, None, block_k, hd), lambda b, h, ki, j: (b, h, ki, 0)),
            pl.BlockSpec((None, None, block_k, hd), lambda b, h, ki, j: (b, h, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, Hkv, S, hd), k.dtype),
            jax.ShapeDtypeStruct((B, Hkv, S, hd), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, hd), jnp.float32),
            pltpu.VMEM((block_k, hd), jnp.float32),
        ],
        compiler_params=_COMPILER_PARAMS,
        interpret=interpret,
    )(q, k, v, do, lse, delta, *mask_ops)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------

# The custom VJP is defined on a function whose PRIMAL OUTPUTS are (out, lse)
# — exactly the non-input residuals the backward needs.  The model names both
# with checkpoint_name, so a remat policy that pins q/k/v + attn_out +
# attn_lse lets the backward run WITHOUT re-executing the forward kernel
# (with out/lse hidden inside the vjp, remat must re-run the S² forward to
# regenerate residuals no matter what the policy saves).
# Forward and backward take SEPARATE tile sizes: the fwd prefers a full-row K
# block (no online-softmax carry — measured ~25% faster at S=2048), while the
# bwd kernels are fastest (and compile reliably) at 1024.
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10))
def _flash(q, k, v, sm_scale, causal, block_q, block_k, interpret,
           bwd_block_q, bwd_block_k, block_mask=None):
    return _fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret,
                block_mask)


def _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret,
               bwd_block_q, bwd_block_k, block_mask=None):
    from jax.ad_checkpoint import checkpoint_name

    out, lse = _fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret,
                    block_mask)
    # names INSIDE the vjp-fwd so remat policies can pin the residuals
    # themselves ("attn_lse" + the model-level "attn_out"/q/k/v names)
    lse = checkpoint_name(lse, "attn_lse")
    return (out, lse), (q, k, v, out, lse)


def _flash_bwd(sm_scale, causal, block_q, block_k, interpret, bwd_block_q,
               bwd_block_k, block_mask, res, g):
    do, _ = g  # lse is consumed only by checkpoint_name: zero cotangent
    return _bwd(sm_scale, causal, bwd_block_q, bwd_block_k, interpret, res,
                do, block_mask)


_flash.defvjp(_flash_fwd, _flash_bwd)


# Same kernels, but lse is a REAL (differentiable) output: the ring
# merge computes output weights from per-block lse, so its cotangent is
# nonzero — _flash would silently drop it (wrong gradients); here it is
# folded into the backward's delta term (see _bwd).
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _flash_lse(q, k, v, sm_scale, causal, block_q, block_k, interpret,
               bwd_block_q, bwd_block_k):
    return _fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret, None)


def _flash_lse_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret,
                   bwd_block_q, bwd_block_k):
    from jax.ad_checkpoint import checkpoint_name

    out, lse = _fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret,
                    None)
    # same residual tagging as _flash_fwd: a remat policy pinning
    # 'attn_lse' must cover the ring path too, or every ring step's
    # backward re-runs the forward kernel
    lse = checkpoint_name(lse, "attn_lse")
    return (out, lse), (q, k, v, out, lse)


def _flash_lse_bwd(sm_scale, causal, block_q, block_k, interpret,
                   bwd_block_q, bwd_block_k, res, g):
    do, dlse = g
    return _bwd(sm_scale, causal, bwd_block_q, bwd_block_k, interpret, res,
                do, None, dlse=dlse)


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def flash_attention(q, k, v, causal: bool = True, sm_scale: Optional[float] = None,
                    bias=None, block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    bwd_block_q: Optional[int] = None,
                    bwd_block_k: Optional[int] = None,
                    interpret: Optional[bool] = None,
                    block_mask=None, return_lse: bool = False):
    """q [B,S,Hq,hd], k/v [B,S,Hkv,hd] -> [B,S,Hq,hd]
    (or ``(out, lse [B,Hq,S])`` with ``return_lse`` — the ring-attention
    inner block consumes the lse for its cross-block merge).

    bias is not fused (alibi models use the XLA path); causal is.
    ``block_mask`` (optional bool [S/block_q, S/block_k]) skips dead blocks in
    forward AND backward — the block-sparse attention path
    (ops/sparse_attention builds the patterns).
    Backward tiles default to min(fwd tile, 1024): the fwd wins with a
    full-row K block while the bwd kernels prefer (and compile reliably at)
    1024.  A ``block_mask`` forces bwd tiles == fwd tiles (the mask grid must
    match every kernel).
    """
    if bias is not None:
        raise NotImplementedError("bias is handled by the XLA attention path")
    S = q.shape[1]
    if block_mask is not None:
        # masked path: ONE tile size for every kernel (the mask grid must
        # match fwd, dq, and dkv), capped at 1024 — the bwd kernels do not
        # compile reliably above that, so the fwd's full-row preference is
        # forfeited here rather than handed to the backward
        block_q = _pick_block(S, min(block_q, 1024))
        block_k = _pick_block(S, min(block_k, 1024))
        bwd_block_q, bwd_block_k = block_q, block_k
        import numpy as _np

        bm = _np.asarray(block_mask)
        want = (S // block_q, S // block_k)
        if bm.shape != want:
            raise ValueError(
                f"block_mask shape {bm.shape} does not match the block grid "
                f"{want} (S={S}, block_q={block_q}, block_k={block_k})")
        # hashable static arg for the custom_vjp/jit caches
        block_mask = tuple(tuple(int(x) for x in row) for row in bm)
    else:
        block_q = _pick_block(S, block_q)
        block_k = _pick_block(S, block_k)
        bwd_block_q = _pick_block(S, bwd_block_q or min(block_q, DEFAULT_BWD_BLOCK))
        bwd_block_k = _pick_block(S, bwd_block_k or min(block_k, DEFAULT_BWD_BLOCK))
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = _interpret_default()
    # [B,S,H,hd] -> [B,H,S,hd]
    qt, kt, vt = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))
    if return_lse:
        if block_mask is not None:
            raise NotImplementedError("return_lse + block_mask")
        # the lse-differentiable variant — callers that CONSUME lse (ring
        # merge) would get silently-wrong grads from _flash's dropped
        # cotangent
        out, lse = _flash_lse(qt, kt, vt, sm_scale, causal, block_q,
                              block_k, interpret, bwd_block_q, bwd_block_k)
        return jnp.swapaxes(out, 1, 2), lse.reshape(lse.shape[0],
                                                    lse.shape[1], -1)
    out, _ = _flash(qt, kt, vt, sm_scale, causal, block_q, block_k,
                    interpret, bwd_block_q, bwd_block_k, block_mask)
    return jnp.swapaxes(out, 1, 2)
