// SIMD CPU Adam/AdamW step for host-offloaded optimizer states.
//
// TPU-native role (reference csrc/adam/cpu_adam.cpp + cpu_adam_impl.cpp):
// with ZeRO-Offload the gradients stream to host RAM and the optimizer step
// runs on the host CPU while the device starts the next forward.  The hot
// loop is a pure elementwise map over four fp32 arrays, so the whole win is
// vectorization + threads: `#pragma omp parallel for simd` lets GCC emit
// AVX2 (or whatever -march=native offers) across all cores, same shape as
// the reference's hand-written AVX512/AVX256 intrinsics but portable.
//
// The optional bf16 output mirrors the reference's fused fp16-param copy
// (cpu_adam.cpp `half* dev_param`): the updated master is rounded
// (nearest-even) to bf16 in the same pass, producing the device compute
// params without a second python-side cast over the buffer.
//
// C ABI for ctypes binding (no pybind11 in this image).

#include <cmath>
#include <cstdint>
#include <cstring>

extern "C" {

static inline uint16_t float_to_bf16_rne(float f) {
  uint32_t x;
  std::memcpy(&x, &f, 4);
  uint32_t lsb = (x >> 16) & 1u;
  x += 0x7fffu + lsb;  // round to nearest even
  return (uint16_t)(x >> 16);
}

// params/grads/m/v: fp32 [n].  step is 1-based.  adam_w_mode: 1 = decoupled
// decay (AdamW), 0 = L2 (decay folded into grad).  bf16_out may be null.
void cpu_adam_step(float* params, const float* grads, float* exp_avg,
                   float* exp_avg_sq, int64_t n, float lr, float beta1,
                   float beta2, float eps, float weight_decay, int adam_w_mode,
                   int bias_correction, int step, uint16_t* bf16_out) {
  float bc1 = 1.0f, bc2 = 1.0f;
  if (bias_correction) {
    bc1 = 1.0f - std::pow(beta1, (float)step);
    bc2 = 1.0f - std::pow(beta2, (float)step);
  }
  const float step_size = lr / bc1;
  const float bc2_sqrt = std::sqrt(bc2);
  const float b1 = beta1, b2 = beta2;
  const float omb1 = 1.0f - beta1, omb2 = 1.0f - beta2;
  const float wd = weight_decay;

#pragma omp parallel for simd schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    float g = grads[i];
    float p = params[i];
    if (!adam_w_mode && wd != 0.0f) g += wd * p;
    float m = b1 * exp_avg[i] + omb1 * g;
    float v = b2 * exp_avg_sq[i] + omb2 * g * g;
    float denom = std::sqrt(v) / bc2_sqrt + eps;
    // decoupled decay uses the RAW lr (p -= lr*wd*p), not lr/bc1 — scaling
    // it by the bias correction would 10x the decay at step 1 (beta1=0.9)
    float new_p = p - step_size * (m / denom);
    if (adam_w_mode && wd != 0.0f) new_p -= lr * wd * p;
    exp_avg[i] = m;
    exp_avg_sq[i] = v;
    params[i] = new_p;
  }
  if (bf16_out) {
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) bf16_out[i] = float_to_bf16_rne(params[i]);
  }
}

// Adagrad (reference csrc/adagrad/cpu_adagrad.cpp): state is the running
// sum of squared gradients.
void cpu_adagrad_step(float* params, const float* grads, float* sq_sum,
                      int64_t n, float lr, float eps, float weight_decay,
                      uint16_t* bf16_out) {
  const float wd = weight_decay;
#pragma omp parallel for simd schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    float g = grads[i];
    float p = params[i];
    if (wd != 0.0f) g += wd * p;
    float s = sq_sum[i] + g * g;
    p -= lr * g / (std::sqrt(s) + eps);
    sq_sum[i] = s;
    params[i] = p;
  }
  if (bf16_out) {
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) bf16_out[i] = float_to_bf16_rne(params[i]);
  }
}

// L2 norm over an fp32 buffer (reference multi_tensor_l2norm use in the
// offload path's grad-norm computation).
double cpu_l2_norm(const float* x, int64_t n) {
  double acc = 0.0;
#pragma omp parallel for simd reduction(+ : acc) schedule(static)
  for (int64_t i = 0; i < n; ++i) acc += (double)x[i] * (double)x[i];
  return std::sqrt(acc);
}

}  // extern "C"
