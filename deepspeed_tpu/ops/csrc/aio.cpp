// Async file I/O engine for tensor swapping (NVMe offload).
//
// TPU-native role (reference csrc/aio/py_lib/deepspeed_aio_thread.cpp +
// deepspeed_py_aio_handle.cpp): ZeRO-Infinity keeps optimizer/parameter
// shards on NVMe and overlaps their reads/writes with compute.  The
// reference uses libaio; this image has no liburing/libaio, so the engine is
// a std::thread pool doing chunked pread/pwrite — the same overlap model
// (submit returns immediately, wait() joins), and chunking across threads
// saturates NVMe queue depth the way multiple aio submissions do.
//
// C ABI for ctypes (no pybind11 in this image).  Handles are process-global
// int64 ids guarded by a mutex.

#include <fcntl.h>
#include <unistd.h>

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Job {
  std::vector<std::thread> workers;
  std::atomic<int> status{0};  // 0 ok, else -errno of first failure
  std::atomic<bool> done{false};
};

std::mutex g_mu;
std::map<int64_t, Job*> g_jobs;
int64_t g_next_id = 1;

int rw_chunk(const char* path, char* buf, int64_t offset, int64_t nbytes,
             bool write) {
  int fd = ::open(path, write ? (O_WRONLY | O_CREAT) : O_RDONLY, 0644);
  if (fd < 0) return -errno;
  int64_t done_b = 0;
  while (done_b < nbytes) {
    ssize_t r = write ? ::pwrite(fd, buf + done_b, nbytes - done_b, offset + done_b)
                      : ::pread(fd, buf + done_b, nbytes - done_b, offset + done_b);
    if (r < 0) {
      int e = -errno;
      ::close(fd);
      return e;
    }
    if (r == 0) {  // short read: file smaller than requested
      ::close(fd);
      return -EIO;
    }
    done_b += r;
  }
  ::close(fd);
  return 0;
}

int64_t submit(const char* path, void* buf, int64_t nbytes, int nthreads,
               bool write) {
  if (nthreads < 1) nthreads = 1;
  if (nbytes < (int64_t)nthreads * (1 << 20)) {  // <1MB/thread: one thread
    nthreads = 1;
  }
  Job* job = new Job();
  std::string p(path);
  int64_t chunk = (nbytes + nthreads - 1) / nthreads;
  for (int t = 0; t < nthreads; ++t) {
    int64_t off = (int64_t)t * chunk;
    int64_t len = std::min(chunk, nbytes - off);
    if (len <= 0) break;
    job->workers.emplace_back([job, p, buf, off, len, write]() {
      int rc = rw_chunk(p.c_str(), (char*)buf + off, off, len, write);
      if (rc != 0) {
        int expected = 0;
        job->status.compare_exchange_strong(expected, rc);
      }
    });
  }
  std::lock_guard<std::mutex> lk(g_mu);
  int64_t id = g_next_id++;
  g_jobs[id] = job;
  return id;
}

}  // namespace

extern "C" {

int64_t ds_aio_submit_write(const char* path, const void* buf, int64_t nbytes,
                            int nthreads) {
  return submit(path, const_cast<void*>(buf), nbytes, nthreads, true);
}

int64_t ds_aio_submit_read(const char* path, void* buf, int64_t nbytes,
                           int nthreads) {
  return submit(path, buf, nbytes, nthreads, false);
}

// Blocks until the job completes; returns 0 or -errno.  Frees the handle.
int ds_aio_wait(int64_t id) {
  Job* job = nullptr;
  {
    std::lock_guard<std::mutex> lk(g_mu);
    auto it = g_jobs.find(id);
    if (it == g_jobs.end()) return -EINVAL;
    job = it->second;
    g_jobs.erase(it);
  }
  for (auto& w : job->workers) w.join();
  int rc = job->status.load();
  delete job;
  return rc;
}

// Synchronous convenience wrappers (reference deepspeed_py_aio.cpp sync path).
int ds_aio_write(const char* path, const void* buf, int64_t nbytes,
                 int nthreads) {
  return ds_aio_wait(ds_aio_submit_write(path, buf, nbytes, nthreads));
}

int ds_aio_read(const char* path, void* buf, int64_t nbytes, int nthreads) {
  return ds_aio_wait(ds_aio_submit_read(path, buf, nbytes, nthreads));
}

}  // extern "C"
