// Async file I/O engine for tensor swapping (NVMe offload).
//
// TPU-native role (reference csrc/aio/py_lib/deepspeed_aio_thread.cpp +
// deepspeed_py_aio_handle.cpp): ZeRO-Infinity keeps optimizer/parameter
// shards on NVMe and overlaps their reads/writes with compute.  The
// reference uses libaio; this image has no liburing/libaio, so the engine is
// a std::thread pool doing chunked pread/pwrite — the same overlap model
// (submit returns immediately, wait() joins), and chunking across threads
// saturates NVMe queue depth the way multiple aio submissions do.
//
// C ABI for ctypes (no pybind11 in this image).  Handles are process-global
// int64 ids guarded by a mutex.

#ifndef _GNU_SOURCE
#define _GNU_SOURCE 1  // O_DIRECT
#endif

#include <fcntl.h>
#include <unistd.h>

#ifndef O_DIRECT
#define O_DIRECT 0  // platform without O_DIRECT: silently buffered
#endif

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Job {
  std::vector<std::thread> workers;
  std::atomic<int> status{0};  // 0 ok, else -errno of first failure
  std::atomic<bool> done{false};
};

std::mutex g_mu;
std::map<int64_t, Job*> g_jobs;
int64_t g_next_id = 1;

int rw_chunk_fd(int fd, char* buf, int64_t offset, int64_t nbytes,
                bool write) {
  int64_t done_b = 0;
  while (done_b < nbytes) {
    ssize_t r = write ? ::pwrite(fd, buf + done_b, nbytes - done_b, offset + done_b)
                      : ::pread(fd, buf + done_b, nbytes - done_b, offset + done_b);
    if (r < 0) return -errno;
    if (r == 0) return -EIO;  // short read: file smaller than requested
    done_b += r;
  }
  return 0;
}

int rw_chunk(const char* path, char* buf, int64_t offset, int64_t nbytes,
             bool write) {
  int fd = ::open(path, write ? (O_WRONLY | O_CREAT) : O_RDONLY, 0644);
  if (fd < 0) return -errno;
  int rc = rw_chunk_fd(fd, buf, offset, nbytes, write);
  ::close(fd);
  return rc;
}

// shared fan-out: split [offset, offset+nbytes) across worker threads.
// Chunk boundaries are rounded up to 4096 so O_DIRECT fds keep aligned
// offsets/lengths on every split (the tail stays aligned whenever the
// caller's total nbytes is aligned, which O_DIRECT requires anyway).
template <typename ChunkFn>
int64_t submit_impl(int64_t nbytes, int nthreads, ChunkFn chunk_fn) {
  if (nthreads < 1) nthreads = 1;
  if (nbytes < (int64_t)nthreads * (1 << 20)) {  // <1MB/thread: one thread
    nthreads = 1;
  }
  Job* job = new Job();
  int64_t chunk = (nbytes + nthreads - 1) / nthreads;
  chunk = (chunk + 4095) & ~(int64_t)4095;
  for (int t = 0; t < nthreads; ++t) {
    int64_t off = (int64_t)t * chunk;
    int64_t len = std::min(chunk, nbytes - off);
    if (len <= 0) break;
    job->workers.emplace_back([job, off, len, chunk_fn]() {
      int rc = chunk_fn(off, len);
      if (rc != 0) {
        int expected = 0;
        job->status.compare_exchange_strong(expected, rc);
      }
    });
  }
  std::lock_guard<std::mutex> lk(g_mu);
  int64_t id = g_next_id++;
  g_jobs[id] = job;
  return id;
}

int64_t submit(const char* path, void* buf, int64_t nbytes, int nthreads,
               bool write) {
  std::string p(path);
  return submit_impl(nbytes, nthreads,
                     [p, buf, write](int64_t off, int64_t len) {
                       return rw_chunk(p.c_str(), (char*)buf + off, off, len,
                                       write);
                     });
}

}  // namespace

extern "C" {

int64_t ds_aio_submit_write(const char* path, const void* buf, int64_t nbytes,
                            int nthreads) {
  return submit(path, const_cast<void*>(buf), nbytes, nthreads, true);
}

int64_t ds_aio_submit_read(const char* path, void* buf, int64_t nbytes,
                           int nthreads) {
  return submit(path, buf, nbytes, nthreads, false);
}

// Blocks until the job completes; returns 0 or -errno.  Frees the handle.
int ds_aio_wait(int64_t id) {
  Job* job = nullptr;
  {
    std::lock_guard<std::mutex> lk(g_mu);
    auto it = g_jobs.find(id);
    if (it == g_jobs.end()) return -EINVAL;
    job = it->second;
    g_jobs.erase(it);
  }
  for (auto& w : job->workers) w.join();
  int rc = job->status.load();
  delete job;
  return rc;
}

// Synchronous convenience wrappers (reference deepspeed_py_aio.cpp sync path).
int ds_aio_write(const char* path, const void* buf, int64_t nbytes,
                 int nthreads) {
  return ds_aio_wait(ds_aio_submit_write(path, buf, nbytes, nthreads));
}

int ds_aio_read(const char* path, void* buf, int64_t nbytes, int nthreads) {
  return ds_aio_wait(ds_aio_submit_read(path, buf, nbytes, nthreads));
}

// ---------------------------------------------------------------------------
// Persistent-fd API (reference deepspeed_py_aio_handle.cpp keeps an open
// handle + pinned buffers per swap file; the per-chunk open/close of the
// path API costs a syscall pair + dentry walk per op).  O_DIRECT bypasses
// the page cache — the reference's default for NVMe — and requires
// 4096-aligned buffer/offset/length; ds_aio_open falls back to buffered
// I/O when the filesystem refuses O_DIRECT, reporting which mode it got.
// ---------------------------------------------------------------------------

// returns fd >= 0, or -errno.  direct=1 requests O_DIRECT (best effort).
int64_t ds_aio_open(const char* path, int for_write, int direct) {
  int flags = for_write ? (O_RDWR | O_CREAT) : O_RDONLY;
  if (direct) {
    int fd = ::open(path, flags | O_DIRECT, 0644);
    if (fd >= 0) return fd;
  }
  int fd = ::open(path, flags, 0644);
  return fd >= 0 ? fd : -errno;
}

int ds_aio_is_direct(int64_t fd) {
  int fl = ::fcntl((int)fd, F_GETFL);
  return fl >= 0 && (fl & O_DIRECT) ? 1 : 0;
}

int ds_aio_close(int64_t fd) { return ::close((int)fd) == 0 ? 0 : -errno; }

// O_DIRECT requires 4096-aligned buffer/offset/length on EVERY op, not just
// at open time (a misaligned tail chunk would fail pread/pwrite with EINVAL
// mid-job).  When an op arrives misaligned on an O_DIRECT fd, drop to
// buffered mode for that fd via fcntl — same data path, page cache back in
// the loop — rather than surfacing a runtime EINVAL from a worker thread.
static void drop_direct_if_misaligned(int64_t fd, const void* buf,
                                      int64_t nbytes, int64_t offset) {
#if O_DIRECT != 0
  if (((uintptr_t)buf | (uint64_t)nbytes | (uint64_t)offset) & 4095) {
    int fl = ::fcntl((int)fd, F_GETFL);
    if (fl >= 0 && (fl & O_DIRECT)) {
      ::fcntl((int)fd, F_SETFL, fl & ~O_DIRECT);
    }
  }
#else
  (void)fd; (void)buf; (void)nbytes; (void)offset;
#endif
}

int64_t ds_aio_submit_pwrite(int64_t fd, const void* buf, int64_t nbytes,
                             int64_t offset, int nthreads) {
  drop_direct_if_misaligned(fd, buf, nbytes, offset);
  char* b = (char*)const_cast<void*>(buf);
  return submit_impl(nbytes, nthreads,
                     [fd, b, offset](int64_t off, int64_t len) {
                       return rw_chunk_fd((int)fd, b + off, offset + off, len,
                                          true);
                     });
}

int64_t ds_aio_submit_pread(int64_t fd, void* buf, int64_t nbytes,
                            int64_t offset, int nthreads) {
  drop_direct_if_misaligned(fd, buf, nbytes, offset);
  char* b = (char*)buf;
  return submit_impl(nbytes, nthreads,
                     [fd, b, offset](int64_t off, int64_t len) {
                       return rw_chunk_fd((int)fd, b + off, offset + off, len,
                                          false);
                     });
}

int ds_aio_pwrite(int64_t fd, const void* buf, int64_t nbytes, int64_t offset,
                  int nthreads) {
  return ds_aio_wait(ds_aio_submit_pwrite(fd, buf, nbytes, offset, nthreads));
}

int ds_aio_pread(int64_t fd, void* buf, int64_t nbytes, int64_t offset,
                 int nthreads) {
  return ds_aio_wait(ds_aio_submit_pread(fd, buf, nbytes, offset, nthreads));
}

}  // extern "C"
