"""Ring attention — sequence/context parallelism over the 'seq' mesh axis.

The reference snapshot has NO sequence parallelism (SURVEY §5: predates
DeepSpeed-Ulysses; its long-sequence story is block-sparse attention).  The
TPU build treats SP as a first-class mesh axis: queries stay resident on
their shard while K/V blocks rotate around the ring via ``lax.ppermute``
(nearest-neighbor ICI hops), and per-block attention results merge with a
running log-sum-exp — attention over sequences N× longer than one chip's
score memory would allow, with compute overlapping the rotation.

The inner block is the PALLAS FLASH KERNEL (``impl='flash'``, the default
whenever the local shard is tile-aligned): per ring step nothing larger
than the kernel's [block_q, block_k] tiles materializes, so per-device
score memory is O(tile²) — independent of S — and the remaining
long-context footprint is the O(S) rotated K/V that scan-AD holds for
backward.  The merge consumes the kernel's native lse output through an
lse-differentiable VJP (the plain kernel's dropped-lse shortcut would
corrupt gradients here).  The einsum fallback ([S/N, S/N] fp32 scores per
step) remains for tile-unaligned shards.

Causal structure: the diagonal block is ring step 0 (outside the scan) and
runs the causal kernel; every scanned block is strictly past or strictly
future, so the scan runs the NON-causal kernel and kills fully-future
blocks by forcing their lse to -1e30 (merge weight underflows to zero —
uniform SPMD control flow, no per-device branching).
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp


def _block_attn(q, k, v, q_off, k_off, sm_scale, causal):
    """q [B,Sq,Hq,hd], k/v [B,Sk,Hkv,hd] -> (o [B,Sq,Hq,hd], lse [B,Hq,Sq]).

    Grouped-query attention stays grouped: q folds to [B,Sq,Hkv,G,hd] and the
    einsums contract against the Hkv-head K/V directly — no materialized
    repeat, so the ring carries (and rotates) only the true KV bytes."""
    B, Sq, Hq, hd = q.shape
    Sk, Hkv = k.shape[1], k.shape[2]
    G = Hq // Hkv
    qg = q.reshape(B, Sq, Hkv, G, hd)
    s = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k).astype(jnp.float32) * sm_scale
    if causal:
        rows = q_off + jax.lax.broadcasted_iota(jnp.int32, (Sq, Sk), 0)
        cols = k_off + jax.lax.broadcasted_iota(jnp.int32, (Sq, Sk), 1)
        s = jnp.where((rows >= cols)[None, None, None], s, -1e30)
    lse = jax.nn.logsumexp(s, axis=-1)                     # [B,Hkv,G,Sq]
    p = jnp.exp(s - lse[..., None]).astype(q.dtype)
    o = jnp.einsum("bhgqk,bkhd->bqhgd", p, v).reshape(B, Sq, Hq, hd)
    return o, lse.reshape(B, Hq, Sq)


def _flash_ok(Sl: int, hd: int) -> bool:
    """Tile alignment for the Pallas inner block (kernel needs 128-multiple
    sequence tiles; lane dim rides hd directly)."""
    return Sl % 128 == 0


def ring_attention(q, k, v, axis_name: str = "seq", causal: bool = True,
                   sm_scale: Optional[float] = None, impl: str = "auto"):
    """Runs INSIDE shard_map: q/k/v are the local sequence shards
    [B, S_local, H, hd]; returns the local output shard.

    ``impl``: 'flash' (Pallas inner block, O(tile²) score memory), 'einsum'
    (the [Sl,Sl] fp32 fallback), or 'auto' (flash when tile-aligned).
    """
    B, Sl, Hq, hd = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(hd)
    if impl == "auto":
        impl = "flash" if _flash_ok(Sl, hd) else "einsum"
    elif impl == "flash" and not _flash_ok(Sl, hd):
        raise ValueError(
            f"ring impl='flash' requires a 128-multiple local shard, got "
            f"S_local={Sl}")
    n = jax.lax.psum(1, axis_name)
    me = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    if impl == "flash":
        from .pallas.flash_attention import flash_attention

        def block(q, k, v, block_causal):
            # lse-differentiable kernel: the merge weights depend on lse
            return flash_attention(q, k, v, causal=block_causal,
                                   sm_scale=sm_scale, return_lse=True)

    def merge(o, lse, o_b, lse_b):
        new_lse = jnp.logaddexp(lse, lse_b)
        w_old = jnp.exp(lse - new_lse)           # [B,H,Sq]
        w_new = jnp.exp(lse_b - new_lse)
        o = (o * jnp.swapaxes(w_old, 1, 2)[..., None]
             + o_b.astype(jnp.float32) * jnp.swapaxes(w_new, 1, 2)[..., None])
        return o, new_lse

    # Step 0 (the local K/V block) runs outside the scan so the ring does
    # exactly n-1 rotations — the carried K/V after the last compute is
    # never permuted just to be discarded.  It is also the ONLY causal
    # block: every scanned block is strictly past or strictly future.
    if impl == "flash":
        o_b, lse_b = block(q, k, v, causal)
    else:
        o_b, lse_b = _block_attn(q, k, v, me * Sl, me * Sl, sm_scale, causal)
    # fp32 accumulator: the running rescale-and-add compounds rounding error
    # across ring steps if carried in bf16; cast once at the end
    o0 = o_b.astype(jnp.float32)
    lse0 = lse_b

    def step(carry, r):
        o, lse, k_cur, v_cur = carry
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        src = (me - r) % n                       # whose K/V block we hold
        if impl == "flash":
            o_b, lse_b = block(q, k_cur, v_cur, False)
            if causal:
                # fully-future block: merge weight underflows to zero (the
                # zero cotangent likewise zeroes its backward contribution)
                lse_b = jnp.where(src < me, lse_b, -1e30)
        else:
            o_b, lse_b = _block_attn(q, k_cur, v_cur, me * Sl, src * Sl,
                                     sm_scale, causal)
        o, lse = merge(o, lse, o_b, lse_b)
        return (o, lse, k_cur, v_cur), None

    (o, _, _, _), _ = jax.lax.scan(step, (o0, lse0, k, v), jnp.arange(1, n))
    return o.astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh, batch_axes, causal: bool = True,
                           sm_scale: Optional[float] = None,
                           seq_axis: str = "seq", head_axis: str = "model",
                           impl: str = "auto"):
    """shard_map wrapper: q/k/v are global [B, S, H, hd] arrays; batch rides
    ``batch_axes``, sequence is split over ``seq_axis``, heads over
    ``head_axis``."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import shard_map_compat

    spec = P(batch_axes, seq_axis, head_axis, None)
    fn = shard_map_compat(
        functools.partial(ring_attention, axis_name=seq_axis, causal=causal,
                          sm_scale=sm_scale, impl=impl),
        mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)
