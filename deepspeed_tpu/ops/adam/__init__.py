"""Host optimizer kernels (reference ``deepspeed/ops/adam/``)."""
from .cpu_adam import DeepSpeedCPUAdam

__all__ = ["DeepSpeedCPUAdam"]
