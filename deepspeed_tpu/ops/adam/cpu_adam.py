"""DeepSpeedCPUAdam — host-side SIMD Adam for offloaded optimizer states.

Reference: ``deepspeed/ops/adam/cpu_adam.py`` (class ``DeepSpeedCPUAdam``)
backed by ``csrc/adam/cpu_adam_impl.cpp``.  The TPU build's native kernel
(ops/csrc/cpu_adam.cpp, OpenMP+SIMD) updates fp32 masters and both moments
in one fused pass over host RAM, optionally emitting the bf16 device view in
the same sweep — the host leg of ZeRO-Offload while the chip runs the next
forward.

Torch-free API: state tensors are numpy arrays (optionally memory-mapped
from NVMe by runtime/swap_tensor); ``step_flat`` is the single-buffer hot
path, ``step`` walks a pytree of parameter leaves.
"""
from __future__ import annotations

import ctypes
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..op_builder import CPUAdamBuilder

_U16 = ctypes.POINTER(ctypes.c_uint16)


def _fptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


class DeepSpeedCPUAdam:
    """Adam/AdamW over host numpy buffers via the native kernel."""

    def __init__(self, lr: float = 1e-3, betas: Tuple[float, float] = (0.9, 0.999),
                 eps: float = 1e-8, weight_decay: float = 0.0,
                 adamw_mode: bool = True, bias_correction: bool = True):
        self.lr = lr
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adamw_mode = adamw_mode
        self.bias_correction = bias_correction
        self.step_count = 0
        self._lib = CPUAdamBuilder().load()

    # -- flat-buffer hot path -------------------------------------------
    def step_flat(self, params: np.ndarray, grads: np.ndarray,
                  exp_avg: np.ndarray, exp_avg_sq: np.ndarray,
                  step: Optional[int] = None,
                  bf16_out: Optional[np.ndarray] = None,
                  lr: Optional[float] = None) -> None:
        """In-place Adam step on contiguous fp32 buffers of equal length."""
        for name, a in (("params", params), ("grads", grads),
                        ("exp_avg", exp_avg), ("exp_avg_sq", exp_avg_sq)):
            if a.dtype != np.float32 or not a.flags["C_CONTIGUOUS"]:
                raise TypeError(f"{name} must be contiguous float32")
        n = params.size
        if not (grads.size == exp_avg.size == exp_avg_sq.size == n):
            raise ValueError("buffer sizes differ")
        out = None
        if bf16_out is not None:
            if bf16_out.dtype != np.uint16 or bf16_out.size != n:
                raise TypeError("bf16_out must be uint16 of the same size")
            out = bf16_out.ctypes.data_as(_U16)
        self._lib.cpu_adam_step(
            _fptr(params), _fptr(grads), _fptr(exp_avg), _fptr(exp_avg_sq),
            n, np.float32(lr if lr is not None else self.lr),
            np.float32(self.betas[0]), np.float32(self.betas[1]),
            np.float32(self.eps), np.float32(self.weight_decay),
            int(self.adamw_mode), int(self.bias_correction),
            int(step if step is not None else self.step_count), out)

    # -- pytree API ------------------------------------------------------
    def init_state(self, params: Any) -> Dict[str, Any]:
        import jax

        zeros = jax.tree_util.tree_map(
            lambda p: np.zeros(np.shape(p), np.float32), params)
        return {"exp_avg": zeros,
                "exp_avg_sq": jax.tree_util.tree_map(np.copy, zeros)}

    def step(self, params: Any, grads: Any, state: Dict[str, Any],
             lr: Optional[float] = None) -> Any:
        """In-place update of a pytree of fp32 numpy leaves; returns params."""
        import jax

        self.step_count += 1
        flat_p = jax.tree_util.tree_leaves(params)
        flat_g = jax.tree_util.tree_leaves(grads)
        flat_m = jax.tree_util.tree_leaves(state["exp_avg"])
        flat_v = jax.tree_util.tree_leaves(state["exp_avg_sq"])
        for p, g, m, v in zip(flat_p, flat_g, flat_m, flat_v):
            for name, a in (("param", p), ("exp_avg", m), ("exp_avg_sq", v)):
                if not a.flags["C_CONTIGUOUS"]:
                    # reshape(-1) would copy and the in-place update would be
                    # silently discarded — refuse instead
                    raise TypeError(f"{name} leaf must be C-contiguous for "
                                    "the in-place native step")
            self.step_flat(p.reshape(-1), np.ascontiguousarray(
                np.asarray(g, np.float32).reshape(-1)), m.reshape(-1),
                v.reshape(-1), step=self.step_count, lr=lr)
        return params

    def l2_norm(self, tree: Any) -> float:
        import jax

        sq = 0.0
        for leaf in jax.tree_util.tree_leaves(tree):
            flat = np.ascontiguousarray(np.asarray(leaf, np.float32).reshape(-1))
            n = self._lib.cpu_l2_norm(_fptr(flat), flat.size)
            sq += n * n
        return float(np.sqrt(sq))
