"""NVMe/filesystem bandwidth bench for the native aio engine.

Parity target: reference ``csrc/aio/py_test`` (``ds_io`` benchmark suite) —
sustained read/write GB/s at varying thread counts, plus an honest baseline
from ``dd`` on the same volume so the engine's overhead is visible.

    python -m deepspeed_tpu.ops.aio_bench --path /tmp/aio_bench \
        --size-mb 256 --threads 1 4 8 [--direct] [--dd]

Prints one JSON line per configuration:
    {"op": "read", "threads": 4, "gbps": 2.31, "direct": false, ...}
"""
from __future__ import annotations

import argparse
import ctypes
import json
import os
import subprocess
import time

import numpy as np

from .op_builder import AsyncIOBuilder

ALIGN = 4096


def _aligned_buffer(nbytes: int) -> np.ndarray:
    """4096-aligned uint8 buffer (O_DIRECT requirement)."""
    raw = np.empty(nbytes + ALIGN, np.uint8)
    off = (-raw.ctypes.data) % ALIGN
    return raw[off:off + nbytes]


def bench_engine(path: str, size_mb: int, threads: int, direct: bool,
                 repeats: int = 3):
    lib = AsyncIOBuilder().load()
    nbytes = size_mb * (1 << 20)
    buf = _aligned_buffer(nbytes)
    buf[:] = np.random.default_rng(0).integers(0, 255, nbytes, np.uint8)
    fd = int(lib.ds_aio_open(path.encode(), 1, int(direct)))
    if fd < 0:
        raise OSError(-fd, f"open {path}")
    got_direct = bool(lib.ds_aio_is_direct(fd))
    out = []
    try:
        for op in ("write", "read"):
            fn = lib.ds_aio_pwrite if op == "write" else lib.ds_aio_pread
            best = 0.0
            for _ in range(repeats):
                t0 = time.perf_counter()
                rc = fn(fd, buf.ctypes.data_as(ctypes.c_void_p), nbytes, 0,
                        threads)
                if rc != 0:
                    raise OSError(-rc, f"aio {op}")
                os.fsync(fd) if op == "write" else None
                dt = time.perf_counter() - t0
                best = max(best, nbytes / dt / 1e9)
            out.append({"op": op, "engine": "ds_aio", "threads": threads,
                        "direct": got_direct, "size_mb": size_mb,
                        "gbps": round(best, 3)})
    finally:
        lib.ds_aio_close(fd)
    return out


def bench_dd(path: str, size_mb: int):
    """Raw ``dd`` on the same volume — the reference comparison point."""
    out = []
    blocks = size_mb
    for op, cmd in (
            ("write", ["dd", f"if=/dev/zero", f"of={path}", "bs=1M",
                       f"count={blocks}", "conv=fdatasync"]),
            ("read", ["dd", f"if={path}", "of=/dev/null", "bs=1M",
                      f"count={blocks}"])):
        t0 = time.perf_counter()
        subprocess.run(cmd, check=True, capture_output=True)
        dt = time.perf_counter() - t0
        out.append({"op": op, "engine": "dd", "size_mb": size_mb,
                    "gbps": round(size_mb * (1 << 20) / dt / 1e9, 3)})
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--path", default="/tmp/ds_aio_bench.bin")
    ap.add_argument("--size-mb", type=int, default=256)
    ap.add_argument("--threads", type=int, nargs="+", default=[1, 4, 8])
    ap.add_argument("--direct", action="store_true",
                    help="request O_DIRECT (falls back to buffered if the "
                         "filesystem refuses)")
    ap.add_argument("--dd", action="store_true",
                    help="also run the raw dd baseline")
    args = ap.parse_args(argv)

    results = []
    for t in args.threads:
        results += bench_engine(args.path, args.size_mb, t, args.direct)
    if args.dd:
        results += bench_dd(args.path + ".dd", args.size_mb)
        try:
            os.unlink(args.path + ".dd")
        except OSError:
            pass
    try:
        os.unlink(args.path)
    except OSError:
        pass
    for r in results:
        print(json.dumps(r))
    return results


if __name__ == "__main__":
    main()
