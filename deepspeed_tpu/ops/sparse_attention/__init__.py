"""Block-sparse attention (reference ``deepspeed/ops/sparse_attention/``).

The reference implements block-sparse attention with Triton matmul/softmax
kernels driven by a layout tensor (``sparse_self_attention.py``,
``matmul.py``, ``softmax.py``).  Here the SAME flash kernel that serves dense
causal attention skips dead blocks from a pattern mask — forward and backward
(ops/pallas/flash_attention.py ``block_mask``).

Cost model caveat (measured on v5e): the skip eliminates dead blocks'
COMPUTE, but the pipelined BlockSpec fetches still stream their K/V bytes
from HBM, so wall-clock improves by less than the density ratio (e.g. 23%
density ≈ 0.86x the all-live time at S=4096).  Long-sequence wins come from
the S² compute reduction; a gather-based fetch skip is the follow-up if
bandwidth-bound shapes matter.
"""
from __future__ import annotations

from typing import Optional

from .sparsity_config import (  # noqa: F401
    BigBirdSparsityConfig,
    BSLongformerSparsityConfig,
    DenseSparsityConfig,
    FixedSparsityConfig,
    LocalSlidingWindowSparsityConfig,
    SparsityConfig,
    VariableSparsityConfig,
)


class SparseSelfAttention:
    """Functional analogue of reference ``SparseSelfAttention`` (sparse_self_
    attention.py): holds a sparsity config, applies block-sparse attention.

    Call with q/k/v shaped [B, S, H, hd] (the model family's layout)."""

    def __init__(self, sparsity_config: SparsityConfig,
                 max_seq_length: int = 2048):
        self.sparsity_config = sparsity_config
        self.max_seq_length = max_seq_length
        self._layout_cache: dict = {}

    def layout(self, seq_len: int):
        if seq_len > self.max_seq_length:
            raise ValueError(
                f"seq_len {seq_len} exceeds max_seq_length "
                f"{self.max_seq_length}")
        if seq_len not in self._layout_cache:
            self._layout_cache[seq_len] = \
                self.sparsity_config.make_layout(seq_len)
        return self._layout_cache[seq_len]

    def __call__(self, q, k, v, sm_scale: Optional[float] = None,
                 interpret: Optional[bool] = None):
        from ..pallas.flash_attention import flash_attention

        S = q.shape[1]
        blk = self.sparsity_config.block
        causal = self.sparsity_config.attention == "unidirectional"
        return flash_attention(
            q, k, v, causal=causal, sm_scale=sm_scale,
            block_q=blk, block_k=blk, interpret=interpret,
            block_mask=self.layout(S))

    def density(self, seq_len: int) -> float:
        """Fraction of live blocks — the COMPUTE cost vs dense.  Wall-clock
        improves by less (dead blocks' K/V bytes still stream from HBM — see
        the module docstring's cost-model caveat)."""
        m = self.layout(seq_len)
        return float(m.sum()) / m.size
