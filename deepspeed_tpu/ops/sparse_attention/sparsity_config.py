"""Block-sparsity pattern builders.

Parity target: reference ``ops/sparse_attention/sparsity_config.py``
(SparsityConfig :10, Dense :63, Fixed :95, Variable :239, BigBird :411,
BSLongformer :546, LocalSlidingWindow :674).  The reference emits a
[heads, num_blocks, num_blocks] layout tensor that drives Triton block-sparse
matmuls; here each config emits a boolean block mask that drives the Pallas
flash kernel's block skip (ops/pallas/flash_attention.py ``block_mask``) —
same sparsity semantics, one shared layout across heads (the TPU kernel
grids over heads; per-head layouts would force per-head programs).

All masks are numpy bool [num_blocks, num_blocks] with ``mask[q, k] = True``
when the (q, k) block participates.  'unidirectional' composes the causal
triangle in; the kernel additionally applies elementwise causal masking
inside diagonal blocks.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np


@dataclasses.dataclass(frozen=True)
class SparsityConfig:
    """Base: block size + attention direction (reference :10-15 fields;
    ``different_layout_per_head`` is intentionally unsupported — see module
    docstring)."""
    num_heads: int = 1
    block: int = 128
    attention: str = "unidirectional"   # unidirectional | bidirectional

    def __post_init__(self):
        if self.attention not in ("unidirectional", "bidirectional"):
            raise ValueError(
                f"attention={self.attention!r} must be 'unidirectional' or "
                "'bidirectional'")

    def num_blocks(self, seq_len: int) -> int:
        if seq_len % self.block:
            raise ValueError(
                f"seq_len {seq_len} must be a multiple of block {self.block}")
        return seq_len // self.block

    def _finalize(self, mask: np.ndarray) -> np.ndarray:
        if self.attention == "unidirectional":
            mask &= np.tril(np.ones_like(mask))
        # a row with no live blocks would make softmax undefined: keep the
        # diagonal always
        np.fill_diagonal(mask, True)
        return mask

    def make_layout(self, seq_len: int) -> np.ndarray:
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class DenseSparsityConfig(SparsityConfig):
    """Everything attends (reference :63) — the parity/debug config."""

    def make_layout(self, seq_len: int) -> np.ndarray:
        n = self.num_blocks(seq_len)
        return self._finalize(np.ones((n, n), bool))


@dataclasses.dataclass(frozen=True)
class FixedSparsityConfig(SparsityConfig):
    """Fixed pattern (reference :95): each block attends to its local window
    of ``num_local_blocks`` and to ``num_global_blocks`` summary blocks at
    each local window's tail (the GPT-3 'fixed' pattern)."""
    num_local_blocks: int = 4
    num_global_blocks: int = 1

    def make_layout(self, seq_len: int) -> np.ndarray:
        n = self.num_blocks(seq_len)
        mask = np.zeros((n, n), bool)
        loc = self.num_local_blocks
        for q in range(n):
            start = (q // loc) * loc
            mask[q, start:start + loc] = True          # local window
        # global: the last `num_global_blocks` of EVERY window are visible
        # from all rows; _finalize's tril trims future ones for causal
        for wstart in range(0, n, loc):
            g0 = max(wstart + loc - self.num_global_blocks, wstart)
            mask[:, g0:wstart + loc] = True
        return self._finalize(mask)


@dataclasses.dataclass(frozen=True)
class VariableSparsityConfig(SparsityConfig):
    """Variable pattern (reference :239): arbitrary local window sizes plus
    explicit global block indices."""
    num_random_blocks: int = 0
    local_window_blocks: tuple = (4,)
    global_block_indices: tuple = (0,)

    def make_layout(self, seq_len: int) -> np.ndarray:
        n = self.num_blocks(seq_len)
        mask = np.zeros((n, n), bool)
        q = 0
        windows = list(self.local_window_blocks)
        while q < n:
            w = windows.pop(0) if windows else self.local_window_blocks[-1]
            end = min(q + w, n)
            mask[q:end, q:end] = True
            q = end
        for g in self.global_block_indices:
            if g < n:
                mask[:, g] = True                      # everyone sees global
                mask[g, :] = True                      # global sees everyone
        if self.num_random_blocks:
            rng = np.random.default_rng(0)             # deterministic layout
            for q in range(n):
                mask[q, rng.integers(0, n, self.num_random_blocks)] = True
        return self._finalize(mask)


@dataclasses.dataclass(frozen=True)
class BigBirdSparsityConfig(SparsityConfig):
    """BigBird (reference :411): sliding window + random + global blocks."""
    num_random_blocks: int = 1
    num_sliding_window_blocks: int = 3
    num_global_blocks: int = 1

    def make_layout(self, seq_len: int) -> np.ndarray:
        n = self.num_blocks(seq_len)
        mask = np.zeros((n, n), bool)
        w = self.num_sliding_window_blocks // 2
        for q in range(n):
            mask[q, max(0, q - w):q + w + 1] = True
        g = self.num_global_blocks
        mask[:, :g] = True
        mask[:g, :] = True
        rng = np.random.default_rng(0)
        for q in range(n):
            mask[q, rng.integers(0, n, self.num_random_blocks)] = True
        return self._finalize(mask)


@dataclasses.dataclass(frozen=True)
class BSLongformerSparsityConfig(SparsityConfig):
    """Longformer (reference :546): sliding window + explicit global ids."""
    num_sliding_window_blocks: int = 3
    global_block_indices: tuple = (0,)

    def make_layout(self, seq_len: int) -> np.ndarray:
        n = self.num_blocks(seq_len)
        mask = np.zeros((n, n), bool)
        w = self.num_sliding_window_blocks // 2
        for q in range(n):
            mask[q, max(0, q - w):q + w + 1] = True
        for g in self.global_block_indices:
            if g < n:
                mask[:, g] = True
                mask[g, :] = True
        return self._finalize(mask)


@dataclasses.dataclass(frozen=True)
class LocalSlidingWindowSparsityConfig(SparsityConfig):
    """Pure sliding window (reference :674)."""
    num_sliding_window_blocks: int = 3

    def make_layout(self, seq_len: int) -> np.ndarray:
        n = self.num_blocks(seq_len)
        mask = np.zeros((n, n), bool)
        w = self.num_sliding_window_blocks // 2
        for q in range(n):
            mask[q, max(0, q - w):q + w + 1] = True
        return self._finalize(mask)
