from .quantizer import (DEFAULT_BLOCK, dequantize_blockwise, quantize_blockwise,
                        quantized_all_gather, quantized_reduce_scatter)

__all__ = ["DEFAULT_BLOCK", "quantize_blockwise", "dequantize_blockwise",
           "quantized_all_gather", "quantized_reduce_scatter"]
