from .quantizer import (DEFAULT_BLOCK, dequantize_blockwise, quantize_blockwise,
                        hierarchical_quantized_reduce_scatter,
                        quantized_all_gather, quantized_reduce_scatter)

__all__ = ["DEFAULT_BLOCK", "quantize_blockwise", "dequantize_blockwise",
           "quantized_all_gather", "quantized_reduce_scatter",
           "hierarchical_quantized_reduce_scatter"]
