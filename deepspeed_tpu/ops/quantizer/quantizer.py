"""Blockwise quantization ops — the ZeRO++ communication primitives.

TPU-native equivalent of the reference quantization kernel family
(csrc/quantization/{quantize.cu,dequantize.cu,swizzled_quantize.cu,
quant_reduce.cu}; python binding deepspeed/ops/quantizer/).  Those CUDA
kernels exist to compress ZeRO-3's two big collectives:

  qwZ — int8-quantized weight all-gather (partition_parameters.py:1067-1087)
  qgZ — quantized hierarchical gradient reduce  (coalesced_collectives.py:31)

Here the quant/dequant math is expressed as XLA ops (reshape + reduce +
round — XLA fuses the whole block pipeline into the surrounding collective
program), and the collectives are `lax` collectives inside shard_map manual
regions, so the wire payload really is int8/int4.

Measured on the round-5 chip (tools/artifacts/zeropp_r5.json, honest
chiptimer): the XLA round-trip runs HBM-bound at ~0.35-0.5 TB/s effective.
A Pallas fusion could at best halve that overhead (~2 HBM passes
theoretical), but the op only pays off on DCN-crossing hops — where the
WIRE dominates the trade by 1-2 orders of magnitude — so the kernel-
engineering spend fails its own cost model; the XLA formulation stays.
The reference's swizzled layout solves a GPU-memory-coalescing problem
the XLA layout engine handles for us.

Symmetric per-block scaling: block of K consecutive elements shares one
fp32 scale = amax/qmax.  int4 packs two lanes per int8 byte (the TPU has no
s4 all-to-all; the reference's swizzled layout solves a GPU-memory-coalescing
problem the XLA layout engine handles for us).
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

DEFAULT_BLOCK = 256


def _qmax(bits: int) -> int:
    if bits == 8:
        return 127
    if bits == 4:
        return 7
    raise ValueError(f"bits must be 4 or 8, got {bits}")


def quantize_blockwise(x: jax.Array, block: int = DEFAULT_BLOCK,
                       bits: int = 8) -> Tuple[jax.Array, jax.Array]:
    """x (any shape) -> (q int8 [nblocks, block(/2 for int4)], scales fp32
    [nblocks]).  Blocks run over the flattened array; the tail block is
    zero-padded (padding quantizes to 0 exactly)."""
    qmax = _qmax(bits)
    flat = x.reshape(-1).astype(jnp.float32)
    pad = (-flat.size) % block
    if pad:
        flat = jnp.pad(flat, (0, pad))
    rows = flat.reshape(-1, block)
    amax = jnp.max(jnp.abs(rows), axis=1)
    scale = jnp.where(amax == 0, 1.0, amax / qmax)
    q = jnp.clip(jnp.round(rows / scale[:, None]), -qmax, qmax).astype(jnp.int8)
    if bits == 4:
        lo = q[:, 0::2] & 0xF
        hi = q[:, 1::2] << 4
        q = (lo | hi).astype(jnp.int8)
    return q, scale


def dequantize_blockwise(q: jax.Array, scale: jax.Array, shape, dtype,
                         block: int = DEFAULT_BLOCK, bits: int = 8) -> jax.Array:
    """Inverse of :func:`quantize_blockwise`."""
    if bits == 4:
        # sign-extend each nibble: shift to the top of the byte, shift back
        lo = (q.astype(jnp.int8) << 4) >> 4
        hi = q.astype(jnp.int8) >> 4
        rows = jnp.stack([lo, hi], axis=-1).reshape(q.shape[0], -1)
    else:
        rows = q
    x = rows.astype(jnp.float32) * scale[:, None]
    size = int(np.prod(shape)) if shape else 1
    return x.reshape(-1)[:size].reshape(shape).astype(dtype)


# ---------------------------------------------------------------------------
# Quantized collectives (run INSIDE shard_map manual regions)
# ---------------------------------------------------------------------------

def quantized_all_gather(x_shard: jax.Array, axis_name, gather_dim: int = 0,
                         block: int = DEFAULT_BLOCK, bits: int = 8,
                         out_dtype=None, grad_bits: int = None,
                         grad_hierarchy=None) -> jax.Array:
    """qwZ: all-gather a parameter shard with an int8/int4 wire format.

    Forward: quantize the local shard -> all_gather(q, scales) -> dequantize
    the full tensor (bytes on the wire: 1/2 (int8) or 1/4 (int4) of bf16).
    ``bits=None`` skips weight quantization (plain all-gather at out_dtype).
    Backward: the exact adjoint of all-gather — a reduce-scatter of the
    output cotangent; fp32 by default, or the qgZ quantized reduction when
    ``grad_bits`` is set.  ``axis_name`` may be a tuple of mesh axes (their
    shards concatenate major-to-minor in tuple order, matching GSPMD's
    dim-spec ordering).  ``grad_hierarchy=(inner_axes, outer_axis)`` routes
    the quantized reduction through the two-hop intra-then-inter path
    (:func:`hierarchical_quantized_reduce_scatter`); the tuple must cover
    exactly the axes of ``axis_name`` with the outer axis FIRST in
    ``axis_name`` (major), so the hierarchical landing matches the gather's
    concatenation order.
    """
    out_dtype = out_dtype or x_shard.dtype
    grad_dtype = x_shard.dtype

    @jax.custom_vjp
    def gather(x):
        if bits is None:
            xs = jax.lax.all_gather(x.astype(out_dtype), axis_name)
            parts = [xs[i] for i in range(xs.shape[0])]
        else:
            q, s = quantize_blockwise(x, block=block, bits=bits)
            qg = jax.lax.all_gather(q, axis_name)    # [n, nblk, block/pack]
            sg = jax.lax.all_gather(s, axis_name)    # [n, nblk]
            parts = [dequantize_blockwise(qg[i], sg[i], x.shape, out_dtype,
                                          block=block, bits=bits)
                     for i in range(qg.shape[0])]
        return jnp.concatenate(parts, axis=gather_dim)

    def gather_fwd(x):
        return gather(x), None

    def gather_bwd(_, dy):
        if grad_bits is None:
            dx = jax.lax.psum_scatter(dy, axis_name,
                                      scatter_dimension=gather_dim, tiled=True)
        elif grad_hierarchy is not None:
            inner, outer = grad_hierarchy
            dx = hierarchical_quantized_reduce_scatter(
                dy, inner, outer, scatter_dim=gather_dim, block=block,
                bits=grad_bits)
        else:
            name = (axis_name if not isinstance(axis_name, (tuple, list))
                    or len(axis_name) > 1 else axis_name[0])
            dx = quantized_reduce_scatter(dy, name, scatter_dim=gather_dim,
                                          block=block, bits=grad_bits)
        return (dx.astype(grad_dtype),)

    gather.defvjp(gather_fwd, gather_bwd)
    return gather(x_shard)


def quantized_reduce_scatter(grads: jax.Array, axis_name, scatter_dim: int = 0,
                             block: int = DEFAULT_BLOCK, bits: int = 8) -> jax.Array:
    """qgZ single-hop: reduce local gradients across ``axis_name`` with a
    quantized wire format, landing each device's shard.

    all_to_all exchanges quantized chunks (each device receives every peer's
    version of ITS chunk), then the sum runs locally in fp32 — one quantize
    per hop, exactly the reference's quantized-reduction semantics
    (csrc/quantization/quant_reduce.cu).  The hierarchical ICI/DCN two-hop
    composes this op over two mesh axes (see zero/zeropp.py).
    """
    n = jax.lax.psum(1, axis_name)
    # split the scatter dim into n chunks: chunk i belongs to device i
    moved = jnp.moveaxis(grads, scatter_dim, 0)
    lead = moved.shape[0]
    assert lead % n == 0, f"dim {scatter_dim} ({lead}) not divisible by {n}"
    chunks = moved.reshape(n, lead // n, *moved.shape[1:])
    per_chunk = int(np.prod(chunks.shape[1:]))
    # pad each chunk to a block multiple so no scale block straddles a chunk
    # boundary (C-order flattening then groups rows evenly into the n chunks)
    pad = (-per_chunk) % block
    flat = chunks.reshape(n, per_chunk)
    if pad:
        flat = jnp.pad(flat, ((0, 0), (0, pad)))
    q, s = quantize_blockwise(flat, block=block, bits=bits)
    nblk = q.shape[0] // n
    q = q.reshape(n, nblk, q.shape[-1])
    s = s.reshape(n, nblk)
    # all_to_all over the chunk axis: device i receives every peer's chunk i
    qx = jax.lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0)
    sx = jax.lax.all_to_all(s, axis_name, split_axis=0, concat_axis=0)
    # dequantize each peer's contribution and sum in fp32
    contribs = [dequantize_blockwise(qx[i], sx[i], (per_chunk + pad,),
                                     jnp.float32, block=block, bits=bits)
                for i in range(qx.shape[0])]
    total = functools.reduce(jnp.add, contribs)[:per_chunk]
    out = jnp.moveaxis(
        total.reshape(lead // n, *moved.shape[1:]), 0, scatter_dim)
    return out.astype(grads.dtype)


def hierarchical_quantized_reduce_scatter(grads: jax.Array, inner_axes,
                                          outer_axis, scatter_dim: int = 0,
                                          block: int = DEFAULT_BLOCK,
                                          bits: int = 8) -> jax.Array:
    """qgZ two-hop: intra-group (ICI) quantized reduce-scatter, THEN
    inter-group (DCN) — the reference's hierarchical all-to-all reduction
    (coalesced_collectives.py:31 + docs/_posts/2023-06-22-zeropp.md): the
    intra hop shrinks the data n_inner× before it crosses the expensive
    links, so the outer hop moves 1/n_inner of the bytes a flat reduction
    over the full group would.

    Landing layout is OUTER-MAJOR (device (i,j) of outer index i, inner
    index j owns chunk ``i*n_inner + j``), matching both GSPMD's partition
    order for a dim sharded ``P((outer, *inner))`` and the concatenation
    order of ``quantized_all_gather`` over ``(outer, *inner)`` — achieved
    by scattering the INNER-chunk axis of a ``[n_outer, n_inner, L/N]``
    view in hop 1 (a strided chunk set), then the outer axis in hop 2.
    Each hop re-quantizes, exactly like the reference's two quantization
    points per gradient.
    """
    n_i = jax.lax.psum(1, inner_axes)
    n_o = jax.lax.psum(1, outer_axis)
    moved = jnp.moveaxis(grads, scatter_dim, 0)
    lead = moved.shape[0]
    n = n_i * n_o
    assert lead % n == 0, (
        f"dim {scatter_dim} ({lead}) not divisible by group {n_o}x{n_i}")
    view = moved.reshape(n_o, n_i, lead // n, *moved.shape[1:])
    # hop 1 — intra: member j of each inner group collects chunk column j
    r1 = quantized_reduce_scatter(view, inner_axes, scatter_dim=1,
                                  block=block, bits=bits)
    r1 = r1.reshape(n_o, lead // n, *moved.shape[1:])
    # hop 2 — inter: n_inner x fewer bytes than a flat reduce would move
    r2 = quantized_reduce_scatter(r1, outer_axis, scatter_dim=0,
                                  block=block, bits=bits)
    out = r2.reshape(lead // n, *moved.shape[1:])
    return jnp.moveaxis(out, 0, scatter_dim).astype(grads.dtype)
