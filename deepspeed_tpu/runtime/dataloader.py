"""Data loaders (reference ``runtime/dataloader.py``: DeepSpeedDataLoader,
RepeatingLoader).

Works over anything indexable (numpy arrays, torch datasets, lists of pytrees)
or any iterable of batches.  Yields *global* micro-batches shaped
``[micro_batch × dp_world, ...]`` as numpy; the engine shards them onto the
mesh (jax.make_array_from_process_local_data on multihost).
"""
from __future__ import annotations

from typing import Any, Iterator, Optional

import numpy as np

import jax


class RepeatingLoader:
    """Wrap an iterator to restart on StopIteration (reference dataloader.py)."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)


class DeepSpeedDataLoader:
    """Batches an indexable dataset into [batch_size, ...] numpy pytrees.

    Data-parallel sharding happens at the array level (each host materializes
    its slice; the engine builds the global array), so there is no
    DistributedSampler analogue — the batch IS global.
    """

    def __init__(self, dataset: Any, batch_size: int, mesh=None, shuffle: bool = True,
                 seed: int = 0, drop_last: bool = True, collate_fn=None,
                 data_sampler=None):
        self.dataset = dataset
        self.batch_size = batch_size
        self.mesh = mesh
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.collate_fn = collate_fn
        self.data_sampler = data_sampler
        self.epoch = 0
        self._len = None

    def __len__(self):
        if self._len is None:
            n = len(self.dataset)
            self._len = n // self.batch_size if self.drop_last else -(-n // self.batch_size)
        return self._len

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def _indices(self) -> np.ndarray:
        idx = np.arange(len(self.dataset))
        if self.shuffle:
            np.random.RandomState(self.seed + self.epoch).shuffle(idx)
        return idx

    def _iter_sampler(self) -> Iterator:
        """LAZY sampler-driven iteration: one index batch drawn per yielded
        batch, so a curriculum sampler's consumed-batch counter (and with it
        the difficulty schedule and any checkpointed state) tracks batches
        actually TRAINED, not an eagerly pre-drawn epoch."""
        it = iter(self.data_sampler)
        buf: list = []
        produced = 0
        while produced < len(self):
            try:
                b = next(it)
            except StopIteration:
                break
            buf.extend(b if hasattr(b, "__len__") else [b])
            while len(buf) >= self.batch_size and produced < len(self):
                sel, buf = buf[:self.batch_size], buf[self.batch_size:]
                produced += 1
                yield self._collate([self.dataset[int(i)] for i in sel])

    def _collate(self, items):
        if self.collate_fn is not None:
            return self.collate_fn(items)
        first = items[0]
        if isinstance(first, dict):
            return {k: np.stack([np.asarray(it[k]) for it in items]) for k in first}
        if isinstance(first, (tuple, list)):
            return tuple(np.stack([np.asarray(it[j]) for it in items])
                         for j in range(len(first)))
        return np.stack([np.asarray(it) for it in items])

    def __iter__(self) -> Iterator:
        if self.data_sampler is not None:
            yield from self._iter_sampler()
            self.epoch += 1
            return
        idx = self._indices()
        nb = len(self)
        for b in range(nb):
            sel = idx[b * self.batch_size:(b + 1) * self.batch_size]
            if len(sel) < self.batch_size and self.drop_last:
                return
            yield self._collate([self.dataset[int(i)] for i in sel])
        self.epoch += 1
