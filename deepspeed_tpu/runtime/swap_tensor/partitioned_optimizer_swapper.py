"""NVMe optimizer-state swapping (ZeRO-Infinity host leg).

Reference mechanisms: ``runtime/swap_tensor/partitioned_optimizer_swapper.py``
(optimizer state on NVMe, aio-overlapped reads/writes around the CPU Adam
step) and ``optimizer_utils.py:OptimizerStateSwapper``.

TPU-native shape: the device runs a grad-only jitted step; fp32 masters and
Adam moments live in per-leaf files under ``swap_dir``.  ``step()`` walks the
leaves as a software pipeline —

  read(i+1) submitted  ->  compute Adam on i (native SIMD kernel)
                       ->  writeback(i) submitted, waited lazily

so NVMe reads of the next leaf and writebacks of the previous one overlap the
current leaf's CPU compute, the same overlap structure as the reference's
swap_in_gradients/swap_out_optimizer pipeline.  The Adam kernel emits the
bf16 device view in the same pass (csrc/cpu_adam.cpp), which is what goes
back to the chip — fp32 state never touches HBM.
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ...ops.adam.cpu_adam import DeepSpeedCPUAdam
from ...ops.op_builder import AsyncIOBuilder
from ...utils.logging import logger


class TensorSwapper:
    """Flat fp32 buffers in files, async via the native aio engine.

    Files are opened ONCE and kept as persistent fds (reference
    ``deepspeed_py_aio_handle.cpp`` holds the handle per swap file) — the
    old per-op open/close cost a syscall pair + dentry walk per leaf per
    step."""

    # fd-cache bound: large models have 3-4 files per param leaf; an
    # unbounded cache would trip RLIMIT_NOFILE (commonly 1024 soft)
    MAX_OPEN_FDS = 256

    def __init__(self, swap_dir: str, aio_threads: int = 4):
        os.makedirs(swap_dir, exist_ok=True)
        self.swap_dir = swap_dir
        self.aio_threads = aio_threads
        self._lib = AsyncIOBuilder().load()
        self._shapes: Dict[str, Tuple[int, ...]] = {}
        self._dtypes: Dict[str, np.dtype] = {}
        import collections

        self._fds: "collections.OrderedDict[str, int]" = \
            collections.OrderedDict()

    def _path(self, name: str) -> str:
        return os.path.join(self.swap_dir, name.replace("/", "__") + ".swp")

    def _fd(self, name: str) -> int:
        fd = self._fds.get(name)
        if fd is not None:
            self._fds.move_to_end(name)
            return fd
        while len(self._fds) >= self.MAX_OPEN_FDS:   # LRU-evict
            _, old = self._fds.popitem(last=False)
            self._lib.ds_aio_close(old)
        fd = int(self._lib.ds_aio_open(self._path(name).encode(), 1, 0))
        if fd < 0:
            raise OSError(-fd, f"aio open failed for {name}")
        self._fds[name] = fd
        return fd

    def close(self) -> None:
        for fd in self._fds.values():
            self._lib.ds_aio_close(fd)
        self._fds.clear()

    def __del__(self):  # noqa: D105
        try:
            self.close()
        except Exception:   # noqa: BLE001 — interpreter teardown
            pass

    def write(self, name: str, arr: np.ndarray) -> None:
        self._shapes[name] = arr.shape
        self._dtypes[name] = arr.dtype
        # bind the (possible) contiguous copy to a local so it outlives the
        # native call — `ascontiguousarray(x).ctypes.data` alone can free
        # the copy before pwrite reads it
        carr = np.ascontiguousarray(arr)
        rc = self._lib.ds_aio_pwrite(self._fd(name), carr.ctypes.data,
                                     carr.nbytes, 0, self.aio_threads)
        if rc != 0:
            raise OSError(-rc, f"aio write failed for {name}")

    def submit_write(self, name: str, arr: np.ndarray) -> int:
        """arr must stay alive until wait()."""
        self._shapes[name] = arr.shape
        self._dtypes[name] = arr.dtype
        return self._lib.ds_aio_submit_pwrite(
            self._fd(name), arr.ctypes.data, arr.nbytes, 0, self.aio_threads)

    def read(self, name: str, out: Optional[np.ndarray] = None) -> np.ndarray:
        out = self._alloc(name, out)
        rc = self._lib.ds_aio_pread(self._fd(name), out.ctypes.data,
                                    out.nbytes, 0, self.aio_threads)
        if rc != 0:
            raise OSError(-rc, f"aio read failed for {name}")
        return out

    def submit_read(self, name: str, out: Optional[np.ndarray] = None
                    ) -> Tuple[int, np.ndarray]:
        out = self._alloc(name, out)
        h = self._lib.ds_aio_submit_pread(self._fd(name), out.ctypes.data,
                                          out.nbytes, 0, self.aio_threads)
        return h, out

    def wait(self, handle: int) -> None:
        rc = self._lib.ds_aio_wait(handle)
        if rc != 0:
            raise OSError(-rc, "aio job failed")

    def _alloc(self, name: str, out: Optional[np.ndarray]) -> np.ndarray:
        shape = self._shapes[name]
        dtype = self._dtypes.get(name, np.dtype(np.float32))
        if out is None:
            out = np.empty(shape, dtype)
        assert out.flags["C_CONTIGUOUS"] and out.dtype == dtype
        return out


class SwappedAdamOptimizer:
    """Adam whose fp32 master + moments live on NVMe; pipelined step."""

    STATES = ("master", "exp_avg", "exp_avg_sq")

    def __init__(self, masters: Dict[str, np.ndarray], swap_dir: str,
                 aio_threads: int = 4, pipeline: bool = True, **adam_kwargs):
        self.swapper = TensorSwapper(swap_dir, aio_threads)
        self.adam = DeepSpeedCPUAdam(**adam_kwargs)
        self.names: List[str] = list(masters)
        self.pipeline = pipeline
        self.step_count = 0
        # per-leaf persistent host buffers (master, m, v, bf16): leaf shapes
        # never change, and reallocating multi-GB state every step is pure
        # allocator churn.  Per-leaf sets are pipeline-safe: overlap is only
        # ever between DIFFERENT leaves, and step() drains all writebacks
        # before returning.
        self._buffers: Dict[str, tuple] = {}
        total = 0
        for name, m in masters.items():
            m32 = np.ascontiguousarray(np.asarray(m, np.float32))
            self.swapper.write(f"{name}.master", m32)
            zeros = np.zeros_like(m32)
            self.swapper.write(f"{name}.exp_avg", zeros)
            self.swapper.write(f"{name}.exp_avg_sq", zeros)
            total += m32.nbytes * 3
        logger.info("SwappedAdamOptimizer: %d leaves, %.1f MB on %s",
                    len(self.names), total / 1e6, swap_dir)

    def _leaf_files(self, name: str) -> List[str]:
        return [f"{name}.{s}" for s in self.STATES]

    def step(self, grads: Dict[str, np.ndarray], lr: Optional[float] = None
             ) -> Dict[str, np.ndarray]:
        """One Adam step over all leaves; returns bf16 (uint16) views."""
        self.step_count += 1
        out: Dict[str, np.ndarray] = {}
        pending_w: List[Tuple[int, Any]] = []  # (handle, keepalive buffers)

        def leaf_buffers(name):
            if name not in self._buffers:
                shape = self.swapper._shapes[f"{name}.master"]
                self._buffers[name] = (
                    np.empty(shape, np.float32), np.empty(shape, np.float32),
                    np.empty(shape, np.float32),
                    np.empty(int(np.prod(shape)), np.uint16))
            return self._buffers[name]

        def read_leaf(name):
            bufs = leaf_buffers(name)
            return [self.swapper.submit_read(f, out=b)
                    for f, b in zip(self._leaf_files(name), bufs[:3])]

        def wait_leaf(hs):
            return [self.swapper.wait(h) or buf for h, buf in hs]

        next_hs = read_leaf(self.names[0]) if self.names else None
        for i, name in enumerate(self.names):
            hs, next_hs = next_hs, None
            if hs is None:  # non-pipelined (or prefetch disabled): read now
                hs = read_leaf(name)
            if self.pipeline and i + 1 < len(self.names):
                next_hs = read_leaf(self.names[i + 1])  # prefetch
            master, m, v = wait_leaf(hs)
            g = np.ascontiguousarray(
                np.asarray(grads[name], np.float32).reshape(-1))
            bf16 = leaf_buffers(name)[3]
            self.adam.step_flat(master.reshape(-1), g, m.reshape(-1),
                                v.reshape(-1), step=self.step_count,
                                bf16_out=bf16, lr=lr)
            out[name] = bf16.reshape(master.shape)
            bufs = (master, m, v)
            handles = [self.swapper.submit_write(f, b)
                       for f, b in zip(self._leaf_files(name), bufs)]
            pending_w.append((handles, bufs))
            if not self.pipeline:
                for h in handles:
                    self.swapper.wait(h)
                pending_w.pop()
            # bound in-flight writebacks to one leaf behind
            while len(pending_w) > 1:
                handles0, _ = pending_w.pop(0)
                for h in handles0:
                    self.swapper.wait(h)
        for handles0, _ in pending_w:
            for h in handles0:
                self.swapper.wait(h)
        return out

    def read_masters(self) -> Dict[str, np.ndarray]:
        return {n: self.swapper.read(f"{n}.master") for n in self.names}

    def read_state(self, name: str) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(master, exp_avg, exp_avg_sq) for one leaf — checkpointing hook."""
        return tuple(self.swapper.read(f) for f in self._leaf_files(name))

    def state_shape(self, name: str) -> Tuple[int, ...]:
        """Master shape without touching the swap files."""
        return tuple(self.swapper._shapes[f"{name}.master"])

    def write_state(self, name: str, master: np.ndarray, m: np.ndarray,
                    v: np.ndarray) -> None:
        """Overwrite one leaf's swap files — checkpoint-restore hook."""
        for f, arr in zip(self._leaf_files(name), (master, m, v)):
            self.swapper.write(f, np.ascontiguousarray(arr, dtype=np.float32))

    def state_bytes(self) -> int:
        return sum(int(np.prod(self.swapper._shapes[f"{n}.master"])) * 4 * 3
                   for n in self.names)


class HostAdamOptimizer:
    """Adam whose fp32 master + moments live in host RAM (ZeRO-Offload,
    reference runtime/zero/stage_1_and_2.py:1041-1124 cpu_offload + the
    csrc/adam/cpu_adam.cpp SIMD step).

    Same ``step(grads) -> bf16 params`` surface as SwappedAdamOptimizer so
    the engine's grad-only path drives either; this one skips the disk
    round-trip — state is resident, the SIMD kernel updates it in place.
    On a single chip this is the path that makes "model bigger than HBM"
    true: the device only ever holds bf16 params + grads, never the fp32
    master/m/v triple.
    """

    def __init__(self, masters: Dict[str, np.ndarray], **adam_kwargs):
        self.adam = DeepSpeedCPUAdam(**adam_kwargs)
        self.names: List[str] = list(masters)
        self.step_count = 0
        self._state: Dict[str, tuple] = {}
        total = 0
        for name, m in masters.items():
            # np.array COPIES: np.asarray of a jax.Array is a zero-copy
            # read-only view of the XLA buffer, and this class mutates the
            # master in place every step
            m32 = np.array(m, np.float32, order="C")
            self._state[name] = (
                m32, np.zeros_like(m32), np.zeros_like(m32),
                np.empty(m32.size, np.uint16))          # bf16 out buffer
            total += m32.nbytes * 3
        logger.info("HostAdamOptimizer: %d leaves, %.1f MB resident in host RAM",
                    len(self.names), total / 1e6)

    def step(self, grads: Dict[str, np.ndarray], lr: Optional[float] = None
             ) -> Dict[str, np.ndarray]:
        """One in-place Adam step over all leaves; returns bf16 (uint16) views."""
        self.step_count += 1
        out: Dict[str, np.ndarray] = {}
        for name in self.names:
            master, m, v, bf16 = self._state[name]
            g = np.ascontiguousarray(
                np.asarray(grads[name], np.float32).reshape(-1))
            self.adam.step_flat(master.reshape(-1), g, m.reshape(-1),
                                v.reshape(-1), step=self.step_count,
                                bf16_out=bf16, lr=lr)
            out[name] = bf16.reshape(master.shape)
        return out

    def read_masters(self) -> Dict[str, np.ndarray]:
        return {n: self._state[n][0] for n in self.names}

    def read_state(self, name: str) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(master, exp_avg, exp_avg_sq) for one leaf — checkpointing hook."""
        master, m, v, _ = self._state[name]
        return master, m, v

    def state_shape(self, name: str) -> Tuple[int, ...]:
        return tuple(self._state[name][0].shape)

    def write_state(self, name: str, master: np.ndarray, m: np.ndarray,
                    v: np.ndarray) -> None:
        """Overwrite one leaf's resident state in place — restore hook."""
        s_master, s_m, s_v, _ = self._state[name]
        np.copyto(s_master, master.reshape(s_master.shape))
        np.copyto(s_m, m.reshape(s_m.shape))
        np.copyto(s_v, v.reshape(s_v.shape))

    def state_bytes(self) -> int:
        return sum(s[0].nbytes * 3 for s in self._state.values())
