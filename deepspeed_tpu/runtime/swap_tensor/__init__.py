"""Tensor swapping to NVMe (reference ``deepspeed/runtime/swap_tensor/``)."""
from .partitioned_optimizer_swapper import SwappedAdamOptimizer, TensorSwapper

__all__ = ["SwappedAdamOptimizer", "TensorSwapper"]
