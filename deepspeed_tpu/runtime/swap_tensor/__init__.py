"""Tensor swapping to NVMe (reference ``deepspeed/runtime/swap_tensor/``)."""
from .partitioned_optimizer_swapper import (HostAdamOptimizer,
                                            SwappedAdamOptimizer,
                                            TensorSwapper)

__all__ = ["HostAdamOptimizer", "SwappedAdamOptimizer", "TensorSwapper"]
