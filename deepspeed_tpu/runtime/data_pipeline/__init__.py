"""Data efficiency suite (reference ``deepspeed/runtime/data_pipeline/``):
curriculum learning, curriculum-aware sampling, memmap indexed datasets,
random layerwise token dropping."""
from .curriculum_scheduler import CurriculumScheduler
from .data_analyzer import DataAnalyzer, load_metric_values
from .data_sampler import (CurriculumBatchSampler,
                           MultiMetricCurriculumSampler)
from .indexed_dataset import MMapIndexedDataset, MMapIndexedDatasetBuilder

__all__ = ["CurriculumScheduler", "CurriculumBatchSampler",
           "MultiMetricCurriculumSampler",
           "DataAnalyzer", "load_metric_values",
           "MMapIndexedDataset", "MMapIndexedDatasetBuilder"]
