"""Memory-mapped indexed dataset (reference
``runtime/data_pipeline/data_sampling/indexed_dataset.py`` — the
Megatron-style .bin/.idx pair).

Own on-disk format (not the Megatron binary layout): ``.bin`` holds raw
concatenated token arrays; ``.idx`` holds a header + per-document lengths.
Reads are ``np.memmap`` views, so the dataset never materializes in RAM and
a TPU-VM host can stream arbitrarily large corpora — the property the
reference format exists for.

    builder = MMapIndexedDatasetBuilder("corpus.bin", dtype=np.int32)
    builder.add_item(np.array([...], np.int32))
    builder.finalize("corpus.idx")

    ds = MMapIndexedDataset("corpus")       # or explicit .bin/.idx prefix
    ds[3] -> np.ndarray (zero-copy view); ds.sizes -> per-doc lengths
"""
from __future__ import annotations

import os
import struct
from typing import Optional

import numpy as np

_MAGIC = b"DSTPUIDX"
_VERSION = 1

_DTYPES = {1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32, 5: np.int64,
           6: np.float32, 7: np.float64, 8: np.uint16}
_DTYPE_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


def data_file_path(prefix: str) -> str:
    return prefix if prefix.endswith(".bin") else prefix + ".bin"


def index_file_path(prefix: str) -> str:
    p = prefix[:-4] if prefix.endswith(".bin") else prefix
    return p + ".idx"


class MMapIndexedDatasetBuilder:
    def __init__(self, bin_path: str, dtype=np.int32):
        self._dtype = np.dtype(dtype)
        if self._dtype not in _DTYPE_CODES:
            raise TypeError(f"unsupported dtype {dtype}")
        self._bin_path = data_file_path(bin_path)
        self._f = open(self._bin_path, "wb")
        self._sizes = []

    def add_item(self, arr: np.ndarray) -> None:
        arr = np.ascontiguousarray(arr, dtype=self._dtype)
        self._f.write(arr.tobytes())
        self._sizes.append(arr.size)

    def add_document(self, arr: np.ndarray) -> None:  # reference alias
        self.add_item(arr)

    def merge_file_(self, other_prefix: str) -> None:
        """Append another dataset's documents (reference builder API)."""
        other = MMapIndexedDataset(other_prefix)
        for i in range(len(other)):
            self.add_item(other[i])

    def finalize(self, idx_path: Optional[str] = None) -> None:
        self._f.close()
        idx_path = idx_path or index_file_path(self._bin_path)
        sizes = np.asarray(self._sizes, np.int64)
        with open(idx_path, "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<IIQ", _VERSION,
                                _DTYPE_CODES[self._dtype], sizes.size))
            f.write(sizes.tobytes())


class MMapIndexedDataset:
    def __init__(self, prefix: str):
        idx_path = index_file_path(prefix)
        with open(idx_path, "rb") as f:
            magic = f.read(len(_MAGIC))
            if magic != _MAGIC:
                raise ValueError(f"{idx_path}: bad magic {magic!r}")
            version, dcode, count = struct.unpack("<IIQ", f.read(16))
            if version != _VERSION:
                raise ValueError(f"{idx_path}: unsupported version {version}")
            self._dtype = np.dtype(_DTYPES[dcode])
            self.sizes = np.frombuffer(f.read(8 * count), np.int64)
        self._pointers = np.zeros(count + 1, np.int64)
        np.cumsum(self.sizes, out=self._pointers[1:])
        bin_path = data_file_path(prefix)
        expected = int(self._pointers[-1]) * self._dtype.itemsize
        actual = os.path.getsize(bin_path)
        if actual != expected:
            raise ValueError(f"{bin_path}: size {actual} != index total "
                             f"{expected} (truncated or mismatched pair)")
        self._data = np.memmap(bin_path, dtype=self._dtype, mode="r")

    def __len__(self) -> int:
        return len(self.sizes)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        if i < 0:
            i += len(self)
        return self._data[self._pointers[i]:self._pointers[i + 1]]

    def get(self, i: int, offset: int = 0, length: Optional[int] = None):
        doc = self[i]
        return doc[offset:offset + length if length is not None else None]

    @property
    def dtype(self):
        return self._dtype

    @property
    def supports_prefetch(self) -> bool:
        return False  # memmap: the OS page cache is the prefetcher
