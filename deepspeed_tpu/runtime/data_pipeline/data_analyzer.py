"""Offline dataset analysis for curriculum learning (reference
``runtime/data_pipeline/data_sampling/data_analyzer.py`` ``DataAnalyzer``).

Map-reduce over a dataset: workers each scan a stride-shard computing a
per-sample difficulty metric and persist partial index files; the reduce
merges them into the arrays the curriculum machinery consumes —

  - ``metric_values.npy``  : float/int metric aligned to sample index —
                             exactly the ``sizes`` input of
                             :class:`..data_sampler.CurriculumBatchSampler`
                             (which derives the difficulty ordering itself).

The reference parallelizes via launcher-spawned ranks; here ``run()`` uses
a thread pool (metric fns are usually tokenizer/IO bound and release the
GIL) and the map/reduce halves stay separately callable so a multi-host
launcher can still fan the map out by (worker_id, num_workers).
"""
from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Optional, Sequence

import numpy as np

DEFAULT_METRIC = "seqlen"


def _seqlen_metric(sample) -> float:
    """Default difficulty: document length (reference curriculum seqlen)."""
    if isinstance(sample, dict):
        sample = sample.get("input_ids", next(iter(sample.values())))
    return float(len(sample))


class DataAnalyzer:
    def __init__(self, metric_fn: Optional[Callable] = None,
                 metric_name: str = DEFAULT_METRIC,
                 num_workers: int = 1, worker_id: int = 0,
                 run_id: Optional[str] = None,
                 metrics: Optional[dict] = None):
        """``metrics={'name': fn, ...}`` analyzes SEVERAL metrics in one
        dataset pass (reference DataAnalyzer's metric_names/metric_functions
        lists); the single ``metric_fn``/``metric_name`` form is the
        one-metric special case."""
        self.metrics = dict(metrics) if metrics else {
            metric_name: metric_fn or _seqlen_metric}
        if metrics and (metric_fn is not None):
            raise ValueError("pass either metrics={...} or metric_fn, not both")
        # single-metric accessors kept for the existing API surface
        self.metric_name = next(iter(self.metrics))
        self.metric_fn = self.metrics[self.metric_name]
        self.num_workers = num_workers
        self.worker_id = worker_id
        # per-run nonce: (dataset_len, num_workers) alone would silently
        # merge a stale shard from a previous run over a same-shaped dataset.
        # Multi-host fan-outs must pass the SAME run_id to every worker and
        # to the reducer; the in-process run() generates one per call.
        self.run_id = run_id

    # -- map -------------------------------------------------------------
    def _shard_file(self, save_path: str, worker_id: int,
                    metric_name: Optional[str] = None) -> str:
        return os.path.join(
            save_path, f"{metric_name or self.metric_name}_w{worker_id}.npz")

    def run_map(self, dataset: Sequence, save_path: str,
                worker_id: Optional[int] = None) -> str:
        """Scan this worker's stride-shard ONCE, computing every metric;
        persist (indices, values) per metric."""
        wid = self.worker_id if worker_id is None else worker_id
        os.makedirs(save_path, exist_ok=True)
        idx = np.arange(wid, len(dataset), self.num_workers)
        # fetch each sample ONCE (disk/mmap datasets: k metrics must not
        # mean k decode passes)
        rows = []
        for i in idx:
            s = dataset[int(i)]
            rows.append([fn(s) for fn in self.metrics.values()])
        arr = np.asarray(rows, np.float64).reshape(len(idx),
                                                   len(self.metrics))
        out = None
        for col, name in enumerate(self.metrics):
            out_m = self._shard_file(save_path, wid, name)
            # fingerprint guards the reduce against merging shards from a
            # different analysis run left behind in the same save_path
            np.savez(out_m, indices=idx, values=arr[:, col],
                     dataset_len=np.int64(len(dataset)),
                     num_workers=np.int64(self.num_workers),
                     run_id=np.asarray(self.run_id or ""))
            if name == self.metric_name:
                out = out_m
        return out

    # -- reduce ----------------------------------------------------------
    def run_reduce(self, save_path: str,
                   metric_name: Optional[str] = None) -> str:
        """Merge every worker shard into the aligned value/order arrays."""
        metric_name = metric_name or self.metric_name
        parts = [self._shard_file(save_path, w, metric_name)
                 for w in range(self.num_workers)]
        missing = [p for p in parts if not os.path.exists(p)]
        if missing:
            raise FileNotFoundError(
                f"reduce before map finished: missing {missing}")
        loaded = []
        fingerprints = set()
        for p in parts:
            with np.load(p) as z:
                loaded.append((z["indices"], z["values"]))
                rid = str(z["run_id"][()]) if "run_id" in z.files else ""
                fingerprints.add((int(z["dataset_len"]),
                                  int(z["num_workers"]), rid))
        want_rid = self.run_id or next(iter(fingerprints))[2]
        if len(fingerprints) != 1 or next(iter(
                fingerprints))[1] != self.num_workers or \
                next(iter(fingerprints))[2] != want_rid:
            raise ValueError(
                f"shard fingerprints disagree ({sorted(fingerprints)}, "
                f"reduce num_workers={self.num_workers}, "
                f"run_id={want_rid!r}) — stale shard "
                "files from a previous analysis in this save_path?")
        n = next(iter(fingerprints))[0]
        values = np.full(n, np.nan)
        for idx, vals in loaded:
            values[idx] = vals
        if np.isnan(values).any():
            raise ValueError("reduce found sample indices no worker covered "
                             "— num_workers mismatch between map and reduce?")
        vpath = os.path.join(save_path, f"{metric_name}_values.npy")
        np.save(vpath, values)
        return vpath

    # -- convenience: in-process parallel map + reduce -------------------
    def run(self, dataset: Sequence, save_path: str) -> np.ndarray:
        if self.run_id is None:
            import uuid

            self.run_id = uuid.uuid4().hex
        with ThreadPoolExecutor(max_workers=self.num_workers) as pool:
            list(pool.map(lambda w: self.run_map(dataset, save_path, w),
                          range(self.num_workers)))
        for name in self.metrics:
            self.run_reduce(save_path, name)
        return load_metric_values(save_path, self.metric_name)

    def run_multi(self, dataset: Sequence, save_path: str) -> dict:
        """One dataset pass, every metric merged:
        ``{name: aligned values array}``."""
        self.run(dataset, save_path)
        return {name: load_metric_values(save_path, name)
                for name in self.metrics}


def load_metric_values(save_path: str,
                       metric_name: str = DEFAULT_METRIC) -> np.ndarray:
    """The ``sizes`` array for CurriculumBatchSampler."""
    return np.load(os.path.join(save_path, f"{metric_name}_values.npy"))
