"""Curriculum-learning difficulty scheduler
(reference ``runtime/data_pipeline/curriculum_scheduler.py:11``).

Maps global step -> difficulty (typically sequence length).  Schedules:
``fixed_linear``, ``fixed_root``, ``fixed_discrete``, ``custom``.

TPU note: every new difficulty is a new static shape, i.e. a recompile.
``difficulty_step`` is therefore not just a rounding convenience here but the
recompile knob — coarse steps (e.g. multiples of 64) bound the number of
compiled programs.  The engine additionally caches compiled steps per
difficulty so revisits are free.
"""
from __future__ import annotations

import math
from typing import Any, Callable, Dict

FIXED_LINEAR = "fixed_linear"
FIXED_ROOT = "fixed_root"
FIXED_DISCRETE = "fixed_discrete"
CUSTOM = "custom"


class CurriculumScheduler:
    def __init__(self, config: Dict[str, Any]):
        for key in ("curriculum_type", "min_difficulty", "max_difficulty",
                    "schedule_type"):
            if key not in config:
                raise ValueError(f"curriculum config missing {key!r}")
        self.state = {
            "min_difficulty": config["min_difficulty"],
            "max_difficulty": config["max_difficulty"],
            "current_difficulty": config["min_difficulty"],
            "schedule_type": config["schedule_type"],
        }
        sched = config.get("schedule_config", {})
        st = config["schedule_type"]
        if st == FIXED_LINEAR:
            for k in ("total_curriculum_step", "difficulty_step"):
                if k not in sched:
                    raise ValueError(f"{st} schedule requires {k!r}")
        elif st == FIXED_ROOT:
            for k in ("total_curriculum_step", "difficulty_step", "root_degree"):
                if k not in sched:
                    raise ValueError(f"{st} schedule requires {k!r}")
        elif st == FIXED_DISCRETE:
            for k in ("difficulty", "max_step"):
                if k not in sched:
                    raise ValueError(f"{st} schedule requires {k!r}")
            if len(sched["max_step"]) != len(sched["difficulty"]) - 1:
                raise ValueError("fixed_discrete: len(max_step) must be "
                                 "len(difficulty) - 1")
        elif st != CUSTOM:
            raise ValueError(f"unknown schedule_type {st!r}")
        self.state["schedule"] = dict(sched)
        self._custom: Callable[[int], int] = None

    # -- reference API ---------------------------------------------------
    def get_current_difficulty(self) -> int:
        return self.state["current_difficulty"]

    def set_current_difficulty(self, difficulty: int) -> None:
        self.state["current_difficulty"] = difficulty

    def set_custom_get_difficulty(self, fn: Callable[[int], int]) -> None:
        self._custom = fn

    def get_state(self):
        return self.state

    def set_state(self, state) -> None:
        self.state = state

    def get_difficulty(self, global_steps: int) -> int:
        st = self.state["schedule_type"]
        if st == FIXED_DISCRETE:
            return self._discrete(global_steps)
        if st == FIXED_LINEAR:
            return self._root(global_steps, degree=1)
        if st == FIXED_ROOT:
            return self._root(global_steps,
                              degree=self.state["schedule"]["root_degree"])
        if self._custom is None:
            raise RuntimeError("custom schedule requires "
                               "set_custom_get_difficulty()")
        return self._custom(global_steps)

    def update_difficulty(self, global_steps: int) -> int:
        if self.state["current_difficulty"] < self.state["max_difficulty"]:
            self.state["current_difficulty"] = self.get_difficulty(global_steps)
        return self.state["current_difficulty"]

    # -- schedules -------------------------------------------------------
    def _discrete(self, step: int) -> int:
        s = self.state["schedule"]
        for level, max_step in zip(s["difficulty"], s["max_step"]):
            if step <= max_step:
                return level
        return s["difficulty"][-1]

    def _root(self, step: int, degree: float) -> int:
        s = self.state["schedule"]
        frac = min(1.0, step / s["total_curriculum_step"]) ** (1.0 / degree)
        span = self.state["max_difficulty"] - self.state["min_difficulty"]
        diff = frac * span + self.state["min_difficulty"]
        # quantize to difficulty_step (the recompile knob) and clamp
        q = s["difficulty_step"]
        diff = int(math.floor(diff / q) * q)
        diff = max(diff, self.state["min_difficulty"])
        return min(diff, self.state["max_difficulty"])
