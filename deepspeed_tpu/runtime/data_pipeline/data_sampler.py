"""Curriculum-aware batch sampling (reference
``runtime/data_pipeline/data_sampling/data_sampler.py:36``
``DeepSpeedDataSampler``).

TPU-first shape: the sampler yields *index batches* whose difficulty metric
(default: document length) is within the curriculum's current difficulty.
Buckets are precomputed with one argsort; each ``set_difficulty`` narrows or
widens the eligible prefix, so stepping the curriculum costs O(1).  Shuffling
is deterministic per (seed, epoch) like the reference, and state round-trips
for checkpoint/resume (``state_dict``/``load_state_dict``).
"""
from __future__ import annotations

from typing import Iterator, List, Optional, Sequence

import numpy as np

from .curriculum_scheduler import CurriculumScheduler


class CurriculumBatchSampler:
    def __init__(self, sizes: Sequence[int], batch_size: int,
                 curriculum: Optional[CurriculumScheduler] = None,
                 seed: int = 1234, drop_last: bool = True):
        self.sizes = np.asarray(sizes)
        self.batch_size = batch_size
        self.curriculum = curriculum
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        self.consumed_batches = 0
        # ascending difficulty; eligible set is always a prefix of this order
        self._order = np.argsort(self.sizes, kind="stable")
        self._sorted_sizes = self.sizes[self._order]

    def _eligible(self) -> np.ndarray:
        if self.curriculum is None:
            return self._order
        diff = self.curriculum.get_current_difficulty()
        cutoff = int(np.searchsorted(self._sorted_sizes, diff, side="right"))
        if cutoff < self.batch_size and not self.drop_last:
            cutoff = min(self.batch_size, len(self._order))
        return self._order[:cutoff]

    def __iter__(self) -> Iterator[List[int]]:
        rng = np.random.default_rng(self.seed + self.epoch)
        while True:
            if self.curriculum is not None:
                self.curriculum.update_difficulty(self.consumed_batches)
            pool = self._eligible()
            if len(pool) < self.batch_size and self.drop_last:
                raise ValueError(
                    f"curriculum difficulty "
                    f"{self.curriculum.get_current_difficulty() if self.curriculum else '-'} "
                    f"admits only {len(pool)} samples < batch {self.batch_size}")
            batch = rng.choice(pool, size=self.batch_size,
                               replace=len(pool) < self.batch_size)
            self.consumed_batches += 1
            yield [int(i) for i in batch]
            if self.consumed_batches % max(len(self.sizes) // self.batch_size, 1) == 0:
                self.epoch += 1
                rng = np.random.default_rng(self.seed + self.epoch)

    # -- checkpoint/resume (reference state_dict contract) ---------------
    def state_dict(self):
        return {"epoch": self.epoch, "consumed_batches": self.consumed_batches,
                "seed": self.seed,
                "curriculum": (self.curriculum.get_state()
                               if self.curriculum else None)}

    def load_state_dict(self, state):
        self.epoch = state["epoch"]
        self.consumed_batches = state["consumed_batches"]
        self.seed = state["seed"]
        if self.curriculum is not None and state.get("curriculum"):
            self.curriculum.set_state(state["curriculum"])


class MultiMetricCurriculumSampler:
    """Cluster-bucketed multi-metric curriculum sampling (reference
    ``DeepSpeedDataSampler``, data_sampling/data_sampler.py:36).

    Each metric carries its own values array, curriculum scheduler,
    difficulty type (``value`` — thresholds in metric units — or
    ``percentile`` — thresholds in 0..max_difficulty rank units) and
    clustering type (``schedule_based`` participates in clustering;
    ``single_cluster`` never constrains).  Whenever any difficulty
    advances, the NEWLY-eligible samples (the intersection of per-metric
    eligible sets minus everything already clustered) form a new shuffled
    cluster; every batch then draws from ALL clusters with probability
    proportional to cluster size, sequentially within each cluster with a
    reshuffle on wrap-around — exactly the reference's sampling scheme,
    with in-memory numpy clusters instead of mmap files (the TPU build's
    datasets feed through the engine loader, not a 100M-doc mmap store).

    Distributed state: the full sampler state (difficulties, clusters,
    positions, RNG bit-generator state, consumed count) round-trips via
    ``state_dict``/``load_state_dict``, which the engine persists inside
    checkpoints — a resumed run continues the SAME sample stream.
    """

    def __init__(self, metrics: dict, batch_size: int, seed: int = 1234):
        if not metrics:
            raise ValueError("MultiMetricCurriculumSampler needs >=1 metric")
        self.metric_names = sorted(metrics)
        self.metrics = metrics
        n_set = {len(np.asarray(m["values"])) for m in metrics.values()}
        if len(n_set) != 1:
            raise ValueError(f"metric value arrays disagree on dataset "
                             f"size: {sorted(n_set)}")
        self.n = n_set.pop()
        self.batch_size = batch_size
        self.seed = seed
        self.consumed_batches = 0
        self.np_rng = np.random.default_rng(seed)
        self.current_difficulties = {m: None for m in self.metric_names}
        self.clusters: List[np.ndarray] = []
        self.positions: List[int] = []
        # precomputed ascending order per metric (percentile eligibility is
        # a prefix of this; value eligibility via searchsorted)
        self._order = {m: np.argsort(np.asarray(metrics[m]["values"]),
                                     kind="stable")
                      for m in self.metric_names}
        self._sorted_vals = {m: np.asarray(metrics[m]["values"])[self._order[m]]
                             for m in self.metric_names}

    # -- eligibility ------------------------------------------------------
    def _eligible(self, name: str, difficulty) -> np.ndarray:
        spec = self.metrics[name]
        if spec.get("clustering_type", "schedule_based") == "single_cluster":
            return np.arange(self.n)
        if spec.get("difficulty_type", "value") == "percentile":
            maxd = spec["scheduler"].state["max_difficulty"]
            cutoff = int(self.n * min(difficulty / maxd, 1.0))
            return self._order[name][:cutoff]
        cutoff = int(np.searchsorted(self._sorted_vals[name], difficulty,
                                     side="right"))
        return self._order[name][:cutoff]

    def _maybe_new_cluster(self) -> None:
        changed = False
        for m in self.metric_names:
            d = self.metrics[m]["scheduler"].update_difficulty(
                self.consumed_batches)
            if d != self.current_difficulties[m]:
                self.current_difficulties[m] = d
                changed = True
        if not changed and self.clusters:
            return
        eligible = None
        for m in self.metric_names:
            e = self._eligible(m, self.current_difficulties[m])
            eligible = e if eligible is None else np.intersect1d(
                eligible, e, assume_unique=True)
        for c in self.clusters:
            eligible = np.setdiff1d(eligible, c, assume_unique=True)
        if eligible is not None and len(eligible):
            self.np_rng.shuffle(eligible)
            self.clusters.append(eligible)
            self.positions.append(0)

    # -- cluster draws ----------------------------------------------------
    def _draw(self, cidx: int, k: int) -> List[int]:
        out: List[int] = []
        while len(out) < k:   # looped wrap: k may exceed the cluster size
            c, pos = self.clusters[cidx], self.positions[cidx]
            take = min(k - len(out), len(c) - pos)
            out += [int(i) for i in c[pos:pos + take]]
            self.positions[cidx] = pos + take
            if self.positions[cidx] >= len(c) and len(out) < k:
                c = c.copy()                    # reshuffle and keep drawing
                self.np_rng.shuffle(c)
                self.clusters[cidx] = c
                self.positions[cidx] = 0
        return out

    def __iter__(self) -> Iterator[List[int]]:
        while True:
            self._maybe_new_cluster()
            if not self.clusters:
                raise ValueError(
                    "no samples eligible at the initial difficulties "
                    f"{self.current_difficulties}")
            sizes = np.asarray([len(c) for c in self.clusters], np.float64)
            weights = sizes / sizes.sum()
            picks = self.np_rng.choice(len(self.clusters), self.batch_size,
                                       replace=True, p=weights)
            counts = np.bincount(picks, minlength=len(self.clusters))
            batch: List[int] = []
            for cidx, k in enumerate(counts):
                if k:
                    batch += self._draw(cidx, int(k))
            self.consumed_batches += 1
            yield batch

    # -- checkpointed distributed state -----------------------------------
    def state_dict(self):
        return {
            "consumed_batches": self.consumed_batches,
            "seed": self.seed,
            "current_difficulties": dict(self.current_difficulties),
            "clusters": [c.tolist() for c in self.clusters],
            "positions": list(self.positions),
            "rng_state": self.np_rng.bit_generator.state,
            "schedulers": {m: self.metrics[m]["scheduler"].get_state()
                           for m in self.metric_names},
        }

    def load_state_dict(self, state):
        self.consumed_batches = state["consumed_batches"]
        self.seed = state["seed"]
        self.current_difficulties = dict(state["current_difficulties"])
        self.clusters = [np.asarray(c, np.int64) for c in state["clusters"]]
        self.positions = list(state["positions"])
        self.np_rng.bit_generator.state = state["rng_state"]
        for m, s in state.get("schedulers", {}).items():
            if m in self.metrics:
                self.metrics[m]["scheduler"].set_state(s)
