"""Curriculum-aware batch sampling (reference
``runtime/data_pipeline/data_sampling/data_sampler.py:36``
``DeepSpeedDataSampler``).

TPU-first shape: the sampler yields *index batches* whose difficulty metric
(default: document length) is within the curriculum's current difficulty.
Buckets are precomputed with one argsort; each ``set_difficulty`` narrows or
widens the eligible prefix, so stepping the curriculum costs O(1).  Shuffling
is deterministic per (seed, epoch) like the reference, and state round-trips
for checkpoint/resume (``state_dict``/``load_state_dict``).
"""
from __future__ import annotations

from typing import Iterator, List, Optional, Sequence

import numpy as np

from .curriculum_scheduler import CurriculumScheduler


class CurriculumBatchSampler:
    def __init__(self, sizes: Sequence[int], batch_size: int,
                 curriculum: Optional[CurriculumScheduler] = None,
                 seed: int = 1234, drop_last: bool = True):
        self.sizes = np.asarray(sizes)
        self.batch_size = batch_size
        self.curriculum = curriculum
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        self.consumed_batches = 0
        # ascending difficulty; eligible set is always a prefix of this order
        self._order = np.argsort(self.sizes, kind="stable")
        self._sorted_sizes = self.sizes[self._order]

    def _eligible(self) -> np.ndarray:
        if self.curriculum is None:
            return self._order
        diff = self.curriculum.get_current_difficulty()
        cutoff = int(np.searchsorted(self._sorted_sizes, diff, side="right"))
        if cutoff < self.batch_size and not self.drop_last:
            cutoff = min(self.batch_size, len(self._order))
        return self._order[:cutoff]

    def __iter__(self) -> Iterator[List[int]]:
        rng = np.random.default_rng(self.seed + self.epoch)
        while True:
            if self.curriculum is not None:
                self.curriculum.update_difficulty(self.consumed_batches)
            pool = self._eligible()
            if len(pool) < self.batch_size and self.drop_last:
                raise ValueError(
                    f"curriculum difficulty "
                    f"{self.curriculum.get_current_difficulty() if self.curriculum else '-'} "
                    f"admits only {len(pool)} samples < batch {self.batch_size}")
            batch = rng.choice(pool, size=self.batch_size,
                               replace=len(pool) < self.batch_size)
            self.consumed_batches += 1
            yield [int(i) for i in batch]
            if self.consumed_batches % max(len(self.sizes) // self.batch_size, 1) == 0:
                self.epoch += 1
                rng = np.random.default_rng(self.seed + self.epoch)

    # -- checkpoint/resume (reference state_dict contract) ---------------
    def state_dict(self):
        return {"epoch": self.epoch, "consumed_batches": self.consumed_batches,
                "seed": self.seed,
                "curriculum": (self.curriculum.get_state()
                               if self.curriculum else None)}

    def load_state_dict(self, state):
        self.epoch = state["epoch"]
        self.consumed_batches = state["consumed_batches"]
        self.seed = state["seed"]
        if self.curriculum is not None and state.get("curriculum"):
            self.curriculum.set_state(state["curriculum"])
