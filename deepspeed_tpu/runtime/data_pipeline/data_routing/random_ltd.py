"""Random layerwise token dropping (random-LTD).

Reference: ``runtime/data_pipeline/data_routing/random_ltd.py`` (the
``RandomLayerTokenDrop`` wrapper) + ``scheduler.py`` (RandomLTDScheduler),
from the Data Efficiency suite: during training each wrapped layer processes
only a random subset of tokens; dropped tokens bypass the layer through the
residual stream, cutting per-layer attention/MLP cost while the kept-token
count anneals up to the full sequence over training.

TPU-first: the subset size is STATIC per compiled program (shapes must be
static under jit), so the engine buckets the scheduler's value and caches one
compiled step per bucket.  Token selection is an argsort of per-token uniform
noise (a shuffle), sorted ascending to preserve temporal order for rotary
positions and causal masking within the subset — the same order-preserving
gather the reference does with torch.sort(indices).
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp


def select_tokens(rng: jax.Array, B: int, S: int, keep: int) -> jax.Array:
    """[B, keep] sorted random token indices (no replacement)."""
    noise = jax.random.uniform(rng, (B, S))
    idx = jnp.argsort(noise, axis=1)[:, :keep]
    return jnp.sort(idx, axis=1)


def gather_tokens(x: jax.Array, idx: jax.Array) -> jax.Array:
    """x [B, S, ...] -> [B, keep, ...] along axis 1."""
    expand = idx.reshape(idx.shape + (1,) * (x.ndim - 2))
    return jnp.take_along_axis(x, jnp.broadcast_to(
        expand, idx.shape + x.shape[2:]), axis=1)


def scatter_tokens(x_full: jax.Array, x_sub: jax.Array, idx: jax.Array
                   ) -> jax.Array:
    """Write the processed subset back; untouched rows keep x_full (the
    residual bypass)."""
    B = x_full.shape[0]
    return x_full.at[jnp.arange(B)[:, None], idx].set(x_sub)


def random_ltd_block(block_fn, cfg, lp, x, positions, rng, keep: int,
                     deterministic: bool) -> Tuple[jax.Array, Any]:
    """Wrap one transformer block with token dropping.

    ``block_fn(lp, x_sub, rng, pos_sub) -> (out_sub, aux)``; inactive (full
    pass-through) when deterministic or keep >= S.
    """
    B, S, _ = x.shape
    if deterministic or keep >= S or keep <= 0:
        return block_fn(lp, x, rng, positions)
    rng, sel = jax.random.split(rng)
    idx = select_tokens(sel, B, S, keep)
    x_sub = gather_tokens(x, idx)
    pos_sub = jnp.take_along_axis(positions, idx, axis=1)
    out_sub, aux = block_fn(lp, x_sub, rng, pos_sub)
    return scatter_tokens(x, out_sub, idx), aux


class RandomLTDScheduler:
    """Anneals the kept-token count (reference scheduler.py API:
    ``update_seq``/``get_current_seq``; fixed_linear schedule)."""

    def __init__(self, config: Dict[str, Any]):
        sched = config.get("random_ltd_schedule", {})
        self.min_value = int(config.get("min_value", sched.get("min_value", 128)))
        self.max_value = int(config.get("max_value", sched.get("max_value", 2048)))
        self.schedule_type = sched.get("schedule_type", "fixed_linear")
        if self.schedule_type != "fixed_linear":
            raise ValueError(f"random_ltd schedule {self.schedule_type!r} "
                             "not supported (fixed_linear only)")
        sc = sched.get("schedule_config", {})
        self.seq_per_step = int(sc.get("seq_per_step", 16))
        self.require_steps = int(sc.get("require_steps", 1000))
        self.current_seq = self.min_value

    def get_current_seq(self) -> int:
        return self.current_seq

    def update_seq(self, global_steps: int) -> int:
        frac = min(1.0, global_steps / self.require_steps)
        raw = self.min_value + frac * (self.max_value - self.min_value)
        # quantize to seq_per_step: this bounds the number of compiled
        # programs (each distinct keep-count is a distinct static shape)
        q = (int(raw) // self.seq_per_step) * self.seq_per_step
        self.current_seq = max(self.min_value, min(q, self.max_value))
        return self.current_seq

    def state_dict(self):
        return {"current_seq": self.current_seq}

    def load_state_dict(self, state):
        self.current_seq = state["current_seq"]
