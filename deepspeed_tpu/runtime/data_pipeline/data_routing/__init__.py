"""Data routing (reference ``data_pipeline/data_routing/``): random-LTD."""
from .random_ltd import (RandomLTDScheduler, gather_tokens, random_ltd_block,
                         scatter_tokens, select_tokens)

__all__ = ["RandomLTDScheduler", "random_ltd_block", "select_tokens",
           "gather_tokens", "scatter_tokens"]
