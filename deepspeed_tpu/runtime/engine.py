"""DeepSpeedEngine — the core training engine (reference ``runtime/engine.py:181``).

TPU-native redesign.  The reference engine wraps ``torch.nn.Module`` and
orchestrates forward/backward/step imperatively with autograd hooks; here the
engine owns a functional ``TrainState`` pytree and ONE jitted ``train_step``
whose data layout (ZeRO stage, TP specs, precision) is declared through the
sharding planner (runtime/zero/planner.py).  What the reference does in
~3,400 lines of hook orchestration, GSPMD does in the compiler:

  - grad allreduce / reduce-scatter  <- grad sharding constraints
    (engine.allreduce_gradients :1830, stage_1_and_2.reduce_* :837)
  - ZeRO-3 param fetch/release       <- param sharding + XLA all-gather
    scheduling (partitioned_param_coordinator.fetch_sub_module :250)
  - all_gather_dp_groups after step  <- params recomputed from sharded
    masters under their own sharding (stage_1_and_2.py:1751)
  - loss scaling + overflow skip     <- lax.cond select inside the step
    (fp16/loss_scaler.py)

Model contract: ``loss_fn(params, batch, rng) -> loss | (loss, aux_dict)``.
Adapters for flax modules / HF models live in ``deepspeed_tpu.models``.
"""
from __future__ import annotations

import dataclasses
import functools
import os
import time
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from .config import DeepSpeedConfig
from .lr_schedules import get_lr_scheduler, constant_lr
from .optimizer import create_optimizer
from .fp16.loss_scaler import (LossScaleState, dynamic_loss_scale_state,
                               static_loss_scale_state, no_loss_scale_state, scale_loss,
                               grads_finite, update_scale)
from .zero.planner import plan_sharding, named_shardings, constrain, ZeroShardingPlan
from .offload import (resolve_offload_mode, apply_streamed_placement,
                      HostSteppedOffload)
from .features import (wire_compression, wire_progressive_layer_drop,
                       wire_curriculum, wire_random_ltd, wire_flops_profiler)
from ..observability.trace import trace_span
from ..parallel.mesh import (dp_world_size, resolve_engine_mesh,
                             BATCH_AXES, ZERO_AXES)
from ..utils.logging import logger, log_dist
from ..utils.timer import SynchronizedWallClockTimer, ThroughputTimer
from .. import comm as dist


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    """Everything the jitted step reads and writes."""

    step: jnp.ndarray                 # i32 global step
    params: Any                       # compute-precision params (fwd/bwd view)
    master_params: Any                # fp32 masters (None when compute is fp32)
    opt_state: Any
    scaler: LossScaleState
    rng: jnp.ndarray
    comm_error: Any = None            # 1-bit error-feedback buffers (per-worker)


def make_grad_accumulator(grad_of_batch, gas: int, accum_dtype=None):
    """Shared microbatch scan: accumulate ``gas`` microbatch gradients.

    run(work, scaler, window, rng) -> (summed grads, losses [gas], new_rng).
    Single source of truth for the accumulation loop (fused train step,
    NVMe grad-only step, and the 1-bit compressed region all use it).
    ``accum_dtype`` is the accumulator precision (reference config
    ``data_types.grad_accum_dtype``, runtime/config.py:867): fp32 by default;
    bf16 halves the live gradient buffer at a small accumulation-rounding
    cost (most relevant for large ``gas``)."""
    accum_dtype = accum_dtype or jnp.float32

    def run(work, scaler, window, rng):
        def micro(carry, microbatch):
            acc, r = carry
            r, sub = jax.random.split(r)
            grads, loss = grad_of_batch(work, scaler, microbatch, sub)
            acc = jax.tree_util.tree_map(
                lambda a, g: a + g.astype(accum_dtype), acc, grads)
            return (acc, r), loss

        zeros = jax.tree_util.tree_map(
            lambda x: jnp.zeros(x.shape, accum_dtype), work)
        (grads, new_rng), losses = jax.lax.scan(micro, (zeros, rng), window,
                                                length=gas)
        return grads, losses, new_rng

    return run


def _xla_options() -> Optional[Dict[str, str]]:
    """Extra XLA compiler options for the train/eval step jits.

    ``DS_TPU_XLA_OPTIONS="k=v,k2=v2"`` — escape hatch for per-job compiler
    tuning (e.g. scheduler or fusion knobs) without code changes; the
    reference exposes the same class of knob via op-builder build flags.
    """
    raw = os.environ.get("DS_TPU_XLA_OPTIONS", "").strip()
    if not raw:
        return None
    opts = {}
    for item in raw.split(","):
        if "=" in item:
            k, v = item.split("=", 1)
            opts[k.strip()] = v.strip()
    return opts or None


def _jit_step(fn, **kw):
    """jax.jit wrapper applying the DS_TPU_XLA_OPTIONS passthrough."""
    opts = _xla_options()
    if opts:
        kw["compiler_options"] = opts
    return jax.jit(fn, **kw)


def _cast_tree(tree, dtype):
    return jax.tree_util.tree_map(
        lambda x: x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x, tree)


def _tree_select(pred, a, b):
    return jax.tree_util.tree_map(lambda x, y: jnp.where(pred, x, y), a, b)


class DeepSpeedEngine:
    def __init__(self, model: Any = None, loss_fn: Optional[Callable] = None,
                 init_fn: Optional[Callable] = None, params: Any = None,
                 param_specs: Any = None, config: Any = None,
                 optimizer: Optional[optax.GradientTransformation] = None,
                 lr_scheduler: Optional[Callable] = None,
                 training_data: Any = None, mesh=None, dont_change_device: bool = False):
        # -- model contract resolution --
        self.model = model
        if model is not None and loss_fn is None:
            # `model` may be an adapter object exposing (init_fn, loss_fn, param_specs)
            loss_fn = getattr(model, "loss_fn", None)
            init_fn = init_fn or getattr(model, "init_fn", None)
            param_specs = param_specs if param_specs is not None else getattr(
                model, "param_specs", None)
            if hasattr(model, "eval_fn"):
                self._eval_fn = model.eval_fn
        if loss_fn is None:
            raise ValueError("engine needs loss_fn(params, batch, rng) (directly or via model)")
        if init_fn is None and params is None:
            raise ValueError("engine needs init_fn(rng)->params or explicit params")
        self.loss_fn = loss_fn
        self._eval_fn = getattr(self, "_eval_fn", None) or loss_fn

        # -- config / mesh --
        self.config = config if isinstance(config, DeepSpeedConfig) else DeepSpeedConfig(config)
        # MiCS/hpZ both factorize the data axis; hpZ's planner divergence
        # (masters/grads on the FULL group, compute view inner-only) is
        # applied below via zero_axes
        hpz = self.config.zero_config.zero_hpz_partition_size
        mesh = resolve_engine_mesh(self.config.mesh, self.config.zero_config,
                                   mesh)
        self.mesh = mesh
        self.dp_world = dp_world_size(mesh)
        self.config.resolve_batch_triad(self.dp_world)
        dist.configure(self.config.comms_logger)

        self.compute_dtype = self.config.precision
        self.use_master_weights = self.compute_dtype != jnp.float32
        self.fp16_enabled = self.config.fp16.enabled
        self.zero_stage = self.config.zero_optimization_stage
        self.gas = self.config.gradient_accumulation_steps
        self.micro_batch_size = self.config.train_micro_batch_size_per_gpu
        self.train_batch_size = self.config.train_batch_size

        pp = self.mesh.shape.get("pipe", 1)
        if pp > 1 and model is not None and hasattr(model, "config"):
            mcfg = model.config
            stages = getattr(mcfg, "pipeline_stages", 1)
            if stages != pp:
                raise ValueError(
                    f"mesh has pipe={pp} but model.config.pipeline_stages={stages}")
            # pipeline_microbatches is DECOUPLED from gas (VERDICT r2 item 3):
            # the per-step window (gas × micro_batch × dp samples) splits into
            # M model-level microbatches; gas remains the optimizer cadence
            micro = getattr(mcfg, "pipeline_microbatches", None) or stages
            window = self.gas * self.micro_batch_size * self.dp_world
            if window % micro:
                raise ValueError(
                    f"pipeline microbatches ({micro}) must divide the "
                    f"per-step sample window gas*micro_batch*dp={window}")

        if self.config.activation_checkpointing.partition_activations:
            # satisfied structurally: saved remat residuals carry the model's
            # sharding constraints, so GSPMD already partitions them over the
            # model/seq axes (the Megatron partition_activations behavior)
            log_dist("activation_checkpointing.partition_activations: saved "
                     "residuals follow the activation shardings (structural "
                     "under GSPMD)", ranks=[0])

        # -- compression (QAT / pruning transform on the compute tree) --
        wire_compression(self, model)

        # -- lr schedule --
        if lr_scheduler is not None:
            self.lr_schedule = lr_scheduler
        elif self.config.scheduler is not None:
            self.lr_schedule = get_lr_scheduler(self.config.scheduler.type,
                                                self.config.scheduler.params)
        else:
            lr = (self.config.optimizer.params.get("lr", 1e-3)
                  if self.config.optimizer else 1e-3)
            self.lr_schedule = constant_lr(lr)

        # -- frozen parameters (reference requires_grad=False semantics:
        #    excluded from updates, grad norm and clipping; still in params
        #    + checkpoints).  The functional analogue of torch's per-tensor
        #    flag: the model exposes ``frozen_spec() -> pytree of bool``
        #    (True = frozen) matching its param tree.  LoRA
        #    (runtime/lora.py) remains the memory-optimal freezing route —
        #    this path keeps the full tree in the optimizer for API parity.
        frozen_spec = getattr(model, "frozen_spec", None)
        self._frozen_mask = frozen_spec() if callable(frozen_spec) else frozen_spec
        if self._frozen_mask is not None and not any(
                jax.tree_util.tree_leaves(self._frozen_mask)):
            self._frozen_mask = None    # nothing frozen: skip the masking

        # -- optimizer --
        self._compression = None
        if optimizer is not None:
            if self._frozen_mask is not None:
                # same contract as engine-built chains: whatever the client
                # chain emits (including weight decay), frozen leaves get a
                # zero update; grads are additionally zeroed in apply_update
                from .optimizer import zero_frozen_updates
                optimizer = optax.chain(
                    optimizer, zero_frozen_updates(self._frozen_mask))
                log_dist("client optimizer wrapped with frozen-parameter "
                         "masking (model.frozen_spec)", ranks=[0])
            self.optimizer = optimizer
        else:
            opt_cfg = self.config.optimizer
            opt_type = opt_cfg.type if opt_cfg else "adamw"
            opt_params = dict(opt_cfg.params) if opt_cfg else {}
            self.optimizer = create_optimizer(opt_type, opt_params, self.lr_schedule,
                                              self.config.gradient_clipping,
                                              frozen_mask=self._frozen_mask)
            norm_type = opt_type.lower().replace("_", "")
            if norm_type in ("onebitadam", "onebitlamb", "zerooneadam"):
                for ax in ("model", "seq", "pipe", "expert"):
                    if self.mesh.shape.get(ax, 1) > 1:
                        raise ValueError(
                            f"1-bit optimizers need a pure-DP mesh ({ax} "
                            f"axis has size {self.mesh.shape[ax]})")
            if norm_type == "zerooneadam":
                # 0/1 Adam (runtime/comm/zero_one.py): variance freeze +
                # local-step intervals — a DISTINCT algorithm from the
                # EF-sign 1-bit path (reference fp16/onebit/zoadam.py)
                if self._frozen_mask is not None:
                    raise NotImplementedError(
                        "model.frozen_spec does not compose with ZeroOneAdam "
                        "(it owns its whole optimizer state outside the "
                        "masked optax chain)")
                if self.zero_stage != 0:
                    raise ValueError(
                        "ZeroOneAdam composes with ZeRO stage 0 only (the "
                        "in-region update reads replicated masters; the "
                        "reference tutorial lists the same ZeRO "
                        "incompatibility)")
                if self.fp16_enabled:
                    raise NotImplementedError(
                        "ZeroOneAdam + fp16 loss scaling: the local-step "
                        "phase has no per-worker overflow protocol")
                if self.config.gradient_clipping:
                    raise NotImplementedError(
                        "ZeroOneAdam supports max_grad_norm=0 only "
                        "(reference zoadam.py has the same default; clipping "
                        "a locally-drifted update is undefined)")
                if self.config.zero_config.offload_optimizer is not None:
                    raise NotImplementedError(
                        "ZeroOneAdam + optimizer offload: unsupported")
                if self._compression_transform is not None:
                    raise NotImplementedError(
                        "ZeroOneAdam + compression_training: the in-region "
                        "update differentiates the raw masters and would "
                        "silently skip the QAT/pruning transform")
                self._compression = {"algo": "zo", "hyper": dict(opt_params)}
            elif norm_type in ("onebitadam", "onebitlamb"):
                # 1-bit path: error-feedback sign-compressed grad exchange
                # after freeze_step warmup (reference fp16/onebit/adam.py:308)
                self._compression = {
                    "algo": "ef",
                    "freeze_step": int(opt_params.get("freeze_step", 100))}
                if self.zero_stage > 1:
                    raise ValueError(
                        "1-bit optimizers compose with ZeRO stage <= 1 only "
                        "(stages 2/3 shard gradients; the reference has the "
                        "same restriction)")

        # -- ZeRO-Infinity parameter offload: params live on NVMe and a
        #    layer-streamed executor (runtime/zero/infinity.py) replaces the
        #    fused jitted step entirely --
        self._param_offload = None
        zpo = self.config.zero_config.offload_param
        po_dev = getattr(zpo.device, "value", zpo.device) if zpo else "none"
        if po_dev == "nvme":
            from .zero.infinity import InfinityParamEngine

            if self._compression_transform is not None:
                raise NotImplementedError(
                    "offload_param + compression_training: unsupported")
            if self._compression is not None:
                raise NotImplementedError(
                    "offload_param + 1-bit optimizers: unsupported")
            if self.config.data_efficiency.data_routing.random_ltd.enabled:
                raise NotImplementedError(
                    "offload_param + random_ltd: the layer-streamed executor "
                    "builds its programs from the base model config")
            if self.config.flops_profiler.enabled:
                raise NotImplementedError(
                    "offload_param + flops_profiler: the profiler hooks the "
                    "fused jitted step, which this path replaces")
            zoo = self.config.zero_config.offload_optimizer
            if zoo is not None and \
                    getattr(zoo.device, "value", zoo.device) != "none":
                raise NotImplementedError(
                    "offload_param already places the optimizer state on its "
                    "own NVMe path (masters + moments live beside the "
                    "params); a simultaneous offload_optimizer config would "
                    "be silently ignored — remove it")
            if self._frozen_mask is not None:
                raise NotImplementedError(
                    "model.frozen_spec does not compose with offload_param "
                    "(the layer-streamed host Adam steps every shard); use "
                    "the LoRA path (runtime/lora.py) to train adapters "
                    "against NVMe-resident frozen weights")
            self._param_offload = InfinityParamEngine(
                self.config, model, self.lr_schedule, mesh)
            self._offload = None
            self.offload_active = False
            self._offload_dev_shardings = None
            self._train_out_shardings = None
            self._compute_cast = None
            self.plan = None
            self.state = None
            self.param_count = self._param_offload.param_count
        else:
            self._init_device_state(init_fn, params, param_specs, mesh, hpz)

        # -- bookkeeping --
        self.global_steps = 0
        self.skipped_steps = 0
        self.micro_steps = 0
        self.timers = SynchronizedWallClockTimer()
        self.tput_timer = ThroughputTimer(batch_size=self.train_batch_size,
                                          steps_per_output=self.config.steps_per_print)
        self._compiled_train_step = None
        self._compiled_grad_step = None
        self._compiled_eval_step = None
        self._compiled_micro_grad = None
        self._compiled_apply_step = None
        self._accum_grads = None
        self._accum_count = 0
        self._window_losses = []
        self._last_grad_norm: Optional[float] = None
        self._data_iterator = None
        # -- optional training features (runtime/features.py owns config
        #    resolution + validation for each; BEFORE the dataloader so an
        #    in-loop curriculum can drive the sampler) --
        wire_progressive_layer_drop(self)
        wire_curriculum(self)
        wire_random_ltd(self, self.model)
        wire_flops_profiler(self)
        # per-program device-time accounting (docs/OBSERVABILITY.md
        # "Per-program accounting"): the fused train step registers its
        # lowered FLOPs on first run; every step counts an invocation and
        # the wall clock between step completions feeds the live
        # train/tflops_est + train/mfu_est gauges (steady-state async
        # dispatch means inter-step wall ~= device step time)
        from ..observability.program_stats import ProgramCatalog

        self.program_catalog = ProgramCatalog()
        self._step_flops: Optional[float] = None
        self._step_wall_t: Optional[float] = None
        self._step_wall_s: Optional[float] = None   # EMA of inter-step wall
        self.training_dataloader = self._build_dataloader(training_data)
        self.monitor = self._build_monitor()
        # opt-in /metrics scrape endpoint (DS_TPU_METRICS_PORT): no-op
        # without the env var, so engine init never binds a socket unasked
        from ..observability.export import maybe_start_metrics_server

        maybe_start_metrics_server(self.monitor)
        # windowed device-trace capture, env-armed (DS_TPU_DEVICE_TRACE):
        # train_batch counts the window down one unit per step
        from ..observability.device_profiler import maybe_capture_from_env

        maybe_capture_from_env()
        self._watchdog = self._build_watchdog()
        log_dist(
            f"engine ready: params={self.param_count:,} zero_stage={self.zero_stage} "
            f"dtype={self.compute_dtype.__name__} mesh={dict(mesh.shape)} "
            f"batch={self.train_batch_size} (micro={self.micro_batch_size} gas={self.gas} "
            f"dp={self.dp_world})", ranks=[0])

    def _init_device_state(self, init_fn, params, param_specs, mesh, hpz):
        """Build the device-resident TrainState: sharded init, ZeRO planning,
        optimizer state, loss scaler, offload placement."""
        # -- sharded initialization (the zero.Init analogue: params are BORN
        #    sharded; nothing ever materializes replicated, reference
        #    partition_parameters.py:681) --
        seed_rng = jax.random.PRNGKey(self.config.seed)
        if params is not None:
            shapes = jax.eval_shape(lambda: params)
            init_thunk = lambda rng: params  # noqa: E731
        else:
            shapes = jax.eval_shape(init_fn, seed_rng)
            init_thunk = init_fn
        if self._frozen_mask is not None:
            mask_td = jax.tree_util.tree_structure(self._frozen_mask)
            shapes_td = jax.tree_util.tree_structure(shapes)
            if mask_td != shapes_td:
                raise ValueError(
                    "model.frozen_spec() structure does not match the param "
                    f"tree: mask {mask_td} vs params {shapes_td}")
            n_frozen = sum(
                int(np.prod(s.shape)) for s, m in zip(
                    jax.tree_util.tree_leaves(shapes),
                    jax.tree_util.tree_leaves(self._frozen_mask)) if m)
            log_dist(f"frozen parameters: {n_frozen:,} excluded from "
                     "updates/grad-norm (model.frozen_spec)", ranks=[0])
        hier = self.config.zero_config.zero_hierarchical_dp_size
        self.plan: ZeroShardingPlan = plan_sharding(
            shapes, self.zero_stage, mesh, tp_specs=param_specs,
            persistence_threshold=self.config.zero_config.stage3_param_persistence_threshold,
            # hpZ: masters/opt/grads on the full group, compute view
            # inner-only — with 'data_outer' MINOR in the dim tuple, so that
            # stripping the outer axis yields the CONTIGUOUS inner shard
            # (outer-major would make the secondary copy a permutation of
            # the true rows; caught by the composition loss-parity test).
            # hierarchical qgZ: EVERYTHING on the full group, outer-MAJOR —
            # the 2-hop reduce lands outer-major by construction.
            zero_axes=(ZERO_AXES + ("data_outer",) if hpz > 1
                       else BATCH_AXES if hier > 1 else ZERO_AXES),
            param_zero_axes=(ZERO_AXES if hpz > 1 else None))
        self._param_shardings = named_shardings(mesh, self.plan.param_specs)
        self._master_shardings = named_shardings(mesh, self.plan.master_specs)
        self._grad_shardings = named_shardings(mesh, self.plan.grad_specs)

        # -- ZeRO++ (qwZ/qgZ): make stage-3's param-gather / grad-reduce
        #    collectives explicit with an int8 wire format --
        zcfg = self.config.zero_config
        if zcfg.zero_quantized_weights or zcfg.zero_quantized_gradients:
            if not self.use_master_weights:
                raise ValueError("ZeRO++ quantized collectives require bf16 or "
                                 "fp16 compute (fp32 has no cast step to hook)")
            from .zero.zeropp import make_zeropp_cast

            # qgZ runs int8 (not the reference's int4) by default: one ICI hop
            # on TPU vs the reference's NVLink+IB two-hop makes bandwidth
            # cheaper and convergence the scarcer resource; int4 remains
            # available in ops/quantizer for the hierarchical path.
            #
            # Region-axes selection = the ZeRO++ composition switch (see
            # make_zeropp_cast): hpZ covers only the outer hop; the
            # hierarchical knob covers both hops with a 2-hop reduce.
            if hpz > 1:
                region_axes, hier_outer = ("data_outer",), None
            elif hier > 1:
                region_axes, hier_outer = BATCH_AXES, "data_outer"
            else:
                region_axes, hier_outer = ZERO_AXES, None
            self._compute_cast = make_zeropp_cast(
                self.plan.master_specs, self.plan.param_specs, mesh,
                self.compute_dtype, region_axes,
                weight_bits=8 if zcfg.zero_quantized_weights else None,
                grad_bits=8 if zcfg.zero_quantized_gradients else None,
                hierarchical_outer=hier_outer)
            if self._compute_cast.num_quantized_leaves == 0:
                logger.warning(
                    "ZeRO++ enabled but no parameter is ZeRO-sharded (all "
                    "below stage3_param_persistence_threshold or indivisible) "
                    "— quantized collectives will not engage")
        else:
            self._compute_cast = None

        with jax.transfer_guard("allow"):
            master = jax.jit(
                lambda rng: _cast_tree(init_thunk(rng), jnp.float32),
                out_shardings=self._master_shardings)(seed_rng)
        if self.use_master_weights:
            params0 = jax.jit(lambda m: _cast_tree(m, self.compute_dtype),
                              out_shardings=self._param_shardings)(master)
        else:
            master_spec_tree = self._master_shardings
            params0 = jax.jit(lambda m: m, out_shardings=master_spec_tree)(master)
            # fp32 mode: params ARE the masters; keep one copy
            master = None

        # -- ZeRO-Offload / ZeRO-Infinity: where the fp32 optimizer state
        #    rests (runtime/offload.py owns the decision + mechanisms).
        self._offload = None
        offload_mode = resolve_offload_mode(
            self.config, mesh, use_master_weights=master is not None,
            fp16_enabled=self.fp16_enabled,
            has_compression=self._compression_transform is not None)
        if offload_mode in ("host_step", "nvme"):
            if self._frozen_mask is not None:
                raise NotImplementedError(
                    "model.frozen_spec does not compose with optimizer "
                    "offload yet (the host-stepped executor updates every "
                    "shard); drop the offload config or use the LoRA path "
                    "(runtime/lora.py) which keeps frozen weights out of "
                    "the optimizer entirely")
            self._offload = HostSteppedOffload(
                self.config, master, self._param_shardings,
                storage=("cpu" if offload_mode == "host_step" else "nvme"),
                fp16_enabled=self.fp16_enabled,
                has_compression=self._compression_transform is not None)
            master = None
            opt_state = ()
        elif self._compression is not None and \
                self._compression.get("algo") == "zo":
            # 0/1 Adam owns its whole optimizer state (ZeroOneState rides
            # the comm_error slot below); no optax state
            opt_state = ()
        else:
            opt_state = jax.jit(self.optimizer.init)(
                master if master is not None else params0)

        if self.fp16_enabled:
            f16 = self.config.fp16
            scaler = (static_loss_scale_state(f16.loss_scale) if f16.loss_scale > 0 else
                      dynamic_loss_scale_state(f16.initial_scale_power, f16.loss_scale_window,
                                               f16.min_loss_scale, f16.hysteresis))
        else:
            scaler = no_loss_scale_state()

        # Scalars/state live replicated on the WHOLE mesh so every leaf of the
        # TrainState shares one device set (jit rejects mixed device sets, and
        # checkpoint restore preserves placements).
        replicated = NamedSharding(mesh, P())
        scaler = jax.device_put(scaler, replicated)
        seed_rng = jax.device_put(seed_rng, replicated)
        step0 = jax.device_put(jnp.int32(0), replicated)
        opt_state = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, replicated)
            if hasattr(x, "shape") and not hasattr(x.sharding, "spec") else x, opt_state)

        # -- ZeRO-Offload streamed placement: optimizer state (and fp32
        #    masters) rest in pinned host memory; XLA streams the dp-shards
        #    over PCIe into the jitted step and lands them back on the host
        #    (out_shardings below), so HBM never holds optimizer state at
        #    rest (reference stage_1_and_2.py:1041-1124 CPU offload).
        self.offload_active = False
        self._offload_dev_shardings = None
        if offload_mode == "streamed":
            opt_state, master, self._offload_dev_shardings, \
                self.offload_active = apply_streamed_placement(opt_state, master)
        comm_error = None
        if self._compression is not None:
            template = master if self.use_master_weights else params0
            if self._compression.get("algo") == "zo":
                from .comm.zero_one import init_zero_one_state

                comm_error = init_zero_one_state(template, self.mesh)
            else:
                from .comm.compressed import init_error_tree

                comm_error = jax.device_put(
                    init_error_tree(template, self.mesh),
                    NamedSharding(self.mesh, P(BATCH_AXES)))
        self.state = TrainState(step=step0, params=params0, master_params=master,
                                opt_state=opt_state, scaler=scaler, rng=seed_rng,
                                comm_error=comm_error)
        # Out-shardings pin every state leaf back to where it started (host
        # for offloaded leaves); metrics come back replicated on device.
        # The matching device-kind shardings stream the offloaded leaves INTO
        # the step (XLA refuses compute on host-placed operands).
        self._train_out_shardings = (
            (jax.tree_util.tree_map(lambda x: x.sharding, self.state), replicated)
            if self.offload_active else None)
        self.param_count = sum(
            int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(shapes))

    # ------------------------------------------------------------------
    def _build_dataloader(self, training_data):
        if training_data is None:
            if self._curriculum_metric_path is not None:
                raise ValueError(
                    "a metric-driven curriculum samples THROUGH the engine "
                    "dataloader — pass training_data to initialize()")
            return None
        from .dataloader import DeepSpeedDataLoader

        sampler = None
        ds_cfg = self.config.data_efficiency.data_sampling
        if ds_cfg.enabled and ds_cfg.curriculum_learning.enabled:
            if self._curriculum_metric_path is not None:
                raise ValueError(
                    "both the legacy curriculum_learning.metric_values_path "
                    "sampler and data_efficiency.data_sampling."
                    "curriculum_learning are configured — they would fight "
                    "over the batch stream; enable exactly one")
            # multi-metric cluster-bucketed curriculum (reference
            # DeepSpeedDataSampler); per-metric values come from
            # DataAnalyzer runs, schedulers from per-metric configs
            from .data_pipeline.curriculum_scheduler import \
                CurriculumScheduler
            from .data_pipeline.data_sampler import \
                MultiMetricCurriculumSampler

            metrics = {}
            for name, mc in ds_cfg.curriculum_learning.curriculum_metrics.items():
                values = np.load(mc.metric_values_path)
                if len(values) != len(training_data):
                    raise ValueError(
                        f"curriculum metric {name!r} has {len(values)} "
                        f"values for a dataset of {len(training_data)} "
                        "samples")
                metrics[name] = {
                    "values": values,
                    "difficulty_type": mc.difficulty_type,
                    "clustering_type": mc.clustering_type,
                    "scheduler": CurriculumScheduler({
                        "curriculum_type": name,
                        "min_difficulty": mc.min_difficulty,
                        "max_difficulty": mc.max_difficulty,
                        "schedule_type": mc.schedule_type,
                        "schedule_config": mc.schedule_config}),
                }
            sampler = MultiMetricCurriculumSampler(
                metrics, batch_size=self.micro_batch_size * self.dp_world,
                seed=self.config.seed)
        elif self._curriculum_metric_path is not None:
            # metric-driven curriculum: difficulty values from a DataAnalyzer
            # run steer the in-loop sampler (reference DeepSpeedDataSampler,
            # data_sampler.py:36)
            from .data_pipeline.data_sampler import CurriculumBatchSampler

            values = np.load(self._curriculum_metric_path)
            if len(values) != len(training_data):
                raise ValueError(
                    f"curriculum metric file has {len(values)} values for a "
                    f"dataset of {len(training_data)} samples")
            sampler = CurriculumBatchSampler(
                values, batch_size=self.micro_batch_size * self.dp_world,
                curriculum=self.curriculum_scheduler, seed=self.config.seed)

        return DeepSpeedDataLoader(training_data,
                                   batch_size=self.micro_batch_size * self.dp_world,
                                   mesh=self.mesh, data_sampler=sampler)

    def _build_monitor(self):
        if not self.config.monitor_config.enabled:
            return None
        from ..monitor.monitor import MonitorMaster

        return MonitorMaster(self.config.monitor_config)

    def _build_watchdog(self):
        rc = getattr(self.config, "resilience", None)
        if rc is None or not rc.watchdog.enabled:
            return None
        from ..resilience.watchdog import HangWatchdog

        return HangWatchdog(timeout_s=rc.watchdog.timeout_s,
                            exit_code=rc.watchdog.exit_code,
                            monitor=self.monitor)

    # ------------------------------------------------------------------
    # The jitted step
    # ------------------------------------------------------------------
    def _make_scaled_grad(self):
        """grad_fn(tree, scaler, batch, sub) -> (scaled grads, loss) —
        shared by the fused train_step scan and the per-microbatch loop.

        ``tree`` is what :meth:`_compute_tree` returned: normally the
        compute-precision (bf16) params — differentiating w.r.t. the bf16
        tree instead of fp32 masters keeps every backward matmul reading
        bf16 weights (measured ~20% step time on v5e: the in-graph
        fp32->bf16 cast makes XLA feed fp32 weight bytes to the bwd dots).
        The cotangents are bf16 either way, so the gradients are bit-
        identical; accumulation still happens in fp32.  With ZeRO++ the
        quantized-gather cast must stay inside the grad (its custom VJP is
        the gradient reduce-scatter), so ``tree`` is the fp32 masters."""
        loss_fn = self.loss_fn
        prescale = self.config.prescale_gradients
        predivide = self.config.gradient_predivide_factor
        cast_inside = self._compute_cast if self.use_master_weights else None
        frozen_mask = self._frozen_mask

        def grad_of_batch(tree, scaler, one_batch, sub):
            def scaled(t):
                p = cast_inside(t) if cast_inside is not None else t
                if frozen_mask is not None:
                    # stop_gradient lets XLA dead-code-eliminate the whole
                    # backward for frozen leaves (the reference's
                    # requires_grad=False computes no grad at all); the
                    # update-side masking in apply_update stays as the
                    # semantic contract for paths that skip this closure
                    p = jax.tree_util.tree_map(
                        lambda m, x: jax.lax.stop_gradient(x) if m else x,
                        frozen_mask, p)
                out = loss_fn(p, one_batch, sub)
                loss, _ = out if isinstance(out, tuple) else (out, {})
                return scale_loss(loss, scaler), loss

            grads, loss = jax.grad(scaled, has_aux=True)(tree)
            if prescale:
                grads = jax.tree_util.tree_map(lambda g: g / predivide, grads)
            return grads, loss

        return grad_of_batch

    def _make_compute_tree(self):
        """tree_fn(masters, step=None) -> the tree grad_of_batch
        differentiates: the bf16/fp16 compute params (cast hoisted out of the
        microbatch scan), or the masters themselves under ZeRO++ / fp32
        compute.  When compression_training is configured the QAT/pruning
        transform applies here, on the compute-precision view, gated by the
        traced step (reference init_compression wraps the matched modules;
        see deepspeed_tpu/compression/compress.py)."""
        use_master = self.use_master_weights
        compute_dtype = self.compute_dtype
        param_shardings = self._param_shardings
        compress = getattr(self, "_compression_transform", None)
        if not use_master or self._compute_cast is not None:
            if compress is not None:
                raise NotImplementedError(
                    "compression_training with fp32 compute / ZeRO++ "
                    "quantized gather is not supported yet")
            return lambda masters, step=None: masters

        def tree_fn(masters, step=None):
            work = constrain(_cast_tree(masters, compute_dtype), param_shardings)
            if compress is not None and step is not None:
                work = constrain(compress(work, step), param_shardings)
            return work

        return tree_fn

    def _make_update_body(self):
        """update(state, masters, opt_in, grads, eff_gas) -> (new_state,
        metrics): unscale, overflow-skip, optimizer update, scaler update,
        master->compute cast.  The single source of truth for step semantics
        (used by both the fused step and the fwd/bwd/step loop)."""
        use_master = self.use_master_weights
        compute_dtype = self.compute_dtype
        optimizer = self.optimizer
        param_shardings = self._param_shardings
        fp16 = self.fp16_enabled
        prescale = self.config.prescale_gradients
        predivide = self.config.gradient_predivide_factor

        frozen_mask = self._frozen_mask

        def apply_update(state: TrainState, masters, opt_in, grads, eff_gas):
            inv = 1.0 / (state.scaler.loss_scale * eff_gas)
            if prescale:
                inv = inv * predivide
            grads = jax.tree_util.tree_map(lambda g: g * inv, grads)
            if frozen_mask is not None:
                # frozen params produce no gradient in the reference
                # (requires_grad=False): zero theirs BEFORE the overflow
                # check, grad norm and clipping so none of the three sees
                # them (a frozen layer's inf would otherwise skip the step)
                grads = jax.tree_util.tree_map(
                    lambda m, g: jnp.zeros_like(g) if m else g,
                    frozen_mask, grads)
            finite = grads_finite(grads) if fp16 else jnp.bool_(True)
            grad_norm = optax.global_norm(grads)
            updates, new_opt = optimizer.update(grads, opt_in, masters)
            new_masters = optax.apply_updates(masters, updates)
            # overflow => skip (reference DynamicLossScaler step-skip semantics)
            new_masters = _tree_select(finite, new_masters, masters)
            new_opt = _tree_select(finite, new_opt, opt_in)
            new_scaler = update_scale(state.scaler, finite)
            if use_master:
                new_params = constrain(_cast_tree(new_masters, compute_dtype),
                                       param_shardings)
                new_master_out = new_masters
            else:
                new_params = new_masters
                new_master_out = None
            new_state = TrainState(step=state.step + 1, params=new_params,
                                   master_params=new_master_out, opt_state=new_opt,
                                   scaler=new_scaler, rng=state.rng)
            metrics = {"grad_norm": grad_norm,
                       "loss_scale": state.scaler.loss_scale,
                       "step_applied": finite}
            return new_state, metrics

        return apply_update

    def _stream_in(self, state: TrainState):
        """(masters, opt_in) for the step, moved device-side when offloaded."""
        masters = state.master_params if self.use_master_weights else state.params
        opt_in = state.opt_state
        if self._offload_dev_shardings is not None:
            m_sh, o_sh = self._offload_dev_shardings
            if self.use_master_weights and m_sh is not None:
                masters = jax.device_put(masters, m_sh)
            opt_in = jax.device_put(opt_in, o_sh)
        return masters, opt_in

    def _swap_ltd_variant(self, keep: int) -> None:
        """Re-point loss_fn at a model variant with the new static keep-count
        and swap in (or rebuild) the matching compiled step."""
        self._ltd_keep = keep
        active = keep < self.model.config.max_seq_len
        variant = type(self.model)(
            self.model.config, attn_impl=getattr(self.model, "attn_impl", "auto"),
            random_ltd=active, random_ltd_keep=int(keep) if active else 0)
        self.loss_fn = variant.loss_fn
        self._compiled_train_step = self._ltd_cache.get(keep)
        # every compiled program that closed over the old loss_fn is stale
        self._compiled_grad_step = None
        self._compiled_micro_grad = None
        log_dist(f"random-LTD: keep={keep} tokens/layer "
                 f"({'active' if active else 'full sequence'})", ranks=[0])

    # -- host-stepped offload surface (runtime/offload.py owns the state;
    #    these properties keep the engine's historical attribute names) --
    @property
    def _nvme_swapper(self):
        return self._offload.optimizer if self._offload is not None else None

    @property
    def _nvme_names(self):
        return self._offload.names if self._offload is not None else None

    def _make_grad_only_step(self):
        gas = self.gas
        accumulate = make_grad_accumulator(self._make_scaled_grad(), gas,
                                           self.config.data_types.jnp_dtype())
        prescale = self.config.prescale_gradients
        predivide = self.config.gradient_predivide_factor
        clip = self.config.gradient_clipping

        def grad_step(state: TrainState, batch):
            work = state.params  # bf16 — masters live on NVMe
            grads, losses, new_rng = accumulate(work, state.scaler, batch,
                                                state.rng)
            # mirror apply_update's normalization: gas mean, predivide
            # compensation (grad_of_batch pre-divided), then global clipping —
            # the host Adam kernel must see exactly what the optax chain would
            scale = (predivide if prescale else 1.0) / gas
            grads = jax.tree_util.tree_map(lambda g: g * scale, grads)
            gnorm = optax.global_norm(grads)
            if clip and clip > 0:
                factor = jnp.minimum(1.0, clip / (gnorm + 1e-6))
                grads = jax.tree_util.tree_map(lambda g: g * factor, grads)
            return grads, jnp.mean(losses), gnorm, new_rng

        return _jit_step(grad_step)

    def _train_batch_nvme(self, global_batch):
        """device grads -> host NVMe Adam -> bf16 params back to device."""
        if self._compiled_grad_step is None:
            self._compiled_grad_step = self._make_grad_only_step()
        self.tput_timer.start()
        grads, loss, grad_norm, new_rng = self._compiled_grad_step(
            self.state, global_batch)
        lr = float(self.lr_schedule(self.global_steps)) \
            if callable(self.lr_schedule) else float(self.lr_schedule)
        new_params = self._offload.host_step(grads, lr)
        self.state = dataclasses.replace(
            self.state, params=new_params, step=self.state.step + 1,
            rng=new_rng)
        self.global_steps += 1
        self.micro_steps += self.gas
        self._last_grad_norm = float(grad_norm)
        loss_val = loss
        self.tput_timer.stop(sync_tree=loss_val)
        metrics = {"loss": loss_val, "grad_norm": grad_norm,
                   "loss_scale": jnp.float32(1.0),
                   "step_applied": jnp.bool_(True)}
        self._emit_monitor_events(metrics)
        if self.global_steps % self.config.steps_per_print == 0:
            self._report_progress(metrics)
        return loss_val

    def _train_batch_param_offload(self, global_batch):
        """ZeRO-Infinity param offload: the layer-streamed executor owns the
        whole step (fwd/bwd layer loop + host Adam)."""
        self.tput_timer.start()
        loss, metrics = self._param_offload.train_batch(global_batch)
        self.global_steps += 1
        self.micro_steps += self.gas
        self._last_grad_norm = float(metrics["grad_norm"])
        self.tput_timer.stop(sync_tree=loss)
        self._emit_monitor_events(metrics)
        if self.global_steps % self.config.steps_per_print == 0:
            self._report_progress(metrics)
        return loss

    def _make_train_step(self):
        gas = self.gas
        grad_specs = self._grad_shardings
        pipeline = self.mesh.shape.get("pipe", 1) > 1
        grad_of_batch = self._make_scaled_grad()
        compute_tree = self._make_compute_tree()
        apply_update = self._make_update_body()
        stream_in = self._stream_in

        compression = self._compression
        if compression is not None and compression.get("algo") == "zo":
            # 0/1 Adam: the region owns grads AND the update (variance
            # freeze + local steps need per-worker momentum/delta state)
            from .comm.zero_one import make_zero_one_step

            use_master = self.use_master_weights
            compute_dtype = self.compute_dtype
            param_shardings = self._param_shardings
            lr_schedule = self.lr_schedule
            template = (self.state.master_params if use_master
                        else self.state.params)
            zo_fn = make_zero_one_step(
                make_grad_accumulator(grad_of_batch, gas,
                                      self.config.data_types.jnp_dtype()),
                self.mesh, gas, compute_dtype, template,
                compression["hyper"])

            def train_step(state: TrainState, batch):
                masters = (state.master_params if use_master
                           else state.params)
                new_rng, region_rng = jax.random.split(state.rng)
                lr = jnp.float32(lr_schedule(state.step))
                new_masters, new_zo, loss, gnorm = zo_fn(
                    masters, state.scaler, batch, region_rng,
                    state.comm_error, state.step, lr)
                if use_master:
                    params = constrain(_cast_tree(new_masters, compute_dtype),
                                       param_shardings)
                    new_state = TrainState(
                        step=state.step + 1, params=params,
                        master_params=new_masters, opt_state=(),
                        scaler=state.scaler, rng=new_rng, comm_error=new_zo)
                else:
                    new_state = TrainState(
                        step=state.step + 1, params=new_masters,
                        master_params=None, opt_state=(),
                        scaler=state.scaler, rng=new_rng, comm_error=new_zo)
                metrics = {"loss": loss, "grad_norm": gnorm,
                           "loss_scale": state.scaler.loss_scale,
                           "step_applied": jnp.bool_(True)}
                return new_state, metrics

            return _jit_step(train_step, donate_argnums=(0,))

        if compression is not None:
            from .comm.compressed import make_compressed_grad_fn

            template = (self.state.master_params if self.use_master_weights
                        else self.state.params)
            comp_grad = make_compressed_grad_fn(
                make_grad_accumulator(grad_of_batch, gas,
                                      self.config.data_types.jnp_dtype()),
                self.mesh, gas,
                compression["freeze_step"], template)

            def train_step(state: TrainState, batch):
                masters, opt_in = stream_in(state)
                work = compute_tree(masters, state.step)
                new_rng, region_rng = jax.random.split(state.rng)
                grads, losses, new_error = comp_grad(
                    work, state.scaler, batch, region_rng, state.comm_error,
                    state.step)
                new_state, metrics = apply_update(state, masters, opt_in,
                                                  grads, gas)
                # overflow => the step was skipped; the error buffer must not
                # absorb the inf/NaN residual or EF poisons every later step
                new_error = _tree_select(metrics["step_applied"], new_error,
                                         state.comm_error)
                new_state = dataclasses.replace(new_state, rng=new_rng,
                                                comm_error=new_error)
                metrics["loss"] = jnp.mean(losses)
                return new_state, metrics

            if self._train_out_shardings is not None:
                return _jit_step(train_step, donate_argnums=(0,),
                                 out_shardings=self._train_out_shardings)
            return _jit_step(train_step, donate_argnums=(0,))

        accumulate = make_grad_accumulator(grad_of_batch, gas,
                                           self.config.data_types.jnp_dtype())

        # 1F1B schedule (model config pipeline_schedule="1f1b"): the manual
        # interleaved executor produces the gradients itself — AD cannot
        # express fwd/bwd interleaving (runtime/pipe/spmd.py:pipeline_1f1b)
        manual_pipe = None
        if pipeline and getattr(getattr(self.model, "config", None),
                                "pipeline_schedule", "gpipe") == "1f1b":
            if self._compression_transform is not None:
                raise NotImplementedError(
                    "pipeline_schedule='1f1b' + compression_training: the "
                    "manual executor differentiates the raw params")
            if self.config.prescale_gradients:
                raise NotImplementedError(
                    "pipeline_schedule='1f1b' + prescale_gradients: "
                    "unsupported")
            if self.progressive_layer_drop is not None:
                raise NotImplementedError(
                    "pipeline_schedule='1f1b' + progressive_layer_drop: the "
                    "manual executor would silently drop pld_theta")
            if self._random_ltd is not None:
                raise NotImplementedError(
                    "pipeline_schedule='1f1b' + random_ltd: unsupported")
            manual_pipe = self.model.pipeline_grad_fn()

        # landing dtype for the per-step gradients (config
        # data_types.grad_accum_dtype, reference runtime/config.py:867):
        # fp32 by default; bf16 halves the live grad buffer also in the
        # gas=1 / pipeline fast paths, not just the accumulation scan
        accum_dtype = self.config.data_types.jnp_dtype() or jnp.float32

        def train_step(state: TrainState, batch):
            masters, opt_in = stream_in(state)
            work = compute_tree(masters, state.step)  # bf16 cast hoisted out of the scan

            if pipeline:
                # pipeline engines consume the whole gas window in ONE call:
                # the model splits it into microbatches internally and the
                # SPMD pipeline overlaps them across stages (reference
                # PipelineEngine.train_batch, pipe/engine.py:286)
                flat = jax.tree_util.tree_map(
                    lambda x: x.reshape((-1,) + x.shape[2:]), batch)
                new_rng, sub = jax.random.split(state.rng)
                if manual_pipe is not None:
                    grads, losses = manual_pipe(work, state.scaler, flat, sub)
                else:
                    grads, losses = grad_of_batch(work, state.scaler, flat, sub)
                grads = jax.tree_util.tree_map(
                    lambda g: g.astype(accum_dtype), grads)
                eff_gas = 1  # loss already averages over the gas window
            elif gas == 1:
                # no accumulation window: skip the scan and the fp32 zero
                # buffer init + add (saves ~12 bytes/param of HBM traffic)
                new_rng, sub = jax.random.split(state.rng)
                grads, losses = grad_of_batch(
                    work, state.scaler,
                    jax.tree_util.tree_map(lambda x: x[0], batch), sub)
                grads = jax.tree_util.tree_map(
                    lambda g: g.astype(accum_dtype), grads)
                eff_gas = 1
            else:
                grads, losses, new_rng = accumulate(work, state.scaler, batch,
                                                    state.rng)
                eff_gas = gas
            # ZeRO-2/3: land the accumulated grads sharded — XLA lowers the DP
            # reduction into reduce-scatter against this constraint
            grads = constrain(grads, grad_specs)
            new_state, metrics = apply_update(state, masters, opt_in, grads, eff_gas)
            new_state = dataclasses.replace(new_state, rng=new_rng)
            metrics["loss"] = jnp.mean(losses)
            return new_state, metrics

        if self._train_out_shardings is not None:
            return _jit_step(train_step, donate_argnums=(0,),
                             out_shardings=self._train_out_shardings)
        return _jit_step(train_step, donate_argnums=(0,))

    def _make_eval_step(self):
        eval_fn = self._eval_fn
        compress = self._compression_transform

        def eval_step(state: TrainState, batch):
            p = state.params
            if compress is not None:
                # evaluate the same quantized/pruned view training optimizes,
                # or validation metrics overstate the compressed model
                p = compress(p, state.step)
            out = eval_fn(p, batch, state.rng)
            loss, aux = out if isinstance(out, tuple) else (out, {})
            return loss, aux

        return _jit_step(eval_step)

    # ------------------------------------------------------------------
    # Public API (reference engine.forward/backward/step + train_batch)
    # ------------------------------------------------------------------
    def _collect_global_batch(self, batch_or_iter):
        """Accept: a full global batch [train_batch, ...]; a [gas, mb, ...]
        pre-stacked batch; or an iterator yielding gas micro-batches."""
        if hasattr(batch_or_iter, "__next__"):
            micro = [next(batch_or_iter) for _ in range(self.gas)]
            batch = jax.tree_util.tree_map(lambda *xs: np.stack(xs), *micro)
        else:
            batch = batch_or_iter
            lead = jax.tree_util.tree_leaves(batch)[0].shape[0]
            if lead == self.gas * self.micro_batch_size * self.dp_world:
                batch = jax.tree_util.tree_map(
                    lambda x: x.reshape((self.gas, -1) + x.shape[1:]), batch)
            elif lead != self.gas:
                raise ValueError(
                    f"batch leading dim {lead} is neither train_batch_size "
                    f"({self.train_batch_size}) nor gas ({self.gas})")
        return self._shard_batch(batch)

    def _shard_batch(self, batch):
        sharding = NamedSharding(self.mesh, P(None, BATCH_AXES))

        def put(x):
            x = np.asarray(x)
            if jax.process_count() > 1:
                # Every host materializes the same GLOBAL batch (the loaders
                # are identically seeded), so each host serves its addressable
                # shards by global index — not make_array_from_process_local_data,
                # which would treat the global batch as a per-host shard.
                return jax.make_array_from_callback(x.shape, sharding,
                                                    lambda idx: x[idx])
            return jax.device_put(x, sharding)

        return jax.tree_util.tree_map(put, batch)

    def train_batch(self, data_iter=None, batch=None) -> jnp.ndarray:
        """One full optimizer step over gas micro-batches (reference
        PipelineEngine.train_batch semantics for the non-pipeline engine).

        Resilience hooks: the ``train.step`` fault-injection site fires on
        entry, and the hang watchdog (config ``resilience.watchdog``) is
        armed for the step's duration — a step wedged inside a collective
        becomes a stack report + supervisor-recyclable exit instead of a
        silent forever-hang.

        Observability: the whole call runs under a ``train.batch`` span
        (with ``train.data``/``train.step`` children in the fused path) on
        the process-global tracer — no-op when tracing is disabled
        (docs/OBSERVABILITY.md)."""
        from ..observability.device_profiler import device_trace_unit
        from ..resilience.fault_injection import SITE_TRAIN_STEP, maybe_fire

        with trace_span("train.batch", step=self.global_steps + 1):
            if self._watchdog is None:
                maybe_fire(SITE_TRAIN_STEP, step=self.global_steps + 1)
                loss = self._train_batch_impl(data_iter=data_iter,
                                              batch=batch)
            else:
                with self._watchdog.armed(
                        f"train_batch step {self.global_steps + 1}"):
                    maybe_fire(SITE_TRAIN_STEP, step=self.global_steps + 1)
                    loss = self._train_batch_impl(data_iter=data_iter,
                                                  batch=batch)
        # windowed device capture: one train step = one capture unit
        # (a global None check when no capture is armed)
        device_trace_unit()
        return loss

    def _train_batch_impl(self, data_iter=None, batch=None) -> jnp.ndarray:
        if batch is None:
            if data_iter is None:
                if self.training_dataloader is None:
                    raise ValueError("train_batch needs a batch, an iterator, or "
                                     "training_data at initialize()")
                if self._data_iterator is None:
                    from .dataloader import RepeatingLoader

                    self._data_iterator = iter(RepeatingLoader(self.training_dataloader))
                data_iter = self._data_iterator
            batch = data_iter
        with trace_span("train.data"):
            global_batch = self._collect_global_batch(batch)
        global_batch = self._inject_pld_theta(global_batch, shape=(self.gas,))
        if self._curriculum_seqlen:
            # legacy seqlen curriculum: truncate the window's sequence dim;
            # jit caches one program per distinct difficulty automatically
            # (metric-driven curricula steer the SAMPLER instead)
            diff = self.curriculum_scheduler.update_difficulty(
                self.global_steps + 1)
            ref = (global_batch["input_ids"] if isinstance(global_batch, dict)
                   and "input_ids" in global_batch
                   else jax.tree_util.tree_leaves(global_batch)[0])
            S = ref.shape[-1]
            # truncate only leaves whose trailing axis IS the sequence axis
            global_batch = jax.tree_util.tree_map(
                lambda x: x[..., :diff]
                if x.ndim >= 3 and x.shape[-1] == S else x, global_batch)
        if self._random_ltd is not None:
            keep = self._random_ltd.update_seq(self.global_steps)
            if keep != self._ltd_keep:
                self._swap_ltd_variant(keep)
        if self._param_offload is not None:
            return self._train_batch_param_offload(global_batch)
        if self._nvme_swapper is not None:
            return self._train_batch_nvme(global_batch)
        if self._compiled_train_step is None:
            self._compiled_train_step = self._make_train_step()
            if self._random_ltd is not None:
                self._ltd_cache[self._ltd_keep] = self._compiled_train_step
        profiling = (self.flops_profiler is not None
                     and self.global_steps + 1 ==
                     self.config.flops_profiler.profile_step)
        if profiling:
            jax.block_until_ready(self.state.params)
            self.flops_profiler.start_profile()
        self.tput_timer.start()
        # the sync point only runs when tracing is enabled: a traced step
        # measures device time (block_until_ready on the loss), an untraced
        # one keeps its async dispatch pipelining
        with trace_span("train.step", step=self.global_steps + 1) as _sp:
            self.state, metrics = self._compiled_train_step(self.state,
                                                            global_batch)
            _sp.sync(metrics["loss"])
        self._account_step(global_batch)
        if profiling:
            from ..profiling.flops_profiler import cost_analysis_of

            float(metrics["loss"])  # scalar read = real device sync (axon-safe)
            self.flops_profiler.stop_profile()
            self.flops_profiler.attach_cost(cost_analysis_of(
                self._compiled_train_step, self.state, global_batch))
            fp = self.config.flops_profiler
            self.flops_profiler.print_model_profile(
                profile_step=fp.profile_step, module_depth=fp.module_depth,
                top_modules=fp.top_modules, detailed=fp.detailed,
                output_file=fp.output_file)
        self.global_steps += 1
        self.micro_steps += self.gas
        self._last_grad_norm = float(metrics["grad_norm"])
        if self.fp16_enabled and not bool(metrics["step_applied"]):
            self.skipped_steps += 1
            log_dist(f"step {self.global_steps}: grad overflow, step skipped; "
                     f"loss scale -> {float(self.state.scaler.loss_scale)}", ranks=[0])
        self.tput_timer.stop(sync_tree=metrics["loss"])
        self._emit_monitor_events(metrics)
        if self.global_steps % self.config.steps_per_print == 0:
            self._report_progress(metrics)
        return metrics["loss"]

    def eval_batch(self, batch) -> jnp.ndarray:
        if self._param_offload is not None:
            # forward-only layer-streamed loop (same NVMe prefetch pipeline)
            return jnp.float32(self._param_offload.eval_batch(
                self._shard_batch_eval(batch)))
        if self._compiled_eval_step is None:
            self._compiled_eval_step = self._make_eval_step()
        micro = self._shard_batch_eval(batch)
        loss, _ = self._compiled_eval_step(self.state, micro)
        return loss

    def _shard_batch_eval(self, batch):
        sharding = NamedSharding(self.mesh, P(BATCH_AXES))
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(np.asarray(x), sharding), batch)

    # ------------------------------------------------------------------
    # Reference-shaped training loop: loss = engine.forward(batch);
    # engine.backward(loss); engine.step().  (reference engine.py:1708,
    # 1849, 2050.)  forward runs one fused fwd+bwd per micro-batch (same
    # total compute as train_batch — JAX has no standalone autograd tape to
    # replay later), backward banks the gradients, step applies the
    # optimizer update at the gradient-accumulation boundary.
    # ------------------------------------------------------------------
    def _inject_pld_theta(self, batch, shape=()):
        """Add the scheduled PLD theta as a batch leaf (replicated global
        array, so multi-controller jit inputs stay consistent).  ``shape`` is
        ``(gas,)`` for the accumulation window (the scan slices it to the
        scalar the model reads) and ``()`` for a single micro-batch."""
        if self.progressive_layer_drop is None:
            return batch
        if not isinstance(batch, dict):
            raise ValueError(
                "progressive_layer_drop needs dict batches ({'input_ids': ...})"
                " so the theta schedule can ride along as 'pld_theta'")
        theta = self.progressive_layer_drop.update_state(self.global_steps)
        arr = jax.device_put(np.full(shape, theta, np.float32),
                             NamedSharding(self.mesh, P()))
        return {**batch, "pld_theta": arr}

    # ------------------------------------------------------------------
    def compute_eigenvalue(self, batch, rng=None):
        """Largest Hessian eigenvalue + per-leaf Rayleigh quotients at the
        current weights (reference engine eigenvalue integration; the values
        feed MoQ-style quantization scheduling)."""
        from .eigenvalue import Eigenvalue

        ec = self.config.eigenvalue
        est = getattr(self, "_eigenvalue_estimator", None)
        if est is None:
            est = Eigenvalue(verbose=ec.verbose, max_iter=ec.max_iter,
                             tol=ec.tol, stability=ec.stability)
            self._eigenvalue_estimator = est  # caches the jitted HVP too
        # the compute-precision view: the loss mixes params with
        # cfg.dtype activations, so fp32 masters would change dtypes
        # mid-scan — differentiate what training differentiates
        params = self.state.params
        micro = self._shard_batch_eval(batch)
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        return est.compute_eigenvalue(self.loss_fn, params, micro, rng)

    # ------------------------------------------------------------------
    def lower_train_step(self, batch):
        """AOT-lower (no backend compile) the fused train step — the cheap
        host-side half of :meth:`compile_train_step`.  The autotuner's
        parallel compile-pruning lowers under a lock (global mesh state) and
        compiles the lowered programs concurrently (XLA releases the GIL)."""
        global_batch = self._collect_global_batch(batch)
        global_batch = self._inject_pld_theta(global_batch, shape=(self.gas,))
        if self._nvme_swapper is not None or self._param_offload is not None:
            raise NotImplementedError(
                "lower_train_step does not cover the NVMe grad-only / "
                "layer-streamed offload paths")
        if self._compiled_train_step is None:
            self._compiled_train_step = self._make_train_step()
        return self._compiled_train_step.lower(self.state, global_batch)

    def compile_train_step(self, batch):
        """AOT-compile the fused train step for ``batch``'s shapes and return
        the ``jax.stages.Compiled`` — its ``memory_analysis()`` /
        ``cost_analysis()`` let tooling (autotuner, flops profiler) judge a
        config without executing a step.  The jit cache is shared, so the
        subsequent ``train_batch`` call does not recompile."""
        global_batch = self._collect_global_batch(batch)
        global_batch = self._inject_pld_theta(global_batch, shape=(self.gas,))
        if self._nvme_swapper is not None or self._param_offload is not None:
            raise NotImplementedError(
                "compile_train_step does not cover the NVMe grad-only / "
                "layer-streamed offload paths")
        if self._compiled_train_step is None:
            self._compiled_train_step = self._make_train_step()
        return self._compiled_train_step.lower(self.state,
                                               global_batch).compile()

    # ------------------------------------------------------------------
    def _make_micro_grad_step(self):
        grad_specs = self._grad_shardings
        grad_of_batch = self._make_scaled_grad()
        compute_tree = self._make_compute_tree()
        stream_in = self._stream_in

        accum_dtype = self.config.data_types.jnp_dtype()

        def micro_grad(state: TrainState, batch, accum):
            masters, _ = stream_in(state)
            rng, sub = jax.random.split(state.rng)
            grads, loss = grad_of_batch(compute_tree(masters, state.step), state.scaler,
                                        batch, sub)
            accum = jax.tree_util.tree_map(
                lambda a, g: a + g.astype(accum_dtype), accum, grads)
            accum = constrain(accum, grad_specs)
            return loss, accum, rng

        return jax.jit(micro_grad, donate_argnums=(2,))

    def _make_apply_step(self):
        gas = self.gas
        apply_update = self._make_update_body()
        stream_in = self._stream_in

        def apply_step(state: TrainState, grads):
            masters, opt_in = stream_in(state)
            return apply_update(state, masters, opt_in, grads, gas)

        if self._train_out_shardings is not None:
            state_sh, rep = self._train_out_shardings
            return jax.jit(apply_step, donate_argnums=(0,),
                           out_shardings=(state_sh, rep))
        return jax.jit(apply_step, donate_argnums=(0,))

    def _zero_grad_buffer(self):
        masters = (self.state.master_params if self.use_master_weights
                   else self.state.params)
        accum_dtype = self.config.data_types.jnp_dtype()
        zeros = jax.jit(
            lambda m: jax.tree_util.tree_map(
                lambda x: jnp.zeros(x.shape, accum_dtype), m),
            out_shardings=self._grad_shardings)(masters)
        return zeros

    def forward(self, batch):
        """Compute the micro-batch loss (gradients computed alongside and
        held for the matching backward())."""
        if self.mesh.shape.get("pipe", 1) > 1:
            raise RuntimeError("pipeline engines train with train_batch(); "
                               "per-microbatch forward/backward is not exposed "
                               "(reference PipelineEngine restriction)")
        if self._param_offload is not None:
            raise RuntimeError(
                "offload_param engines train with train_batch() (the layer-"
                "streamed executor owns the fwd/bwd schedule)")
        if self._compression is not None:
            raise NotImplementedError(
                "1-bit optimizers run through train_batch() (the compressed "
                "exchange spans the whole accumulation window)")
        if self._compiled_micro_grad is None:
            self._compiled_micro_grad = self._make_micro_grad_step()
        if self._accum_grads is None:
            self._accum_grads = self._zero_grad_buffer()
            self._accum_count = 0
        if self._accum_count >= self.gas:
            raise RuntimeError(
                f"forward() beyond the accumulation window: {self._accum_count} "
                f"micro-batches already banked with gas={self.gas}; call step()")
        micro = self._shard_batch_eval(batch)
        micro = self._inject_pld_theta(micro, shape=())
        if self._accum_count == 0:
            self.tput_timer.start()
        with trace_span("train.forward", micro=self._accum_count) as _sp:
            loss, self._accum_grads, rng = self._compiled_micro_grad(
                self.state, micro, self._accum_grads)
            _sp.sync(loss)
        self.state = dataclasses.replace(self.state, rng=rng)
        self._window_losses.append(loss)
        self._backward_pending = True
        return loss

    def backward(self, loss=None):
        """Bank the gradients computed by the matching forward()."""
        assert getattr(self, "_backward_pending", False), \
            "backward() without a preceding forward()"
        # the fused fwd+bwd already ran under train.forward; this span marks
        # the accumulation bookkeeping so the reference-shaped loop's
        # timeline still shows all three phases
        with trace_span("train.backward", micro=self._accum_count):
            self._backward_pending = False
            self._accum_count += 1
            self.micro_steps += 1
        return loss

    def step(self):
        """Apply the optimizer update at the gradient-accumulation boundary;
        a mid-window step() is a no-op (reference skips until boundary)."""
        assert not getattr(self, "_backward_pending", False), \
            "step() with a forward() missing its backward()"
        if self._accum_count == 0:
            raise RuntimeError("step() with no accumulated gradients")
        if self._accum_count < self.gas:
            return None
        if self._compiled_apply_step is None:
            self._compiled_apply_step = self._make_apply_step()
        with trace_span("train.step", step=self.global_steps + 1) as _sp:
            self.state, metrics = self._compiled_apply_step(self.state,
                                                            self._accum_grads)
            _sp.sync(metrics["grad_norm"])
        self._accum_grads = None
        self._accum_count = 0
        self.global_steps += 1
        self._last_grad_norm = float(metrics["grad_norm"])
        # same bookkeeping/observability stream as train_batch
        metrics["loss"] = jnp.mean(jnp.stack(self._window_losses))
        self._window_losses = []
        if self.fp16_enabled and not bool(metrics["step_applied"]):
            self.skipped_steps += 1
            log_dist(f"step {self.global_steps}: grad overflow, step skipped; "
                     f"loss scale -> {float(self.state.scaler.loss_scale)}", ranks=[0])
        self.tput_timer.stop(sync_tree=metrics["loss"])
        self._emit_monitor_events(metrics)
        if self.global_steps % self.config.steps_per_print == 0:
            self._report_progress(metrics)
        return metrics

    def is_gradient_accumulation_boundary(self) -> bool:
        """True while the accumulation window is full — i.e. the banked
        micro-batches complete a window and step() will apply the update
        (reference engine.py is_gradient_accumulation_boundary semantics:
        true when processing the window's last micro-batch)."""
        return self._accum_count > 0 and self._accum_count % self.gas == 0

    # ------------------------------------------------------------------
    def _account_step(self, global_batch) -> None:
        """Per-program accounting for the fused train step
        (docs/OBSERVABILITY.md "Per-program accounting"): register the
        compiled step's lowered FLOPs once (no backend compile — the
        lowering hits the jit trace cache for these avals), count the
        invocation, and EMA the inter-step wall clock.  At steady state
        the loop is device-bound, so the wall between step RETURNS tracks
        the device step time without adding a sync point.  NOTE: lax.scan
        bodies (scan_layers, the gas accumulation loop) are counted once
        by XLA's analysis, so the estimate UNDERCOUNTS scanned configs —
        same caveat as the flops profiler; treat mfu_est as a trend gauge,
        not the bench's certified figure."""
        now = time.perf_counter()
        if self._step_flops is None:
            # register_call owns the lower()/cost_analysis() protocol
            # (and its failure path: zeros + a warning, never a raise)
            self.program_catalog.register_call(
                "train_step", self._compiled_train_step, self.state,
                global_batch)
            self._step_flops = self.program_catalog.flops_of("train_step")
        self.program_catalog.invoke("train_step")
        if self._step_wall_t is not None:
            dt = now - self._step_wall_t
            self._step_wall_s = (dt if self._step_wall_s is None
                                 else 0.8 * self._step_wall_s + 0.2 * dt)
        self._step_wall_t = now

    def _emit_monitor_events(self, metrics):
        if self.monitor is None:
            return
        events = [("Train/Samples/train_loss", float(metrics["loss"]), self.global_steps),
                  ("Train/Samples/lr", self.get_current_lr(), self.global_steps)]
        if self.fp16_enabled:
            events.append(("Train/Samples/loss_scale",
                           float(metrics["loss_scale"]), self.global_steps))
        if self.progressive_layer_drop is not None:
            events.append(("Train/Samples/pld_theta",
                           self.progressive_layer_drop.get_theta(),
                           self.global_steps))
        if self._step_flops and self._step_wall_s:
            # live roofline gauges (docs/OBSERVABILITY.md): achieved
            # model-flops throughput from the compiled step's cost and the
            # inter-step wall EMA; mfu_est divides by the operator-stated
            # roof (DS_TPU_PEAK_TFLOPS, e.g. the bench's measured matmul
            # peak) and reads 0 until one is provided — dashboards never
            # branch on configuration
            from ..observability.program_stats import peak_flops_per_sec

            achieved = self._step_flops / self._step_wall_s
            peak = peak_flops_per_sec()
            events.append(("train/tflops_est", achieved / 1e12,
                           self.global_steps))
            events.append(("train/mfu_est",
                           achieved / peak if peak else 0.0,
                           self.global_steps))
        self.monitor.write_events(events)

    def _report_progress(self, metrics):
        log_dist(f"step={self.global_steps}, skipped={self.skipped_steps}, "
                 f"lr={self.get_current_lr():.3e}, loss={float(metrics['loss']):.4f}, "
                 f"grad_norm={float(metrics['grad_norm']):.3f}", ranks=[0])

    def get_lr(self) -> list:
        """Current learning rate(s), one per param group (reference
        engine.get_lr -> lr_scheduler.get_lr(), a list; this engine has one
        logical group).  Scalar convenience: ``get_current_lr()``."""
        return [self.get_current_lr()]

    def get_current_lr(self) -> float:
        step = self.global_steps if self.state is None else self.state.step
        return float(self.lr_schedule(step))

    @property
    def loss_scale(self) -> float:
        if self.state is None:
            return 1.0  # offload_param: bf16-only, no loss scaling
        return float(self.state.scaler.loss_scale)

    def get_global_grad_norm(self) -> Optional[float]:
        """Global gradient norm of the most recent optimizer step (None until
        the first step completes)."""
        return self._last_grad_norm

    @property
    def module(self):
        if self.state is None:
            raise NotImplementedError(
                "offload_param engines hold no device param tree; use "
                "engine._param_offload.read_masters() for the fp32 leaves")
        return self.state.params

    def get_params(self, fp32: bool = False):
        if self.state is None:
            raise NotImplementedError(
                "offload_param engines hold no device param tree; use "
                "engine._param_offload.read_masters() for the fp32 leaves")
        if fp32 and self.state.master_params is not None:
            return self.state.master_params
        return self.state.params

    # ------------------------------------------------------------------
    # Checkpointing (reference engine.py:2593-3365) — see checkpoint_engine/
    # ------------------------------------------------------------------
    def save_checkpoint(self, save_dir, tag=None, client_state=None, save_latest=True):
        """Save the full training state.  With a host-stepped offload
        optimizer active (ZeRO-Offload host RAM / ZeRO-Infinity NVMe), the
        host-resident fp32 masters + Adam moments are serialized alongside
        the orbax tree (reference swap_tensor/optimizer_utils.py)."""
        from .checkpoint_engine.orbax_engine import save_engine_checkpoint

        with trace_span("ckpt.save",
                        tag=str(tag) if tag is not None else
                        f"global_step{self.global_steps}"):
            return save_engine_checkpoint(self, save_dir, tag=tag,
                                          client_state=client_state,
                                          save_latest=save_latest)

    def wait_for_checkpoint(self):
        """Block until an in-flight async save (checkpoint.async_save) is
        durable and `latest` is published; re-raises a failed save.  No-op
        for synchronous saves (reference Nebula commit barrier).  The join
        is bounded (the engine's finalize timeout) and the hang watchdog is
        armed around it, so a wedged storage write ends in a stack report +
        restartable exit, never a hung shutdown."""
        from .checkpoint_engine.async_engine import wait_for_pending_checkpoint

        with trace_span("ckpt.finalize"):
            if self._watchdog is None:
                return wait_for_pending_checkpoint(self)
            with self._watchdog.armed("async-checkpoint finalize"):
                return wait_for_pending_checkpoint(self)

    def replica_snapshot(self) -> bytes:
        """Serialize the live train state to one host-RAM byte slab for
        the pod replica layer (elasticity/replication.py): a device→host
        copy, never a filesystem write — see checkpoint_engine/
        replica_snapshot.py for the format."""
        from .checkpoint_engine.replica_snapshot import snapshot_train_state

        return snapshot_train_state(self)

    def replica_ingest(self, payload: bytes) -> int:
        """Rebuild the train state from a replica slab (live-adoption
        path); leaves re-shard against the current mesh.  Returns the
        restored global step."""
        from .checkpoint_engine.replica_snapshot import ingest_train_state

        return ingest_train_state(self, payload)

    def load_checkpoint(self, load_dir, tag=None, load_optimizer_states=True,
                        load_lr_scheduler_states=True, load_module_only=False):
        from .checkpoint_engine.orbax_engine import load_engine_checkpoint

        with trace_span("ckpt.load",
                        tag=str(tag) if tag is not None else "latest"):
            return load_engine_checkpoint(
                self, load_dir, tag=tag,
                load_optimizer_states=load_optimizer_states,
                load_module_only=load_module_only)
