"""Config keys and defaults (compact analogue of runtime/constants.py, 422 LoC)."""

TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"

OPTIMIZER = "optimizer"
SCHEDULER = "scheduler"
FP16 = "fp16"
BF16 = "bf16"
ZERO_OPTIMIZATION = "zero_optimization"
GRADIENT_CLIPPING = "gradient_clipping"
GRADIENT_CLIPPING_DEFAULT = 0.0

STEPS_PER_PRINT = "steps_per_print"
STEPS_PER_PRINT_DEFAULT = 10

WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"
MEMORY_BREAKDOWN = "memory_breakdown"

PRESCALE_GRADIENTS = "prescale_gradients"
GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"

DUMP_STATE = "dump_state"

# ZeRO stages (reference runtime/zero/config.py:84 ZeroStageEnum)
ZERO_STAGE_DISABLED = 0
ZERO_STAGE_OPTIMIZER_STATES = 1
ZERO_STAGE_GRADIENTS = 2
ZERO_STAGE_WEIGHTS = 3

ROUTE_TRAIN = "train"
ROUTE_EVAL = "eval"
ROUTE_PREDICT = "predict"
ROUTE_ENCODE = "encode"
