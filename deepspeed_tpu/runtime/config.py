"""Master JSON config (the analogue of ``runtime/config.py``'s DeepSpeedConfig).

Config surface keeps the reference's key names wherever the concept survives the
TPU redesign (train_batch_size triad, fp16/bf16 blocks, zero_optimization with
stage 0-3 + offload + ZeRO++ knobs, gradient_clipping, monitor blocks,
flops_profiler, wall_clock_breakdown, …) and adds one TPU-native section:
``"mesh"`` — the parallelism layout (dp/tp/pp/ep/sp) that the reference spread
across mpu arguments, pipeline module args and expert-group setup
(utils/groups.py) instead.

Batch triad resolution/validation mirrors reference runtime/config.py
(train_batch = micro_batch × gradient_accumulation_steps × dp_world).
"""
from __future__ import annotations

import json
from enum import Enum
from pathlib import Path
from typing import Any, Dict, List, Optional, Union

from pydantic import Field, model_validator

from .config_utils import DeepSpeedConfigModel
from . import constants as C
from ..utils.logging import logger


class OffloadDeviceEnum(str, Enum):
    none = "none"
    cpu = "cpu"
    nvme = "nvme"


class DeepSpeedZeroOffloadParamConfig(DeepSpeedConfigModel):
    """zero_optimization.offload_param (reference runtime/zero/offload_config.py)."""

    device: OffloadDeviceEnum = OffloadDeviceEnum.none
    nvme_path: Optional[str] = None
    buffer_count: int = Field(5, ge=0)
    buffer_size: int = Field(100_000_000, ge=0)
    max_in_cpu: int = Field(1_000_000_000, ge=0)
    pin_memory: bool = False


class DeepSpeedZeroOffloadOptimizerConfig(DeepSpeedConfigModel):
    """zero_optimization.offload_optimizer (reference runtime/zero/offload_config.py)."""

    device: OffloadDeviceEnum = OffloadDeviceEnum.none
    nvme_path: Optional[str] = None
    buffer_count: int = Field(4, ge=0)
    pin_memory: bool = False
    pipeline_read: bool = False
    pipeline_write: bool = False
    fast_init: bool = False
    ratio: float = Field(1.0, ge=0.0, le=1.0)
    # device=cpu execution strategy (TPU-specific): True = host SIMD Adam on
    # RAM-resident state (device never holds fp32 master/m/v — the reference
    # cpu_offload semantics, required for models near HBM capacity on few
    # chips); False = state parked in pinned host memory and streamed
    # through the jitted step (cheaper per step when dp shards the state
    # thin).  None = auto: host step when the mesh has ONE data shard.
    host_step: Optional[bool] = None


class ZeroConfig(DeepSpeedConfigModel):
    """zero_optimization block (reference runtime/zero/config.py:38-283).

    On TPU, stages are realized as sharding plans over the mesh's DP axes
    (see runtime/zero/planner.py) rather than hook-driven partitioning:
      0 = replicated (plain DP), 1 = optimizer states sharded,
      2 = + gradients reduce-scattered into shards, 3 = + parameters sharded.
    """

    stage: int = Field(0, ge=0, le=3)
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    reduce_bucket_size: int = Field(500_000_000, ge=0)
    allgather_partitions: bool = True
    allgather_bucket_size: int = Field(500_000_000, ge=0)
    overlap_comm: bool = True
    round_robin_gradients: bool = False
    elastic_checkpoint: bool = False

    offload_param: Optional[DeepSpeedZeroOffloadParamConfig] = None
    offload_optimizer: Optional[DeepSpeedZeroOffloadOptimizerConfig] = None

    # stage-3 knobs (kept for API parity; prefetch/persistence map to XLA
    # scheduling hints and the "small params stay replicated" threshold)
    sub_group_size: int = Field(1_000_000_000, ge=0)
    stage3_max_live_parameters: int = Field(1_000_000_000, ge=0)
    stage3_max_reuse_distance: int = Field(1_000_000_000, ge=0)
    stage3_prefetch_bucket_size: int = Field(50_000_000, ge=0)
    stage3_param_persistence_threshold: int = Field(100_000, ge=0)
    stage3_gather_16bit_weights_on_model_save: bool = False

    # ZeRO++ (reference zero/config.py:38-41; partition_parameters.py:1019-1158)
    zero_hpz_partition_size: int = Field(1, ge=1)
    zero_quantized_weights: bool = False
    zero_quantized_gradients: bool = False

    # MiCS (reference zero/mics.py)
    mics_shard_size: int = Field(-1)
    mics_hierarchical_params_gather: bool = False

    # hierarchical qgZ (reference coalesced_collectives.py:31 — the 2-hop
    # intra-node -> inter-node quantized gradient reduction): inner ZeRO
    # group size (the ICI domain); grads quantize-reduce within the inner
    # group first, then across 'data_outer', moving 1/inner of the bytes
    # over the expensive links
    zero_hierarchical_dp_size: int = Field(-1)

    ignore_unused_parameters: bool = True

    @model_validator(mode="after")
    def _validate(self):
        if self.zero_quantized_weights or self.zero_quantized_gradients:
            if self.stage != 3:
                raise ValueError("ZeRO++ quantized collectives require stage 3")
        if self.mics_shard_size == 0 or self.mics_shard_size < -1:
            raise ValueError(
                f"mics_shard_size={self.mics_shard_size} invalid: must be -1 "
                "(disabled) or a positive shard-group size")
        if self.mics_shard_size > 0 and self.stage != 3:
            raise ValueError("mics_shard_size (MiCS) requires ZeRO stage 3")
        if self.mics_hierarchical_params_gather and self.mics_shard_size <= 0:
            raise ValueError(
                "mics_hierarchical_params_gather requires mics_shard_size > 0")
        if self.zero_hpz_partition_size > 1 and self.stage != 3:
            raise ValueError(
                "zero_hpz_partition_size (ZeRO++ hpZ) requires stage 3")
        if self.zero_hierarchical_dp_size > 1 and self.stage != 3:
            raise ValueError(
                "zero_hierarchical_dp_size (hierarchical qgZ) requires "
                "stage 3")
        if self.zero_hierarchical_dp_size > 1 and self.mics_shard_size > 0:
            raise ValueError(
                "zero_hierarchical_dp_size and mics_shard_size both "
                "factorize the data axis — enable one or the other")
        if self.zero_hierarchical_dp_size > 1 \
                and self.zero_hpz_partition_size > 1:
            raise ValueError(
                "zero_hierarchical_dp_size and zero_hpz_partition_size both "
                "factorize the data axis — hpZ already makes the outer hop "
                "the only explicit one; hierarchical qgZ needs masters "
                "sharded over both hops")
        return self


class FP16Config(DeepSpeedConfigModel):
    """fp16 block (reference runtime/fp16/loss_scaler.py semantics)."""

    enabled: bool = False
    auto_cast: bool = False
    loss_scale: float = Field(0.0, ge=0.0)  # 0 => dynamic
    initial_scale_power: int = Field(16, ge=0)
    loss_scale_window: int = Field(1000, ge=1)
    hysteresis: int = Field(2, ge=1)
    consecutive_hysteresis: bool = False
    min_loss_scale: float = Field(1.0, ge=0.0)


class BF16Config(DeepSpeedConfigModel):
    """bf16 block (reference runtime/bf16_optimizer.py): bf16 compute with
    fp32 master weights + fp32 grad accumulation, sharded like ZeRO-1."""

    enabled: bool = False
    # accumulate gradients in fp32 across micro-batches (reference always does)
    fp32_grad_accum: bool = True


class OptimizerConfig(DeepSpeedConfigModel):
    type: str = "adamw"
    params: Dict[str, Any] = Field(default_factory=dict)


class SchedulerConfig(DeepSpeedConfigModel):
    type: str = "WarmupLR"
    params: Dict[str, Any] = Field(default_factory=dict)


class MeshConfig(DeepSpeedConfigModel):
    """TPU-native parallelism layout — dp is inferred when left at 0."""

    dp: int = Field(0, ge=0)  # 0 => infer from device count
    tp: int = Field(1, ge=1)
    pp: int = Field(1, ge=1)
    ep: int = Field(1, ge=1)
    sp: int = Field(1, ge=1)


class ActivationCheckpointingConfig(DeepSpeedConfigModel):
    """activation_checkpointing block (reference checkpointing.py:789 configure).

    On TPU this maps to jax.checkpoint policies; partition_activations maps to
    sharding the saved residuals over the model/seq axes."""

    partition_activations: bool = False
    cpu_checkpointing: bool = False
    contiguous_memory_optimization: bool = False
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False


class TensorBoardConfig(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class WandbConfig(DeepSpeedConfigModel):
    enabled: bool = False
    group: Optional[str] = None
    team: Optional[str] = None
    project: str = "deepspeed_tpu"


class CSVConfig(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class MonitorConfig(DeepSpeedConfigModel):
    tensorboard: TensorBoardConfig = Field(default_factory=TensorBoardConfig)
    wandb: WandbConfig = Field(default_factory=WandbConfig)
    csv_monitor: CSVConfig = Field(default_factory=CSVConfig)

    @property
    def enabled(self) -> bool:
        return self.tensorboard.enabled or self.wandb.enabled or self.csv_monitor.enabled


class FlopsProfilerConfig(DeepSpeedConfigModel):
    enabled: bool = False
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None


class CommsLoggerConfig(DeepSpeedConfigModel):
    enabled: bool = False
    verbose: bool = False
    prof_all: bool = True
    debug: bool = False
    prof_ops: List[str] = Field(default_factory=list)


class CheckpointConfig(DeepSpeedConfigModel):
    tag_validation: str = "Warn"  # Ignore | Warn | Fail
    load_universal: bool = False
    use_node_local_storage: bool = False
    parallel_write_pipeline: bool = False
    # Nebula-analogue async tiered save (reference nebula_checkpoint_engine):
    # save_checkpoint returns after the device->host snapshot; the storage
    # write runs in the background and `latest` is published only on commit
    async_save: bool = False


class WatchdogConfig(DeepSpeedConfigModel):
    """Hang watchdog (resilience/watchdog.py): armed around ``train_batch``
    and async-checkpoint finalization; past ``timeout_s`` it dumps an
    all-thread stack report through the monitor layer and exits
    ``exit_code`` so the elastic supervisor can recycle the process."""

    enabled: bool = False
    timeout_s: float = Field(600.0, gt=0.0)
    exit_code: int = 85   # resilience.watchdog.RC_HANG


class ResilienceConfig(DeepSpeedConfigModel):
    """``resilience`` block: checkpoint verification + hang watchdog (fault
    injection is env/test-driven via DS_TPU_FAULTS, never config)."""

    # verify manifest.json (checksums + payload listing) before any load
    verify_on_load: bool = True
    watchdog: WatchdogConfig = Field(default_factory=WatchdogConfig)


class DataTypeConfig(DeepSpeedConfigModel):
    grad_accum_dtype: Optional[str] = None


class PipelineConfig(DeepSpeedConfigModel):
    """pipeline block — schedule/microbatch knobs (engine-level; stage count
    comes from mesh.pp)."""

    pipe_partitioned: bool = True
    grad_partitioned: bool = True
    activation_checkpoint_interval: int = 0
    partition_method: str = "parameters"


class ProgressiveLayerDropConfig(DeepSpeedConfigModel):
    """``progressive_layer_drop`` block (reference runtime/config.py PLD
    keys; runtime/progressive_layer_drop.py)."""

    enabled: bool = False
    theta: float = Field(0.5, gt=0.0, le=1.0)
    gamma: float = Field(0.001, ge=0.0)


class EigenvalueConfig(DeepSpeedConfigModel):
    """``eigenvalue`` block (reference runtime/eigenvalue.py knobs; device/
    layer-name knobs are meaningless on the pytree design and not accepted)."""

    enabled: bool = False
    verbose: bool = False
    max_iter: int = Field(100, ge=1)
    tol: float = Field(1e-2, gt=0.0)
    stability: float = Field(1e-6, ge=0.0)


class CurriculumLearningLegacyConfig(DeepSpeedConfigModel):
    """Top-level ``curriculum_learning`` block (reference legacy curriculum,
    runtime/config.py ``curriculum_enabled_legacy``): the engine truncates
    the batch sequence to the scheduled difficulty."""

    enabled: bool = False
    curriculum_type: str = "seqlen"
    min_difficulty: int = 8
    max_difficulty: int = 1024
    schedule_type: str = "fixed_linear"
    schedule_config: Dict[str, Any] = Field(default_factory=dict)
    # non-seqlen curriculum types: per-sample difficulty values (a
    # DataAnalyzer ``<metric>_values.npy``) driving the in-loop sampler
    metric_values_path: Optional[str] = None


class RandomLTDConfig(DeepSpeedConfigModel):
    enabled: bool = False
    min_value: int = 128
    max_value: int = 2048
    random_ltd_schedule: Dict[str, Any] = Field(default_factory=dict)


class DataRoutingConfig(DeepSpeedConfigModel):
    enabled: bool = False
    random_ltd: RandomLTDConfig = Field(default_factory=RandomLTDConfig)


class CurriculumMetricConfig(DeepSpeedConfigModel):
    """One metric of the multi-metric curriculum (reference
    ``data_efficiency.data_sampling.curriculum_learning.curriculum_metrics``
    entries, constants.py CURRICULUM_LEARNING_METRICS)."""

    metric_values_path: str  # a DataAnalyzer `<metric>_values.npy`
    difficulty_type: str = "value"          # 'value' | 'percentile'
    clustering_type: str = "schedule_based"  # | 'single_cluster'
    min_difficulty: int = 1
    max_difficulty: int = 100
    schedule_type: str = "fixed_linear"
    schedule_config: Dict[str, Any] = Field(default_factory=dict)

    @model_validator(mode="after")
    def _validate(self):
        if self.difficulty_type not in ("value", "percentile"):
            raise ValueError(
                f"difficulty_type={self.difficulty_type!r}: 'value' or "
                "'percentile'")
        if self.clustering_type not in ("schedule_based", "single_cluster"):
            raise ValueError(
                f"clustering_type={self.clustering_type!r}: "
                "'schedule_based' or 'single_cluster'")
        return self


class CurriculumLearningConfig(DeepSpeedConfigModel):
    """Multi-metric cluster-bucketed curriculum (reference
    data_sampling/data_sampler.py:36 DeepSpeedDataSampler)."""

    enabled: bool = False
    curriculum_metrics: Dict[str, CurriculumMetricConfig] = Field(
        default_factory=dict)

    @model_validator(mode="after")
    def _validate(self):
        if self.enabled and not self.curriculum_metrics:
            raise ValueError(
                "data_sampling.curriculum_learning.enabled needs >=1 entry "
                "in curriculum_metrics")
        return self


class DataSamplingConfig(DeepSpeedConfigModel):
    enabled: bool = False
    curriculum_learning: CurriculumLearningConfig = Field(
        default_factory=CurriculumLearningConfig)

    @model_validator(mode="after")
    def _validate(self):
        if self.curriculum_learning.enabled and not self.enabled:
            raise ValueError(
                "data_sampling.curriculum_learning.enabled=true requires "
                "data_sampling.enabled=true (the engine gates on both — a "
                "silently-ignored curriculum would train uniformly)")
        return self


class DataEfficiencyConfig(DeepSpeedConfigModel):
    enabled: bool = False
    data_routing: DataRoutingConfig = Field(default_factory=DataRoutingConfig)
    data_sampling: DataSamplingConfig = Field(
        default_factory=DataSamplingConfig)


class AIOConfig(DeepSpeedConfigModel):
    block_size: int = 1048576
    queue_depth: int = 8
    thread_count: int = 1
    single_submit: bool = False
    overlap_events: bool = True


class ElasticityConfig(DeepSpeedConfigModel):
    enabled: bool = False
    max_train_batch_size: int = 2000
    micro_batch_sizes: List[int] = Field(default_factory=lambda: [2, 4, 6])
    min_gpus: int = 1
    max_gpus: int = 10000
    min_time: int = 0
    version: float = 0.1
    ignore_non_elastic_batch_info: bool = False
    prefer_larger_batch: bool = True
    num_gpus_per_node: int = Field(1, ge=1)
    model_parallel_size: int = Field(1, ge=1)


class DataTypesConfig(DeepSpeedConfigModel):
    """``data_types`` block (reference runtime/config.py:867): gradient
    accumulation precision.  None/fp32 = exact fp32 accumulation; bf16 halves
    the live gradient buffer."""

    grad_accum_dtype: Optional[str] = None

    @model_validator(mode="after")
    def _validate(self):
        if self.grad_accum_dtype not in (None, "fp32", "float32", "bf16",
                                         "bfloat16"):
            raise ValueError(
                f"data_types.grad_accum_dtype={self.grad_accum_dtype!r} "
                "must be fp32 or bf16")
        return self

    def jnp_dtype(self):
        import jax.numpy as jnp

        if self.grad_accum_dtype in ("bf16", "bfloat16"):
            return jnp.bfloat16
        return jnp.float32


class DeepSpeedConfigError(Exception):
    pass


class DeepSpeedConfig:
    """Master config object (reference runtime/config.py DeepSpeedConfig).

    Accepts a dict, a JSON file path, or None; resolves the batch-size triad
    against the mesh's data-parallel world size.
    """

    def __init__(self, config: Union[None, str, Path, Dict[str, Any]] = None,
                 dp_world_size: Optional[int] = None):
        if config is None:
            config = {}
        if isinstance(config, (str, Path)):
            with open(config, "r") as f:
                config = json.load(f)
        if not isinstance(config, dict):
            raise DeepSpeedConfigError(f"config must be dict or path, got {type(config)}")
        self._param_dict = dict(config)

        self.mesh = MeshConfig(**config.get("mesh", {}))
        self.zero_config = ZeroConfig(**config.get(C.ZERO_OPTIMIZATION, {}))
        self.fp16 = FP16Config(**config.get(C.FP16, {}))
        self.bf16 = BF16Config(**config.get(C.BF16, {}))
        if self.fp16.enabled and self.bf16.enabled:
            raise DeepSpeedConfigError("fp16 and bf16 cannot both be enabled")

        opt = config.get(C.OPTIMIZER)
        self.optimizer = OptimizerConfig(**opt) if opt is not None else None
        sched = config.get(C.SCHEDULER)
        self.scheduler = SchedulerConfig(**sched) if sched is not None else None

        self.gradient_clipping: float = float(
            config.get(C.GRADIENT_CLIPPING, C.GRADIENT_CLIPPING_DEFAULT))
        self.prescale_gradients: bool = bool(config.get(C.PRESCALE_GRADIENTS, False))
        self.gradient_predivide_factor: float = float(
            config.get(C.GRADIENT_PREDIVIDE_FACTOR, 1.0))
        self.steps_per_print: int = int(config.get(C.STEPS_PER_PRINT, C.STEPS_PER_PRINT_DEFAULT))
        self.wall_clock_breakdown: bool = bool(config.get(C.WALL_CLOCK_BREAKDOWN, False))
        self.memory_breakdown: bool = bool(config.get(C.MEMORY_BREAKDOWN, False))
        self.dump_state: bool = bool(config.get(C.DUMP_STATE, False))
        self.seed: int = int(config.get("seed", 42))

        self.activation_checkpointing = ActivationCheckpointingConfig(
            **config.get("activation_checkpointing", {}))
        self.monitor_config = MonitorConfig(**{
            k: v for k, v in config.items() if k in ("tensorboard", "wandb", "csv_monitor")})
        self.flops_profiler = FlopsProfilerConfig(**config.get("flops_profiler", {}))
        self.comms_logger = CommsLoggerConfig(**config.get("comms_logger", {}))
        self.checkpoint_config = CheckpointConfig(**config.get("checkpoint", {}))
        self.resilience = ResilienceConfig(**config.get("resilience", {}))
        self.data_types = DataTypeConfig(**config.get("data_types", {}))
        self.pipeline = PipelineConfig(**config.get("pipeline", {}))
        self.aio = AIOConfig(**config.get("aio", {}))
        self.curriculum_learning = CurriculumLearningLegacyConfig(
            **config.get("curriculum_learning", {}))
        self.data_efficiency = DataEfficiencyConfig(
            **config.get("data_efficiency", {}))
        self.elasticity = ElasticityConfig(**config.get("elasticity", {}))
        self.data_types = DataTypesConfig(**config.get("data_types", {}))
        self.progressive_layer_drop = ProgressiveLayerDropConfig(
            **config.get("progressive_layer_drop", {}))
        self.eigenvalue = EigenvalueConfig(**config.get("eigenvalue", {}))

        self.gradient_accumulation_steps: Optional[int] = config.get(
            C.GRADIENT_ACCUMULATION_STEPS)
        self.train_batch_size: Optional[int] = config.get(C.TRAIN_BATCH_SIZE)
        self.train_micro_batch_size_per_gpu: Optional[int] = config.get(
            C.TRAIN_MICRO_BATCH_SIZE_PER_GPU)

        self._reject_unimplemented_knobs()

        if dp_world_size is not None:
            self.resolve_batch_triad(dp_world_size)

    def _reject_unimplemented_knobs(self) -> None:
        """Fail fast on accepted-but-unimplemented settings.

        Schema parity with the reference means every knob parses; a knob that
        parses but does nothing is a silent lie (a user enabling offload must
        not discover at OOM time that it was inert).  Any setting listed here
        raises NotImplementedError at config time; entries are removed as the
        backing feature lands.
        """
        bad: List[str] = []
        zc = self.zero_config

        if self._param_dict.get("sparse_gradients", False):
            bad.append(
                "sparse_gradients (XLA fuses the embedding scatter-add and "
                "ZeRO/TP already shard the exchange; a variable-nnz sparse "
                "allreduce is inexpressible under static shapes — see "
                "runtime/sparse_tensor.py for the fixed-width row-sparse "
                "utility and the full position)")

        if zc.offload_param is not None and \
                zc.offload_param.device == OffloadDeviceEnum.cpu:
            bad.append("zero_optimization.offload_param.device=cpu "
                       "(use device=nvme for the layer-streamed param "
                       "offload, or offload_optimizer for state-only offload)")
        if zc.offload_param is not None and \
                zc.offload_param.device == OffloadDeviceEnum.nvme:
            if not zc.offload_param.nvme_path:
                bad.append("zero_optimization.offload_param.device=nvme "
                           "requires nvme_path")
            if zc.stage != 3:
                bad.append("zero_optimization.offload_param requires "
                           "stage=3 (reference restriction)")
        if zc.offload_optimizer is not None and \
                zc.offload_optimizer.device == OffloadDeviceEnum.nvme and \
                not zc.offload_optimizer.nvme_path:
            bad.append("zero_optimization.offload_optimizer.device=nvme "
                       "requires nvme_path")
        ac = self.activation_checkpointing
        for knob in ("cpu_checkpointing", "contiguous_memory_optimization",
                     "synchronize_checkpoint_boundary", "profile"):
            if getattr(ac, knob):
                bad.append(f"activation_checkpointing.{knob}")
        if ac.number_checkpoints is not None:
            bad.append("activation_checkpointing.number_checkpoints "
                       "(contiguous-buffer partitioning)")

        if bad:
            raise NotImplementedError(
                "config enables features this build does not implement yet: "
                + "; ".join(bad))

    # -- batch triad (reference runtime/config.py `_batch_assertion` et al.) --
    def resolve_batch_triad(self, dp_world_size: int) -> None:
        if self.elasticity.enabled:
            self._resolve_elastic_triad(dp_world_size)
            return
        tb, mb, gas = (self.train_batch_size, self.train_micro_batch_size_per_gpu,
                       self.gradient_accumulation_steps)
        if tb is not None and mb is not None and gas is not None:
            pass
        elif tb is not None and mb is not None:
            gas = tb // (mb * dp_world_size)
        elif tb is not None and gas is not None:
            mb = tb // (gas * dp_world_size)
        elif mb is not None and gas is not None:
            tb = mb * gas * dp_world_size
        elif tb is not None:
            gas = 1
            mb = tb // dp_world_size
        elif mb is not None:
            gas = 1
            tb = mb * dp_world_size
        else:
            raise DeepSpeedConfigError(
                "at least one of train_batch_size / train_micro_batch_size_per_gpu "
                "must be set")
        if gas < 1 or mb < 1 or tb != mb * gas * dp_world_size:
            raise DeepSpeedConfigError(
                f"batch triad inconsistent: train_batch_size={tb} != "
                f"micro_batch({mb}) * gas({gas}) * dp_world({dp_world_size})")
        self.train_batch_size, self.train_micro_batch_size_per_gpu = tb, mb
        self.gradient_accumulation_steps = gas

    def _resolve_elastic_triad(self, dp_world_size: int) -> None:
        """Elastic mode: the batch triad comes from the elastic plan, not the
        user's knobs (reference elasticity handling in runtime/config.py —
        explicit batch settings conflict unless ignore_non_elastic_batch_info)."""
        from ..elasticity import (ensure_immutable_elastic_config,
                                  resolve_plan_for_current_world)
        if getattr(self, "elastic_plan", None) is not None:
            return  # already resolved (engine re-calls resolve_batch_triad)
        ec = self.elasticity
        user_set = [k for k, v in (
            ("train_batch_size", self.train_batch_size),
            ("train_micro_batch_size_per_gpu", self.train_micro_batch_size_per_gpu),
            ("gradient_accumulation_steps", self.gradient_accumulation_steps),
        ) if v is not None]
        if user_set and not ec.ignore_non_elastic_batch_info:
            raise DeepSpeedConfigError(
                f"elasticity is enabled but {user_set} are also set; elastic "
                "training derives the batch triad from the plan — remove them "
                "or set elasticity.ignore_non_elastic_batch_info")
        ensure_immutable_elastic_config(ec.model_dump())
        plan = resolve_plan_for_current_world(
            ec, dp_world_size, node_size=ec.num_gpus_per_node,
            model_parallel_size=ec.model_parallel_size)
        (self.train_batch_size, self.train_micro_batch_size_per_gpu,
         self.gradient_accumulation_steps) = plan.as_triad()
        self.elastic_plan = plan

    # -- convenience accessors used by the engine --
    @property
    def zero_enabled(self) -> bool:
        return self.zero_config.stage > 0

    @property
    def zero_optimization_stage(self) -> int:
        return self.zero_config.stage

    @property
    def precision(self):
        import jax.numpy as jnp

        if self.bf16.enabled:
            return jnp.bfloat16
        if self.fp16.enabled:
            return jnp.float16
        return jnp.float32

    def to_dict(self) -> Dict[str, Any]:
        return dict(self._param_dict)

    def print_config(self) -> None:
        logger.info("DeepSpeedConfig:\n" + json.dumps(self._param_dict, indent=2, default=str))
