"""Config-model base utilities.

Parity with the reference's ``runtime/config_utils.py:16`` — a pydantic base
class providing: unknown-field tolerance with a warning, deprecated-field
migration (``deprecated=True`` + ``new_param`` in json_schema_extra), and
``"auto"`` value passthrough (reference :54; callers resolve "auto" later).
"""
from __future__ import annotations

from typing import Any, Dict

from pydantic import BaseModel, ConfigDict

from ..utils.logging import logger

AUTO_VALUE = "auto"


class DeepSpeedConfigModel(BaseModel):
    """Base for all subsystem configs (reference runtime/config_utils.py:16).

    Usage of deprecated fields::

        old_name: int = Field(0, json_schema_extra={"deprecated": True, "new_param": "new_name"})
    """

    model_config = ConfigDict(
        validate_assignment=True,
        use_enum_values=True,
        populate_by_name=True,
        extra="ignore",
        protected_namespaces=(),
        arbitrary_types_allowed=True,
    )

    def __init__(self, strict: bool = False, **data):
        if not strict:  # drop "auto" so field defaults apply (reference :54)
            data = {k: v for k, v in data.items() if not (v == AUTO_VALUE and k != "precision")}
        super().__init__(**data)
        self._migrate_deprecated(data)

    def _migrate_deprecated(self, data: Dict[str, Any]) -> None:
        for name, field in type(self).model_fields.items():
            extra = field.json_schema_extra or {}
            if not isinstance(extra, dict) or not extra.get("deprecated"):
                continue
            if name not in data:
                continue
            new_param = extra.get("new_param")
            logger.warning(f"Config parameter {name} is deprecated" +
                           (f"; use {new_param} instead" if new_param else ""))
            if new_param and new_param not in data:
                # copy the deprecated value onto its replacement
                object.__setattr__(self, new_param, getattr(self, name))

    def get(self, key: str, default: Any = None) -> Any:
        return getattr(self, key, default)

    def __getitem__(self, key: str) -> Any:
        return getattr(self, key)


def get_scalar_param(param_dict: Dict[str, Any], param_name: str, param_default_value: Any) -> Any:
    return param_dict.get(param_name, param_default_value)


def get_dict_param(param_dict: Dict[str, Any], param_name: str, param_default_value: Any) -> Any:
    return param_dict.get(param_name, param_default_value)
