"""SPMD pipeline executor (TPU-native redesign of ``runtime/pipe/engine.py``).

The reference runs pipeline parallelism as a per-rank instruction stream
(1F1B ``TrainSchedule``, schedule.py:189) with explicit p2p sends of
activations between stage processes (pipe/engine.py:913-1104, p2p.py:50).
Under a single SPMD program that structure collapses into a *shifted-buffer
scan*:

  - layer params are stacked [P, Lp, ...] and sharded over the 'pipe' mesh
    axis — each pipe shard holds its stage's layers;
  - the live state is one [P, mb, S, D] buffer, stage-sharded on dim 0;
  - each scan step vmaps the stage body over P (every stage computes in
    parallel on its current microbatch) then rolls the buffer one stage
    forward — XLA lowers the roll of a pipe-sharded dim to a
    ``collective_permute`` over ICI, the analogue of p2p.send/recv;
  - microbatch t enters stage 0 at step t and exits stage P-1 at step
    t+P-1; total steps M + P - 1, bubble (P-1)/(M+P-1) (GPipe fill/drain —
    the 1F1B memory shape comes from per-microbatch remat instead of
    activation stashes).

Backward needs no schedule at all: AD of the scan replays the same wavefront
in reverse, and the transposed collective_permute carries the activation
grads the reference moves with SendGrad/RecvGrad.
"""
from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def pipeline_apply(stage_fn: Callable[[Any, jnp.ndarray, jnp.ndarray], Tuple[jnp.ndarray, jnp.ndarray]],
                   stage_params: Any, x_micro: jnp.ndarray,
                   rng: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Run M microbatches through P pipeline stages.

    stage_fn(stage_layer_params, x [mb,S,D], rng) -> (x, aux) — one stage's
    layer stack, vmapped over the leading [P] dim of ``stage_params``.
    x_micro: [M, mb, S, D] embedded microbatches.
    Returns (y_micro [M, mb, S, D], aux_sum).
    """
    P_ = jax.tree_util.tree_leaves(stage_params)[0].shape[0]
    M = x_micro.shape[0]
    state = jnp.zeros((P_,) + x_micro.shape[1:], x_micro.dtype)
    pad = jnp.zeros((P_ - 1,) + x_micro.shape[1:], x_micro.dtype)
    xs = jnp.concatenate([x_micro, pad], axis=0)          # [M+P-1, mb, S, D]

    def step(carry, inp):
        state, t = carry
        x_in, = inp
        state = state.at[0].set(x_in)
        rngs = jax.vmap(lambda s: jax.random.fold_in(
            jax.random.fold_in(rng, t), s))(jnp.arange(P_))
        state, aux = jax.vmap(stage_fn)(stage_params, state, rngs)
        # during fill/drain a stage computes on zero padding; mask its aux
        sid = jnp.arange(P_)
        valid = (t >= sid) & (t < sid + M)
        out = state[P_ - 1]
        state = jnp.roll(state, 1, axis=0)                # stage s -> s+1
        return (state, t + 1), (out, jnp.sum(aux * valid))

    (_, _), (outs, auxs) = jax.lax.scan(step, (state, jnp.int32(0)), (xs,))
    # microbatch t exits at scan step t + P - 1
    return outs[P_ - 1:], jnp.sum(auxs)


def stage_layer_count(num_layers: int, num_stages: int) -> int:
    if num_layers % num_stages:
        raise ValueError(
            f"num_layers={num_layers} not divisible by pipeline stages={num_stages}")
    return num_layers // num_stages


def pipeline_1f1b(stage_fn: Callable, head_fn: Callable, stage_params: Any,
                  head_params: Any, x_micro: jnp.ndarray,
                  labels_micro: jnp.ndarray, rng: jnp.ndarray):
    """True 1F1B: ONE scan interleaves forward and backward wavefronts
    (reference ``runtime/pipe/schedule.py:189`` ``TrainSchedule`` — there an
    imperative per-rank instruction stream; here both wavefronts are buffers
    rolling in opposite directions over the 'pipe' axis).

    Why not AD of the GPipe scan (``pipeline_apply``): AD must finish the
    whole forward before the first backward step, so every one of the
    ``M+P-1`` saved carries is live at once — activation stash grows with M.
    Here stage p's activation for microbatch m lives exactly
    ``2(P-p)-1`` ticks (fwd at tick p+m, cotangent arrives at 2P-1-p+m —
    forced by the immediate cot chaining, the lockstep analogue of the
    reference's ``num_pipe_buffers = P-p`` in-flight bound,
    schedule.py:247).  The stash is therefore a per-stage-sized ring packed
    into ONE flat buffer of ``sum_p 2(P-p)-1 = P²`` entries:
    **activation memory is exactly P²·mb·S·D, independent of M** — the
    1F1B memory contract that lets M (and with it the bubble term
    (P-1)/(M+P-1)) grow freely.  (A uniform 2P ring per stage — 2P²
    total — was the r3 allocation; the packed rings halve it to the
    schedule's true lower bound.)

    Timing (lockstep SPMD): ``M + 2P - 1`` ticks, each tick = one stage
    forward + one stage backward everywhere (≈3 fwd-units).  GPipe-via-AD
    spans ``2(M+P-1)`` half-ticks ≈ ``3(M+P-1)`` units — 1F1B trades
    ``3(P-1)`` extra units of drain for the M-independent memory.  Pick per
    job via ``pipeline_schedule`` ("gpipe" when activations fit, "1f1b"
    when they don't).

    Contract:
      stage_fn(stage_layer_params, x [mb,S,D], rng) -> x      (no aux)
      head_fn(head_params, y [mb,S,D], labels [mb,S]) -> loss (scaled —
        its vjp IS the gradient source; callers fold loss-scale/M here)
    Returns (losses [M] f32, dstage_params, dhead_params, dx_micro).
    """
    P_ = jax.tree_util.tree_leaves(stage_params)[0].shape[0]
    M = x_micro.shape[0]
    # per-stage ring sizes: stage p's activation lives 2(P-p)-1 ticks; the
    # rings pack contiguously into one flat [P²] buffer (global slot =
    # offset_p + m mod K_p; ranges are disjoint so the scatter is safe).
    # Under a pipe-sharded mesh GSPMD splits dim 0 evenly (P²/pp per shard
    # — the memory halving vs the old uniform 2P ring holds per-device);
    # rings straddle shard boundaries, so some tick gathers cross shards —
    # ~one state-sized transfer, same order as the roll's ppermute
    ring_np = 2 * (P_ - np.arange(P_)) - 1                   # [P] K_p
    ring_k = jnp.asarray(ring_np, jnp.int32)
    ring_off = jnp.asarray(
        np.concatenate([[0], np.cumsum(ring_np)[:-1]]), jnp.int32)
    stash_total = int(ring_np.sum())                         # = P²
    T = M + 2 * P_ - 1
    mb_shape = x_micro.shape[1:]

    # per-tick feeds, padded to T ticks
    zero_mb = jnp.zeros((1,) + mb_shape, x_micro.dtype)
    xs_in = jnp.concatenate(
        [x_micro, jnp.broadcast_to(zero_mb, (T - M,) + mb_shape)], axis=0)
    zero_lb = jnp.zeros((1,) + labels_micro.shape[1:], labels_micro.dtype)
    # head consumes the exit of tick t: microbatch t-(P-1)
    labels_pad = jnp.concatenate([
        jnp.broadcast_to(zero_lb, (P_ - 1,) + labels_micro.shape[1:]),
        labels_micro,
        jnp.broadcast_to(zero_lb, (T - M - P_ + 1,) + labels_micro.shape[1:]),
    ], axis=0)

    f32 = lambda t: jax.tree_util.tree_map(  # noqa: E731
        lambda g: g.astype(jnp.float32), t)

    def stage_bwd_one(lp, x, r, cot, mask):
        _, vjp = jax.vjp(lambda lp_, x_: stage_fn(lp_, x_, r), lp, x)
        dlp, dx = vjp(cot)
        m = mask.astype(jnp.float32)
        return (jax.tree_util.tree_map(lambda g: g.astype(jnp.float32) * m,
                                       dlp),
                dx * mask.astype(dx.dtype))

    sid = jnp.arange(P_)

    def tick(carry, inp):
        state, cot, stash, dstage, dhead, t = carry
        x_in, labels_t = inp

        # ---- backward half: bwd(m_b, p) at tick 2P-1-p+m_b ----
        m_b = t - (2 * P_ - 1 - sid)                        # [P]
        bwd_valid = (m_b >= 0) & (m_b < M)
        slot_b = ring_off + jnp.remainder(jnp.maximum(m_b, 0), ring_k)
        x_stash = stash[slot_b]                              # [P, mb, S, D]
        rngs_b = jax.vmap(
            lambda m, p: jax.random.fold_in(jax.random.fold_in(rng, m), p)
        )(jnp.maximum(m_b, 0), sid)
        dlp, dx = jax.vmap(stage_bwd_one)(stage_params, x_stash, rngs_b,
                                          cot, bwd_valid)
        dstage = jax.tree_util.tree_map(lambda a, g: a + g, dstage, dlp)
        dx_out = dx[0]                                       # stage 0 -> embed

        # ---- forward half: fwd(m_f, p) at tick p+m_f ----
        state = state.at[0].set(x_in)
        m_f = t - sid
        fwd_valid = (m_f >= 0) & (m_f < M)
        slot_f = ring_off + jnp.remainder(jnp.maximum(m_f, 0), ring_k)
        keep = fwd_valid.reshape((P_,) + (1,) * len(mb_shape))
        stash = stash.at[slot_f].set(jnp.where(keep, state, stash[slot_f]))
        rngs_f = jax.vmap(
            lambda m, p: jax.random.fold_in(jax.random.fold_in(rng, m), p)
        )(jnp.maximum(m_f, 0), sid)
        new_state = jax.vmap(stage_fn)(stage_params, state, rngs_f)

        # ---- head on this tick's exit (microbatch t-(P-1)) ----
        m_h = t - (P_ - 1)
        head_valid = ((m_h >= 0) & (m_h < M)).astype(jnp.float32)
        y = new_state[P_ - 1]
        loss_t, (dh, dy) = jax.value_and_grad(head_fn, argnums=(0, 1))(
            head_params, y, labels_t)
        dhead = jax.tree_util.tree_map(
            lambda a, g: a + g.astype(jnp.float32) * head_valid, dhead, dh)

        # ---- roll both wavefronts ----
        new_state = jnp.roll(new_state, 1, axis=0)           # stage s -> s+1
        # cot[p] <- dx from stage p+1's bwd; cot[P-1] <- head's dy
        new_cot = jnp.concatenate(
            [dx[1:], (dy * head_valid.astype(dy.dtype))[None]], axis=0)
        return ((new_state, new_cot, stash, dstage, dhead, t + 1),
                (loss_t * head_valid, dx_out))

    state0 = jnp.zeros((P_,) + mb_shape, x_micro.dtype)
    cot0 = jnp.zeros((P_,) + mb_shape, x_micro.dtype)
    stash0 = jnp.zeros((stash_total,) + mb_shape, x_micro.dtype)
    dstage0 = jax.tree_util.tree_map(
        lambda x: jnp.zeros(x.shape, jnp.float32), stage_params)
    dhead0 = jax.tree_util.tree_map(
        lambda x: jnp.zeros(x.shape, jnp.float32), head_params)
    (_, _, _, dstage, dhead, _), (losses_t, dxs_t) = jax.lax.scan(
        tick, (state0, cot0, stash0, dstage0, dhead0, jnp.int32(0)),
        (xs_in, labels_pad))
    # microbatch m's loss lands at tick P-1+m; its embed cotangent exits
    # stage 0's bwd at tick 2P-1+m
    losses = jax.lax.dynamic_slice_in_dim(losses_t, P_ - 1, M, 0)
    dx_micro = jax.lax.dynamic_slice_in_dim(dxs_t, 2 * P_ - 1, M, 0)
    return losses, dstage, dhead, dx_micro
