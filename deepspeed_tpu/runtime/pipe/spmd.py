"""SPMD pipeline executor (TPU-native redesign of ``runtime/pipe/engine.py``).

The reference runs pipeline parallelism as a per-rank instruction stream
(1F1B ``TrainSchedule``, schedule.py:189) with explicit p2p sends of
activations between stage processes (pipe/engine.py:913-1104, p2p.py:50).
Under a single SPMD program that structure collapses into a *shifted-buffer
scan*:

  - layer params are stacked [P, Lp, ...] and sharded over the 'pipe' mesh
    axis — each pipe shard holds its stage's layers;
  - the live state is one [P, mb, S, D] buffer, stage-sharded on dim 0;
  - each scan step vmaps the stage body over P (every stage computes in
    parallel on its current microbatch) then rolls the buffer one stage
    forward — XLA lowers the roll of a pipe-sharded dim to a
    ``collective_permute`` over ICI, the analogue of p2p.send/recv;
  - microbatch t enters stage 0 at step t and exits stage P-1 at step
    t+P-1; total steps M + P - 1, bubble (P-1)/(M+P-1) (GPipe fill/drain —
    the 1F1B memory shape comes from per-microbatch remat instead of
    activation stashes).

Backward needs no schedule at all: AD of the scan replays the same wavefront
in reverse, and the transposed collective_permute carries the activation
grads the reference moves with SendGrad/RecvGrad.
"""
from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp


def pipeline_apply(stage_fn: Callable[[Any, jnp.ndarray, jnp.ndarray], Tuple[jnp.ndarray, jnp.ndarray]],
                   stage_params: Any, x_micro: jnp.ndarray,
                   rng: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Run M microbatches through P pipeline stages.

    stage_fn(stage_layer_params, x [mb,S,D], rng) -> (x, aux) — one stage's
    layer stack, vmapped over the leading [P] dim of ``stage_params``.
    x_micro: [M, mb, S, D] embedded microbatches.
    Returns (y_micro [M, mb, S, D], aux_sum).
    """
    P_ = jax.tree_util.tree_leaves(stage_params)[0].shape[0]
    M = x_micro.shape[0]
    state = jnp.zeros((P_,) + x_micro.shape[1:], x_micro.dtype)
    pad = jnp.zeros((P_ - 1,) + x_micro.shape[1:], x_micro.dtype)
    xs = jnp.concatenate([x_micro, pad], axis=0)          # [M+P-1, mb, S, D]

    def step(carry, inp):
        state, t = carry
        x_in, = inp
        state = state.at[0].set(x_in)
        rngs = jax.vmap(lambda s: jax.random.fold_in(
            jax.random.fold_in(rng, t), s))(jnp.arange(P_))
        state, aux = jax.vmap(stage_fn)(stage_params, state, rngs)
        # during fill/drain a stage computes on zero padding; mask its aux
        sid = jnp.arange(P_)
        valid = (t >= sid) & (t < sid + M)
        out = state[P_ - 1]
        state = jnp.roll(state, 1, axis=0)                # stage s -> s+1
        return (state, t + 1), (out, jnp.sum(aux * valid))

    (_, _), (outs, auxs) = jax.lax.scan(step, (state, jnp.int32(0)), (xs,))
    # microbatch t exits at scan step t + P - 1
    return outs[P_ - 1:], jnp.sum(auxs)


def stage_layer_count(num_layers: int, num_stages: int) -> int:
    if num_layers % num_stages:
        raise ValueError(
            f"num_layers={num_layers} not divisible by pipeline stages={num_stages}")
    return num_layers // num_stages
