"""PipelineModule / LayerSpec (reference ``runtime/pipe/module.py:85``).

The reference lazily builds per-stage torch modules from ``LayerSpec`` lists
and partitions layers across stages by parameter count or uniformly
(module.py: "parameters"/"uniform" balancing).  The TPU analogue keeps the
same authoring surface — a list of layer thunks + a partitioner — and is a
full *model* the engine can train (``deepspeed_tpu.initialize(model=pm)``):

  - layer contract: ``spec.build()`` returns an object with
    ``init(rng) -> params`` and ``apply(params, x) -> x`` (or
    ``(x, aux)``); bare callables with no ``init`` are parameterless.
  - ``num_stages == 1``: layers compose sequentially under one jit —
    heterogeneous structures, tied weights, everything goes.
  - ``num_stages > 1``: executes on the SPMD shifted-buffer scan
    (spmd.pipeline_apply) over the mesh's 'pipe' axis.  SPMD pipelining
    vmaps ONE stage program over all stages, so the stages must be
    structurally identical (same layer count, same param treedef/shapes) —
    the partitioner checks this and says so.  Embedding/head-style
    first/last asymmetry belongs outside the pipelined body (the
    transformer family does exactly that: models/transformer.py embeds
    before ``pipeline_apply`` and projects after).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import numpy as np


class LayerSpec:
    """Deferred layer construction (reference module.py:29)."""

    def __init__(self, typename: Callable, *args, **kwargs):
        self.typename = typename
        self.args = args
        self.kwargs = kwargs

    def build(self):
        return self.typename(*self.args, **self.kwargs)

    def __repr__(self):
        return f"LayerSpec({getattr(self.typename, '__name__', self.typename)})"


class TiedLayerSpec(LayerSpec):
    """Layers sharing parameters across stages (reference module.py:76) —
    e.g. tied input/output embeddings.  The SPMD build shares tied params by
    construction (one leaf in the pytree), so `key` only groups specs."""

    def __init__(self, key: str, typename: Callable, *args,
                 forward_fn: Optional[Callable] = None, **kwargs):
        super().__init__(typename, *args, **kwargs)
        self.key = key
        self.forward_fn = forward_fn


def partition_balanced(weights: Sequence[float], num_parts: int) -> List[int]:
    """Split ``weights`` into ``num_parts`` contiguous chunks minimizing the
    heaviest chunk (the reference's ds_utils.partition_balanced).  Returns
    part boundaries of length num_parts+1.  O(n^2 * p) DP — layer counts are
    small."""
    n = len(weights)
    if num_parts > n:
        raise ValueError(f"cannot split {n} layers into {num_parts} stages")
    prefix = np.concatenate([[0.0], np.cumsum(weights)])

    # dp[p][i] = minimal max-chunk-weight splitting first i items into p parts
    INF = float("inf")
    dp = np.full((num_parts + 1, n + 1), INF)
    cut = np.zeros((num_parts + 1, n + 1), dtype=int)
    dp[0][0] = 0.0
    for p in range(1, num_parts + 1):
        for i in range(p, n + 1):
            for j in range(p - 1, i):
                cost = max(dp[p - 1][j], prefix[i] - prefix[j])
                if cost < dp[p][i]:
                    dp[p][i] = cost
                    cut[p][i] = j
    bounds = [n]
    for p in range(num_parts, 0, -1):
        bounds.append(cut[p][bounds[-1]])
    return list(reversed(bounds))


class PipelineModule:
    """Authoring surface for layer-list pipelines.

    For the transformer family the engine path is ``TransformerConfig.
    pipeline_stages`` (uniform stages over identical blocks — scan-friendly).
    PipelineModule covers the reference's general case: heterogeneous layer
    lists, balanced partitioning, tied weights.
    """

    def __init__(self, layers: Sequence[LayerSpec], num_stages: int,
                 partition_method: str = "parameters",
                 loss_fn: Optional[Callable] = None,
                 microbatches: Optional[int] = None):
        self.layer_specs = list(layers)
        self.num_stages = num_stages
        self.partition_method = partition_method
        # the training loss head, loss_fn(outputs, batch) -> scalar.  (Named
        # loss_fn in the ctor for reference parity — PipelineModule(…,
        # loss_fn=…) — but stored apart from the engine-facing
        # ``self.loss_fn`` method, which wraps it with the pipeline run.)
        self.loss_head = loss_fn
        self._microbatches = microbatches
        self.parts = self._partition()

    def _layer_weights(self) -> List[float]:
        if self.partition_method == "uniform":
            return [1.0] * len(self.layer_specs)
        if self.partition_method == "parameters":
            weights = []
            for spec in self.layer_specs:
                built = spec.build() if isinstance(spec, LayerSpec) else spec
                count = getattr(built, "param_count", None)
                weights.append(float(count) if count is not None else 1.0)
            return weights
        raise ValueError(f"unknown partition_method {self.partition_method}")

    def _partition(self) -> List[int]:
        return partition_balanced(self._layer_weights(), self.num_stages)

    def stage_layers(self, stage_id: int) -> List[LayerSpec]:
        lo, hi = self.parts[stage_id], self.parts[stage_id + 1]
        return self.layer_specs[lo:hi]

    def stage_of_layer(self, layer_idx: int) -> int:
        for s in range(self.num_stages):
            if self.parts[s] <= layer_idx < self.parts[s + 1]:
                return s
        raise IndexError(layer_idx)

    def tied_keys(self) -> Dict[str, List[int]]:
        tied: Dict[str, List[int]] = {}
        for i, spec in enumerate(self.layer_specs):
            if isinstance(spec, TiedLayerSpec):
                tied.setdefault(spec.key, []).append(i)
        return tied

    # ------------------------------------------------------------------
    # Execution path: the engine model contract (init_fn / loss_fn /
    # param_specs / config), reference PipelineEngine.train_batch
    # (runtime/pipe/engine.py:286) collapsed onto the SPMD executor.
    # ------------------------------------------------------------------

    @property
    def config(self):
        micro = self._microbatches or self.num_stages
        return _PipeModuleConfig(pipeline_stages=self.num_stages,
                                 pipeline_microbatches=micro)

    def _built(self) -> List[Any]:
        if not hasattr(self, "_built_layers"):
            self._built_layers = [
                spec.build() if isinstance(spec, LayerSpec) else spec
                for spec in self.layer_specs]
        return self._built_layers

    def _uniform_stage_shape(self, inits) -> None:
        """num_stages>1 precondition: every stage identical in structure."""
        import jax

        counts = {self.parts[s + 1] - self.parts[s]
                  for s in range(self.num_stages)}
        if self.tied_keys() and self.num_stages > 1:
            raise ValueError(
                "TiedLayerSpec is not supported on the SPMD pipeline path: "
                "one stage program is vmapped over all stages, so "
                "cross-stage parameter sharing has no home.  Keep tied "
                "embeddings/heads outside the pipelined body (see "
                "models/transformer.py) or use num_stages=1.")
        if len(counts) != 1:
            raise ValueError(
                f"SPMD pipelining needs structurally identical stages; "
                f"partition {self.parts} gives unequal layer counts "
                f"{sorted(counts)}.  Use partition_method='uniform' with a "
                f"layer count divisible by num_stages.")
        lp = counts.pop()
        ref = inits[:lp]
        for s in range(1, self.num_stages):
            seg = inits[s * lp:(s + 1) * lp]
            same = (jax.tree_util.tree_structure(seg)
                    == jax.tree_util.tree_structure(ref)) and all(
                a.shape == b.shape and a.dtype == b.dtype
                for a, b in zip(jax.tree_util.tree_leaves(seg),
                                jax.tree_util.tree_leaves(ref)))
            if not same:
                raise ValueError(
                    f"SPMD pipelining needs structurally identical stages; "
                    f"stage {s} differs from stage 0 in param "
                    f"treedef/shapes.")

    def init_fn(self, rng):
        from ...utils.init_on_device import on_device_init

        return on_device_init(self._init_impl)(rng)

    def _init_impl(self, rng):
        import jax
        import jax.numpy as jnp

        layers = self._built()
        keys = jax.random.split(rng, len(layers))
        tied_params: Dict[str, Any] = {}
        inits: List[Any] = []
        for i, (spec, layer, k) in enumerate(
                zip(self.layer_specs, layers, keys)):
            if isinstance(spec, TiedLayerSpec):
                if spec.key not in tied_params:
                    tied_params[spec.key] = layer.init(k)
                inits.append(_TiedRef(spec.key))
            elif hasattr(layer, "init"):
                inits.append(layer.init(k))
            else:
                inits.append({})                  # parameterless callable
        if self.num_stages > 1:
            self._uniform_stage_shape(inits)
            lp = len(layers) // self.num_stages
            # stack per-stage trees leaf-wise: [P, ...] rides the 'pipe' axis
            per_stage = [
                jax.tree_util.tree_map(
                    lambda *xs: jnp.stack(xs),
                    *[_stage_tree(inits[s * lp + j]) for s in range(self.num_stages)])
                for j in range(lp)]
            return {"stages": per_stage}
        return {"layers": inits, "tied": tied_params}

    @property
    def param_specs(self):
        from jax.sharding import PartitionSpec as P

        if self.num_stages <= 1:
            return None                           # planner default (replicated)
        shapes = jax.eval_shape(self._init_impl, jax.random.PRNGKey(0))
        # stage dim of every stacked leaf rides 'pipe'; inner dims replicated
        return jax.tree_util.tree_map(
            lambda x: P(*("pipe",) + (None,) * (x.ndim - 1)), shapes)

    def loss_fn(self, params, batch, rng=None):
        import jax
        import jax.numpy as jnp

        if self.loss_head is None:
            raise ValueError("PipelineModule needs loss_fn=(outputs, batch) "
                             "-> scalar to train")
        layers = self._built()
        x = batch["inputs"] if isinstance(batch, dict) else batch
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        aux_total = jnp.float32(0.0)
        if self.num_stages > 1:
            from .spmd import pipeline_apply

            M = self._microbatches or self.num_stages
            B = x.shape[0]
            if B % M:
                raise ValueError(
                    f"batch {B} not divisible by {M} pipeline microbatches")
            lp = len(layers) // self.num_stages
            stage_layers = layers[:lp]            # stages are uniform

            def stage_fn(lp_params, xs, srng):
                # layers are applied deterministically (the layer contract
                # carries no rng); srng is pipeline_apply plumbing only
                del srng
                aux = jnp.float32(0.0)
                for j, layer in enumerate(stage_layers):
                    xs, a = _apply(layer, lp_params[j], xs)
                    aux = aux + a
                return xs, aux

            xm = x.reshape((M, B // M) + x.shape[1:])
            y, aux_sum = pipeline_apply(stage_fn, params["stages"], xm, rng)
            out = y.reshape((B,) + y.shape[2:])
            aux_total = aux_sum / M
        else:
            tied = params.get("tied", {})
            out = x
            for spec, layer, p in zip(self.layer_specs, layers,
                                      params["layers"]):
                if isinstance(p, _TiedRef):
                    p = tied[p.key]
                    fwd = getattr(spec, "forward_fn", None)
                    if fwd is not None:
                        # tied reuse with its own forward (reference
                        # TiedLayerSpec(forward_fn=...): e.g. the embedding
                        # weights applied transposed as the output head)
                        out = fwd(p, out)
                        continue
                out, a = _apply(layer, p, out)
                aux_total = aux_total + a
        return self.loss_head(out, batch) + aux_total


@dataclasses.dataclass(frozen=True)
class _PipeModuleConfig:
    pipeline_stages: int
    pipeline_microbatches: int


@jax.tree_util.register_static
@dataclasses.dataclass(frozen=True)
class _TiedRef:
    """Placeholder leaf pointing a tied layer at its shared parameters."""
    key: str


def _stage_tree(t):
    if isinstance(t, _TiedRef):  # unreachable (tied rejected for pipe>1)
        raise ValueError("tied params cannot be stage-stacked")
    return t


def _apply(layer, p, x):
    """Layer call normalizer: returns (x, aux)."""
    import jax.numpy as jnp

    fn = getattr(layer, "apply", layer)
    out = fn(p, x) if (hasattr(layer, "apply") or hasattr(layer, "init")) \
        else fn(x)
    if isinstance(out, tuple):
        return out[0], jnp.float32(out[1])
    return out, jnp.float32(0.0)
