"""PipelineModule / LayerSpec (reference ``runtime/pipe/module.py:85``).

The reference lazily builds per-stage torch modules from ``LayerSpec`` lists
and partitions layers across stages by parameter count or uniformly
(module.py: "parameters"/"uniform" balancing).  The TPU analogue keeps the
same authoring surface — a list of layer thunks + a partitioner — but the
product is a *stacked-parameter pytree* plus stage boundaries for the SPMD
executor (spmd.py), not live modules.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np


class LayerSpec:
    """Deferred layer construction (reference module.py:29)."""

    def __init__(self, typename: Callable, *args, **kwargs):
        self.typename = typename
        self.args = args
        self.kwargs = kwargs

    def build(self):
        return self.typename(*self.args, **self.kwargs)

    def __repr__(self):
        return f"LayerSpec({getattr(self.typename, '__name__', self.typename)})"


class TiedLayerSpec(LayerSpec):
    """Layers sharing parameters across stages (reference module.py:76) —
    e.g. tied input/output embeddings.  The SPMD build shares tied params by
    construction (one leaf in the pytree), so `key` only groups specs."""

    def __init__(self, key: str, typename: Callable, *args,
                 forward_fn: Optional[Callable] = None, **kwargs):
        super().__init__(typename, *args, **kwargs)
        self.key = key
        self.forward_fn = forward_fn


def partition_balanced(weights: Sequence[float], num_parts: int) -> List[int]:
    """Split ``weights`` into ``num_parts`` contiguous chunks minimizing the
    heaviest chunk (the reference's ds_utils.partition_balanced).  Returns
    part boundaries of length num_parts+1.  O(n^2 * p) DP — layer counts are
    small."""
    n = len(weights)
    if num_parts > n:
        raise ValueError(f"cannot split {n} layers into {num_parts} stages")
    prefix = np.concatenate([[0.0], np.cumsum(weights)])

    # dp[p][i] = minimal max-chunk-weight splitting first i items into p parts
    INF = float("inf")
    dp = np.full((num_parts + 1, n + 1), INF)
    cut = np.zeros((num_parts + 1, n + 1), dtype=int)
    dp[0][0] = 0.0
    for p in range(1, num_parts + 1):
        for i in range(p, n + 1):
            for j in range(p - 1, i):
                cost = max(dp[p - 1][j], prefix[i] - prefix[j])
                if cost < dp[p][i]:
                    dp[p][i] = cost
                    cut[p][i] = j
    bounds = [n]
    for p in range(num_parts, 0, -1):
        bounds.append(cut[p][bounds[-1]])
    return list(reversed(bounds))


class PipelineModule:
    """Authoring surface for layer-list pipelines.

    For the transformer family the engine path is ``TransformerConfig.
    pipeline_stages`` (uniform stages over identical blocks — scan-friendly).
    PipelineModule covers the reference's general case: heterogeneous layer
    lists, balanced partitioning, tied weights.
    """

    def __init__(self, layers: Sequence[LayerSpec], num_stages: int,
                 partition_method: str = "parameters",
                 loss_fn: Optional[Callable] = None):
        self.layer_specs = list(layers)
        self.num_stages = num_stages
        self.partition_method = partition_method
        self.loss_fn = loss_fn
        self.parts = self._partition()

    def _layer_weights(self) -> List[float]:
        if self.partition_method == "uniform":
            return [1.0] * len(self.layer_specs)
        if self.partition_method == "parameters":
            weights = []
            for spec in self.layer_specs:
                built = spec.build() if isinstance(spec, LayerSpec) else spec
                count = getattr(built, "param_count", None)
                weights.append(float(count) if count is not None else 1.0)
            return weights
        raise ValueError(f"unknown partition_method {self.partition_method}")

    def _partition(self) -> List[int]:
        return partition_balanced(self._layer_weights(), self.num_stages)

    def stage_layers(self, stage_id: int) -> List[LayerSpec]:
        lo, hi = self.parts[stage_id], self.parts[stage_id + 1]
        return self.layer_specs[lo:hi]

    def stage_of_layer(self, layer_idx: int) -> int:
        for s in range(self.num_stages):
            if self.parts[s] <= layer_idx < self.parts[s + 1]:
                return s
        raise IndexError(layer_idx)

    def tied_keys(self) -> Dict[str, List[int]]:
        tied: Dict[str, List[int]] = {}
        for i, spec in enumerate(self.layer_specs):
            if isinstance(spec, TiedLayerSpec):
                tied.setdefault(spec.key, []).append(i)
        return tied
