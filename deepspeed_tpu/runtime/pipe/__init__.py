"""Pipeline parallelism (reference ``runtime/pipe/`` + ``deepspeed/pipe``).

Production path: the SPMD shifted-buffer executor (:mod:`spmd`), driven from
``TransformerConfig.pipeline_stages`` or a :class:`PipelineModule`.
"""
from .spmd import pipeline_apply, stage_layer_count
from .module import LayerSpec, PipelineModule, TiedLayerSpec, partition_balanced
from .schedule import (InferenceSchedule, PipeSchedule, TrainSchedule,
                       ForwardPass, BackwardPass, LoadMicroBatch, OptimizerStep,
                       RecvActivation, RecvGrad, ReduceGrads, ReduceTiedGrads,
                       SendActivation, SendGrad)

__all__ = ["pipeline_apply", "stage_layer_count", "LayerSpec", "PipelineModule",
           "TiedLayerSpec", "partition_balanced", "PipeSchedule", "TrainSchedule",
           "InferenceSchedule", "ForwardPass", "BackwardPass", "LoadMicroBatch",
           "OptimizerStep", "RecvActivation", "RecvGrad", "ReduceGrads",
           "ReduceTiedGrads", "SendActivation", "SendGrad"]
