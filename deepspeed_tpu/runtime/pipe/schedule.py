"""Pipeline schedules (reference ``runtime/pipe/schedule.py``).

The reference drives each rank through these instruction streams at runtime
(``PipelineEngine._exec_schedule``, pipe/engine.py:1293).  In the TPU build
the production executor is the SPMD shifted-buffer scan (spmd.py) — XLA owns
the overlap — so these classes serve as the *planning and analysis* layer:
they enumerate exactly which (stage, microbatch, phase) work units run at
each tick, power the scheduling tests, and document the 1F1B semantics the
SPMD program realizes.  API parity: ``PipeSchedule`` (:11), ``TrainSchedule``
(:189) with its step→microbatch mapping (:258-298), ``InferenceSchedule``
(:135), instruction classes (:327-487).
"""
from __future__ import annotations

from typing import Iterator, List


class PipeInstruction:
    def __init__(self, **kwargs):
        self.name = self.__class__.__name__
        self.kwargs = kwargs
        for k, v in kwargs.items():
            setattr(self, k, v)

    def __repr__(self):
        if self.kwargs:
            args = ", ".join(f"{k}={v}" for k, v in self.kwargs.items())
            return f"{self.name}({args})"
        return self.name

    def __eq__(self, other):
        return (type(self) is type(other)) and self.kwargs == other.kwargs

    def __hash__(self):
        return hash((type(self), tuple(sorted(self.kwargs.items()))))


class OptimizerStep(PipeInstruction):
    pass


class ReduceGrads(PipeInstruction):
    pass


class ReduceTiedGrads(PipeInstruction):
    pass


class LoadMicroBatch(PipeInstruction):
    pass  # kwargs: buffer_id


class ForwardPass(PipeInstruction):
    pass  # kwargs: buffer_id


class BackwardPass(PipeInstruction):
    pass  # kwargs: buffer_id


class SendActivation(PipeInstruction):
    pass  # kwargs: buffer_id


class RecvActivation(PipeInstruction):
    pass  # kwargs: buffer_id


class SendGrad(PipeInstruction):
    pass  # kwargs: buffer_id


class RecvGrad(PipeInstruction):
    pass  # kwargs: buffer_id


class PipeSchedule:
    """Enumerates the instruction stream for one (stage, #microbatch) pair."""

    def __init__(self, micro_batches: int, stages: int, stage_id: int):
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = stage_id - 1
        self.next_stage = stage_id + 1

    def steps(self) -> Iterator[List[PipeInstruction]]:
        raise NotImplementedError

    @property
    def num_micro_batches(self) -> int:
        return self.micro_batches

    @property
    def is_first_stage(self) -> bool:
        return self.stage_id == 0

    @property
    def is_last_stage(self) -> bool:
        return self.stage_id == self.stages - 1

    def _valid_micro_batch(self, micro_batch_id: int) -> bool:
        return 0 <= micro_batch_id < self.micro_batches

    def _valid_stage(self, stage_id: int) -> bool:
        return 0 <= stage_id < self.stages

    def num_pipe_buffers(self) -> int:
        return self.micro_batches

    def __iter__(self):
        return iter(self.steps())


class InferenceSchedule(PipeSchedule):
    """Forward-only wavefront (reference :135)."""

    def steps(self):
        total_steps = self.micro_batches + self.stages - 1
        for step_id in range(total_steps):
            micro_batch_id = step_id - self.stage_id
            cmds: List[PipeInstruction] = []
            if self._valid_micro_batch(micro_batch_id):
                if self.is_first_stage:
                    cmds.append(LoadMicroBatch(buffer_id=self._buffer_idx(micro_batch_id)))
                else:
                    cmds.append(RecvActivation(buffer_id=self._buffer_idx(micro_batch_id)))
                cmds.append(ForwardPass(buffer_id=self._buffer_idx(micro_batch_id)))
                if not self.is_last_stage:
                    cmds.append(SendActivation(buffer_id=self._buffer_idx(micro_batch_id)))
            yield cmds

    def num_pipe_buffers(self) -> int:
        return 2

    def _buffer_idx(self, micro_batch_id: int) -> int:
        return micro_batch_id % self.num_pipe_buffers()


class TrainSchedule(PipeSchedule):
    """1F1B (reference :189): steady-state alternates one forward with one
    backward; early steps fill, late steps drain.  Total 2*(M + S - 1) ticks;
    peak activation stash = num_pipe_buffers() microbatches."""

    def steps(self):
        prev_micro_batch_id = -1
        total_steps = 2 * (self.micro_batches + self.stages - 1)
        for step_id in range(total_steps):
            micro_batch_id, is_forward = self._step_to_micro_batch(step_id)
            cmds: List[PipeInstruction] = []

            # exchange activations/grads with neighbors
            if self._valid_micro_batch(prev_micro_batch_id):
                if is_forward:
                    if self._valid_stage(self.prev_stage):
                        cmds.append(SendGrad(buffer_id=self._buffer_idx(prev_micro_batch_id)))
                else:
                    if self._valid_stage(self.next_stage):
                        cmds.append(SendActivation(buffer_id=self._buffer_idx(prev_micro_batch_id)))
            if self._valid_micro_batch(micro_batch_id):
                if is_forward:
                    if self._valid_stage(self.prev_stage):
                        cmds.append(RecvActivation(buffer_id=self._buffer_idx(micro_batch_id)))
                    else:
                        cmds.append(LoadMicroBatch(buffer_id=self._buffer_idx(micro_batch_id)))
                    cmds.append(ForwardPass(buffer_id=self._buffer_idx(micro_batch_id)))
                else:
                    if self._valid_stage(self.next_stage):
                        cmds.append(RecvGrad(buffer_id=self._buffer_idx(micro_batch_id)))
                    cmds.append(BackwardPass(buffer_id=self._buffer_idx(micro_batch_id)))

            # final tick: reduce + step
            if step_id == total_steps - 1:
                cmds.append(ReduceTiedGrads())
                cmds.append(ReduceGrads())
                cmds.append(OptimizerStep())

            prev_micro_batch_id = micro_batch_id
            yield cmds

    def _step_to_micro_batch(self, step_id: int):
        """Reference :258-298: even ticks run forwards, odd ticks backwards,
        offset by the stage id."""
        if _is_even(step_id) and _is_even(self.stage_id):
            micro_batch_id = self._even_step_forward_id(step_id)
            is_forward = True
        elif _is_odd(step_id) and _is_odd(self.stage_id):
            micro_batch_id = self._odd_step_forward_id(step_id)
            is_forward = True
        elif _is_even(step_id) and _is_odd(self.stage_id):
            micro_batch_id = self._even_step_backward_id(step_id)
            is_forward = False
        else:
            micro_batch_id = self._odd_step_backward_id(step_id)
            is_forward = False
        return micro_batch_id, is_forward

    def _even_step_forward_id(self, step_id):
        base = step_id // 2
        return base - self.stage_id // 2

    def _odd_step_forward_id(self, step_id):
        base = (step_id - 1) // 2
        return base - self.stage_id // 2

    def _even_step_backward_id(self, step_id):
        base = step_id // 2
        return base - self.stages + (self.stage_id + 1) // 2

    def _odd_step_backward_id(self, step_id):
        base = ((step_id - 1) // 2) - self.stages + 1
        return base + self.stage_id // 2

    def num_pipe_buffers(self) -> int:
        buffers = min(self.stages - self.stage_id, self.micro_batches)
        return max(2, buffers)

    def _buffer_idx(self, micro_batch_id: int) -> int:
        assert self._valid_micro_batch(micro_batch_id)
        return micro_batch_id % self.num_pipe_buffers()


def _is_even(x: int) -> bool:
    return x % 2 == 0


def _is_odd(x: int) -> bool:
    return x % 2 != 0
