"""Pipeline schedules (reference ``runtime/pipe/schedule.py``).

The reference drives each rank through these instruction streams at runtime
(``PipelineEngine._exec_schedule``, pipe/engine.py:1293).  In the TPU build
the production executor is the SPMD shifted-buffer scan (spmd.py) — XLA owns
the overlap — so these classes serve as the *planning and analysis* layer:
they enumerate exactly which (stage, microbatch, phase) work units run at
each tick, power the scheduling tests, and document the 1F1B semantics the
SPMD program realizes.  API parity: ``PipeSchedule`` (:11), ``TrainSchedule``
(:189) with its step→microbatch mapping (:258-298), ``InferenceSchedule``
(:135), instruction classes (:327-487).
"""
from __future__ import annotations

from typing import Iterator, List


class PipeInstruction:
    def __init__(self, **kwargs):
        self.name = self.__class__.__name__
        self.kwargs = kwargs
        for k, v in kwargs.items():
            setattr(self, k, v)

    def __repr__(self):
        if self.kwargs:
            args = ", ".join(f"{k}={v}" for k, v in self.kwargs.items())
            return f"{self.name}({args})"
        return self.name

    def __eq__(self, other):
        return (type(self) is type(other)) and self.kwargs == other.kwargs

    def __hash__(self):
        return hash((type(self), tuple(sorted(self.kwargs.items()))))


class OptimizerStep(PipeInstruction):
    pass


class ReduceGrads(PipeInstruction):
    pass


class ReduceTiedGrads(PipeInstruction):
    pass


class LoadMicroBatch(PipeInstruction):
    pass  # kwargs: buffer_id


class ForwardPass(PipeInstruction):
    pass  # kwargs: buffer_id


class BackwardPass(PipeInstruction):
    pass  # kwargs: buffer_id


class SendActivation(PipeInstruction):
    pass  # kwargs: buffer_id


class RecvActivation(PipeInstruction):
    pass  # kwargs: buffer_id


class SendGrad(PipeInstruction):
    pass  # kwargs: buffer_id


class RecvGrad(PipeInstruction):
    pass  # kwargs: buffer_id


class PipeSchedule:
    """Enumerates the instruction stream for one (stage, #microbatch) pair."""

    def __init__(self, micro_batches: int, stages: int, stage_id: int):
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = stage_id - 1
        self.next_stage = stage_id + 1

    def steps(self) -> Iterator[List[PipeInstruction]]:
        raise NotImplementedError

    @property
    def num_micro_batches(self) -> int:
        return self.micro_batches

    @property
    def is_first_stage(self) -> bool:
        return self.stage_id == 0

    @property
    def is_last_stage(self) -> bool:
        return self.stage_id == self.stages - 1

    def _valid_micro_batch(self, micro_batch_id: int) -> bool:
        return 0 <= micro_batch_id < self.micro_batches

    def _valid_stage(self, stage_id: int) -> bool:
        return 0 <= stage_id < self.stages

    def num_pipe_buffers(self) -> int:
        return self.micro_batches

    def __iter__(self):
        return iter(self.steps())


class InferenceSchedule(PipeSchedule):
    """Forward-only wavefront (reference :135)."""

    def steps(self):
        total_steps = self.micro_batches + self.stages - 1
        for step_id in range(total_steps):
            micro_batch_id = step_id - self.stage_id
            cmds: List[PipeInstruction] = []
            if self._valid_micro_batch(micro_batch_id):
                if not self.is_first_stage:
                    cmds.append(RecvActivation(buffer_id=self._buffer_idx(micro_batch_id)))
                if self.is_first_stage or self.is_last_stage:
                    # first stage loads inputs; last stage loads labels
                    cmds.append(LoadMicroBatch(buffer_id=self._buffer_idx(micro_batch_id)))
                cmds.append(ForwardPass(buffer_id=self._buffer_idx(micro_batch_id)))
                if not self.is_last_stage:
                    cmds.append(SendActivation(buffer_id=self._buffer_idx(micro_batch_id)))
            yield cmds

    def num_pipe_buffers(self) -> int:
        return 2

    def _buffer_idx(self, micro_batch_id: int) -> int:
        return micro_batch_id % self.num_pipe_buffers()


class TrainSchedule(PipeSchedule):
    """Synchronous 1F1B on a global wavefront clock.

    Derivation (original, replaces the reference's four parity-case helpers
    with one closed form).  Put all S stages on one shared clock where every
    tick is either a forward slot or a backward slot for a given stage:

    * Forward of microbatch ``m`` enters stage 0 at tick ``2m`` and ripples
      down one stage per tick, so on stage ``s`` it fires at

          t_fwd(m, s) = s + 2m

    * The loss for microbatch ``m`` is ready when the last stage finishes its
      forward, and the backward wave ripples back *up* one stage per tick:

          t_bwd(m, s) = (2S - 1 - s) + 2m

      (on the last stage this is t_fwd + 1: backward immediately follows
      forward — the 1F1B steady state).

    Because ``t_fwd - s`` is even and ``t_bwd + s`` is odd, each tick is
    unambiguously a forward or a backward slot for a stage — stage ``s`` runs
    forwards on ticks with the same parity as ``s`` and backwards on the
    opposite parity, alternating 1F/1B once full.  The last backward
    (m = M-1, s = 0) lands on tick 2(M + S - 1) - 1, giving the familiar
    2(M + S - 1) total ticks.

    Activation-stash bound: forward ``m + B`` on stage ``s`` overwrites
    buffer ``m % B``; safety requires t_bwd(m, s) < t_fwd(m + B, s), i.e.
    B >= S - s — deeper stages retire activations sooner, so the stash
    shrinks linearly toward the last stage.
    """

    def steps(self):
        prev_micro_batch_id = -1
        total_steps = 2 * (self.micro_batches + self.stages - 1)
        for step_id in range(total_steps):
            micro_batch_id, is_forward = self._work_at_tick(step_id)
            cmds: List[PipeInstruction] = []

            # Ship the previous tick's product to the neighbor that needs it
            # this tick: a finished forward feeds the next stage, a finished
            # backward feeds grads to the previous stage.
            if self._valid_micro_batch(prev_micro_batch_id):
                if is_forward:
                    if self._valid_stage(self.prev_stage):
                        cmds.append(SendGrad(buffer_id=self._buffer_idx(prev_micro_batch_id)))
                else:
                    if self._valid_stage(self.next_stage):
                        cmds.append(SendActivation(buffer_id=self._buffer_idx(prev_micro_batch_id)))
            if self._valid_micro_batch(micro_batch_id):
                buf = self._buffer_idx(micro_batch_id)
                if is_forward:
                    if self._valid_stage(self.prev_stage):
                        cmds.append(RecvActivation(buffer_id=buf))
                    if self.is_first_stage or self.is_last_stage:
                        # First stage loads inputs; last stage loads labels.
                        cmds.append(LoadMicroBatch(buffer_id=buf))
                    cmds.append(ForwardPass(buffer_id=buf))
                else:
                    if self._valid_stage(self.next_stage):
                        cmds.append(RecvGrad(buffer_id=buf))
                    cmds.append(BackwardPass(buffer_id=buf))

            # final tick: reduce + step
            if step_id == total_steps - 1:
                cmds.append(ReduceTiedGrads())
                cmds.append(ReduceGrads())
                cmds.append(OptimizerStep())

            prev_micro_batch_id = micro_batch_id
            yield cmds

    def _work_at_tick(self, tick: int):
        """Invert the wavefront formulas: which (microbatch, phase) does this
        stage run at ``tick``?  The returned microbatch may be out of range
        (fill/drain bubbles); callers filter with ``_valid_micro_batch``."""
        if (tick - self.stage_id) % 2 == 0:
            return (tick - self.stage_id) // 2, True
        return (tick - (2 * self.stages - 1 - self.stage_id)) // 2, False

    def num_pipe_buffers(self) -> int:
        # B >= S - s from the stash bound above; >=2 for send/compute overlap.
        return max(2, min(self.stages - self.stage_id, self.micro_batches))

    def _buffer_idx(self, micro_batch_id: int) -> int:
        assert self._valid_micro_batch(micro_batch_id)
        return micro_batch_id % self.num_pipe_buffers()
