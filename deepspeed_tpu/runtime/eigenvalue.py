"""Hessian eigenvalue estimation (reference ``runtime/eigenvalue.py:12``).

The reference power-iterates with torch double-backward per block and feeds
the per-layer values into MoQ's quantization scheduling.  JAX makes the core
primitive free: the Hessian-vector product is ``jvp`` of ``grad`` (forward-
over-reverse), one jittable function — no retain_graph bookkeeping, no
per-layer module walking.  Per-layer values fall out of the pytree structure:
the power iteration runs on the whole param tree and per-leaf Rayleigh
quotients are reported for layer-wise consumers.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple


import jax
import jax.numpy as jnp

from ..utils.logging import log_dist


def _tree_dot(a, b) -> jnp.ndarray:
    leaves = jax.tree_util.tree_map(
        lambda x, y: jnp.sum(x.astype(jnp.float32) * y.astype(jnp.float32)),
        a, b)
    return functools.reduce(jnp.add, jax.tree_util.tree_leaves(leaves))


def _tree_norm(a) -> jnp.ndarray:
    return jnp.sqrt(_tree_dot(a, a))


def _normalize(a):
    n = _tree_norm(a) + 1e-12
    # divide in fp32, return in each leaf's dtype (tangent-dtype contract)
    return jax.tree_util.tree_map(
        lambda x: (x.astype(jnp.float32) / n).astype(x.dtype), a)


def hvp(loss_fn: Callable, params: Any, batch: Any, rng, v: Any) -> Any:
    """Hessian-vector product at ``params`` along ``v`` (fwd-over-rev).

    Honors the engine's loss contract ``loss | (loss, aux_dict)``."""
    def scalar_loss(p):
        out = loss_fn(p, batch, rng)
        return out[0] if isinstance(out, tuple) else out

    grad_fn = jax.grad(scalar_loss)
    _, hv = jax.jvp(grad_fn, (params,), (v,))
    return hv


class Eigenvalue:
    """Power-iteration largest-eigenvalue estimator (reference :12).

    Config parity: ``eigenvalue`` block keys max_iter / tol / stability /
    verbose (device/layer knobs are meaningless here — the pytree IS the
    layer decomposition).
    """

    def __init__(self, verbose: bool = False, max_iter: int = 100,
                 tol: float = 1e-2, stability: float = 1e-6):
        self.verbose = verbose
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability
        self._hvp_cache: Dict[int, Callable] = {}

    def _jitted_hvp(self, loss_fn: Callable) -> Callable:
        """One compiled HVP per loss_fn — periodic (MoQ-style) callers must
        not pay a retrace per invocation."""
        key = id(loss_fn)
        if key not in self._hvp_cache:
            self._hvp_cache[key] = jax.jit(
                lambda p, b, r, vv: hvp(loss_fn, p, b, r, vv))
        return self._hvp_cache[key]

    def compute_eigenvalue(self, loss_fn: Callable, params: Any, batch: Any,
                           rng: Optional[jax.Array] = None
                           ) -> Tuple[float, Dict[str, float]]:
        """(lambda_max, per-leaf Rayleigh quotients).

        The per-leaf dict maps '/'-joined param paths to vᵀHv restricted to
        that leaf — the layer-wise signal the reference feeds MoQ.
        """
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        # tangents must match the primal dtype leaf-wise (bf16 params -> bf16
        # v); the Rayleigh/norm reductions still accumulate in fp32.
        # fold_in by leaf INDEX: deterministic across processes/runs (str-hash
        # is salted per interpreter) and distinct for same-shaped leaves
        leaves, treedef = jax.tree_util.tree_flatten(params)
        v = _normalize(jax.tree_util.tree_unflatten(treedef, [
            jax.random.normal(jax.random.fold_in(rng, i), x.shape,
                              jnp.float32).astype(x.dtype)
            for i, x in enumerate(leaves)]))

        hvp_fn = self._jitted_hvp(loss_fn)
        eig_prev = jnp.float32(0.0)
        eig = jnp.float32(0.0)
        for i in range(self.max_iter):
            hv = hvp_fn(params, batch, rng, v)
            eig = _tree_dot(v, hv)                       # Rayleigh quotient
            norm = _tree_norm(hv)
            if float(norm) < self.stability:
                break
            v = jax.tree_util.tree_map(
                lambda x: (x.astype(jnp.float32) / (norm + 1e-12))
                .astype(x.dtype), hv)
            if i > 0 and abs(float(eig - eig_prev)) <= \
                    self.tol * max(abs(float(eig)), 1e-12):
                break
            eig_prev = eig
        if self.verbose:
            log_dist(f"eigenvalue: lambda_max≈{float(eig):.4e} "
                     f"after {i + 1} iters", ranks=[0])

        hv = hvp_fn(params, batch, rng, v)
        per_leaf: Dict[str, float] = {}
        flat_v = jax.tree_util.tree_flatten_with_path(v)[0]
        flat_h = jax.tree_util.tree_leaves(hv)
        from ..utils.debug import path_str
        for (path, vl), hl in zip(flat_v, flat_h):
            per_leaf[path_str(path)] = float(jnp.sum(
                vl.astype(jnp.float32) * hl.astype(jnp.float32)))
        return float(eig), per_leaf
