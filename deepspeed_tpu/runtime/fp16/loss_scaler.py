"""Loss scaling (reference ``runtime/fp16/loss_scaler.py``: LossScaler /
DynamicLossScaler).

Functional re-design: scaler state is a small pytree carried in the TrainState
and updated *inside* the jitted step with ``lax`` control flow — the reference's
"check overflow → skip step → halve scale" becomes a ``jnp.where`` select on
the updated vs. previous params (SURVEY §7 "dynamic loss scaling / overflow
skip inside jit").
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class LossScaleState(NamedTuple):
    loss_scale: jnp.ndarray        # f32 scalar
    good_steps: jnp.ndarray        # i32: consecutive overflow-free steps
    hysteresis: jnp.ndarray        # i32: remaining tolerated overflows
    # static config packed as arrays so the state stays a pytree of leaves
    scale_window: jnp.ndarray      # i32
    min_scale: jnp.ndarray         # f32
    scale_factor: jnp.ndarray      # f32
    init_hysteresis: jnp.ndarray   # i32
    dynamic: jnp.ndarray           # bool


def static_loss_scale_state(loss_scale: float) -> LossScaleState:
    """Fixed scale (reference LossScaler)."""
    return LossScaleState(
        loss_scale=jnp.float32(loss_scale),
        good_steps=jnp.int32(0),
        hysteresis=jnp.int32(1),
        scale_window=jnp.int32(1),
        min_scale=jnp.float32(loss_scale),
        scale_factor=jnp.float32(1.0),
        init_hysteresis=jnp.int32(1),
        dynamic=jnp.bool_(False),
    )


def dynamic_loss_scale_state(initial_scale_power: int = 16, loss_scale_window: int = 1000,
                             min_loss_scale: float = 1.0, hysteresis: int = 2,
                             scale_factor: float = 2.0) -> LossScaleState:
    """Reference DynamicLossScaler defaults (loss_scaler.py)."""
    return LossScaleState(
        loss_scale=jnp.float32(2.0 ** initial_scale_power),
        good_steps=jnp.int32(0),
        hysteresis=jnp.int32(hysteresis),
        scale_window=jnp.int32(loss_scale_window),
        min_scale=jnp.float32(min_loss_scale),
        scale_factor=jnp.float32(scale_factor),
        init_hysteresis=jnp.int32(hysteresis),
        dynamic=jnp.bool_(True),
    )


def no_loss_scale_state() -> LossScaleState:
    return static_loss_scale_state(1.0)


def scale_loss(loss, state: LossScaleState):
    return loss * state.loss_scale.astype(loss.dtype)


def unscale_grads(grads, state: LossScaleState):
    inv = (1.0 / state.loss_scale).astype(jnp.float32)
    return jax.tree_util.tree_map(lambda g: g.astype(jnp.float32) * inv, grads)


def grads_finite(grads) -> jnp.ndarray:
    """Global all-finite check (the reference's has_overflow, inverted).

    Computed on already (or to-be) reduced grads; under pjit the reduction is
    global so every shard agrees — the reference's cross-rank overflow
    allreduce (stage_1_and_2.py ``has_overflow``) comes for free.
    """
    leaves = jax.tree_util.tree_leaves(grads)
    if not leaves:
        return jnp.bool_(True)
    finite = [jnp.all(jnp.isfinite(leaf)) for leaf in leaves]
    return jnp.stack(finite).all()


def update_scale(state: LossScaleState, is_finite) -> LossScaleState:
    """Post-step scale update (reference DynamicLossScaler.update_scale):

    - overflow: consume hysteresis; once exhausted, scale /= factor (>= min),
      reset the good-step counter
    - no overflow for `scale_window` consecutive steps: scale *= factor,
      reset counter and hysteresis
    """
    def on_finite(s: LossScaleState) -> LossScaleState:
        good = s.good_steps + 1
        grow = (good % s.scale_window) == 0
        new_scale = jnp.where(grow, s.loss_scale * s.scale_factor, s.loss_scale)
        return s._replace(loss_scale=new_scale, good_steps=good,
                          hysteresis=jnp.where(grow, s.init_hysteresis, s.hysteresis))

    def on_overflow(s: LossScaleState) -> LossScaleState:
        hys = s.hysteresis - 1
        drop = hys <= 0
        new_scale = jnp.where(drop, jnp.maximum(s.loss_scale / s.scale_factor, s.min_scale),
                              s.loss_scale)
        return s._replace(loss_scale=new_scale, good_steps=jnp.int32(0),
                          hysteresis=jnp.where(drop, s.init_hysteresis, hys))

    updated = jax.lax.cond(jnp.asarray(is_finite), on_finite, on_overflow, state)
    # static scaler: state never changes
    return jax.tree_util.tree_map(
        lambda new, old: jnp.where(state.dynamic, new, old), updated, state)
