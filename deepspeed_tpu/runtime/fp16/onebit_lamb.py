"""1-bit LAMB — compensated layerwise adaptivity under compression
(reference ``deepspeed/runtime/fp16/onebit/lamb.py``, arXiv 2104.06069).

The problem the reference solves: LAMB's per-layer trust ratio
``||w|| / ||update||`` needs a fresh second moment, but the 1-bit
compression stage must FREEZE the variance (compressed gradients are too
noisy to feed it).  Plain "LAMB + compression" therefore either loses
layerwise adaptivity or corrupts it.  The compensated algorithm:

  warmup (step < freeze_step)  — baseline LAMB; per-leaf trust ratio
      ``clip(||w||/||u||, min..max)`` is EMA'd into ``lamb_coeff_freeze``
      (``coeff_beta``); at ``freeze_step`` the variance is snapshotted
      into a shadow ``nu_fresh``.
  compression (step >= freeze_step) — the VARIANCE ``nu`` is frozen; the
      shadow ``nu_fresh`` keeps updating from the (compressed-averaged)
      gradients; the trust ratio applied is

          lamb_coeff = lamb_coeff_freeze * factor,
          factor = max( (sqrt(nu)+eps) / (sqrt(nu_fresh)+eps) )

      blended by the weight-decay update ratio, clipped to
      ``factor_min..factor_max``, and rate-limited to ±``factor_threshold``
      per step — the frozen coefficient tracks how much SMALLER the real
      denominator has become without ever consuming the noisy variance.

TPU-native mapping.  The reference compresses the momentum allreduce and
rescales each momentum by ``scaling_coeff = united_scale / rms_p`` so one
flat 1-bit pass compresses well; here the wire compression is the engine's
error-feedback exchange (``runtime/comm/compressed.py``) whose BLOCKWISE
scales adapt per 256-element block — a strictly finer-grained version of
``scaling_coeff`` — and the gradients arriving at this transform are
already the compressed average, so ``grad_reconstruct`` is simply the
incoming gradient.  The compensated math (frozen ``nu`` + shadow
``nu_fresh`` + factor-scaled frozen coefficient) is implemented exactly.
"""
from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax


class OnebitLambState(NamedTuple):
    count: jnp.ndarray          # scalar int32 step counter
    mu: Any                     # first moment, per leaf
    nu: Any                     # second moment (FROZEN after freeze_step)
    nu_fresh: Any               # shadow second moment (keeps updating)
    lamb_coeff_freeze: Any      # per-leaf scalar: EMA'd warmup trust ratio
    last_factor: Any            # per-leaf scalar: rate-limit memory


def scale_by_onebit_lamb(b1: float = 0.9, b2: float = 0.999,
                         eps: float = 1e-8, freeze_step: int = 100,
                         weight_decay: float = 0.0,
                         max_coeff: float = 10.0, min_coeff: float = 0.01,
                         coeff_beta: float = 0.9, factor_max: float = 4.0,
                         factor_min: float = 0.5,
                         factor_threshold: float = 0.1
                         ) -> optax.GradientTransformation:
    """The full 1-bit LAMB update (weight decay folded in, like the
    reference couples it into the trust ratio) — chain with the engine's
    ``-lr`` scaling only."""

    def init_fn(params):
        zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
        scal = jax.tree_util.tree_map(
            lambda _: jnp.zeros((), jnp.float32), params)
        ones = jax.tree_util.tree_map(
            lambda _: jnp.ones((), jnp.float32), params)
        return OnebitLambState(
            count=jnp.zeros((), jnp.int32), mu=zeros,
            nu=jax.tree_util.tree_map(jnp.zeros_like, params),
            nu_fresh=jax.tree_util.tree_map(jnp.zeros_like, params),
            lamb_coeff_freeze=scal, last_factor=ones)

    def _norm(x):
        return jnp.sqrt(jnp.sum(jnp.square(x.astype(jnp.float32))))

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError("scale_by_onebit_lamb needs params")
        count = state.count + 1
        warm = count <= freeze_step

        def leaf(g, p, mu, nu, nu_fresh, coeff_frz, last_factor):
            g32 = g.astype(jnp.float32)
            mu = b1 * mu + (1 - b1) * g32
            # variance: live during warmup, FROZEN after; the shadow keeps
            # updating from the (compressed-averaged) gradient.  The
            # freeze_step snapshot nu->nu_fresh falls out of the same two
            # selects (at count == freeze_step both see the warmup value).
            nu_live = b2 * nu + (1 - b2) * jnp.square(g32)
            nu = jnp.where(warm, nu_live, nu)
            nu_fresh = jnp.where(warm, nu_live,
                                 b2 * nu_fresh + (1 - b2) * jnp.square(g32))
            denom = jnp.sqrt(nu) + eps
            prelim = mu / denom
            p32 = p.astype(jnp.float32)
            upd = prelim + weight_decay * p32 if weight_decay else prelim

            # -- warmup trust ratio + its EMA ----------------------------
            w_norm = _norm(p32)
            u_norm = _norm(upd)
            raw = jnp.where((w_norm > 0) & (u_norm > 0),
                            w_norm / jnp.maximum(u_norm, 1e-30), 1.0)
            coeff = jnp.clip(raw, min_coeff, max_coeff)
            coeff_frz_new = jnp.where(
                coeff != 1.0, coeff_beta * coeff_frz + (1 - coeff_beta) * coeff,
                coeff_frz)

            # -- compression-stage factor --------------------------------
            denom_fresh = jnp.sqrt(nu_fresh) + eps
            factor = jnp.max(denom / denom_fresh)
            if weight_decay:
                ratio = jnp.minimum(1.0, _norm(prelim)
                                    / jnp.maximum(u_norm, 1e-30))
                factor = factor * ratio + (1.0 - ratio)
            factor = jnp.clip(factor, factor_min, factor_max)
            factor = jnp.clip(factor, last_factor * (1.0 - factor_threshold),
                              last_factor * (1.0 + factor_threshold))

            coeff_frz = jnp.where(warm, coeff_frz_new, coeff_frz)
            last_factor = jnp.where(warm, 1.0, factor)
            lamb_coeff = jnp.where(warm, coeff, coeff_frz * factor)
            out = (lamb_coeff * upd).astype(g.dtype)
            return out, mu, nu, nu_fresh, coeff_frz, last_factor

        results = jax.tree_util.tree_map(
            leaf, updates, params, state.mu, state.nu, state.nu_fresh,
            state.lamb_coeff_freeze, state.last_factor)
        flat, treedef = jax.tree_util.tree_flatten(
            results, is_leaf=lambda x: isinstance(x, tuple))
        unzip = [jax.tree_util.tree_unflatten(treedef, [t[i] for t in flat])
                 for i in range(6)]
        out, mu, nu, nu_fresh, coeff_frz, last_factor = unzip
        return out, OnebitLambState(count=count, mu=mu, nu=nu,
                                    nu_fresh=nu_fresh,
                                    lamb_coeff_freeze=coeff_frz,
                                    last_factor=last_factor)

    return optax.GradientTransformation(init_fn, update_fn)
