"""Optimizer construction (reference ``engine._configure_basic_optimizer``,
engine.py:1229-1302).

The reference dispatches on config ``optimizer.type`` to FusedAdam / CPUAdam /
FusedLamb / OneBitAdam / ... CUDA extensions.  On TPU, "fused" is what XLA does
to an optax update chain by default (the whole elementwise update compiles into
a handful of fused loops over the flat buffers); the Pallas multi-tensor kernel
in ``ops/adam`` exists for the cases XLA's fusion misses.  This module maps the
reference's optimizer names onto optax transforms and wires in grad clipping
(global-norm, computed globally under pjit — the reference's
``scaled_global_norm`` collective comes for free).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import optax

from ..utils.logging import logger

ADAM_OPTIMIZER = "adam"
ADAMW_OPTIMIZER = "adamw"
LAMB_OPTIMIZER = "lamb"
ONEBIT_ADAM_OPTIMIZER = "onebitadam"
ONEBIT_LAMB_OPTIMIZER = "onebitlamb"
ZERO_ONE_ADAM_OPTIMIZER = "zerooneadam"
SGD_OPTIMIZER = "sgd"
ADAGRAD_OPTIMIZER = "adagrad"
LION_OPTIMIZER = "lion"
RMSPROP_OPTIMIZER = "rmsprop"

SUPPORTED = [ADAM_OPTIMIZER, ADAMW_OPTIMIZER, LAMB_OPTIMIZER, ONEBIT_ADAM_OPTIMIZER,
             ONEBIT_LAMB_OPTIMIZER, ZERO_ONE_ADAM_OPTIMIZER, SGD_OPTIMIZER,
             ADAGRAD_OPTIMIZER, LION_OPTIMIZER, RMSPROP_OPTIMIZER]


def _base_transform(name: str, params: Dict[str, Any]) -> optax.GradientTransformation:
    name = name.lower()
    betas = params.get("betas", (0.9, 0.999))
    b1, b2 = betas[0], betas[1]
    eps = params.get("eps", 1e-8)
    weight_decay = params.get("weight_decay", 0.0)

    # first-moment storage dtype (optax mu_dtype): "bfloat16" halves Adam's m
    # buffer — the reference's memory-lean optimizer-state options analogue
    mu_dtype = params.get("mu_dtype")

    if name in (ADAM_OPTIMIZER, ADAMW_OPTIMIZER):
        adam_w_mode = params.get("adam_w_mode", name == ADAMW_OPTIMIZER)
        chain = [optax.scale_by_adam(b1=b1, b2=b2, eps=eps, mu_dtype=mu_dtype)]
        if weight_decay:
            if adam_w_mode:
                chain.append(optax.add_decayed_weights(weight_decay))
            else:  # L2-regularization mode: decay added to the raw grad
                chain.insert(0, optax.add_decayed_weights(weight_decay))
        return optax.chain(*chain)
    if name in (ONEBIT_ADAM_OPTIMIZER, ZERO_ONE_ADAM_OPTIMIZER):
        # Adam math on the compressed-averaged gradient; the error-feedback
        # sign-compressed DP exchange itself lives in runtime/comm/compressed.py
        # and is wired in by the engine (freeze_step warmup included).
        params = {k: v for k, v in params.items()
                  if k not in ("freeze_step", "cuda_aware", "comm_backend_name")}
        return _base_transform(ADAM_OPTIMIZER, params)
    if name in (LAMB_OPTIMIZER, ONEBIT_LAMB_OPTIMIZER):
        return optax.chain(
            optax.scale_by_adam(b1=b1, b2=b2, eps=eps, mu_dtype=mu_dtype),
            optax.add_decayed_weights(weight_decay) if weight_decay else optax.identity(),
            optax.scale_by_trust_ratio(),
        )
    if name == SGD_OPTIMIZER:
        momentum = params.get("momentum", 0.0)
        chain = []
        if weight_decay:
            chain.append(optax.add_decayed_weights(weight_decay))
        if momentum:
            chain.append(optax.trace(decay=momentum, nesterov=params.get("nesterov", False)))
        return optax.chain(*chain) if chain else optax.identity()
    if name == ADAGRAD_OPTIMIZER:
        return optax.scale_by_rss(initial_accumulator_value=params.get(
            "initial_accumulator_value", 0.1), eps=eps)
    if name == LION_OPTIMIZER:
        return optax.chain(
            optax.scale_by_lion(b1=params.get("betas", (0.9, 0.99))[0],
                                b2=params.get("betas", (0.9, 0.99))[1]),
            optax.add_decayed_weights(weight_decay) if weight_decay else optax.identity(),
        )
    if name == RMSPROP_OPTIMIZER:
        return optax.scale_by_rms(decay=params.get("alpha", 0.99), eps=eps)
    raise ValueError(f"unsupported optimizer {name!r}; supported: {SUPPORTED}")


def create_optimizer(opt_type: str, opt_params: Optional[Dict[str, Any]] = None,
                     lr_schedule: Optional[Callable] = None,
                     gradient_clipping: float = 0.0) -> optax.GradientTransformation:
    """Build the full update chain:  clip -> optimizer math -> -lr(step)·update."""
    opt_params = dict(opt_params or {})
    lr = opt_params.get("lr", 1e-3)
    chain = []
    if gradient_clipping and gradient_clipping > 0:
        chain.append(optax.clip_by_global_norm(gradient_clipping))
    chain.append(_base_transform(opt_type, opt_params))
    if lr_schedule is not None:
        chain.append(optax.scale_by_learning_rate(lr_schedule))
    else:
        chain.append(optax.scale_by_learning_rate(lr))
    return optax.chain(*chain)
