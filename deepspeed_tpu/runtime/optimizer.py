"""Optimizer construction (reference ``engine._configure_basic_optimizer``,
engine.py:1229-1302).

The reference dispatches on config ``optimizer.type`` to FusedAdam / CPUAdam /
FusedLamb / OneBitAdam / ... CUDA extensions.  On TPU, "fused" is what XLA does
to an optax update chain by default (the whole elementwise update compiles into
a handful of fused loops over the flat buffers); the Pallas multi-tensor kernel
in ``ops/adam`` exists for the cases XLA's fusion misses.  This module maps the
reference's optimizer names onto optax transforms and wires in grad clipping
(global-norm, computed globally under pjit — the reference's
``scaled_global_norm`` collective comes for free).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import optax

from ..utils.logging import logger

ADAM_OPTIMIZER = "adam"
ADAMW_OPTIMIZER = "adamw"
LAMB_OPTIMIZER = "lamb"
ONEBIT_ADAM_OPTIMIZER = "onebitadam"
ONEBIT_LAMB_OPTIMIZER = "onebitlamb"
ZERO_ONE_ADAM_OPTIMIZER = "zerooneadam"
SGD_OPTIMIZER = "sgd"
ADAGRAD_OPTIMIZER = "adagrad"
LION_OPTIMIZER = "lion"
RMSPROP_OPTIMIZER = "rmsprop"

SUPPORTED = [ADAM_OPTIMIZER, ADAMW_OPTIMIZER, LAMB_OPTIMIZER, ONEBIT_ADAM_OPTIMIZER,
             ONEBIT_LAMB_OPTIMIZER, ZERO_ONE_ADAM_OPTIMIZER, SGD_OPTIMIZER,
             ADAGRAD_OPTIMIZER, LION_OPTIMIZER, RMSPROP_OPTIMIZER]


def _scale_by_adam_ds(b1: float, b2: float, eps: float,
                      mu_dtype=None, nu_dtype=None) -> optax.GradientTransformation:
    """Adam moment update with independently storable m/nu dtypes.

    optax.scale_by_adam only exposes ``mu_dtype``; the second moment always
    lands in the parameter dtype (fp32 masters ⇒ 4 bytes/param).  Storing nu
    in bf16 halves that buffer — on a 16G chip that is the difference between
    fitting a ~740M-param Adam run with saved-activation remat or not.  The
    moment math itself stays fp32 (bf16 is only the at-rest format), matching
    the reference's memory-lean optimizer-state options
    (reference runtime/bf16_optimizer.py's fp32-master + low-precision-state
    split; ZeroOneAdam/1-bit state compression is the extreme of the same idea).

    Numerics caveat for ``nu_dtype=bfloat16``: with b2=0.999 the per-step nu
    increment is ~0.001·(g²−nu), below bf16's half-ulp (~0.002·nu) once nu
    approaches steady state — late-training nu can freeze at a stale value,
    inflating the denominator as gradients decay.  Treat bf16 nu as a
    memory-pressure escape hatch (or lower b2), not a free win; mu (b1=0.9,
    increments ~0.1·|g−mu|) is far less affected.
    """
    import jax
    import jax.numpy as jnp

    def init(params):
        mu = jax.tree_util.tree_map(
            lambda p: jnp.zeros_like(p, dtype=mu_dtype or p.dtype), params)
        nu = jax.tree_util.tree_map(
            lambda p: jnp.zeros_like(p, dtype=nu_dtype or p.dtype), params)
        return optax.ScaleByAdamState(count=jnp.zeros([], jnp.int32), mu=mu, nu=nu)

    def update(updates, state, params=None):
        del params
        count = state.count + 1
        cf = count.astype(jnp.float32)
        bc1 = 1.0 - b1 ** cf
        bc2 = 1.0 - b2 ** cf

        def upd(g, m, n):
            g32 = g.astype(jnp.float32)
            m32 = b1 * m.astype(jnp.float32) + (1.0 - b1) * g32
            n32 = b2 * n.astype(jnp.float32) + (1.0 - b2) * jnp.square(g32)
            out = (m32 / bc1) / (jnp.sqrt(n32 / bc2) + eps)
            return (out, m32.astype(m.dtype), n32.astype(n.dtype))

        flat = jax.tree_util.tree_map(upd, updates, state.mu, state.nu)
        outs = jax.tree_util.tree_map(lambda t: t[0], flat,
                                      is_leaf=lambda t: isinstance(t, tuple))
        mus = jax.tree_util.tree_map(lambda t: t[1], flat,
                                     is_leaf=lambda t: isinstance(t, tuple))
        nus = jax.tree_util.tree_map(lambda t: t[2], flat,
                                     is_leaf=lambda t: isinstance(t, tuple))
        return outs, optax.ScaleByAdamState(count=count, mu=mus, nu=nus)

    return optax.GradientTransformation(init, update)


def _base_transform(name: str, params: Dict[str, Any]) -> optax.GradientTransformation:
    name = name.lower()
    betas = params.get("betas", (0.9, 0.999))
    b1, b2 = betas[0], betas[1]
    eps = params.get("eps", 1e-8)
    weight_decay = params.get("weight_decay", 0.0)

    # moment storage dtypes: "bfloat16" halves Adam's m (mu_dtype) and/or v
    # (nu_dtype) buffers — the reference's memory-lean optimizer-state options
    mu_dtype = params.get("mu_dtype")
    nu_dtype = params.get("nu_dtype")

    def _adam_core():
        if nu_dtype is not None:
            return _scale_by_adam_ds(b1, b2, eps, mu_dtype=mu_dtype,
                                     nu_dtype=nu_dtype)
        return optax.scale_by_adam(b1=b1, b2=b2, eps=eps, mu_dtype=mu_dtype)

    if name in (ADAM_OPTIMIZER, ADAMW_OPTIMIZER):
        adam_w_mode = params.get("adam_w_mode", name == ADAMW_OPTIMIZER)
        chain = [_adam_core()]
        if weight_decay:
            if adam_w_mode:
                chain.append(optax.add_decayed_weights(weight_decay))
            else:  # L2-regularization mode: decay added to the raw grad
                chain.insert(0, optax.add_decayed_weights(weight_decay))
        return optax.chain(*chain)
    if name in (ONEBIT_ADAM_OPTIMIZER, ZERO_ONE_ADAM_OPTIMIZER):
        # Adam math on the compressed-averaged gradient; the error-feedback
        # sign-compressed DP exchange itself lives in runtime/comm/compressed.py
        # and is wired in by the engine (freeze_step warmup included).
        params = {k: v for k, v in params.items()
                  if k not in ("freeze_step", "cuda_aware", "comm_backend_name")}
        return _base_transform(ADAM_OPTIMIZER, params)
    if name == ONEBIT_LAMB_OPTIMIZER:
        # compensated 1-bit LAMB (reference fp16/onebit/lamb.py): frozen
        # variance + factor-scaled frozen trust ratio after freeze_step;
        # the EF-compressed grad exchange is wired by the engine with the
        # SAME freeze_step, so wire compression and variance freeze flip
        # together
        from .fp16.onebit_lamb import scale_by_onebit_lamb

        return scale_by_onebit_lamb(
            b1=b1, b2=b2, eps=eps,
            freeze_step=int(params.get("freeze_step", 100)),
            weight_decay=weight_decay,
            max_coeff=float(params.get("max_coeff", 10.0)),
            min_coeff=float(params.get("min_coeff", 0.01)),
            coeff_beta=float(params.get("coeff_beta", 0.9)),
            factor_max=float(params.get("factor_max", 4.0)),
            factor_min=float(params.get("factor_min", 0.5)),
            factor_threshold=float(params.get("factor_threshold", 0.1)))
    if name == LAMB_OPTIMIZER:
        return optax.chain(
            _adam_core(),
            optax.add_decayed_weights(weight_decay) if weight_decay else optax.identity(),
            optax.scale_by_trust_ratio(),
        )
    if name == SGD_OPTIMIZER:
        momentum = params.get("momentum", 0.0)
        chain = []
        if weight_decay:
            chain.append(optax.add_decayed_weights(weight_decay))
        if momentum:
            chain.append(optax.trace(decay=momentum, nesterov=params.get("nesterov", False)))
        return optax.chain(*chain) if chain else optax.identity()
    if name == ADAGRAD_OPTIMIZER:
        return optax.scale_by_rss(initial_accumulator_value=params.get(
            "initial_accumulator_value", 0.1), eps=eps)
    if name == LION_OPTIMIZER:
        return optax.chain(
            optax.scale_by_lion(b1=params.get("betas", (0.9, 0.99))[0],
                                b2=params.get("betas", (0.9, 0.99))[1]),
            optax.add_decayed_weights(weight_decay) if weight_decay else optax.identity(),
        )
    if name == RMSPROP_OPTIMIZER:
        return optax.scale_by_rms(decay=params.get("alpha", 0.99), eps=eps)
    raise ValueError(f"unsupported optimizer {name!r}; supported: {SUPPORTED}")


def zero_frozen_updates(frozen_mask) -> optax.GradientTransformation:
    """Final-link masking for frozen parameters (the reference's
    ``requires_grad=False`` contract: frozen params receive NO update, not
    even weight decay — ``add_decayed_weights`` earlier in the chain would
    otherwise still move them).  ``frozen_mask`` is a static pytree of
    Python bools matching the param tree (True = frozen), so the masking
    resolves at trace time and frozen leaves cost nothing in the compiled
    step.  Composition of stock combinators: ``masked`` applies
    ``set_to_zero`` to exactly the frozen leaves and passes the rest
    through untouched."""
    return optax.masked(optax.set_to_zero(), frozen_mask)


def create_optimizer(opt_type: str, opt_params: Optional[Dict[str, Any]] = None,
                     lr_schedule: Optional[Callable] = None,
                     gradient_clipping: float = 0.0,
                     frozen_mask: Any = None) -> optax.GradientTransformation:
    """Build the full update chain:  clip -> optimizer math -> -lr(step)·update.

    frozen_mask: optional pytree of bools (True = frozen) shaped like the
    param tree — frozen leaves get a zero update (the engine additionally
    zeroes their incoming gradients so clipping/grad-norm exclude them,
    matching the reference where ``requires_grad=False`` params produce no
    ``.grad`` at all)."""
    opt_params = dict(opt_params or {})
    lr = opt_params.get("lr", 1e-3)
    chain = []
    if gradient_clipping and gradient_clipping > 0:
        chain.append(optax.clip_by_global_norm(gradient_clipping))
    chain.append(_base_transform(opt_type, opt_params))
    if lr_schedule is not None:
        chain.append(optax.scale_by_learning_rate(lr_schedule))
    else:
        chain.append(optax.scale_by_learning_rate(lr))
    if frozen_mask is not None:
        chain.append(zero_frozen_updates(frozen_mask))
    return optax.chain(*chain)
