"""ZeRO++ — quantized ZeRO-3 collectives (qwZ / qgZ).

Reference mechanisms: int8 quantized weight all-gather
(runtime/zero/partition_parameters.py:1067-1158 + csrc/quantization/
swizzled_quantize.cu) and quantized hierarchical gradient reduce
(runtime/comm/coalesced_collectives.py:31 + quant_reduce.cu), claimed 4x
communication reduction vs plain ZeRO-3 (docs/_posts/2023-06-22-zeropp.md).

TPU-native redesign.  Under GSPMD, stage-3's param all-gather and grad
reduce-scatter are *implicit* (XLA inserts them against sharding
constraints) — implicit collectives can't change wire format.  ZeRO++ makes
exactly those two collectives explicit, per parameter leaf, as a manual
shard_map region that gathers over the ZeRO axes only (tensor/sequence
shards pass through the region untouched):

  forward : quantize shard (int8 blockwise) -> all_gather -> dequantize
            = qwZ, 2x fewer bytes than bf16 (4x vs fp32)
  backward: custom VJP reduce-scatters the param cotangent; with qgZ the
            reduce runs through the int8/int4 all-to-all quantized-reduction
            (ops/quantizer/quantized_reduce_scatter)

Persistent (small, replicated) params keep the plain cast path — same as
the reference, which never quantizes persistent params.
"""
from __future__ import annotations

import functools
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from jax.sharding import PartitionSpec as P

from ...ops.quantizer import DEFAULT_BLOCK, quantized_all_gather


def _entry_axes(entry) -> Tuple[str, ...]:
    if entry is None:
        return ()
    if isinstance(entry, str):
        return (entry,)
    return tuple(entry)


def _zero_axes_in_spec(spec: P, zero_axes) -> Tuple[Optional[int], Tuple[str, ...]]:
    """(dim, axes) of the ZeRO-sharded dimension of this spec (None if the
    leaf is not ZeRO-sharded)."""
    for dim, entry in enumerate(spec):
        axes = tuple(a for a in _entry_axes(entry) if a in zero_axes)
        if axes:
            return dim, axes
    return None, ()


def _quantized_gather_leaf(x, axis_names: Tuple[str, ...], gather_dim: int,
                           compute_dtype, weight_bits: Optional[int],
                           grad_bits: Optional[int], block: int,
                           grad_hierarchy=None):
    """Runs inside the manual region.  x: local master shard (fp32); the
    wire-format + VJP logic is the shared op in ops/quantizer."""
    return quantized_all_gather(x, axis_names, gather_dim=gather_dim,
                                block=block, bits=weight_bits,
                                out_dtype=compute_dtype, grad_bits=grad_bits,
                                grad_hierarchy=grad_hierarchy)


def _strip_axes(spec: P, drop) -> P:
    entries = []
    for e in spec:
        axes = tuple(a for a in _entry_axes(e) if a not in drop)
        entries.append(axes if len(axes) > 1 else (axes[0] if axes else None))
    while entries and entries[-1] is None:
        entries.pop()
    return P(*entries)


def make_zeropp_cast(master_specs: Any, param_specs: Any, mesh, compute_dtype,
                     zero_axes, weight_bits: Optional[int],
                     grad_bits: Optional[int],
                     block: int = DEFAULT_BLOCK,
                     hierarchical_outer: Optional[str] = None):
    """cast_fn(masters) -> compute params, with explicit quantized
    collectives on every ZeRO-sharded leaf.  Drop-in for the engine's
    ``_cast_tree(masters, compute_dtype)``.

    Fully-manual shard_map per leaf: in_specs carry the leaf's complete
    sharding (TP axes included — their shards pass through untouched), the
    region gathers over the ZeRO axes only, and out_specs keep the TP axes.
    (The partial-manual ``axis_names`` mode would be the natural fit but
    crashes XLA's SPMD partitioner in this jax/XLA version.)

    ``zero_axes`` selects WHICH axes the region covers — the composition
    switch (reference partition_parameters.py:1019-1158 composes hpZ with
    qwZ/qgZ; coalesced_collectives.py:31 is the hierarchical reduce):
      plain qwZ/qgZ      ZERO_AXES: full gather/reduce, quantized
      hpZ × qwZ/qgZ      ('data_outer',): only the expensive outer hop is
                         explicit+quantized; the inner per-layer gathers
                         stay implicit GSPMD over ICI in bf16
      hierarchical qgZ   BATCH_AXES + ``hierarchical_outer='data_outer'``:
                         the backward reduce runs the two-hop
                         intra-then-inter quantized path
    The master spec (not the param spec) locates the sharded dim, so the
    hpZ case — where the compute view drops 'data_outer' — still finds it."""
    from ...parallel.mesh import shard_map_compat

    def leaf_fn(master_spec: P, param_spec: P):
        from ...parallel.mesh import BATCH_AXES

        dim, axes = _zero_axes_in_spec(master_spec, zero_axes)
        if dim is None:
            return None  # unsharded master: plain cast
        pdim, _ = _zero_axes_in_spec(param_spec, BATCH_AXES)
        if pdim is None:
            return None  # persistent param (replicated compute view)
        grad_hierarchy = None
        if hierarchical_outer is not None and hierarchical_outer in axes \
                and len(axes) > 1 and grad_bits is not None:
            if axes[0] != hierarchical_outer:
                raise ValueError(
                    f"hierarchical qgZ requires the outer axis "
                    f"{hierarchical_outer!r} MAJOR in the spec entry {axes} "
                    "(landing layout must match the gather order)")
            grad_hierarchy = (tuple(a for a in axes
                                    if a != hierarchical_outer),
                              hierarchical_outer)
        region = functools.partial(
            _quantized_gather_leaf, axis_names=axes, gather_dim=dim,
            compute_dtype=compute_dtype, weight_bits=weight_bits,
            grad_bits=grad_bits, block=block, grad_hierarchy=grad_hierarchy)
        return shard_map_compat(region, mesh, in_specs=(master_spec,),
                                out_specs=_strip_axes(master_spec, zero_axes))

    gathers = jax.tree_util.tree_map(
        leaf_fn, master_specs, param_specs,
        is_leaf=lambda x: isinstance(x, P))
    num_quantized = sum(
        g is not None for g in jax.tree_util.tree_leaves(
            gathers, is_leaf=lambda x: x is None or callable(x)))

    def cast(masters):
        def apply(g, m):
            if g is None:
                return m.astype(compute_dtype) if jnp.issubdtype(
                    m.dtype, jnp.floating) else m
            return g(m)

        return jax.tree_util.tree_map(
            apply, gathers, masters,
            is_leaf=lambda x: x is None or callable(x))

    cast.num_quantized_leaves = num_quantized
    return cast
