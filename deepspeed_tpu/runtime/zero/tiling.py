"""Tiled linear for memory-bounded big matmuls (reference
``runtime/zero/tiling.py`` ``TiledLinear``).

The reference splits one huge ``nn.Linear`` into an in_splits x out_splits
grid of small Linears so ZeRO-3 only ever gathers one tile's weights at a
time (tiling.py:296).  The TPU-native version keeps the math one logical
einsum but walks the tiles with ``lax.scan`` and re-constrains each slice to
its ZeRO sharding inside the loop body: under GSPMD the all-gather XLA
inserts for a ZeRO-3-sharded weight then happens per tile inside the scan,
bounding the gathered-weight working set to ``W.size / splits`` instead of
the full matrix.  (With a replicated weight the scan is just a chunked
matmul — correct, slightly slower; use plain ``@``.)

No module tree to rewrite and no ``copy_params_from`` surface is needed: the
weight stays ONE logical array, so checkpoints, TP specs, and optimizer
state are unchanged — tiling is purely an execution-schedule choice.
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp


def tiled_linear(x: jax.Array, w: jax.Array, bias: Optional[jax.Array] = None,
                 out_splits: int = 1, in_splits: int = 1,
                 shard_spec: Any = None) -> jax.Array:
    """``x [..., d_in] @ w [d_in, d_out] (+ bias)`` walked tile-by-tile.

    out_splits tiles the output dim (each scan step computes a column block
    with 1/out_splits of the weights live); in_splits tiles the contraction
    dim (each step accumulates a partial product).  ``shard_spec`` is the
    weight's PartitionSpec — re-asserted on every tile so the per-tile
    gather stays per-tile instead of being hoisted.
    """
    d_in, d_out = w.shape
    if d_out % out_splits or d_in % in_splits:
        raise ValueError(
            f"weight [{d_in},{d_out}] not divisible by "
            f"in_splits={in_splits}/out_splits={out_splits}")

    def constrain(t):
        if shard_spec is None:
            return t
        from ...parallel.mesh import constrain_spec

        return constrain_spec(t, shard_spec)

    if out_splits > 1:
        # [out_splits, d_in, d_out/os] column tiles; with in_splits > 1 each
        # column tile is additionally walked down the contraction dim so the
        # live weight slice is W.size/(out_splits*in_splits)
        wt = jnp.moveaxis(w.reshape(d_in, out_splits, d_out // out_splits), 1, 0)

        def col(_, wi):
            if in_splits > 1:
                yi = tiled_linear(x, wi, None, out_splits=1,
                                  in_splits=in_splits, shard_spec=shard_spec)
            else:
                yi = x @ constrain(wi)
            return None, yi

        _, cols = jax.lax.scan(col, None, wt)
        y = jnp.moveaxis(cols, 0, -2).reshape(x.shape[:-1] + (d_out,))
    elif in_splits > 1:
        xt = jnp.moveaxis(x.reshape(x.shape[:-1] + (in_splits, d_in // in_splits)),
                          -2, 0)
        wt = w.reshape(in_splits, d_in // in_splits, d_out)

        # fp32 scan carry AND fp32 dot outputs (preferred_element_type keeps
        # the MXU accumulator unrounded): a bf16 carry or per-split bf16 dot
        # rounding would lose the fp32 accumulation a single dense matmul
        # gets, with error growing in in_splits; cast back to the promoted
        # dtype after the scan
        out_dtype = jnp.promote_types(x.dtype, w.dtype)

        def acc(carry, xw):
            xi, wi = xw
            part = jnp.matmul(xi, constrain(wi),
                              preferred_element_type=jnp.float32)
            return carry + part, None

        zero = jnp.zeros(x.shape[:-1] + (d_out,), jnp.float32)
        y, _ = jax.lax.scan(acc, zero, (xt, wt))
        y = y.astype(out_dtype)
    else:
        y = x @ constrain(w)
    if bias is not None:
        y = y + bias
    return y


class TiledLinear:
    """Layer-object form (PipelineModule layer contract: init/apply)."""

    def __init__(self, d_in: int, d_out: int, out_splits: int = 1,
                 in_splits: int = 1, use_bias: bool = True,
                 shard_spec: Any = None, dtype=jnp.float32):
        self.d_in, self.d_out = d_in, d_out
        self.out_splits, self.in_splits = out_splits, in_splits
        self.use_bias = use_bias
        self.shard_spec = shard_spec
        self.dtype = dtype
        self.param_count = d_in * d_out + (d_out if use_bias else 0)

    def init(self, rng):
        w = jax.random.normal(rng, (self.d_in, self.d_out), self.dtype) \
            * (self.d_in ** -0.5)
        p = {"w": w}
        if self.use_bias:
            p["b"] = jnp.zeros((self.d_out,), self.dtype)
        return p

    def apply(self, p, x):
        return tiled_linear(x, p["w"], p.get("b"),
                            out_splits=self.out_splits,
                            in_splits=self.in_splits,
                            shard_spec=self.shard_spec)
