"""ZeRO as a sharding plan (TPU-native redesign of stages 0-3).

The reference implements ZeRO with flattened partitions, autograd hooks and
hand-rolled bucketed collectives (``runtime/zero/stage_1_and_2.py:90``,
``stage3.py:67``, ``partition_parameters.py``).  Under XLA/GSPMD the same
memory/communication behavior is *declared* instead of orchestrated:

  stage 0  params R | grads R (allreduce)        | opt R
  stage 1  params R | grads R (allreduce)        | opt sharded over DP
  stage 2  params R | grads sharded (→ XLA emits reduce-scatter) | opt sharded
  stage 3  params sharded (→ XLA emits per-layer all-gather, the
           fetch/release machinery of partitioned_param_coordinator.py) |
           grads sharded | opt sharded

``R`` = replicated over the DP axes (still sharded over model/seq axes by any
tensor-parallel spec the model supplies).  The planner composes the model's TP
PartitionSpec with the ZeRO axes: it picks the largest dimension whose
per-(tp)shard size divides the DP world and assigns ``('data','expert')``
there.  Params smaller than ``stage3_param_persistence_threshold`` stay
replicated in stage 3 — exactly the reference's persistent-param optimization
(parameter_offload.py:347) but with zero bookkeeping.

The prefetch-window knobs (`stage3_max_live_parameters`,
`stage3_prefetch_bucket_size`, `stage3_max_reuse_distance`) are accepted for
schema parity and validated, but NOT translated further: XLA's latency-hiding
scheduler owns all-gather placement and double-buffering under jit, and it
makes those decisions from the compiled program's live ranges — the quantities
the reference's Python-side coordinator approximated with these knobs.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...parallel.mesh import ZERO_AXES, axis_size


@dataclasses.dataclass(frozen=True)
class ZeroShardingPlan:
    """Per-pytree sharding specs produced by :func:`plan_sharding`."""

    param_specs: Any      # compute params (bf16/fp16) — what the fwd/bwd sees
    master_specs: Any     # fp32 master params (== param_specs sharded at stage>=1)
    grad_specs: Any       # gradient shardings (stage>=2 sharded)
    opt_specs: Any        # optimizer state per-param shardings (== master_specs)
    stage: int


def _spec_axes_in_dim(entry) -> Tuple[str, ...]:
    if entry is None:
        return ()
    if isinstance(entry, str):
        return (entry,)
    return tuple(entry)


def _shard_dim_for(shape: Tuple[int, ...], base_spec: P, mesh: Mesh, zero_size: int,
                   used_axes: frozenset) -> Optional[int]:
    """Pick the dimension to shard over the ZeRO axes: the largest dim whose
    per-TP-shard size is divisible by the DP world and which doesn't already
    carry a DP axis."""
    best_dim, best_size = None, 0
    entries = list(base_spec) + [None] * (len(shape) - len(base_spec))
    for dim, extent in enumerate(shape):
        axes_here = _spec_axes_in_dim(entries[dim])
        if used_axes & set(axes_here):
            return None  # already ZeRO-sharded (explicit user spec) — keep it
        tp_div = int(np.prod([mesh.shape[a] for a in axes_here])) if axes_here else 1
        if extent % tp_div != 0:
            continue
        per_shard = extent // tp_div
        if per_shard % zero_size == 0 and extent > best_size:
            best_dim, best_size = dim, extent
    return best_dim


def _compose_spec(shape: Tuple[int, ...], base_spec: Optional[P], mesh: Mesh,
                  zero_axes: Tuple[str, ...],
                  preferred_dim: Optional[int] = None) -> P:
    base_spec = base_spec if base_spec is not None else P()
    zero_size = axis_size(mesh, list(zero_axes))
    if zero_size == 1:
        return base_spec
    dim = None
    if preferred_dim is not None:
        # hpZ: the compute view must shard the SAME dim the master does —
        # the quantized-gather region strips the outer axis from the master
        # spec, which only yields the param spec when the dims agree
        entries = list(base_spec) + [None] * (len(shape) - len(base_spec))
        axes_here = _spec_axes_in_dim(entries[preferred_dim])
        tp_div = (int(np.prod([mesh.shape[a] for a in axes_here]))
                  if axes_here else 1)
        if (not (set(axes_here) & set(zero_axes))  # never duplicate an axis
                and shape[preferred_dim] % tp_div == 0
                and (shape[preferred_dim] // tp_div) % zero_size == 0):
            dim = preferred_dim
    if dim is None:
        dim = _shard_dim_for(shape, base_spec, mesh, zero_size,
                             frozenset(zero_axes))
    if dim is None:
        return base_spec
    entries = list(base_spec) + [None] * (len(shape) - len(base_spec))
    existing = _spec_axes_in_dim(entries[dim])
    entries[dim] = tuple(existing) + tuple(zero_axes)
    while entries and entries[-1] is None:
        entries.pop()
    return P(*entries)


def _leaf_size(shape) -> int:
    return int(np.prod(shape)) if shape else 1


def plan_sharding(param_shapes: Any, stage: int, mesh: Mesh, tp_specs: Optional[Any] = None,
                  persistence_threshold: int = 0,
                  zero_axes: Tuple[str, ...] = ZERO_AXES,
                  param_zero_axes: Optional[Tuple[str, ...]] = None) -> ZeroShardingPlan:
    """Build the ZeRO sharding plan for a pytree of parameter ShapeDtypeStructs.

    tp_specs: optional pytree of PartitionSpec with the model's tensor/sequence
    parallel sharding (e.g. from flax ``nn.with_partitioning`` metadata); ZeRO
    axes are composed on top.

    param_zero_axes: axes for the COMPUTE params when they differ from the
    master/grad axes — the ZeRO++ hpZ secondary partition (reference
    partition_parameters.py:1019 ``zero_hpz_partition_size``): masters/opt/
    grads stay sharded over the full group while the bf16 forward view shards
    only within the inner (intra-node) group, so per-layer all-gathers ride
    the cheap links and the extra memory is params/hpz per device.
    """
    if tp_specs is None:
        tp_specs = jax.tree_util.tree_map(lambda _: P(), param_shapes)
    hpz_mode = param_zero_axes is not None and param_zero_axes != zero_axes
    param_zero_axes = param_zero_axes if param_zero_axes is not None else zero_axes

    def spec_for(shaped, base, threshold, axes, preferred_dim=None):
        shape = tuple(shaped.shape)
        if threshold and _leaf_size(shape) < threshold:
            return base if base is not None else P()
        return _compose_spec(shape, base, mesh, axes,
                             preferred_dim=preferred_dim)

    def _zero_dim_of(spec: P, axes) -> Optional[int]:
        for dim, entry in enumerate(spec):
            if set(_spec_axes_in_dim(entry)) & set(axes):
                return dim
        return None

    # stage >= 1: master/opt sharded; no size threshold (opt state is the
    # memory hog the stage exists to shard)
    master = (jax.tree_util.tree_map(
        lambda s, b: spec_for(s, b, 0, zero_axes), param_shapes, tp_specs)
        if stage >= 1 else tp_specs)
    # stage >= 3: compute params sharded, small params persist replicated.
    # Under hpZ the param spec must use the SAME dim as the master spec
    # (the secondary partition is the master shard re-gathered over the
    # outer axis only).
    params = (jax.tree_util.tree_map(
        lambda s, b, m: spec_for(
            s, b, persistence_threshold, param_zero_axes,
            preferred_dim=(_zero_dim_of(m, zero_axes) if hpz_mode else None)),
        param_shapes, tp_specs, master)
        if stage >= 3 else tp_specs)
    # stage >= 2: grads land sharded (XLA lowers the DP reduction to
    # reduce-scatter + the step's gather); stage 3 grads match param sharding
    # — except under hpZ, where the primary (full) partition owns grads/opt
    if stage >= 3:
        grads = params if param_zero_axes == zero_axes else master
    elif stage == 2:
        grads = master
    else:
        grads = tp_specs
    return ZeroShardingPlan(param_specs=params, master_specs=master, grad_specs=grads,
                            opt_specs=master, stage=stage)


def named_shardings(mesh: Mesh, specs: Any) -> Any:
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P))


def constrain(tree: Any, specs: Any) -> Any:
    """Apply with_sharding_constraint leaf-wise (inside jit)."""
    return jax.tree_util.tree_map(
        lambda x, s: jax.lax.with_sharding_constraint(x, s), tree, specs)
