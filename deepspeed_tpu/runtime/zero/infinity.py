"""ZeRO-Infinity parameter offload — the layer-streamed executor.

Reference mechanisms: ``runtime/swap_tensor/partitioned_param_swapper.py:36``
(parameters on NVMe, swapped in around each submodule's forward/backward) and
``runtime/zero/stage3.py:502-536`` (offload_param wiring).  The reference
drives this with per-module autograd hooks; a TPU/XLA program cannot pause
mid-graph to page weights, so the executor IS the schedule:

  - bf16 params live in per-layer NVMe files (native aio engine).
  - The train step is a Python loop over layers; each layer is ONE jitted
    program (identical shapes -> one compiled executable reused L times).
  - Forward: prefetch layer i+1 from NVMe while layer i computes; keep only
    the [B,S,D] boundary activations on device.
  - Backward: reverse loop; ``jax.vjp`` of the layer block recomputes the
    layer's internals (per-layer remat for free) and yields (dparams, dx).
  - Gradients accumulate in host RAM (fp32); the native SIMD Adam streams
    fp32 masters + moments from NVMe leaf by leaf (same pipeline as
    SwappedAdamOptimizer) and writes updated bf16 params back to NVMe.

Peak device memory = ONE layer's params + boundary activations + one layer's
grads — a model whose weights exceed HBM trains on one chip.  Peak host
memory = fp32 grads (4 B/param); masters + moments (12 B/param) stay on NVMe.

Throughput follows the host<->device link and NVMe bandwidth by construction
(the reference has the same property; its sweet spot is the same: maximize
arithmetic intensity per byte streamed).
"""
from __future__ import annotations

import math
import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ...utils.logging import log_dist
from ...parallel.mesh import BATCH_AXES, constrain_spec
from ..swap_tensor.partitioned_optimizer_swapper import TensorSwapper
from ...ops.adam.cpu_adam import DeepSpeedCPUAdam


def _bf16():
    import ml_dtypes

    return ml_dtypes.bfloat16


def _idx_key(idx, shape) -> str:
    """Normalized hashable key for a device index (tuple of slices)."""
    return ",".join(f"{s.indices(d)[0]}:{s.indices(d)[1]}"
                    for s, d in zip(idx, shape))


def _norm_slices(idx, shape):
    return tuple(slice(*s.indices(d)[:2]) for s, d in zip(idx, shape))


def _leaf_shards(mesh, spec, shape, multi: bool):
    """Per-process shard descriptors for one leaf.

    Returns ``{idx_key: (suffix, slices)}`` plus ``{suffix: weight}`` where
    weight = 1 / (#processes holding that shard) — the grad-norm correction
    so globally-summed squared norms count each distinct shard once.
    Single-process collapses to ONE full-leaf shard with suffix '' (the
    legacy file layout, byte-identical behavior)."""
    if not multi:
        full = tuple(slice(0, d) for d in shape)
        return {_idx_key(full, shape): ("", full)}, {"": 1.0}
    sharding = NamedSharding(mesh, spec)
    holders: Dict[str, set] = {}
    for dev, idx in sharding.devices_indices_map(shape).items():
        holders.setdefault(_idx_key(idx, shape), set()).add(dev.process_index)
    local = {}
    for dev, idx in sharding.addressable_devices_indices_map(shape).items():
        local.setdefault(_idx_key(idx, shape), idx)
    info, weights = {}, {}
    for n, (key, idx) in enumerate(sorted(local.items())):
        sfx = f".s{n}"
        info[key] = (sfx, _norm_slices(idx, shape))
        weights[sfx] = 1.0 / len(holders[key])
    return info, weights


class InfinityParamEngine:
    """Owns NVMe-resident params + optimizer state and the layer-streamed
    train step (engine.train_batch delegates here when
    ``zero_optimization.offload_param.device == "nvme"``)."""

    STATES = ("master", "exp_avg", "exp_avg_sq")

    def __init__(self, config, model, lr_schedule, mesh):
        if model is None or not hasattr(model, "config") or \
                not hasattr(model.config, "num_layers"):
            raise NotImplementedError(
                "offload_param needs the native transformer family "
                "(deepspeed_tpu.models.CausalLM): the layer-streamed "
                "executor must know the model's layer structure")
        cfg = model.config
        from ...models.transformer import has_moe

        if isinstance(cfg.num_experts, (tuple, list)):
            raise NotImplementedError(
                "offload_param with a PR-MoE pyramid (per-layer expert "
                "counts) is not supported: the layer stream needs uniform "
                "layer files")
        self._moe = has_moe(cfg)
        if cfg.pipeline_stages > 1:
            raise NotImplementedError(
                "offload_param composes with pipeline_stages=1 (a pipelined "
                "stage already holds only its own layers)")
        if getattr(cfg, "random_ltd", False):
            raise NotImplementedError("offload_param + random_ltd: unsupported")
        if config.progressive_layer_drop.enabled:
            raise NotImplementedError(
                "offload_param + progressive_layer_drop: unsupported")
        if config.fp16.enabled:
            raise NotImplementedError(
                "offload_param pairs with bf16 (fp16 overflow handling would "
                "need host-side loss-scale bookkeeping)")
        if config.precision != jnp.bfloat16:
            raise ValueError("offload_param requires bf16 compute (fp32 "
                             "params have no compact streaming format)")
        if not getattr(cfg, "causal", True) or \
                getattr(cfg, "type_vocab_size", 0):
            raise NotImplementedError(
                "offload_param trains causal LMs (encoder models have no "
                "next-token loss for the layer-streamed executor)")
        # Multi-host: per-host shard files — each process stores ONLY the
        # unique addressable shards of every leaf (the reference swapper is
        # per-rank by the same construction,
        # partitioned_param_swapper.py:36), so host RAM/NVMe per process
        # scales down with the process count for sharded leaves
        self._multi = jax.process_count() > 1
        # bind the host side (SIMD Adam + aio threadpool) to one NUMA node
        # BEFORE the pools spawn (threads inherit the mask); DS_TPU_NUMA_NODE
        # overrides, 'off' disables
        from ...utils.numa import bind_for_offload

        bind_for_offload()
        opt_cfg = config.optimizer
        opt_type = (opt_cfg.type if opt_cfg else "adamw").lower()
        if opt_type not in ("adam", "adamw"):
            raise NotImplementedError(
                f"offload_param runs the native CPU Adam on the host; "
                f"optimizer {opt_type!r} is not supported")

        self.cfg = cfg
        self.model = model
        self.mesh = mesh
        self.config = config
        self.lr_schedule = lr_schedule
        self.gas = config.gradient_accumulation_steps
        self.clip = config.gradient_clipping
        self.attn_impl = getattr(model, "attn_impl", "auto")
        self.step_count = 0

        p = dict(opt_cfg.params) if opt_cfg else {}
        self.adam = DeepSpeedCPUAdam(
            lr=p.get("lr", 1e-3), betas=tuple(p.get("betas", (0.9, 0.999))),
            eps=p.get("eps", 1e-8), weight_decay=p.get("weight_decay", 0.0),
            adamw_mode=bool(p.get("adam_w_mode", opt_type == "adamw")))
        # moment STORE dtypes (same memory-lean knobs as the fused device
        # optimizer's mu_dtype/nu_dtype): bf16 halves the NVMe footprint of
        # m and/or v — 14 B/param (fp32 moments) -> 10 B/param with both,
        # the difference between a 7B store fitting a ~90 GB disk or not.
        # The host Adam always steps fp32; bf16 is the at-rest format.
        self._mu16 = str(p.get("mu_dtype", "")).lower() == "bfloat16"
        self._nu16 = str(p.get("nu_dtype", "")).lower() == "bfloat16"
        zc = config.zero_config.offload_param
        nvme_path = zc.nvme_path
        if self._multi:
            # shard files are process-local; a shared filesystem must not
            # collide across hosts
            nvme_path = os.path.join(nvme_path,
                                     f"proc{jax.process_index()}")
        self.swapper = TensorSwapper(
            nvme_path, aio_threads=max(config.aio.thread_count, 1))

        self._init_param_store(config.seed)
        self._build_programs()
        total = self.param_count
        opt_bytes = 4 + (2 if self._mu16 else 4) + (2 if self._nu16 else 4)
        log_dist(
            f"ZeRO-Infinity param offload: {total:,} params "
            f"({total * 2 / 1e9:.2f} GB bf16) + optimizer state "
            f"({total * opt_bytes / 1e9:.2f} GB, moments "
            f"{'bf16' if self._mu16 else 'fp32'}/"
            f"{'bf16' if self._nu16 else 'fp32'}) on NVMe at {zc.nvme_path}; "
            f"device holds 1/{cfg.num_layers} of the layer stack at a time",
            ranks=[0])

    # ------------------------------------------------------------------
    # Param store: init on HOST (never materialize the full model on device),
    # split into stem / per-layer / head leaves, file per leaf.
    # ------------------------------------------------------------------
    def _init_param_store(self, seed: int):
        from ...models.transformer import init_params, param_specs

        cpu = jax.local_devices(backend="cpu")[0]
        with jax.default_device(cpu):
            params = init_params(self.cfg, jax.random.PRNGKey(seed))
        params = jax.tree_util.tree_map(
            lambda x: np.asarray(x, np.float32), params)

        specs = param_specs(self.cfg)
        L = self.cfg.num_layers
        self.num_layers = L
        self.layer_keys: List[str] = sorted(params["layers"].keys())
        # per-layer leaf spec = stacked spec minus the leading L dim
        self._layer_specs = {
            k: P(*tuple(specs["layers"][k])[1:]) for k in self.layer_keys}
        self._layer_shapes = {
            k: params["layers"][k].shape[1:] for k in self.layer_keys}

        self.stem_keys = [k for k in ("embed", "pos_embed", "embed_norm_scale",
                                      "embed_norm_bias") if k in params]
        self.head_keys = [k for k in ("final_norm_scale", "final_norm_bias",
                                      "lm_head", "lm_head_bias") if k in params]
        # every top-level leaf must be claimed — a silently-dropped param
        # would train a different model than the config describes
        unclaimed = set(params) - set(self.stem_keys) - set(self.head_keys) \
            - {"layers"}
        if unclaimed:
            raise NotImplementedError(
                f"offload_param: unhandled top-level param leaves "
                f"{sorted(unclaimed)} — the layer-streamed executor does not "
                "know where they belong")
        self._flat_specs = {k: specs[k] for k in
                            self.stem_keys + self.head_keys}
        self._flat_shapes = {k: params[k].shape
                             for k in self.stem_keys + self.head_keys}

        self.param_count = sum(
            int(np.prod(l.shape))
            for l in jax.tree_util.tree_leaves(params))

        # per-leaf shard descriptors (single-process: one '' full shard)
        self._flat_shards: Dict[str, Dict] = {}
        self._shard_weight: Dict[str, float] = {}
        for k in self.stem_keys + self.head_keys:
            info, w = _leaf_shards(self.mesh, self._flat_specs[k],
                                   self._flat_shapes[k], self._multi)
            self._flat_shards[k] = info
            for sfx, wt in w.items():
                self._shard_weight[f"{k}{sfx}"] = wt
        self._layer_shards: Dict[str, Dict] = {}
        self._layer_shard_weight: Dict[str, float] = {}
        for k in self.layer_keys:
            info, w = _leaf_shards(self.mesh, self._layer_specs[k],
                                   self._layer_shapes[k], self._multi)
            self._layer_shards[k] = info
            self._layer_shard_weight[k] = w
        for i in range(L):
            for k in self.layer_keys:
                for sfx, wt in self._layer_shard_weight[k].items():
                    self._shard_weight[f"layers.{i}.{k}{sfx}"] = wt

        bf16 = _bf16()
        # write every SHARD: fp32 master + zero moments (store dtype) +
        # bf16 param
        def put(name, arr32, shards):
            for sfx, slices in shards.values():
                piece = np.ascontiguousarray(arr32[slices])
                self.swapper.write(f"{name}{sfx}.master", piece)
                z = np.zeros_like(piece)
                self.swapper.write(f"{name}{sfx}.exp_avg",
                                   z.astype(bf16) if self._mu16 else z)
                self.swapper.write(f"{name}{sfx}.exp_avg_sq",
                                   z.astype(bf16) if self._nu16 else z)
                self.swapper.write(f"{name}{sfx}.param", piece.astype(bf16))
                self._leaf_names.append(f"{name}{sfx}")

        self._leaf_names: List[str] = []
        for k in self.stem_keys + self.head_keys:
            put(k, params[k], self._flat_shards[k])
        for i in range(L):
            for k in self.layer_keys:
                put(f"layers.{i}.{k}",
                    np.ascontiguousarray(params["layers"][k][i]),
                    self._layer_shards[k])

        # stem + head are touched every microbatch (the reference's
        # persistence-threshold behavior): resident bf16 device copies
        self._stem_dev = {k: self._put_flat(k) for k in self.stem_keys}
        self._head_dev = {k: self._put_flat(k) for k in self.head_keys}

        # double-buffered pinned host buffers for the layer stream
        # (keyed per shard; single-process = one '' shard per leaf)
        def shard_shape(k, slices):
            return tuple(s.stop - s.start for s in slices)

        self._layer_bufs = [
            {(k, sfx): np.empty(shard_shape(k, slices), bf16)
             for k in self.layer_keys
             for sfx, slices in self._layer_shards[k].values()}
            for _ in range(2)]
        # host fp32 gradient accumulators (allocated lazily per window)
        self._host_grads: Optional[Dict[str, np.ndarray]] = None

    def _put_flat(self, key, arr=None):
        """Global stem/head array from the process-local shard files.
        ``arr`` (single-process fast path) skips the NVMe re-read."""
        sharding = NamedSharding(self.mesh, self._flat_specs[key])
        if not self._multi:
            if arr is None:
                arr = self.swapper.read(f"{key}.param")
            return jax.device_put(arr, sharding)
        shape = self._flat_shapes[key]
        info = self._flat_shards[key]
        cache: Dict[str, np.ndarray] = {}

        def cb(idx):
            sfx = info[_idx_key(idx, shape)][0]
            if sfx not in cache:
                cache[sfx] = self.swapper.read(f"{key}{sfx}.param")
            return cache[sfx]

        return jax.make_array_from_callback(shape, sharding, cb)

    def _put_layer(self, bufs):
        # .copy(): device_put from numpy can be zero-copy on the CPU backend,
        # and these double-buffered read buffers are refilled by the next
        # aio submit — the device array must own its memory
        if not self._multi:
            return {k: jax.device_put(
                bufs[(k, "")].copy(),
                NamedSharding(self.mesh, self._layer_specs[k]))
                for k in self.layer_keys}
        out = {}
        for k in self.layer_keys:
            shape = self._layer_shapes[k]
            info = self._layer_shards[k]
            sharding = NamedSharding(self.mesh, self._layer_specs[k])
            cache: Dict[str, np.ndarray] = {}   # one copy per unique shard
            # (make_array_from_callback calls the cb per DEVICE; partially
            # replicated local shards would otherwise copy N_local times)

            def cb(idx, _i=info, _s=shape, _k=k, _c=cache):
                sfx = _i[_idx_key(idx, _s)][0]
                if sfx not in _c:
                    _c[sfx] = bufs[(_k, sfx)].copy()
                return _c[sfx]

            out[k] = jax.make_array_from_callback(shape, sharding, cb)
        return out

    # ------------------------------------------------------------------
    # The five jitted programs (each compiled once; layer programs are
    # shape-identical across layers so XLA reuses one executable).
    # ------------------------------------------------------------------
    def _build_programs(self):
        from ...models.transformer import (_block, _norm, cross_entropy_loss)

        cfg = self.cfg
        attn_impl = self.attn_impl
        if attn_impl == "auto":
            attn_impl = "xla"
        act_spec = P(BATCH_AXES, "seq", None)
        tied = cfg.tie_embeddings
        f32 = lambda t: jax.tree_util.tree_map(  # noqa: E731
            lambda g: g.astype(jnp.float32), t)

        def positions_of(tokens):
            B, S = tokens.shape
            return jnp.broadcast_to(
                jnp.arange(S, dtype=jnp.int32)[None, :], (B, S))

        def stem_body(stem, tokens):
            x = stem["embed"].astype(cfg.dtype)[tokens]
            if "pos_embed" in stem:
                x = x + stem["pos_embed"].astype(cfg.dtype)[
                    positions_of(tokens)]
            if "embed_norm_scale" in stem:   # Bloom embedding LayerNorm
                x = _norm(cfg, x, stem["embed_norm_scale"],
                          stem.get("embed_norm_bias"))
            return constrain_spec(x, act_spec)

        moe = self._moe
        # single source: the SAME value is jit-baked into layer_bwd's aux
        # cotangent and read by the loss reporting in _micro_fwd_bwd /
        # eval_batch — they must never disagree
        self._aux_coef = aux_coef = cfg.moe_aux_loss_coef

        def layer_body(lp, x, rng, deterministic=False):
            B, S, _ = x.shape
            pos = jnp.broadcast_to(
                jnp.arange(S, dtype=jnp.int32)[None, :], (B, S))
            y, aux = _block(cfg, lp, x, pos, rng, attn_impl,
                            deterministic=deterministic)
            y = constrain_spec(y, act_spec)
            # MoE: the load-balancing aux is part of the loss, so it must be
            # a layer OUTPUT for the vjp to route router gradients
            return (y, aux) if moe else y

        def head_body(head, stem, x, labels):
            if "final_norm_scale" in head:
                xn = _norm(cfg, x, head["final_norm_scale"],
                           head.get("final_norm_bias"))
            else:                            # final_norm=False configs
                xn = x
            if tied:
                logits = xn @ stem["embed"].astype(cfg.dtype).T
            else:
                logits = xn @ head["lm_head"].astype(cfg.dtype)
                if "lm_head_bias" in head:
                    logits = logits + head["lm_head_bias"].astype(cfg.dtype)
            return cross_entropy_loss(logits, labels)

        self._stem_fwd = jax.jit(stem_body)
        self._layer_fwd = jax.jit(layer_body)
        # eval variants: deterministic blocks + loss-only head (no vjp)
        self._layer_fwd_det = jax.jit(
            lambda lp, x, rng: layer_body(lp, x, rng, deterministic=True))
        self._head_fwd = jax.jit(head_body)

        def head_vjp(head, stem, x, labels):
            if tied:
                loss, (dhead, dstem, dx) = jax.value_and_grad(
                    head_body, argnums=(0, 1, 2))(head, stem, x, labels)
            else:
                loss, (dhead, dx) = jax.value_and_grad(
                    head_body, argnums=(0, 2))(head, stem, x, labels)
                dstem = {}
            return loss, f32(dhead), f32(dstem), dx

        self._head_vjp = jax.jit(head_vjp)

        if moe:
            def layer_bwd(lp, x, rng, dy):
                (y, aux), vjp = jax.vjp(
                    lambda l, xi: layer_body(l, xi, rng), lp, x)
                # d(ce + coef*sum_l aux_l)/d(layer l) — aux cotangent = coef
                dlp, dx = vjp((dy, jnp.asarray(aux_coef, aux.dtype)))
                return f32(dlp), dx
        else:
            def layer_bwd(lp, x, rng, dy):
                y, vjp = jax.vjp(lambda l, xi: layer_body(l, xi, rng), lp, x)
                dlp, dx = vjp(dy)
                return f32(dlp), dx

        self._layer_bwd = jax.jit(layer_bwd)

        def stem_bwd(stem, tokens, dx):
            _, vjp = jax.vjp(lambda s: stem_body(s, tokens), stem)
            (dstem,) = vjp(dx)
            return f32(dstem)

        self._stem_bwd = jax.jit(stem_bwd)

    # ------------------------------------------------------------------
    # Layer streaming
    # ------------------------------------------------------------------
    def _submit_layer(self, i: int, slot: int):
        bufs = self._layer_bufs[slot]
        return [self.swapper.submit_read(f"layers.{i}.{k}{sfx}.param",
                                         out=bufs[(k, sfx)])
                for k in self.layer_keys
                for sfx, _ in self._layer_shards[k].values()], slot

    def _collect_layer(self, pending):
        handles, slot = pending
        for h, _ in handles:
            self.swapper.wait(h)
        return self._put_layer(self._layer_bufs[slot])

    # ------------------------------------------------------------------
    # Train step
    # ------------------------------------------------------------------
    def _accum(self, name: str, g) -> None:
        if self._host_grads is None:
            self._host_grads = {}
        if self._multi:
            # pull only the process-local unique shards of the global grad
            if name.startswith("layers."):
                leaf_key = name.split(".", 2)[2]
                info = self._layer_shards[leaf_key]
            else:
                info = self._flat_shards[name]
            shape = g.shape
            seen = set()
            for sh in g.addressable_shards:
                key = _idx_key(sh.index, shape)
                sfx = info[key][0]
                if sfx in seen:
                    continue          # replicated across local devices
                seen.add(sfx)
                with jax.transfer_guard("allow"):
                    arr = np.asarray(sh.data, np.float32)
                self._accum_host(f"{name}{sfx}", arr)
            return
        with jax.transfer_guard("allow"):
            arr = np.asarray(g, np.float32)
        self._accum_host(name, arr)

    def _accum_host(self, key: str, arr: np.ndarray) -> None:
        buf = self._host_grads.get(key)
        if buf is None:
            # np.asarray of a jax.Array is a read-only zero-copy view; the
            # accumulator mutates in place, so it must own writable memory
            self._host_grads[key] = np.array(arr, np.float32, order="C")
        else:
            buf += arr

    def _stream_forward(self, tokens, keys, layer_fwd, keep: bool):
        """Prefetch-pipelined forward over all layers.  ``keep`` retains the
        boundary activations (training) — eval discards them.  Returns
        ``(x_final, xs_or_None, last_layer_params, moe_aux_sum)``."""
        x = self._stem_fwd(self._stem_dev, tokens)
        xs = [x] if keep else None
        pending = self._submit_layer(0, 0)
        lp = None
        aux_sum = jnp.float32(0.0)
        for i in range(self.num_layers):
            nxt = (self._submit_layer(i + 1, (i + 1) % 2)
                   if i + 1 < self.num_layers else None)
            lp = self._collect_layer(pending)
            out = layer_fwd(lp, x, keys[i])
            if self._moe:
                x, aux = out
                aux_sum = aux_sum + aux
            else:
                x = out
            if keep:
                xs.append(x)
            pending = nxt
        return x, xs, lp, aux_sum

    @staticmethod
    def _tokens_labels(batch):
        if isinstance(batch, dict):
            tokens = batch["input_ids"]
            labels = batch.get("labels")
        else:
            tokens, labels = batch, None
        if labels is None:
            labels = jnp.concatenate(
                [tokens[:, 1:], jnp.full_like(tokens[:, :1], -100)], axis=1)
        return tokens, labels

    def _to_global(self, arr):
        """Multi-host: every process feeds the same host batch; build the
        dp-sharded global array from it.  Arrays that are already jax global
        arrays (the engine's _shard_batch path) pass through — np.asarray on
        a non-addressable array would throw."""
        if not self._multi or isinstance(arr, jax.Array):
            return arr
        a = np.asarray(arr)
        sharding = NamedSharding(self.mesh,
                                 P(BATCH_AXES, *([None] * (a.ndim - 1))))
        return jax.make_array_from_callback(a.shape, sharding,
                                            lambda idx: a[idx])

    def _micro_fwd_bwd(self, tokens, labels, rng):
        L = self.num_layers
        tokens = self._to_global(tokens)
        labels = self._to_global(labels)
        keys = jax.random.split(rng, L)
        x, xs, last_lp, aux_sum = self._stream_forward(
            tokens, keys, self._layer_fwd, keep=True)

        loss, dhead, dstem_h, dx = self._head_vjp(
            self._head_dev, self._stem_dev, xs[L], labels)
        if self._moe:
            # reported loss matches the fused engine: ce + coef*sum(aux);
            # the aux GRADIENT flows via the layer vjp's aux cotangent
            loss = loss + self._aux_coef * aux_sum
        for k, g in dhead.items():
            self._accum(k, g)
        for k, g in dstem_h.items():
            self._accum(k, g)

        bwd_slot = 0

        def submit_rev(i):
            nonlocal bwd_slot
            s, bwd_slot = bwd_slot, bwd_slot ^ 1
            return self._submit_layer(i, s)

        pending = submit_rev(L - 2) if L > 1 else None
        for i in reversed(range(L)):
            if i == L - 1:
                lp = last_lp
            else:
                lp = self._collect_layer(pending)
                pending = None
            if i > 0 and pending is None:
                pending = submit_rev(i - 1)  # prefetch under layer i's bwd
            dlp, dx = self._layer_bwd(lp, xs[i], keys[i], dx)
            for k, g in dlp.items():
                self._accum(f"layers.{i}.{k}", g)
            xs[i + 1] = None  # free the boundary activation
            del lp

        dstem = self._stem_bwd(self._stem_dev, tokens, dx)
        for k, g in dstem.items():
            self._accum(k, g)
        return loss

    def eval_batch(self, batch) -> float:
        """Forward-only layer-streamed evaluation: deterministic blocks
        (dropout off), loss-only head (no vjp), no activations kept."""
        tokens, labels = self._tokens_labels(batch)
        tokens = self._to_global(tokens)
        labels = self._to_global(labels)
        keys = jax.random.split(jax.random.PRNGKey(self.config.seed),
                                self.num_layers)
        x, _, _, aux_sum = self._stream_forward(
            tokens, keys, self._layer_fwd_det, keep=False)
        loss = self._head_fwd(self._head_dev, self._stem_dev, x, labels)
        if self._moe:
            loss = loss + self._aux_coef * aux_sum
        with jax.transfer_guard("allow"):
            return float(np.asarray(loss))

    def train_batch(self, batch) -> Tuple[Any, Dict[str, Any]]:
        """batch: device tree with leading [gas] dim ({'input_ids', optional
        'labels'}).  Returns (mean_loss, metrics)."""
        if isinstance(batch, dict):
            if "positions" in batch:
                raise NotImplementedError(
                    "offload_param: custom positions not supported")
            tokens_all = batch["input_ids"]
            labels_all = batch.get("labels")
        else:
            tokens_all, labels_all = batch, None

        self._host_grads = None
        rng = jax.random.fold_in(jax.random.PRNGKey(self.config.seed),
                                 self.step_count)
        losses = []
        for g in range(self.gas):
            tokens = tokens_all[g]
            if labels_all is not None:
                labels = labels_all[g]
            else:
                _, labels = self._tokens_labels(tokens)
            losses.append(self._micro_fwd_bwd(
                tokens, labels, jax.random.fold_in(rng, g)))

        lr = float(self.lr_schedule(self.step_count)) \
            if callable(self.lr_schedule) else float(self.lr_schedule)
        grad_norm = self._apply_adam(lr)
        self.step_count += 1
        with jax.transfer_guard("allow"):
            mean_loss = float(np.mean([np.asarray(l) for l in losses]))
        metrics = {"loss": jnp.float32(mean_loss),
                   "grad_norm": jnp.float32(grad_norm),
                   "loss_scale": jnp.float32(1.0),
                   "step_applied": jnp.bool_(True)}
        return metrics["loss"], metrics

    # ------------------------------------------------------------------
    # Host Adam over NVMe-streamed state (same read/compute/writeback
    # pipeline as SwappedAdamOptimizer, fused with the bf16 param rewrite).
    # ------------------------------------------------------------------
    def _apply_adam(self, lr: float) -> float:
        grads = self._host_grads
        assert grads is not None, "train window produced no gradients"
        inv_gas = 1.0 / self.gas
        sq = 0.0
        for name, g in grads.items():
            g *= inv_gas
            # weight corrects for shards held by several processes (weight
            # 1/#holders; single-process weights are all 1.0) so the global
            # sum counts each distinct shard exactly once
            sq += self._shard_weight.get(name, 1.0) * float(np.vdot(g, g))
        if self._multi:
            # every process must clip with the SAME global norm
            from jax.experimental import multihost_utils

            sq = float(np.sum(multihost_utils.process_allgather(
                np.float64(sq))))
        gnorm = math.sqrt(sq)
        factor = 1.0
        if self.clip and self.clip > 0 and gnorm > self.clip:
            factor = self.clip / (gnorm + 1e-6)

        bf16 = _bf16()
        step = self.step_count + 1
        for name in self._leaf_names:
            g = grads[name]
            if factor != 1.0:
                g = g * factor
            master = self.swapper.read(f"{name}.master")
            m = self.swapper.read(f"{name}.exp_avg")
            v = self.swapper.read(f"{name}.exp_avg_sq")
            # the host Adam steps fp32; bf16 is only the at-rest format
            m32 = (np.ascontiguousarray(m, np.float32) if self._mu16 else m)
            v32 = (np.ascontiguousarray(v, np.float32) if self._nu16 else v)
            out16 = np.empty(master.size, np.uint16)
            self.adam.step_flat(master.reshape(-1),
                                np.ascontiguousarray(g.reshape(-1)),
                                m32.reshape(-1), v32.reshape(-1), step=step,
                                bf16_out=out16, lr=lr)
            self.swapper.write(f"{name}.master", master)
            self.swapper.write(f"{name}.exp_avg",
                               m32.astype(bf16) if self._mu16 else m32)
            self.swapper.write(f"{name}.exp_avg_sq",
                               v32.astype(bf16) if self._nu16 else v32)
            new16 = out16.view(bf16).reshape(master.shape)
            self.swapper.write(f"{name}.param", new16)
            if name in self._stem_dev:
                self._stem_dev[name] = self._put_flat(name, new16)
            elif name in self._head_dev:
                self._head_dev[name] = self._put_flat(name, new16)
        if self._multi:
            # shard-named leaves: rebuild the global stem/head arrays from
            # the updated shard files once, after all shards stepped
            for k in self.stem_keys:
                self._stem_dev[k] = self._put_flat(k)
            for k in self.head_keys:
                self._head_dev[k] = self._put_flat(k)
        self._host_grads = None
        return gnorm

    # ------------------------------------------------------------------
    # Checkpointing — streamed leaf-by-leaf so the full 12 B/param state is
    # never resident in host RAM (the invariant the whole module exists for).
    # ------------------------------------------------------------------
    def _read_leaf_state(self, name: str):
        return (self.swapper.read(f"{name}.master"),
                self.swapper.read(f"{name}.exp_avg"),
                self.swapper.read(f"{name}.exp_avg_sq"))

    def _write_leaf_state(self, name: str, master, m, v) -> None:
        master = np.ascontiguousarray(master, np.float32)
        bf16 = _bf16()
        self.swapper.write(f"{name}.master", master)
        # checkpoint files stay fp32; the STORE keeps its at-rest dtype
        self.swapper.write(f"{name}.exp_avg", np.ascontiguousarray(
            m, bf16 if self._mu16 else np.float32))
        self.swapper.write(f"{name}.exp_avg_sq", np.ascontiguousarray(
            v, bf16 if self._nu16 else np.float32))
        # the bf16 compute params derive from the restored masters
        new16 = master.astype(_bf16())
        self.swapper.write(f"{name}.param", new16)
        if name in self._stem_dev:
            self._stem_dev[name] = self._put_flat(name, new16)
        elif name in self._head_dev:
            self._head_dev[name] = self._put_flat(name, new16)

    def _ckpt_dir(self, base: str) -> str:
        """Multi-host shard state is process-local — one subdir per host."""
        return (os.path.join(base, f"proc{jax.process_index()}")
                if self._multi else base)

    def save_state_files(self, out_dir: str) -> None:
        from ..offload import save_offload_state_files

        save_offload_state_files(self._ckpt_dir(out_dir), self._leaf_names,
                                 self._read_leaf_state, self.step_count)

    def load_state_files(self, in_dir: str) -> None:
        from ..offload import load_offload_state_files

        shapes = {n: self.swapper._shapes[f"{n}.master"]
                  for n in self._leaf_names}
        self.step_count = load_offload_state_files(
            self._ckpt_dir(in_dir), self._leaf_names, self._write_leaf_state,
            expected_shapes=shapes)
        if self._multi:
            for k in self.stem_keys:
                self._stem_dev[k] = self._put_flat(k)
            for k in self.head_keys:
                self._head_dev[k] = self._put_flat(k)

    def read_masters(self) -> Dict[str, np.ndarray]:
        return {n: self.swapper.read(f"{n}.master")
                for n in self._leaf_names}
