"""Progressive Layer Drop (reference ``runtime/progressive_layer_drop.py``).

The schedule is the reference's: theta(t) = (1 - theta_bar)·exp(-gamma·t) +
theta_bar — keep probability decays from 1.0 toward ``theta`` as training
progresses, so early training sees the full network and later steps train a
stochastically shallower one (arXiv:2010.13369).

Like the reference, the engine owns the SCHEDULE and the model applies the
drop: the reference exposes ``get_state()['pld_theta']`` for the client
model's forward; here ``pld_keep_mask`` turns (theta, rng) into per-layer
keep decisions the scan-based transformer folds in (depth-scaled: layer i
(1-based) of L keeps with probability 1 - (i/L)·(1-theta), so the first
layer keeps with ~1 - (1-theta)/L and the last with theta — deeper layers
drop more, per the paper's schedule).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..utils.logging import log_dist


class ProgressiveLayerDrop:
    def __init__(self, theta: float = 0.5, gamma: float = 0.001):
        self.theta = theta
        self.gamma = gamma
        self.current_theta = 1.0
        log_dist(f"Enabled progressive layer dropping (theta = {self.theta})",
                 ranks=[0])

    def get_state(self):
        return {"progressive_layer_drop": True, "pld_theta": self.get_theta()}

    def get_theta(self) -> float:
        return self.current_theta

    def update_state(self, global_step: int) -> float:
        self.current_theta = float(
            (1.0 - self.theta) * np.exp(-self.gamma * global_step) + self.theta)
        return self.current_theta


def pld_theta_at(step, theta: float, gamma: float):
    """Traced schedule for use inside a jitted step."""
    return (1.0 - theta) * jnp.exp(-gamma * step.astype(jnp.float32)) + theta


def pld_keep_mask(rng, num_layers: int, theta):
    """Per-layer keep decisions [L] bool: layer i keeps with probability
    1 - (i+1)/L · (1 - theta) (paper's depth-scaled schedule; the first
    layers almost never drop, the last drops with ~(1-theta))."""
    depth = (jnp.arange(num_layers, dtype=jnp.float32) + 1.0) / num_layers
    p_keep = 1.0 - depth * (1.0 - theta)
    return jax.random.uniform(rng, (num_layers,)) < p_keep
