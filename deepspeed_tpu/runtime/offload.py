"""Engine-side offload wiring — ZeRO-Offload and ZeRO-Infinity placement.

Three placements for the fp32 optimizer state (masters + Adam moments),
mirroring the reference's offload matrix (``runtime/zero/stage_1_and_2.py``
cpu_offload, ``runtime/zero/stage3.py:502`` offload_optimizer/offload_param,
``runtime/swap_tensor/partitioned_optimizer_swapper.py``):

  streamed   state rests in pinned host memory; XLA streams dp-shards over
             PCIe into the ONE jitted step and lands them back on the host
             (sharding memory kinds — no torch-style hook orchestration).
  host_step  state resident in host RAM; the device runs a grad-only jitted
             step and the host applies the native SIMD Adam between steps.
  nvme       as host_step, but state lives in per-leaf files driven by the
             native aio engine with a read/compute/writeback pipeline
             (ZeRO-Infinity).

`resolve_offload_mode` owns the decision (including the reference's
``host_step`` auto heuristic); `HostSteppedOffload` owns the host/NVMe
optimizer and the device<->host exchange; `apply_streamed_placement` owns
the pinned-host placement.  The engine composes these — it holds no offload
policy of its own.
"""
from __future__ import annotations

from typing import Dict, List

import numpy as np

import jax

from ..utils.logging import logger, log_dist
from ..parallel.mesh import dp_world_size


def resolve_offload_mode(config, mesh, use_master_weights: bool,
                         fp16_enabled: bool, has_compression: bool) -> str:
    """Which optimizer-state placement this config selects.

    Returns one of ``"none" | "streamed" | "host_step" | "nvme"``.

    ``device=cpu`` with ONE data shard: park-and-stream would still pull the
    FULL fp32 master/m/v into HBM inside the step, so single-shard cpu
    offload routes through the same host-step path as NVMe (state in RAM
    instead of on disk) unless ``host_step=False`` forces streaming.
    """
    zc = config.zero_config
    dev = zc.offload_optimizer.device if zc.offload_optimizer else "none"
    dev = getattr(dev, "value", dev)
    if dev == "nvme":
        return "nvme"
    if dev != "cpu":
        return "none"
    hs = zc.offload_optimizer.host_step
    if hs is not None:
        return "host_step" if bool(hs) else "streamed"
    # auto: host step only where it's BOTH needed (one data shard —
    # streaming would pull the full fp32 state into HBM inside the step)
    # and supported by the host path's preconditions; otherwise keep the
    # streamed placement, which handles fp32/fp16/any-optimizer/
    # compression and checkpointing
    opt_cfg = config.optimizer
    opt_type = (opt_cfg.type if opt_cfg else "adamw").lower()
    host_step = (dp_world_size(mesh) == 1
                 and use_master_weights
                 and not fp16_enabled
                 and not has_compression
                 and opt_type in ("adam", "adamw"))
    return "host_step" if host_step else "streamed"


def apply_streamed_placement(opt_state, master):
    """ZeRO-Offload streamed placement: move optimizer state (and fp32
    masters) to pinned host memory so HBM never holds them at rest; XLA
    streams the dp-shards over PCIe into the jitted step (reference
    stage_1_and_2.py:1041-1124 CPU offload, TPU-native form).

    Returns ``(opt_state, master, dev_shardings, active)`` where
    ``dev_shardings`` are the matching device-kind shardings that stream the
    leaves INTO the step (XLA refuses compute on host-placed operands), or
    ``None`` when the placement is a no-op (CPU backend).
    """
    if jax.devices()[0].platform == "cpu":
        # Host and "device" memory are the same RAM on the CPU backend (and
        # XLA cannot compile placement annotations on a forced multi-device
        # host mesh) — the placement would be a no-op; the code path is
        # still exercised minus memory kinds.
        logger.warning(
            "offload_optimizer.device=cpu: CPU backend — host memory IS "
            "device memory; offload placement skipped")
        return opt_state, master, None, False
    to_host = lambda x: jax.device_put(  # noqa: E731
        x, x.sharding.with_memory_kind("pinned_host"))
    opt_state = jax.tree_util.tree_map(to_host, opt_state)
    if master is not None:
        master = jax.tree_util.tree_map(to_host, master)
    to_dev = lambda x: x.sharding.with_memory_kind("device")  # noqa: E731
    dev_shardings = (
        jax.tree_util.tree_map(to_dev, master) if master is not None else None,
        jax.tree_util.tree_map(to_dev, opt_state))
    return opt_state, master, dev_shardings, True


class HostSteppedOffload:
    """Owns the host/NVMe optimizer state and the device<->host exchange for
    the grad-only train path (ZeRO-Offload host step / ZeRO-Infinity).

    Step cost = one fp32-grad download + one bf16-param upload per step
    (params bytes x6 round trip) — ~0.4s/step for a 1B model over a TPU-VM's
    local PCIe.  On remote/tunneled device backends that link can be orders
    of magnitude slower; offload throughput follows the host link, by
    construction.
    """

    def __init__(self, config, master, param_shardings, storage: str,
                 fp16_enabled: bool, has_compression: bool):
        if master is None:
            raise ValueError("optimizer offload requires bf16/fp16 "
                             "compute (fp32 params have no separate masters "
                             "to offload)")
        if fp16_enabled:
            raise NotImplementedError(
                "host-stepped offload currently pairs with bf16 (fp16 dynamic "
                "loss scaling would need host-side overflow handling)")
        if has_compression:
            raise NotImplementedError(
                "compression_training with host-stepped optimizer offload is "
                "not supported: the grad-only step differentiates the raw "
                "params and would silently skip the QAT/pruning transform")
        # the host Adam sweep + any aio threads inherit this affinity —
        # cross-NUMA master/moment traffic is the reference's numactl case
        from ..utils.numa import bind_for_offload

        bind_for_offload()
        opt_cfg = config.optimizer
        opt_type = (opt_cfg.type if opt_cfg else "adamw").lower()
        if opt_type not in ("adam", "adamw"):
            raise NotImplementedError(
                f"host-stepped offload runs the native CPU Adam kernel; "
                f"optimizer {opt_type!r} is not supported on the host path")
        from .swap_tensor import HostAdamOptimizer, SwappedAdamOptimizer

        self.storage = storage
        zc = config.zero_config.offload_optimizer
        p = dict(opt_cfg.params) if opt_cfg else {}
        flat, treedef = jax.tree_util.tree_flatten_with_path(master)
        self.names: List[str] = [jax.tree_util.keystr(path)
                                 for path, _ in flat]
        self.treedef = treedef
        self.param_shardings = param_shardings
        with jax.transfer_guard("allow"):
            masters_np = {n: np.asarray(x, np.float32)
                          for n, (_, x) in zip(self.names, flat)}
        adam_kw = dict(
            lr=p.get("lr", 1e-3), betas=tuple(p.get("betas", (0.9, 0.999))),
            eps=p.get("eps", 1e-8), weight_decay=p.get("weight_decay", 0.0),
            adamw_mode=bool(p.get("adam_w_mode", opt_type == "adamw")))
        if storage == "cpu":
            self.optimizer = HostAdamOptimizer(masters_np, **adam_kw)
            log_dist("ZeRO-Offload: optimizer state in host RAM "
                     f"({self.optimizer.state_bytes() / 1e9:.2f} GB), "
                     "host SIMD Adam step", ranks=[0])
        else:
            self.optimizer = SwappedAdamOptimizer(
                masters_np, zc.nvme_path,
                aio_threads=max(config.aio.thread_count,
                                config.aio.queue_depth // 2, 1),
                pipeline=bool(zc.pipeline_read or zc.pipeline_write),
                **adam_kw)
            log_dist(f"ZeRO-Infinity: optimizer state on NVMe at "
                     f"{zc.nvme_path} "
                     f"({self.optimizer.state_bytes() / 1e9:.2f} GB)",
                     ranks=[0])

    # -- per-step exchange --------------------------------------------------
    def host_step(self, grads_tree, lr: float):
        """fp32 grads (device tree) -> host Adam -> new bf16 param tree."""
        import ml_dtypes

        flat_grads = jax.tree_util.tree_leaves(grads_tree)
        with jax.transfer_guard("allow"):
            grads_np = {n: np.asarray(g, np.float32)
                        for n, g in zip(self.names, flat_grads)}
        bf16 = self.optimizer.step(grads_np, lr=lr)
        leaves = []
        shard_leaves = jax.tree_util.tree_leaves(self.param_shardings)
        for n, sh in zip(self.names, shard_leaves):
            leaves.append(jax.device_put(bf16[n].view(ml_dtypes.bfloat16), sh))
        return jax.tree_util.tree_unflatten(self.treedef, leaves)

    # -- checkpointing ------------------------------------------------------
    # Streamed leaf-by-leaf (one master/m/v triple resident at a time), so
    # checkpointing never materializes the full 12 B/param state in host RAM
    # — the same reason the reference streams swapped state to files next to
    # the torch checkpoint (``swap_tensor/optimizer_utils.py``).
    def save_state_files(self, out_dir: str) -> None:
        save_offload_state_files(out_dir, self.names,
                                 self.optimizer.read_state,
                                 int(self.optimizer.step_count))

    def load_state_files(self, in_dir: str) -> None:
        shapes = {n: self.optimizer.state_shape(n) for n in self.names}
        step = load_offload_state_files(in_dir, self.names,
                                        self.optimizer.write_state,
                                        expected_shapes=shapes)
        self.optimizer.step_count = step


def save_offload_state_files(out_dir: str, names, read_state,
                             step_count: int) -> None:
    """One .npy per (leaf, state) + meta.json, written sequentially —
    peak extra host memory is one leaf's fp32 triple."""
    import json
    import os

    os.makedirs(out_dir, exist_ok=True)
    for i, name in enumerate(names):
        master, m, v = read_state(name)
        np.save(os.path.join(out_dir, f"{i:05d}.master.npy"),
                np.asarray(master, np.float32))
        np.save(os.path.join(out_dir, f"{i:05d}.exp_avg.npy"),
                np.asarray(m, np.float32))
        np.save(os.path.join(out_dir, f"{i:05d}.exp_avg_sq.npy"),
                np.asarray(v, np.float32))
    with open(os.path.join(out_dir, "meta.json"), "w") as f:
        json.dump({"step_count": int(step_count), "names": list(names)}, f)


def load_offload_state_files(in_dir: str, names, write_state,
                             expected_shapes=None) -> int:
    """Counterpart of :func:`save_offload_state_files`; returns the saved
    step count.  Validates the leaf list against the engine's and (when
    ``expected_shapes`` maps name->shape) each leaf's shape — leaf names are
    keystr paths, so a same-architecture model of a different width would
    otherwise pass name validation and silently corrupt the swap files."""
    import json
    import os

    with open(os.path.join(in_dir, "meta.json")) as f:
        meta = json.load(f)
    if list(meta["names"]) != list(names):
        raise ValueError(
            "offload checkpoint param-tree mismatch: checkpoint has "
            f"{len(meta['names'])} leaves, engine has {len(names)}")
    for i, name in enumerate(names):
        master = np.load(os.path.join(in_dir, f"{i:05d}.master.npy"))
        if expected_shapes is not None and \
                tuple(master.shape) != tuple(expected_shapes[name]):
            raise ValueError(
                f"offload checkpoint shape mismatch at {name!r}: "
                f"checkpoint {master.shape}, engine "
                f"{tuple(expected_shapes[name])}")
        write_state(
            name, master,
            np.load(os.path.join(in_dir, f"{i:05d}.exp_avg.npy")),
            np.load(os.path.join(in_dir, f"{i:05d}.exp_avg_sq.npy")))
    return int(meta["step_count"])
