"""LR schedules (reference ``runtime/lr_schedules.py``, 763 LoC).

Implements the reference's four schedules — ``LRRangeTest``, ``OneCycle``,
``WarmupLR``, ``WarmupDecayLR`` (reference :18-22) — as pure ``step -> lr``
callables (optax-schedule shaped) so they compile into the jitted train step.
A small registry + ``get_lr_scheduler`` mirrors the config-driven construction
(engine._configure_lr_scheduler).
"""
from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional

import jax.numpy as jnp

Schedule = Callable[[Any], Any]  # step (int array) -> lr (float array)

LR_RANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"
COSINE_ANNEALING = "CosineAnnealing"  # TPU extra: common for LLM pretraining

VALID_LR_SCHEDULES = [LR_RANGE_TEST, ONE_CYCLE, WARMUP_LR, WARMUP_DECAY_LR, COSINE_ANNEALING]


def lr_range_test(lr_range_test_min_lr: float = 1e-3, lr_range_test_step_size: int = 2000,
                  lr_range_test_step_rate: float = 1.0,
                  lr_range_test_staircase: bool = False, **_) -> Schedule:
    """Increase LR over time to find a good range (reference LRRangeTest)."""

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        interval = (jnp.floor(step / lr_range_test_step_size)
                    if lr_range_test_staircase else step / lr_range_test_step_size)
        return lr_range_test_min_lr * (1.0 + interval * lr_range_test_step_rate)

    return schedule


def one_cycle(cycle_min_lr: float = 0.0, cycle_max_lr: float = 1e-3,
              cycle_first_step_size: int = 2000, cycle_second_step_size: Optional[int] = None,
              cycle_first_stair_count: int = 0, cycle_second_stair_count: Optional[int] = None,
              decay_step_size: int = 0, decay_lr_rate: float = 0.0, **_) -> Schedule:
    """Triangular one-cycle LR with optional post-cycle decay (reference OneCycle).

    Momentum cycling from the reference is handled by the optimizer wrapper
    when enabled; the LR leg is here.
    """
    second = cycle_second_step_size if cycle_second_step_size is not None else cycle_first_step_size
    total_cycle = cycle_first_step_size + second

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        in_up = step < cycle_first_step_size
        up_frac = jnp.clip(step / cycle_first_step_size, 0.0, 1.0)
        down_frac = jnp.clip((step - cycle_first_step_size) / second, 0.0, 1.0)
        cyc_lr = jnp.where(
            in_up,
            cycle_min_lr + (cycle_max_lr - cycle_min_lr) * up_frac,
            cycle_max_lr - (cycle_max_lr - cycle_min_lr) * down_frac,
        )
        if decay_step_size > 0:
            decay_steps = jnp.maximum(step - total_cycle, 0.0) / decay_step_size
            decayed = cycle_min_lr / (1.0 + decay_lr_rate * decay_steps)
            return jnp.where(step > total_cycle, decayed, cyc_lr)
        return jnp.where(step > total_cycle, cycle_min_lr, cyc_lr)

    return schedule


def warmup_lr(warmup_min_lr: float = 0.0, warmup_max_lr: float = 1e-3,
              warmup_num_steps: int = 1000, warmup_type: str = "log", **_) -> Schedule:
    """Warmup then hold (reference WarmupLR; log or linear ramp)."""

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        frac = jnp.clip((step + 1.0) / max(warmup_num_steps, 1), 1e-8, 1.0)
        if warmup_type == "log":
            gamma = jnp.clip(jnp.log(step + 1.0) / math.log(max(warmup_num_steps, 2)), 0.0, 1.0)
        else:
            gamma = frac
        return jnp.where(step >= warmup_num_steps, warmup_max_lr,
                         warmup_min_lr + (warmup_max_lr - warmup_min_lr) * gamma)

    return schedule


def warmup_decay_lr(total_num_steps: int, warmup_min_lr: float = 0.0,
                    warmup_max_lr: float = 1e-3, warmup_num_steps: int = 1000,
                    warmup_type: str = "log", **_) -> Schedule:
    """Warmup then linear decay to zero over total_num_steps (reference WarmupDecayLR)."""
    wl = warmup_lr(warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type)

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        decay = jnp.clip(
            (total_num_steps - step) / max(float(total_num_steps - warmup_num_steps), 1.0),
            0.0, 1.0)
        return jnp.where(step < warmup_num_steps, wl(step), warmup_max_lr * decay)

    return schedule


def cosine_annealing(total_num_steps: int, warmup_num_steps: int = 0,
                     warmup_max_lr: float = 1e-3, warmup_min_lr: float = 0.0,
                     cosine_min_ratio: float = 0.1, **_) -> Schedule:
    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        warm = warmup_min_lr + (warmup_max_lr - warmup_min_lr) * jnp.clip(
            step / max(warmup_num_steps, 1), 0.0, 1.0)
        prog = jnp.clip((step - warmup_num_steps) / max(total_num_steps - warmup_num_steps, 1),
                        0.0, 1.0)
        floor = warmup_max_lr * cosine_min_ratio
        cos = floor + (warmup_max_lr - floor) * 0.5 * (1.0 + jnp.cos(jnp.pi * prog))
        return jnp.where(step < warmup_num_steps, warm, cos)

    return schedule


_REGISTRY: Dict[str, Callable[..., Schedule]] = {
    LR_RANGE_TEST: lr_range_test,
    ONE_CYCLE: one_cycle,
    WARMUP_LR: warmup_lr,
    WARMUP_DECAY_LR: warmup_decay_lr,
    COSINE_ANNEALING: cosine_annealing,
}


def get_lr_scheduler(type_name: str, params: Optional[Dict] = None) -> Schedule:
    if type_name not in _REGISTRY:
        raise ValueError(f"unknown scheduler {type_name!r}; valid: {VALID_LR_SCHEDULES}")
    return _REGISTRY[type_name](**(params or {}))


def constant_lr(lr: float) -> Schedule:
    return lambda step: jnp.full((), lr, jnp.float32)
