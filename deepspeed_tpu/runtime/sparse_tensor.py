"""Row-sparse gradient representation (reference ``runtime/sparse_tensor.py``).

The reference wraps torch sparse COO tensors so embedding gradients travel
as (indices, values) through its allreduce (``engine.py:2369-2440``), saving
comm when the touched vocabulary rows are far fewer than the table.

TPU/XLA position, stated honestly: inside one jitted SPMD program the
embedding backward is a scatter-add XLA fuses into the gradient buffer, and

  - under ZeRO stage >= 1 the [V, d] gradient is reduce-scattered (each
    shard receives 1/dp of it) — the dense exchange is already sharded;
  - under tensor parallelism the table is vocab-sharded (P('model', None))
    and the gradient never exists unsharded.

What XLA does NOT do is row-compress a pure-DP stage-0 allreduce, and
static shapes make the reference's variable-nnz exchange inexpressible as
one program.  ``deepspeed_tpu`` therefore REJECTS ``sparse_gradients: true``
at config time (accepted-but-inert knobs are lies) and offers this module
for host-side tooling parity: a fixed-width row-sparse value type with the
reference's ``to_dense``/``add``/``sparse_allreduce`` surface, usable in
custom data/comm pipelines where the row count is static (B·S rows per
step, duplicates allowed).
"""
from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class SparseTensor:
    """Fixed-width row-sparse [V, d] tensor: ``rows [N] i32`` (duplicates
    allowed — they sum) + ``values [N, d]``.  The static row count N is what
    makes this jit-compatible where torch COO's dynamic nnz is not."""

    rows: jnp.ndarray
    values: jnp.ndarray
    dense_rows: int = dataclasses.field(metadata=dict(static=True),
                                        default=0)

    def to_dense(self) -> jnp.ndarray:
        out = jnp.zeros((self.dense_rows, self.values.shape[-1]),
                        self.values.dtype)
        return out.at[self.rows].add(self.values)

    def add(self, other: "SparseTensor") -> "SparseTensor":
        if other.dense_rows != self.dense_rows:
            raise ValueError("dense_rows mismatch")
        return SparseTensor(
            rows=jnp.concatenate([self.rows, other.rows]),
            values=jnp.concatenate([self.values, other.values]),
            dense_rows=self.dense_rows)

    @property
    def nbytes(self) -> int:
        return int(self.rows.size * self.rows.dtype.itemsize
                   + self.values.size * self.values.dtype.itemsize)


def from_embedding_grad(tokens: jnp.ndarray, cotangent: jnp.ndarray,
                        vocab_size: int) -> SparseTensor:
    """The embedding-lookup gradient as row-sparse data: lookup
    ``E[tokens]`` with output cotangent ``g`` has gradient
    ``scatter_add(zeros, tokens, g)`` — this keeps the (token, g) pairs
    instead (N = tokens.size static)."""
    return SparseTensor(rows=tokens.reshape(-1).astype(jnp.int32),
                        values=cotangent.reshape(
                            -1, cotangent.shape[-1]),
                        dense_rows=vocab_size)


def sparse_allreduce(st: SparseTensor, axis_name: str) -> SparseTensor:
    """Inside a shard_map region: exchange (rows, values) over ``axis_name``
    — wire bytes = dp·N·(4 + d·itemsize) vs the dense V·d·itemsize
    (the reference's sparse_allreduce win, engine.py:2404)."""
    from jax import lax

    rows = lax.all_gather(st.rows, axis_name, tiled=True)
    values = lax.all_gather(st.values, axis_name, tiled=True)
    return SparseTensor(rows=rows, values=values, dense_rows=st.dense_rows)
