"""Orbax-backed checkpointing (the default CheckpointEngine).

Directory layout keeps the reference's shape (engine.py:2525-2592):

    save_dir/
      latest                  # text file with the newest tag (reference `latest`)
      <tag>/
        state/                # orbax sharded pytree: TrainState
        client_state.json     # engine counters + user client_state
        ds_config.json        # config snapshot for tag validation

Because orbax stores *global* (logically unsharded) arrays with per-shard
layout metadata, a checkpoint written on one (tp,pp,dp) layout restores onto
any other — the reference needed a whole subsystem for this (universal
checkpoint, ``deepspeed/checkpoint/``, reshape tools); here resharding is the
restore path itself: we restore against abstract arrays carrying the *current*
mesh's shardings.  ZeRO-3's "consolidated fp16 save" (engine.py:3287) is
``save_16bit_model`` below: a gather-free orbax save of the compute params.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import jax
import numpy as np

from .checkpoint_engine import CheckpointEngine
from ...resilience.fault_injection import (SITE_CKPT_LOAD, SITE_CKPT_SAVE,
                                           SITE_LATEST_PUBLISH, maybe_fire)
from ...resilience.integrity import (LATEST_FILE, build_manifest,
                                     mark_incomplete, verify_checkpoint_dir,
                                     write_manifest)
from ...utils.logging import logger, log_dist


def _ocp():
    import orbax.checkpoint as ocp

    return ocp


class OrbaxCheckpointEngine(CheckpointEngine):
    def save(self, state_dict: Any, path: str) -> None:
        ocp = _ocp()
        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save(os.path.abspath(path), state_dict, force=True)

    def load(self, path: str, target: Any = None, shardings: Any = None) -> Any:
        ocp = _ocp()
        path = os.path.abspath(path)
        with ocp.StandardCheckpointer() as ckptr:
            if target is not None:
                abstract = jax.tree_util.tree_map(
                    lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s)
                    if hasattr(x, "shape") else x,
                    target, shardings) if shardings is not None else jax.tree_util.tree_map(
                        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=getattr(
                            x, "sharding", None)) if hasattr(x, "shape") else x, target)
                return ckptr.restore(path, abstract)
            return ckptr.restore(path)


def _read_latest(save_dir: str) -> Optional[str]:
    p = os.path.join(save_dir, LATEST_FILE)
    if os.path.exists(p):
        with open(p) as f:
            return f.read().strip()
    return None


def save_engine_checkpoint(engine, save_dir: str, tag: Optional[str] = None,
                           client_state: Optional[Dict] = None, save_latest: bool = True):
    tag = tag or f"global_step{engine.global_steps}"
    ckpt_dir = os.path.join(save_dir, str(tag))
    maybe_fire(SITE_CKPT_SAVE, path=ckpt_dir, tag=str(tag))
    os.makedirs(ckpt_dir, exist_ok=True)
    if jax.process_index() == 0:
        # torn-save marker: removed when the manifest commits; a crash in
        # between leaves a tag verify_checkpoint_dir rejects (vs. a LEGACY
        # manifest-less tag, which stays loadable)
        mark_incomplete(ckpt_dir)

    async_save = bool(getattr(engine.config, "checkpoint_config", None)
                      and engine.config.checkpoint_config.async_save)
    if async_save:
        from .async_engine import (AsyncOrbaxCheckpointEngine,
                                   wait_for_pending_checkpoint)

        # serialize against a still-pending previous save (orbax would queue
        # it anyway; joining keeps the latest-file ordering deterministic)
        wait_for_pending_checkpoint(engine)
        if getattr(engine, "_async_ckpt_engine", None) is None:
            engine._async_ckpt_engine = AsyncOrbaxCheckpointEngine()
        ce: Any = engine._async_ckpt_engine
    else:
        ce = OrbaxCheckpointEngine()
    if engine.state is not None:
        ce.save(engine.state, os.path.join(ckpt_dir, "state"))

    offload = (getattr(engine, "_offload", None)
               or getattr(engine, "_param_offload", None))
    if offload is not None and (jax.process_index() == 0
                                or getattr(offload, "_multi", False)):
        # Host-stepped offload (ZeRO-Offload host RAM / ZeRO-Infinity NVMe):
        # the fp32 masters + Adam moments live OUTSIDE the TrainState, so
        # they ride alongside the orbax tree, streamed one leaf at a time
        # (reference swap_tensor/optimizer_utils.py checkpoints swapped
        # state the same way: tensors to files next to the torch checkpoint).
        offload.save_state_files(os.path.join(ckpt_dir, "offload_optimizer"))

    from ...checkpoint.universal import CHECKPOINT_VERSION

    meta = {
        "checkpoint_version": CHECKPOINT_VERSION,
        "global_steps": engine.global_steps,
        "skipped_steps": engine.skipped_steps,
        "micro_steps": engine.micro_steps,
        "param_count": engine.param_count,
        "zero_stage": engine.zero_stage,
        "mesh_shape": {k: int(v) for k, v in dict(engine.mesh.shape).items()},
        "client_state": client_state or {},
    }
    # curriculum / data-sampler state (reference DeepSpeedDataSampler
    # state_dict rides the checkpoint, data_sampler.py): without it a
    # resumed run restarts the difficulty schedule from zero
    sampler = getattr(getattr(engine, "training_dataloader", None),
                      "data_sampler", None)
    if sampler is not None and hasattr(sampler, "state_dict"):
        meta["data_sampler"] = sampler.state_dict()
    elif engine.curriculum_scheduler is not None:
        meta["curriculum"] = engine.curriculum_scheduler.get_state()
    if jax.process_index() == 0:
        with open(os.path.join(ckpt_dir, "client_state.json"), "w") as f:
            json.dump(meta, f, indent=2)
        with open(os.path.join(ckpt_dir, "ds_config.json"), "w") as f:
            json.dump(engine.config.to_dict(), f, indent=2, default=str)
    manifest = build_manifest(engine, str(tag)) \
        if jax.process_index() == 0 else None
    if async_save:
        # commit semantics: `latest` is published by the finalizer thread
        # only once the background write is durable — the caller returns
        # now, having paid only the device->host snapshot.  The manifest is
        # finalized there too: its payload listing must see the durable
        # orbax files, and its presence is the commit marker.
        from .async_engine import async_save_engine_checkpoint

        async_save_engine_checkpoint(engine, save_dir, ckpt_dir, str(tag),
                                     save_latest, manifest=manifest)
        log_dist(f"async checkpoint {tag} snapshotted; committing in "
                 f"background -> {ckpt_dir}", ranks=[0])
        return ckpt_dir
    if jax.process_index() == 0:
        # manifest last (commit marker), then the `latest` pointer: a crash
        # between any two writes leaves either an uncommitted tag dir or a
        # committed tag `latest` doesn't see — never a published torn tag
        write_manifest(ckpt_dir, manifest)
        if save_latest:
            maybe_fire(SITE_LATEST_PUBLISH, path=save_dir, tag=str(tag))
            with open(os.path.join(save_dir, LATEST_FILE), "w") as f:
                f.write(str(tag))
    log_dist(f"saved checkpoint {tag} -> {ckpt_dir}", ranks=[0])
    return ckpt_dir


def load_engine_checkpoint(engine, load_dir: str, tag: Optional[str] = None,
                           load_optimizer_states: bool = True, load_module_only: bool = False):
    if getattr(engine, "_pending_ckpt_thread", None) is not None:
        # never read through an in-flight async save
        from .async_engine import wait_for_pending_checkpoint

        wait_for_pending_checkpoint(engine)
    tag = tag or _read_latest(load_dir)
    if tag is None:
        logger.warning(f"no `latest` file in {load_dir}; nothing loaded")
        return None, {}
    ckpt_dir = os.path.join(load_dir, str(tag))
    if not os.path.isdir(ckpt_dir):
        raise FileNotFoundError(f"checkpoint tag dir not found: {ckpt_dir}")
    maybe_fire(SITE_CKPT_LOAD, path=ckpt_dir, tag=str(tag))
    if getattr(getattr(engine, "config", None), "resilience", None) is None \
            or engine.config.resilience.verify_on_load:
        # manifest check (raises CheckpointIntegrityError on a torn or
        # bit-rotted tag) BEFORE any engine state is mutated, so a caller
        # like ElasticAgent can quarantine and fall back cleanly
        verify_checkpoint_dir(ckpt_dir)

    offload = (getattr(engine, "_offload", None)
               or getattr(engine, "_param_offload", None))
    if offload is not None and (load_module_only or not load_optimizer_states):
        # Checked BEFORE any state mutation: host-stepped/param-offload
        # engines derive the device params FROM the host fp32 masters on
        # every step — restoring state.params alone would be silently
        # overwritten by stale masters at the next step (and a param-offload
        # engine has no orbax state at all).
        raise NotImplementedError(
            "partial checkpoint loads (load_module_only / "
            "load_optimizer_states=False) are not supported with a "
            "host-stepped or param-offload optimizer: params are derived "
            "from the host fp32 masters, so a weights-only load would be "
            "discarded at the next step.  Load the full checkpoint, or "
            "export weights via checkpoint/zero_to_fp32.py.")

    ce = OrbaxCheckpointEngine()
    if engine.state is not None:
        # Restore against the CURRENT state's shardings — this IS
        # cross-topology resharding (saved on any mesh layout, restored onto
        # this one).
        restored = ce.load(os.path.join(ckpt_dir, "state"), target=engine.state)
        if load_module_only or not load_optimizer_states:
            restored = dataclasses_replace_state(engine.state, restored,
                                                 module_only=load_module_only,
                                                 opt=load_optimizer_states)
        engine.state = restored

    if offload is not None:
        off_dir = os.path.join(ckpt_dir, "offload_optimizer")
        if not os.path.isdir(off_dir):
            raise FileNotFoundError(
                f"checkpoint {tag} has no offload_optimizer/ but this "
                "engine runs a host-stepped offload optimizer — it was saved "
                "without offload or from an incompatible config")
        offload.load_state_files(off_dir)

    meta = {}
    meta_path = os.path.join(ckpt_dir, "client_state.json")
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        engine.global_steps = meta.get("global_steps", engine.global_steps)
        engine.skipped_steps = meta.get("skipped_steps", engine.skipped_steps)
        engine.micro_steps = meta.get("micro_steps", engine.micro_steps)
        if load_optimizer_states and not load_module_only:
            # full resume only: a weights-only load starts a FRESH run whose
            # curriculum must begin at min_difficulty
            sampler = getattr(getattr(engine, "training_dataloader", None),
                              "data_sampler", None)
            if sampler is not None and meta.get("data_sampler") is not None \
                    and hasattr(sampler, "load_state_dict"):
                sampler.load_state_dict(meta["data_sampler"])
            elif engine.curriculum_scheduler is not None \
                    and meta.get("curriculum") is not None:
                engine.curriculum_scheduler.set_state(meta["curriculum"])
    log_dist(f"loaded checkpoint {tag} from {ckpt_dir}", ranks=[0])
    return ckpt_dir, meta.get("client_state", {})


def dataclasses_replace_state(current, restored, module_only: bool, opt: bool):
    """Keep current opt state / counters when only the module is wanted."""
    import dataclasses

    kw = {}
    if module_only:
        kw = dict(opt_state=current.opt_state, scaler=current.scaler, step=current.step,
                  rng=current.rng)
    elif not opt:
        kw = dict(opt_state=current.opt_state)
    return dataclasses.replace(restored, **kw)


def save_16bit_model(engine, save_dir: str, filename: str = "pytree_model"):
    """Consolidated compute-precision weights only (reference save_16bit_model,
    engine.py:3354)."""
    if engine.state is None:
        raise NotImplementedError(
            "save_16bit_model with offload_param: the bf16 params already "
            "live as per-leaf NVMe files (offload_param.nvme_path)")
    os.makedirs(save_dir, exist_ok=True)
    ce = OrbaxCheckpointEngine()
    path = os.path.join(save_dir, filename)
    ce.save(engine.state.params, path)
    return path
