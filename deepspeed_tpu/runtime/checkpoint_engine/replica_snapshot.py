"""In-RAM train-state slabs for checkpoint-free pod recovery (ISSUE 20).

The replica layer (elasticity/replication.py) needs two engine hooks:

- :func:`snapshot_train_state`: flatten the live :class:`TrainState` to
  host RAM as one self-describing byte slab — a device→host copy plus
  ``tobytes()``, nothing else on the step path.  The format is raw
  little-endian leaf bytes behind a JSON header (``np.savez`` cannot
  round-trip ml_dtypes leaves like bfloat16; raw bytes + a recorded
  dtype name can).
- :func:`ingest_train_state`: rebuild the state from a slab INTO the
  current engine — leaves are re-sharded with ``jax.device_put`` against
  the engine's live shardings (the adopting round may run on a smaller
  mesh than the one that sealed the slab), and the step counters
  (``global_steps`` / ``skipped_steps`` / ``micro_steps``) come back so
  the round resumes at the sealed step + 1.

The slab carries the *structure-free* leaf list: both sides flatten the
engine's own ``TrainState``, so a slab only ingests into an engine built
from the same config (same treedef).  A leaf-count or shape mismatch is
a hard error — adoption must fall back to the durable checkpoint rather
than load a half-matching state.
"""
from __future__ import annotations

import json
import struct
from typing import List

import jax
import jax.numpy as jnp
import numpy as np

MAGIC = b"DSTPUREP1"
_LEN = struct.Struct("<Q")


def _leaves(engine) -> List:
    leaves, _ = jax.tree_util.tree_flatten(engine.state)
    return leaves


def snapshot_train_state(engine) -> bytes:
    """Serialize the engine's live train state to one byte slab."""
    hosted = [np.asarray(jax.device_get(x)) for x in _leaves(engine)]
    header = {
        "format": 1,
        "global_steps": int(engine.global_steps),
        "skipped_steps": int(engine.skipped_steps),
        "micro_steps": int(engine.micro_steps),
        "n_leaves": len(hosted),
        "leaves": [{"shape": list(a.shape), "dtype": a.dtype.name}
                   for a in hosted],
    }
    head = json.dumps(header, sort_keys=True).encode("utf-8")
    parts = [MAGIC, _LEN.pack(len(head)), head]
    parts.extend(np.ascontiguousarray(a).tobytes() for a in hosted)
    return b"".join(parts)


def _resolve_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        # ml_dtypes names (bfloat16, float8_*): resolve through jnp,
        # whose dtypes are numpy-extension dtypes usable by frombuffer
        return np.dtype(getattr(jnp, name))


def ingest_train_state(engine, payload: bytes) -> int:
    """Rebuild the engine's train state from a slab produced by
    :func:`snapshot_train_state`; returns the restored global step."""
    if not payload.startswith(MAGIC):
        raise ValueError("replica slab has a bad magic — not a "
                         "snapshot_train_state payload")
    off = len(MAGIC)
    (head_len,) = _LEN.unpack_from(payload, off)
    off += _LEN.size
    header = json.loads(payload[off:off + head_len].decode("utf-8"))
    off += head_len
    if int(header.get("format", -1)) != 1:
        raise ValueError(f"replica slab format {header.get('format')} "
                         "is not supported")
    cur_leaves, treedef = jax.tree_util.tree_flatten(engine.state)
    if len(cur_leaves) != int(header["n_leaves"]):
        raise ValueError(
            f"replica slab carries {header['n_leaves']} leaves but the "
            f"engine's state has {len(cur_leaves)} — config mismatch")
    view = memoryview(payload)
    rebuilt = []
    for cur, spec in zip(cur_leaves, header["leaves"]):
        dtype = _resolve_dtype(spec["dtype"])
        shape = tuple(int(s) for s in spec["shape"])
        n = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
        if off + n > len(payload):
            raise ValueError("replica slab is truncated mid-leaf")
        arr = np.frombuffer(view[off:off + n], dtype=dtype).reshape(shape)
        off += n
        cur_shape = tuple(getattr(cur, "shape", shape))
        if cur_shape != shape:
            raise ValueError(
                f"replica slab leaf shape {shape} does not match the "
                f"engine's {cur_shape} — config mismatch")
        rebuilt.append(jax.device_put(arr, getattr(cur, "sharding", None)))
    if off != len(payload):
        raise ValueError("replica slab has trailing bytes — torn payload")
    engine.state = jax.tree_util.tree_unflatten(treedef, rebuilt)
    engine.global_steps = int(header["global_steps"])
    engine.skipped_steps = int(header["skipped_steps"])
    engine.micro_steps = int(header["micro_steps"])
    return engine.global_steps
