"""Checkpoint engine ABC (reference ``runtime/checkpoint_engine/checkpoint_engine.py:9``).

Pluggable save/load/commit; implementations: orbax (default, sharding-aware,
async-capable — the Nebula-analogue tiering comes from orbax's async
checkpointing) and a plain msgpack engine for host-only state.
"""
from __future__ import annotations

import abc
from typing import Any, Optional


class CheckpointEngine(abc.ABC):
    def __init__(self, config_params=None):
        self.config = config_params

    @abc.abstractmethod
    def save(self, state_dict: Any, path: str) -> None:
        ...

    @abc.abstractmethod
    def load(self, path: str, target: Any = None, shardings: Any = None) -> Any:
        ...

    def create(self, tag: str) -> None:
        """Start of a checkpoint under `tag` (reference create)."""

    def commit(self, tag: str) -> bool:
        """All files for `tag` saved; finalize (reference commit)."""
        return True

    def makedirs(self, path: str, exist_ok: bool = True) -> None:
        import os

        os.makedirs(path, exist_ok=exist_ok)
