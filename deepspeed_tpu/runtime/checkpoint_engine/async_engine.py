"""Async checkpoint engine with commit semantics (reference
``runtime/checkpoint_engine/nebula_checkpoint_engine.py``).

Nebula's contract: ``save()`` returns once the state is snapshotted to a
fast tier and the persistent write proceeds in the background; ``latest``
becomes visible only when the tag is *committed* (durable), so a crash
mid-write can never leave ``latest`` pointing at a torn checkpoint.

The TPU-native implementation rides orbax's AsyncCheckpointer: ``save()``
blocks only for the device→host snapshot (the part that must happen before
training mutates the arrays — Nebula's tier-0 copy), then storage I/O runs
on orbax's background thread.  A finalize thread per tag waits for
durability and only then writes ``latest`` — the commit barrier.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Optional

from .checkpoint_engine import CheckpointEngine
from .orbax_engine import LATEST_FILE, OrbaxCheckpointEngine
from ...observability.trace import trace_span
from ...resilience.fault_injection import SITE_LATEST_PUBLISH, maybe_fire
from ...resilience.integrity import write_manifest
from ...utils.logging import log_dist, logger

# upper bound on joining a wedged finalize thread at shutdown/next-save when
# the engine carries no explicit timeout
DEFAULT_FINALIZE_TIMEOUT_S = 600.0


class AsyncOrbaxCheckpointEngine(CheckpointEngine):
    """Keep ONE instance alive across saves — the async checkpointer owns a
    background thread and serializes overlapping saves itself."""

    def __init__(self, config_params=None, timeout_secs: int = 600):
        super().__init__(config_params)
        import orbax.checkpoint as ocp

        self.timeout_secs = timeout_secs
        self._ckptr = ocp.AsyncCheckpointer(
            ocp.StandardCheckpointHandler(), timeout_secs=timeout_secs)
        self._sync = OrbaxCheckpointEngine()

    def save(self, state_dict: Any, path: str) -> None:
        """Returns after the device→host snapshot; the write is async."""
        import orbax.checkpoint as ocp

        self._ckptr.save(os.path.abspath(path),
                         args=ocp.args.StandardSave(state_dict), force=True)

    def load(self, path: str, target: Any = None, shardings: Any = None) -> Any:
        self.wait()   # never read through an in-flight write
        return self._sync.load(path, target=target, shardings=shardings)

    def commit(self, tag: str) -> bool:
        self.wait()
        return True

    def wait(self) -> None:
        self._ckptr.wait_until_finished()

    def close(self) -> None:
        self._ckptr.close()


def async_save_engine_checkpoint(engine, save_dir: str, ckpt_dir: str,
                                 tag: str, save_latest: bool,
                                 manifest=None) -> None:
    """Launch the commit finalizer: wait for durability, then write the
    manifest (the commit marker), then (and only then) publish ``latest``.
    Stores the thread on the engine so ``wait_for_checkpoint()`` / the next
    load can join it."""
    ce: AsyncOrbaxCheckpointEngine = engine._async_ckpt_engine

    def finalize():
        # runs on the commit thread: the ckpt.commit span lands in the
        # flight recorder under THIS thread's name, so a dump during a
        # wedged finalize shows the open span next to the main thread's
        try:
            with trace_span("ckpt.commit", tag=str(tag)):
                ce.commit(tag)
                import jax

                if jax.process_index() == 0:
                    if manifest is not None:
                        # after commit: the payload listing must see the
                        # durable orbax files
                        write_manifest(ckpt_dir, manifest)
                    if save_latest:
                        maybe_fire(SITE_LATEST_PUBLISH, path=save_dir, tag=tag)
                        with open(os.path.join(save_dir, LATEST_FILE),
                                  "w") as f:
                            f.write(str(tag))
        except Exception as e:   # surface on wait; never publish latest
            # the main path only reads/clears this AFTER t.join() proves
            # the commit thread dead (wait_for_pending_checkpoint), so
            # the join is the synchronization point, not a lock
            engine._async_ckpt_error = e   # dslint: guarded-by(thread-join)
            logger.error(f"async checkpoint {tag} failed: {e}")
            return
        log_dist(f"committed async checkpoint {tag} -> {ckpt_dir}", ranks=[0])

    t = threading.Thread(target=finalize, name=f"ckpt-commit-{tag}",
                         daemon=True)
    engine._pending_ckpt_thread = t
    t.start()


def wait_for_pending_checkpoint(engine, timeout_s: Optional[float] = None) -> None:
    """Join the in-flight async save, re-raising its failure if any.

    The join is BOUNDED: a wedged storage write must not hang shutdown (or
    the next save/load) forever.  The bound comes from, in order: the
    ``timeout_s`` argument, the async engine's ``timeout_secs``, or
    ``DEFAULT_FINALIZE_TIMEOUT_S``.  On timeout the finalize thread is left
    referenced (it may still complete and publish ``latest``) and a
    descriptive error is raised — under the elastic supervisor that exit
    recycles the process, which is the only real cure for a wedged write."""
    t: Optional[threading.Thread] = getattr(engine, "_pending_ckpt_thread",
                                            None)
    if t is not None:
        if timeout_s is None:
            ce = getattr(engine, "_async_ckpt_engine", None)
            timeout_s = float(getattr(ce, "timeout_secs", None)
                              or DEFAULT_FINALIZE_TIMEOUT_S)
        t.join(timeout=timeout_s)
        if t.is_alive():
            raise RuntimeError(
                f"async checkpoint finalize ({t.name}) still running after "
                f"{timeout_s:.0f}s — the storage write is wedged.  `latest` "
                "still points at the previous committed tag; restart the "
                "process (the elastic supervisor does this automatically) "
                "and inspect storage health.")
        engine._pending_ckpt_thread = None
    err = getattr(engine, "_async_ckpt_error", None)
    if err is not None:
        engine._async_ckpt_error = None
        raise RuntimeError("async checkpoint save failed") from err
