"""Engine feature wiring — compression, PLD, curriculum, random-LTD, profiler.

Each ``wire_*`` function owns one optional engine capability's config
resolution and validation, keeping ``DeepSpeedEngine.__init__`` a composition
root rather than a 460-line special-case ladder.  Attribute names on the
engine are part of the public surface (tests and reference parity:
``engine.progressive_layer_drop``, ``engine.curriculum_scheduler``) and are
preserved exactly.
"""
from __future__ import annotations

import dataclasses

from ..utils.logging import log_dist


def wire_compression(engine, model):
    """QAT / pruning param transform + activation fake-quant (reference
    ``deepspeed/compression/compress.py init_compression``).

    Sets ``engine._compression_transform`` and, when activation quantization
    is configured, pushes the knobs into the model config (the transformer
    applies fake-quant at the post-norm attention/MLP inputs) — activation
    quantization is a FORWARD concern, not a param transform.
    """
    from ..compression import build_param_transform, parse_compression_config

    model_heads = getattr(getattr(model, "config", None), "num_heads", None)
    engine._compression_transform = build_param_transform(
        engine.config._param_dict, num_heads=model_heads)
    aq = [t for t in parse_compression_config(engine.config._param_dict)
          if t.kind == "activation_quantization"]
    if not aq:
        return
    mcfg = getattr(model, "config", None)
    if mcfg is None or not hasattr(mcfg, "act_quant_bits"):
        raise NotImplementedError(
            "activation_quantization needs a model whose config "
            "supports act_quant_bits (deepspeed_tpu.models.CausalLM)")
    t = aq[0]
    # the wiring is MODEL-WIDE (one bits value at every block's
    # attention/MLP inputs): reject config shapes it cannot honor
    # instead of silently approximating them
    all_bits = {int(g.params.get("bits", 8)) for g in t.groups} or {8}
    if len(all_bits) > 1 or any(
            set(g.modules) not in ({"*"}, set()) for g in t.groups):
        raise NotImplementedError(
            "activation_quantization is applied model-wide: use ONE "
            "group with modules=['*'] and a single bits value")
    if int(t.shared.get("schedule_offset", 0)) != 0:
        raise NotImplementedError(
            "activation_quantization.schedule_offset is not "
            "supported (fake-quant engages from step 0)")
    if t.shared.get("range_calibration", "dynamic") != "dynamic":
        raise NotImplementedError(
            "activation_quantization static range calibration is not "
            "wired from the config (dynamic per-tensor only)")
    bits = all_bits.pop()
    sym = t.shared.get("quantization_type", "asymmetric") == "symmetric"
    model.config = dataclasses.replace(
        mcfg, act_quant_bits=bits, act_quant_symmetric=sym)
    log_dist(f"activation quantization: {bits}-bit "
             f"{'symmetric' if sym else 'asymmetric'} at the "
             "attention/MLP inputs", ranks=[0])


def wire_progressive_layer_drop(engine):
    """Reference ``engine.progressive_layer_drop``: the schedule lives on the
    engine, the model consumes ``batch['pld_theta']``."""
    engine.progressive_layer_drop = None
    pld_cfg = engine.config.progressive_layer_drop
    if pld_cfg.enabled:
        from .progressive_layer_drop import ProgressiveLayerDrop

        engine.progressive_layer_drop = ProgressiveLayerDrop(
            theta=pld_cfg.theta, gamma=pld_cfg.gamma)


def wire_curriculum(engine):
    """Curriculum learning (reference legacy curriculum +
    ``data_pipeline/data_sampling/data_sampler.py:36`` DeepSpeedDataSampler).

    Two modes:
      - ``curriculum_type == "seqlen"``: the engine truncates each batch's
        sequence dim to the scheduled difficulty (legacy behavior).
      - any other type: the difficulty is an ARBITRARY per-sample metric —
        the engine's dataloader samples through a CurriculumBatchSampler
        over ``metric_values_path`` (a DataAnalyzer output aligned to the
        dataset), stepping difficulty in-loop per consumed batch.
    """
    engine.curriculum_scheduler = None
    engine._curriculum_seqlen = False
    engine._curriculum_metric_path = None
    cl = engine.config.curriculum_learning
    if cl.enabled:
        from .data_pipeline.curriculum_scheduler import CurriculumScheduler

        if cl.curriculum_type == "seqlen":
            engine._curriculum_seqlen = True
        elif not cl.metric_values_path:
            raise ValueError(
                f"curriculum_type {cl.curriculum_type!r} schedules an "
                "arbitrary difficulty metric through the data sampler — "
                "set curriculum_learning.metric_values_path to a "
                "DataAnalyzer metric file (run_map/run_reduce) aligned "
                "to the training dataset")
        else:
            engine._curriculum_metric_path = cl.metric_values_path
        engine.curriculum_scheduler = CurriculumScheduler({
            "curriculum_type": cl.curriculum_type,
            "min_difficulty": cl.min_difficulty,
            "max_difficulty": cl.max_difficulty,
            "schedule_type": cl.schedule_type,
            "schedule_config": cl.schedule_config,
        })


def wire_random_ltd(engine, model):
    """Random layerwise token dropping (reference
    ``runtime/data_pipeline/data_routing/random_ltd.py``)."""
    engine._random_ltd = None
    engine._ltd_keep = None
    engine._ltd_cache = {}
    rltd = engine.config.data_efficiency.data_routing.random_ltd
    if rltd.enabled:
        from .data_pipeline.data_routing.random_ltd import RandomLTDScheduler

        if model is None or not hasattr(model, "config") \
                or not hasattr(model.config, "random_ltd"):
            raise ValueError("random_ltd requires a CausalLM-style model "
                             "(TransformerConfig with random_ltd fields)")
        engine._random_ltd = RandomLTDScheduler(
            {"min_value": rltd.min_value, "max_value": rltd.max_value,
             "random_ltd_schedule": rltd.random_ltd_schedule})


def wire_flops_profiler(engine):
    engine.flops_profiler = None
    if engine.config.flops_profiler.enabled:
        from ..profiling.flops_profiler import FlopsProfiler

        engine.flops_profiler = FlopsProfiler(
            engine=engine, config=engine.config.flops_profiler)
        if engine.config.flops_profiler.profile_step <= 1:
            log_dist("flops_profiler: profile_step=1 measures the first "
                     "call, which INCLUDES jit compilation — set "
                     "profile_step>=2 for steady-state latency", ranks=[0])
