"""0/1 Adam — variance freeze + local-step intervals (arXiv 2202.06009).

Reference: ``deepspeed/runtime/fp16/onebit/zoadam.py`` (``ZeroOneAdam``).
This is a DISTINCT algorithm from 1-bit Adam (``onebit/adam.py``), with two
mechanisms the EF-sign path does not have:

  1. **Adaptive variance freeze** (the 0 in 0/1): the second moment updates
     only on an exponentially-growing interval schedule (``var_interval``
     doubles every ``var_update_scaler`` updates) and freezes entirely after
     ``var_freeze_step``.  On var-update steps gradients sync in full
     precision; on other warmup steps they sync 1-bit compressed.
  2. **Local steps** (the 1): after the variance freezes, workers stop
     synchronizing every step.  Each worker applies Adam updates against its
     LOCAL gradients; every ``local_step_interval`` steps (interval doubles
     every ``local_step_scaler`` steps, clipped at ``local_step_clipper``)
     the accumulated per-worker update is exchanged 1-bit-compressed, the
     average replaces the local speculation, and the momentum resyncs as
     ``m = -ū/Σlr`` (zoadam.py:246-262).

TPU-native formulation.  The reference lets each worker's ``p.data`` drift
between syncs — impossible for a replicated SPMD array.  Here the synced
parameters stay replicated and each worker carries a **delta** tree (its
accumulated local updates, per-worker state sharded over the data axis like
the EF error buffers); the in-region gradient evaluates at ``p + delta_w``,
which is exactly the reference's drifted ``p.data``.  At a sync step the
delta is folded into the replicated params via the compressed exchange and
zeroed.  One jitted step contains both phases under ``lax.cond`` on the
traced step counter.

Composition limits (mirroring the reference's: the 0/1 Adam tutorial lists
ZeRO incompatibility): pure-DP mesh, ZeRO stage 0, no fp16 loss scaling, no
gradient clipping (reference ``max_grad_norm`` default 0 is the only
supported value).
"""
from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .compressed import (DEFAULT_BLOCK, _pad_len, compressed_mean)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class ZeroOneState:
    """Everything 0/1 Adam carries across steps.

    Per-worker leaves (flat ``[w * npad]`` f32, sharded over the DP axes):
    ``exp_avg`` (momentum — diverges between syncs), ``delta`` (accumulated
    local updates), ``error`` (EF residual).  Replicated: ``exp_avg_sq``
    (param-shaped — updated only with synced gradients), ``lrs`` and the
    interval counters."""

    exp_avg: Any
    exp_avg_sq: Any
    delta: Any
    error: Any
    lrs: jnp.ndarray
    var_interval: jnp.ndarray
    var_counter: jnp.ndarray
    local_interval: jnp.ndarray
    local_counter: jnp.ndarray


def init_zero_one_state(params: Any, mesh, block: int = DEFAULT_BLOCK
                        ) -> ZeroOneState:
    """Freshly-initialized state, device_put with the right shardings."""
    from ...parallel.mesh import BATCH_AXES, axis_size

    w = axis_size(mesh, BATCH_AXES)
    perw = NamedSharding(mesh, P(BATCH_AXES))
    rep = NamedSharding(mesh, P())

    def flatw(x):
        return jax.device_put(
            jnp.zeros((w * _pad_len(x.size, block),), jnp.float32), perw)

    def repz(x):
        return jax.device_put(jnp.zeros(x.shape, jnp.float32), rep)

    scalar = lambda v, dt=jnp.int32: jax.device_put(  # noqa: E731
        jnp.asarray(v, dt), rep)
    return ZeroOneState(
        exp_avg=jax.tree_util.tree_map(flatw, params),
        exp_avg_sq=jax.tree_util.tree_map(repz, params),
        delta=jax.tree_util.tree_map(flatw, params),
        error=jax.tree_util.tree_map(flatw, params),
        lrs=scalar(0.0, jnp.float32),
        var_interval=scalar(1), var_counter=scalar(0),
        local_interval=scalar(1), local_counter=scalar(0))


def make_zero_one_step(accumulate, mesh, gas: int, compute_dtype,
                       param_template: Any, hyper: dict,
                       block: int = DEFAULT_BLOCK):
    """Build ``fn(masters, scaler, window, rng, zo_state, step, lr)`` ->
    ``(new_masters, new_zo_state, mean_loss, grad_norm)``.

    ``accumulate`` is the shared microbatch scan (grads are
    loss_scale*gas-scaled sums; this path unscales in-region since it owns
    the whole update)."""
    from ...parallel.mesh import BATCH_AXES, manual_region, shard_map_compat

    b1, b2 = hyper.get("betas", (0.9, 0.999))
    eps = hyper.get("eps", 1e-8)
    wd = hyper.get("weight_decay", 0.0)
    var_freeze_step = int(hyper.get("var_freeze_step", 100000))
    var_update_scaler = int(hyper.get("var_update_scaler", 16))
    local_step_scaler = int(hyper.get("local_step_scaler", 32678))
    local_step_clipper = int(hyper.get("local_step_clipper", 16))

    pads = jax.tree_util.tree_map(lambda x: _pad_len(x.size, block),
                                  param_template)

    def unflat(flat, ref):
        return flat[:ref.size].reshape(ref.shape)

    def flat(x, npad):
        return jnp.pad(x.ravel(), (0, npad - x.size))

    def region(masters, scaler, window, rng, zo: ZeroOneState, step, lr):
        count = step + 1  # reference state['step'] after its increment
        # at the warmup->frozen boundary the EF buffers switch metric
        # (gradient residual -> accumulated-momentum residual): reset once
        # (zoadam.py reinitial_error_buffer)
        first_frozen = count == var_freeze_step + 1
        error = jax.tree_util.tree_map(
            lambda e: jnp.where(first_frozen, jnp.zeros_like(e), e), zo.error)

        delta_tree = jax.tree_util.tree_map(unflat, zo.delta, masters)
        p_eff = jax.tree_util.tree_map(
            lambda p, d: (p + d).astype(compute_dtype), masters, delta_tree)
        local_grads, losses, _ = accumulate(p_eff, scaler, window, rng)
        inv = (1.0 / (scaler.loss_scale * gas)).astype(jnp.float32)
        local_grads = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32) * inv, local_grads)
        m_tree = jax.tree_util.tree_map(unflat, zo.exp_avg, masters)

        def pair_map(fn, *trees):
            is_pair = lambda t: isinstance(t, tuple)  # noqa: E731
            out = jax.tree_util.tree_map(fn, *trees)
            a = jax.tree_util.tree_map(lambda t: t[0], out, is_leaf=is_pair)
            b = jax.tree_util.tree_map(lambda t: t[1], out, is_leaf=is_pair)
            return a, b

        # ---------------- phase A: warmup (variance still updating) -------
        def phase_a(error):
            on_var = (count % zo.var_interval) == 0

            def full_sync():
                g = jax.tree_util.tree_map(
                    lambda x: lax.pmean(x, BATCH_AXES), local_grads)
                return g, error

            def onebit_sync():
                fg = jax.tree_util.tree_map(flat, local_grads, pads)
                means, errs = pair_map(
                    lambda f, e: compressed_mean(f, e, BATCH_AXES, block),
                    fg, error)
                g = jax.tree_util.tree_map(unflat, means, local_grads)
                return g, errs

            g, new_error = lax.cond(on_var, full_sync, onebit_sync)
            new_m = jax.tree_util.tree_map(
                lambda m, gi: b1 * m + (1.0 - b1) * gi, m_tree, g)
            new_v = jax.tree_util.tree_map(
                lambda v, gi: jnp.where(on_var,
                                        b2 * v + (1.0 - b2) * gi * gi, v),
                zo.exp_avg_sq, g)
            upd = jax.tree_util.tree_map(
                lambda m, v, p: m / (jnp.sqrt(v) + eps) + wd * p,
                new_m, new_v, masters)
            new_p = jax.tree_util.tree_map(
                lambda p, u: p - lr * u, masters, upd)
            gnorm = jnp.sqrt(sum(
                jnp.vdot(gi, gi) for gi in jax.tree_util.tree_leaves(g)))
            # exponential var-interval schedule (zoadam.py:268-272)
            vc = jnp.where(on_var, zo.var_counter + 1, zo.var_counter)
            grow = vc == var_update_scaler
            new_var_counter = jnp.where(grow, 0, vc)
            new_var_interval = jnp.where(grow, zo.var_interval * 2,
                                         zo.var_interval)
            return (new_p, new_m, new_v,
                    jax.tree_util.tree_map(jnp.zeros_like, zo.delta),
                    new_error, jnp.float32(0.0),
                    new_var_interval, new_var_counter,
                    zo.local_interval, zo.local_counter, gnorm)

        # ---------------- phase B: frozen variance, local steps -----------
        def phase_b(error):
            new_m = jax.tree_util.tree_map(
                lambda m, gi: b1 * m + (1.0 - b1) * gi, m_tree, local_grads)
            upd = jax.tree_util.tree_map(
                lambda m, v, p, d: m / (jnp.sqrt(v) + eps) + wd * (p + d),
                new_m, zo.exp_avg_sq, masters, delta_tree)
            new_delta_tree = jax.tree_util.tree_map(
                lambda d, u: d - lr * u, delta_tree, upd)
            new_lrs = zo.lrs + lr
            on_sync = (count % zo.local_interval) == 0

            def sync():
                # delta * (sqrt(v)+eps) = -Σ lr·m  (zoadam.py:248)
                buf = jax.tree_util.tree_map(
                    lambda d, v: d * (jnp.sqrt(v) + eps),
                    new_delta_tree, zo.exp_avg_sq)
                fb = jax.tree_util.tree_map(flat, buf, pads)
                means, errs = pair_map(
                    lambda f, e: compressed_mean(f, e, BATCH_AXES, block),
                    fb, error)
                buf_avg = jax.tree_util.tree_map(unflat, means, masters)
                m_sync = jax.tree_util.tree_map(
                    lambda ba: -ba / new_lrs, buf_avg)
                p_new = jax.tree_util.tree_map(
                    lambda p, ba, v: p + ba / (jnp.sqrt(v) + eps),
                    masters, buf_avg, zo.exp_avg_sq)
                zero_delta = jax.tree_util.tree_map(jnp.zeros_like, new_delta_tree)
                return p_new, m_sync, zero_delta, errs, jnp.float32(0.0)

            def local():
                return (masters, new_m, new_delta_tree, error, new_lrs)

            p_new, m_out, delta_out, err_out, lrs_out = lax.cond(
                on_sync, sync, local)
            # pmean the SQUARED sums before the sqrt so the metric stays
            # norm-like across phases (phase A reports the norm of the synced
            # gradient; mean-of-norms would jump discontinuously at
            # var_freeze_step)
            gsq = sum(jnp.vdot(gi, gi)
                      for gi in jax.tree_util.tree_leaves(local_grads))
            gnorm = jnp.sqrt(lax.pmean(gsq, BATCH_AXES))
            # local-step interval schedule (zoadam.py:284-289)
            lc = zo.local_counter + 1
            grow = lc == local_step_scaler
            new_local_counter = jnp.where(grow, 0, lc)
            new_local_interval = jnp.where(
                grow, jnp.minimum(local_step_clipper, zo.local_interval * 2),
                zo.local_interval)
            return (p_new, m_out, zo.exp_avg_sq, delta_out, err_out, lrs_out,
                    zo.var_interval, zo.var_counter,
                    new_local_interval, new_local_counter, gnorm)

        def phase_b_packed(error):
            (p_new, m_out, v_out, delta_out, err_out, lrs_out, vi, vc, li,
             lc, gnorm) = phase_b(error)
            delta_flat = jax.tree_util.tree_map(flat, delta_out, pads)
            m_flat = jax.tree_util.tree_map(flat, m_out, pads)
            return (p_new, m_flat, v_out, delta_flat, err_out, lrs_out,
                    vi, vc, li, lc, gnorm)

        def phase_a_packed(error):
            (p_new, m_out, v_out, delta_flat, err_out, lrs_out, vi, vc, li,
             lc, gnorm) = phase_a(error)
            m_flat = jax.tree_util.tree_map(flat, m_out, pads)
            return (p_new, m_flat, v_out, delta_flat, err_out, lrs_out,
                    vi, vc, li, lc, gnorm)

        (new_p, m_flat, new_v, delta_flat, new_error, new_lrs, vi, vc, li,
         lc, gnorm) = lax.cond(count <= var_freeze_step,
                               phase_a_packed, phase_b_packed, error)
        new_zo = ZeroOneState(
            exp_avg=m_flat, exp_avg_sq=new_v, delta=delta_flat,
            error=new_error, lrs=new_lrs, var_interval=vi, var_counter=vc,
            local_interval=li, local_counter=lc)
        return new_p, new_zo, lax.pmean(jnp.mean(losses), BATCH_AXES), gnorm

    rep = jax.tree_util.tree_map(lambda _: P(), param_template)
    perw = jax.tree_util.tree_map(lambda _: P(BATCH_AXES), param_template)
    repz = jax.tree_util.tree_map(lambda _: P(), param_template)
    zo_specs = ZeroOneState(
        exp_avg=perw, exp_avg_sq=repz, delta=perw, error=perw,
        lrs=P(), var_interval=P(), var_counter=P(),
        local_interval=P(), local_counter=P())
    sm = shard_map_compat(
        region, mesh,
        in_specs=(rep, P(), P(None, BATCH_AXES), P(), zo_specs, P(), P()),
        out_specs=(rep, zo_specs, P(), P()))

    def fn(masters, scaler, window, rng, zo_state, step, lr):
        with manual_region():
            return sm(masters, scaler, window, rng, zo_state, step, lr)

    return fn
