"""Compressed communication backends (reference ``deepspeed/runtime/comm/``)."""
from .compressed import (compressed_mean, ef_compress, ef_decode,
                         init_error_tree, make_compressed_grad_fn, pack_signs,
                         unpack_signs)

__all__ = ["compressed_mean", "ef_compress", "ef_decode", "init_error_tree",
           "make_compressed_grad_fn", "pack_signs", "unpack_signs"]
