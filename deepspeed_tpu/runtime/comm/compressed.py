"""Error-feedback 1-bit compressed gradient collective.

Reference mechanism: ``deepspeed/runtime/comm/nccl.py:54``
(``compressed_allreduce`` — sign compression + per-chunk scale + persistent
error feedback), used by the 1-bit optimizer family
(``runtime/fp16/onebit/adam.py:308``, docs claim up to 26x comm reduction).

TPU-first redesign.  The engine's normal DP gradient reduction is *implicit*
(XLA inserts it against sharding constraints), and an implicit collective
cannot change wire format.  So the 1-bit path computes LOCAL gradients inside
one fully-manual ``shard_map`` region over the data axis and performs the
compressed exchange explicitly:

  1. corrected = local_grad + error           (error feedback)
  2. per-block scale = mean(|corrected|)      (fp32, one per `block` elems)
  3. signs packed 8-per-byte                  (uint8 wire tensor)
  4. all_gather(packed signs), all_gather(scales) over 'data'
  5. decode each peer, average -> approximate mean gradient
  6. error = corrected - decode(own message)  (what compression lost)

Wire bytes per element: 1/8 (signs) + 4/block (scales) ≈ 0.14 B at block=256
vs 4 B fp32 — the reference's ~26x.  The uint8 all-gather is structurally
checkable in the compiled HLO (like the ZeRO++ tests do for s8).

The engine engages this path for ``optimizer.type`` one of
OneBitAdam / OneBitLamb / ZeroOneAdam with plain Adam/LAMB momentum math on
the compressed-averaged gradient (documented divergence: the reference
compresses the *momentum* after a warmup freeze; compressing the gradient
keeps the same wire format + error-feedback dynamics and composes with the
SPMD engine without forking the optimizer state across workers).  Before
``freeze_step`` (the reference's warmup) gradients are exchanged in full
precision.
"""
from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

DEFAULT_BLOCK = 256
_BITS = np.asarray([1, 2, 4, 8, 16, 32, 64, 128], np.uint8)


def pack_signs(signs: jax.Array) -> jax.Array:
    """bool [n] (n % 8 == 0) -> uint8 [n/8], bit i = element 8k+i."""
    b = signs.reshape(-1, 8).astype(jnp.uint8)
    return (b * jnp.asarray(_BITS)).sum(axis=1).astype(jnp.uint8)


def unpack_signs(packed: jax.Array) -> jax.Array:
    """uint8 [m] -> f32 [m*8] of ±1."""
    bits = (packed[:, None] & jnp.asarray(_BITS)) > 0
    return jnp.where(bits, 1.0, -1.0).astype(jnp.float32).reshape(-1)


def _pad_len(n: int, block: int) -> int:
    lcm = np.lcm(block, 8)
    return int(-(-n // lcm) * lcm)


def ef_compress(flat: jax.Array, error: jax.Array, block: int = DEFAULT_BLOCK):
    """flat f32 [npad] + error [npad] -> (packed u8, scales f32, new_error).

    Scale is the per-block mean magnitude of the corrected tensor, so
    decode(message) = sign * scale is the 1-bit quantization with minimal
    L1 error per block (the reference's convention, nccl.py:91).
    """
    corrected = flat + error
    nb = corrected.shape[0] // block
    blocks = corrected.reshape(nb, block)
    scales = jnp.mean(jnp.abs(blocks), axis=1)  # [nb]
    signs = corrected >= 0
    packed = pack_signs(signs)
    decoded = (jnp.where(signs.reshape(nb, block), 1.0, -1.0)
               * scales[:, None]).reshape(-1)
    new_error = corrected - decoded
    return packed, scales, new_error


def ef_decode(packed: jax.Array, scales: jax.Array, block: int) -> jax.Array:
    signs = unpack_signs(packed)  # [npad]
    return (signs.reshape(-1, block) * scales[:, None]).reshape(-1)


def compressed_mean(flat: jax.Array, error: jax.Array, axis: str,
                    block: int = DEFAULT_BLOCK) -> Tuple[jax.Array, jax.Array]:
    """INSIDE a manual region: EF-compressed mean of ``flat`` over ``axis``.

    Returns (approx mean over workers, new local error)."""
    packed, scales, new_error = ef_compress(flat, error, block)
    all_packed = lax.all_gather(packed, axis)   # [w, n/8] uint8 on the wire
    all_scales = lax.all_gather(scales, axis)   # [w, nb]  fp32 (tiny)
    decoded = jax.vmap(lambda p, s: ef_decode(p, s, block))(all_packed, all_scales)
    return decoded.mean(axis=0), new_error


def init_error_tree(params: Any, mesh, block: int = DEFAULT_BLOCK) -> Any:
    """Per-worker error buffers: one flat f32 [w * npad] leaf per param leaf,
    sharded over the data axis so each worker owns its own slice."""
    from ...parallel.mesh import BATCH_AXES, axis_size

    w = axis_size(mesh, BATCH_AXES)

    def one(x):
        npad = _pad_len(x.size, block)
        return jnp.zeros((w * npad,), jnp.float32)

    return jax.tree_util.tree_map(one, params)


def error_tree_specs(params: Any) -> Any:
    from ...parallel.mesh import BATCH_AXES

    return jax.tree_util.tree_map(lambda _: P(BATCH_AXES), params)


def make_compressed_grad_fn(accumulate, mesh, gas: int, freeze_step: int,
                            param_template: Any, block: int = DEFAULT_BLOCK):
    """Build the manual-region gradient function for the 1-bit path.

    ``accumulate`` is ``engine.make_grad_accumulator(grad_of_batch, gas)`` —
    the shared microbatch scan.  Returns
    ``fn(work_params, scaler, batch_window, rng, error, step)``
    -> (mean_grads, losses, new_error); ``batch_window`` is [gas, B_global,...].
    Requires a pure-DP mesh (engine validates).
    """
    from ...parallel.mesh import manual_region, shard_map_compat
    from ...parallel.mesh import BATCH_AXES

    pads = jax.tree_util.tree_map(lambda x: _pad_len(x.size, block),
                                  param_template)

    def region(work, scaler, window, rng, error, step):
        local_grads, losses, _ = accumulate(work, scaler, window, rng)

        def full_precision():
            g = jax.tree_util.tree_map(
                lambda x: lax.pmean(x, BATCH_AXES), local_grads)
            return g, error

        def one_bit():
            # The accumulated grads are loss-scale*gas-scaled; the error
            # buffer must carry residuals in UNSCALED units or every dynamic
            # loss-scale change would mis-weight the carried error vs the
            # current gradients.  Compress unscaled, re-scale the mean so
            # apply_update's single unscale stays correct.
            inv = (1.0 / (scaler.loss_scale * gas)).astype(jnp.float32)
            flat_grads = jax.tree_util.tree_map(
                lambda g, npad: jnp.pad(g.ravel() * inv, (0, npad - g.size)),
                local_grads, pads)
            out = jax.tree_util.tree_map(
                lambda f, e: compressed_mean(f, e, BATCH_AXES, block),
                flat_grads, error)
            is_pair = lambda t: isinstance(t, tuple)  # noqa: E731
            means = jax.tree_util.tree_map(lambda t: t[0], out, is_leaf=is_pair)
            errs = jax.tree_util.tree_map(lambda t: t[1], out, is_leaf=is_pair)
            g = jax.tree_util.tree_map(
                lambda m, ref: (m[:ref.size] / inv).reshape(ref.shape), means,
                local_grads)
            return g, errs

        grads, new_error = lax.cond(step < freeze_step, full_precision, one_bit)
        losses = lax.pmean(losses, BATCH_AXES)
        return grads, losses, new_error

    rep = jax.tree_util.tree_map(lambda _: P(), param_template)
    err_specs = error_tree_specs(param_template)
    # window leaves are [gas, B_global, ...]: shard dim 1 over the DP axes
    # (prefix spec broadcasts over every batch leaf)
    sm = shard_map_compat(
        region, mesh,
        in_specs=(rep, P(), P(None, BATCH_AXES), P(), err_specs, P()),
        out_specs=(rep, P(), err_specs))

    def fn(work, scaler, window, rng, error, step):
        with manual_region():
            return sm(work, scaler, window, rng, error, step)

    return fn
