"""LoRA adapters for the hybrid (RLHF) engine.

Parity target: DeepSpeed-Chat's LoRA utilities plus the reference hybrid
engine's ``fuse_lora_weight``/``unfuse_lora_weight``
(``runtime/hybrid_engine.py:138-160``): during generation the low-rank
deltas are folded into the base weights so the inference kernels see plain
matrices; before training resumes they are unfolded.

TPU-native shape: LoRA is a FUNCTIONAL transform.  The trainable tree IS the
adapter tree (the engine trains whatever ``init_fn`` returns — base weights
are a closed-over constant, naturally frozen), and "fusing" is a jitted pure
function ``fused = base + A @ B * (alpha/r)`` whose output feeds the decode
program.  There is no module surgery and no unfuse bookkeeping — the base
tree is never mutated; ``unfuse`` merely drops the cached fused tree.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from ..utils.logging import log_dist

# attention projections — DeepSpeed-Chat's default LoRA surface
DEFAULT_TARGETS: Tuple[str, ...] = ("wq", "wk", "wv", "wo")


@dataclasses.dataclass(frozen=True)
class LoRAConfig:
    rank: int = 8
    alpha: float = 16.0
    targets: Tuple[str, ...] = DEFAULT_TARGETS

    def validate(self) -> "LoRAConfig":
        """Reject impossible configs BEFORE any math touches them.

        ``rank=0`` used to surface as a bare ``ZeroDivisionError`` from
        ``.scaling``; ``alpha<=0`` silently zeroed or sign-flipped the
        delta; empty/duplicate ``targets`` produced an adapter tree that
        trained nothing or double-counted a projection."""
        if int(self.rank) < 1:
            raise ValueError(f"LoRA rank={self.rank} must be >= 1")
        if not (float(self.alpha) > 0.0):
            raise ValueError(f"LoRA alpha={self.alpha} must be > 0")
        if not self.targets:
            raise ValueError("LoRA targets must name at least one layer "
                             "weight")
        if len(set(self.targets)) != len(self.targets):
            raise ValueError(f"duplicate LoRA targets: {list(self.targets)}")
        return self

    @property
    def scaling(self) -> float:
        return self.alpha / self.rank


def init_lora_params(base_layers: Dict[str, Any], cfg: LoRAConfig,
                     rng: jax.Array) -> Dict[str, Any]:
    """A/B factors for each targeted layer weight.

    Targets are leaves of the model's stacked ``layers`` dict with shape
    [L, d_in, d_out].  A ~ N(0, 1/r) [L, d_in, r], B = 0 [L, r, d_out]
    (zero-init B makes step-0 output exactly the base model)."""
    cfg.validate()
    out: Dict[str, Any] = {}
    keys = jax.random.split(rng, len(cfg.targets))
    for k, key in zip(cfg.targets, keys):
        if k not in base_layers:
            raise ValueError(f"LoRA target {k!r} not in model layers "
                             f"({sorted(base_layers)})")
        w = base_layers[k]
        if w.ndim != 3:
            raise NotImplementedError(
                f"LoRA target {k!r} has rank-{w.ndim} weight; only stacked "
                "[L, d_in, d_out] matmul weights are supported")
        L, d_in, d_out = w.shape
        out[k] = {
            "A": jax.random.normal(key, (L, d_in, cfg.rank), jnp.float32)
            * (1.0 / cfg.rank),
            "B": jnp.zeros((L, cfg.rank, d_out), jnp.float32),
        }
    return out


def lora_param_specs(cfg: LoRAConfig) -> Dict[str, Any]:
    """Adapters are tiny — replicate them (r ≪ d makes TP sharding noise)."""
    from jax.sharding import PartitionSpec as P

    return {k: {"A": P(None, None, None), "B": P(None, None, None)}
            for k in cfg.targets}


def apply_lora(base_params: Dict[str, Any], lora: Dict[str, Any],
               scaling: float, dtype=None) -> Dict[str, Any]:
    """``fused = base + A @ B * scaling`` on the targeted layer weights —
    the reference's fuse_lora_weight as a pure function."""
    layers = dict(base_params["layers"])
    for k, ab in lora.items():
        w = layers[k]
        delta = jnp.einsum("lir,lro->lio", ab["A"], ab["B"]) * scaling
        layers[k] = (w + delta.astype(w.dtype))
    out = dict(base_params)
    out["layers"] = layers
    return out


class LoRAModel:
    """Engine adapter: train ONLY the LoRA tree against frozen base weights.

    Satisfies the engine's model contract (init_fn/loss_fn/param_specs), so
    ``deepspeed_tpu.initialize(model=LoRAModel(base, base_params, cfg))``
    runs ZeRO/offload/etc. over the adapter tree while the base weights ride
    as a closed-over constant."""

    def __init__(self, base_model, base_params, lora_config: LoRAConfig):
        lora_config.validate()
        self.base_model = base_model
        # frozen base rides in the COMPUTE dtype (cfg.dtype): the fused tree
        # must match the activation dtype or every matmul/scan would mix
        # precisions (and an fp32 base would double the frozen footprint)
        dt = base_model.config.dtype
        self.base_params = jax.tree_util.tree_map(
            lambda x: x.astype(dt)
            if jnp.issubdtype(x.dtype, jnp.floating) else x, base_params)
        self.lora_config = lora_config
        self.config = base_model.config
        self.param_specs = lora_param_specs(lora_config)
        n = sum(int(jnp.size(l)) for l in
                jax.tree_util.tree_leaves(base_params))
        log_dist(f"LoRA: rank={lora_config.rank} over "
                 f"{list(lora_config.targets)} — base {n:,} params frozen",
                 ranks=[0])

    def init_fn(self, rng):
        return init_lora_params(self.base_params["layers"], self.lora_config,
                                rng)

    def fused(self, lora):
        return apply_lora(self.base_params, lora, self.lora_config.scaling)

    def loss_fn(self, lora, batch, rng):
        return self.base_model.loss_fn(self.fused(lora), batch, rng)

    def eval_fn(self, lora, batch, rng):
        return self.base_model.eval_fn(self.fused(lora), batch, rng)

    # KV-cache decode contract passthrough (generation uses fused weights)
    def init_cache(self, *a, **k):
        return self.base_model.init_cache(*a, **k)

    def cache_specs(self):
        return self.base_model.cache_specs()

    def apply_cached(self, lora, tokens, cache, positions, input_mask):
        return self.base_model.apply_cached(self.fused(lora), tokens, cache,
                                            positions, input_mask)

    def apply_fn(self, lora, *a, **k):
        return self.base_model.apply_fn(self.fused(lora), *a, **k)
