"""Hybrid engine: one weight set, training AND fast generation (RLHF).

Parity target: reference ``runtime/hybrid_engine.py:32``
(``DeepSpeedHybridEngine`` — the DeepSpeed-Chat actor engine whose
``generate()`` runs with inference kernels/containers over the SAME weights
ZeRO is training, gathering/partitioning params on each train↔eval flip).

TPU-native redesign: the reference's hard part — swapping torch modules for
inference containers and un/re-partitioning ZeRO shards around every
generate — disappears here.  The training engine already maintains a
compute-precision (bf16) param view next to the fp32 masters, and the
inference engine's compiled generate program takes params as an ARGUMENT.
So hybrid = hand the live training view to the KV-cache decode program:
zero copies, zero re-partitioning, no mode flip; XLA reshards between the
training and decode layouts automatically if they differ.
"""
from __future__ import annotations

import time
from typing import Any, Optional

from ..utils.logging import log_dist


class DeepSpeedHybridEngine:
    """Wraps a training engine with a generation path over the live weights.

    ``model`` must expose ``apply_cached`` (the KV-cache step — e.g.
    ``deepspeed_tpu.models.CausalLM``); defaults to the training engine's
    model.  Typical RLHF actor loop::

        hybrid = DeepSpeedHybridEngine(engine)
        rollout = hybrid.generate(prompts, max_new_tokens=128)
        ...score rollout, build the PPO batch...
        hybrid.train_batch(batch=ppo_batch)
    """

    def __init__(self, engine, model: Any = None, inference_config=None):
        from ..inference.engine import InferenceEngine
        from ..inference.config import DeepSpeedInferenceConfig

        self.engine = engine
        model = model or engine.model
        if model is None or not hasattr(model, "apply_cached"):
            raise ValueError(
                "hybrid engine needs a KV-cache-capable model (apply_cached); "
                "pass the CausalLM adapter the training engine was built with")
        self.model = model
        # LoRA actor (runtime/lora.py LoRAModel): generation fuses the
        # adapters into the base weights ONCE per call instead of per decode
        # step (reference fuse_lora_weight/unfuse_lora_weight,
        # hybrid_engine.py:138-160)
        self._lora = model if hasattr(model, "fused") and \
            hasattr(model, "base_model") else None
        self._gen_model = self._lora.base_model if self._lora else model
        self._fuse_jit = None
        self._fused_params = None
        self._fused_at_step = None
        # compute_dtype may be a dtype CLASS (jnp.bfloat16) or a dtype
        # INSTANCE (np.dtype("bfloat16")) — `.__name__` only exists on the
        # class and crashed on instances; jnp.dtype() normalizes both
        import jax.numpy as jnp

        dtype_name = jnp.dtype(engine.compute_dtype).name
        cfg = inference_config or DeepSpeedInferenceConfig(
            dtype={"bfloat16": "bf16", "float16": "fp16"}.get(dtype_name,
                                                              "fp32"))
        # params=None: generation always reads the LIVE training view
        self._infer = InferenceEngine(self._gen_model, config=cfg, params=None,
                                      apply_fn=self._gen_model.apply_fn,
                                      mesh=engine.mesh)
        self._generate_calls = 0
        self._generate_time = 0.0

    # -- LoRA fuse/unfuse (reference hybrid_engine.py:138-160) --
    def fuse_lora_weight(self):
        """Materialize base + A@B·scale for generation.  Pure function of
        the live adapter tree — the base weights are never mutated, so
        'unfuse' is just dropping this cache."""
        if self._lora is None:
            return  # API parity no-op (reference skips without LoRA too)
        import jax

        if self._fuse_jit is None:
            self._fuse_jit = jax.jit(self._lora.fused)
        self._fused_params = self._fuse_jit(self.engine.state.params)
        self._fused_at_step = self.engine.global_steps

    def unfuse_lora_weight(self):
        self._fused_params = None
        self._fused_at_step = None

    def _generation_params(self):
        if self._lora is None:
            return self.engine.state.params
        if self._fused_params is None or \
                self._fused_at_step != self.engine.global_steps:
            self.fuse_lora_weight()   # auto-refresh after training flips
        return self._fused_params

    # -- generation over the live weights (reference generate():238) --
    def generate(self, input_ids, **kwargs) -> Any:
        t0 = time.perf_counter()
        out = self._infer.generate(input_ids, model=self._gen_model,
                                   params=self._generation_params(), **kwargs)
        self._generate_time += time.perf_counter() - t0
        self._generate_calls += 1
        return out

    # -- batched rollouts through the serving stack (docs/HYBRID.md) --
    def rollout_engine(self, **kwargs):
        """A :class:`~..rollout.RolloutEngine` sharing this hybrid
        engine's live weights, LoRA fuse cache and model: rollouts run
        through the continuous-batching paged serving engine (per-slot
        sampling lanes, warm-restart supervision, weight-epoch KV
        invalidation) instead of sequential :meth:`generate` — the
        production RLHF actor path.  Kwargs configure the underlying
        ``ServingEngine`` (``b_slots``, ``max_model_len``, ...)."""
        from ..rollout import RolloutEngine

        return RolloutEngine(self, **kwargs)

    # -- training passthrough --
    def train_batch(self, *args, **kwargs):
        return self.engine.train_batch(*args, **kwargs)

    def eval_batch(self, *args, **kwargs):
        return self.engine.eval_batch(*args, **kwargs)

    def save_checkpoint(self, *args, **kwargs):
        return self.engine.save_checkpoint(*args, **kwargs)

    def load_checkpoint(self, *args, **kwargs):
        return self.engine.load_checkpoint(*args, **kwargs)

    # reference mode flips are no-ops here (no container swap needed), kept
    # for API parity with DeepSpeed-Chat call sites
    def eval(self):
        return self

    def train(self, mode: bool = True):
        return self

    @property
    def module(self):
        return self.engine.module

    def report_generate_latency(self) -> Optional[float]:
        """Mean generate() wall-clock (reference _generate latency stats)."""
        if not self._generate_calls:
            return None
        mean = self._generate_time / self._generate_calls
        log_dist(f"hybrid engine: {self._generate_calls} generate calls, "
                 f"mean {mean * 1e3:.1f} ms", ranks=[0])
        return mean
