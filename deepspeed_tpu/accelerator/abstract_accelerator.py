"""Accelerator abstraction (L0).

TPU-native re-design of the reference's ``accelerator/abstract_accelerator.py:10``
(``DeepSpeedAccelerator`` ABC, ~50 methods).  The torch-specific surface
(Streams/Events, ``torch.cuda`` memory pools) does not map to XLA: streams are
owned by the runtime and synchronization is ``block_until_ready``.  What we keep
is the *seam*: device enumeration/selection, RNG, memory stats, dtype support,
``communication_backend_name`` and the op-builder hooks, so every layer above
talks to ``get_accelerator()`` instead of ``jax.devices()`` directly and the
whole stack runs unchanged on a simulated CPU mesh.
"""
from __future__ import annotations

import abc
from typing import Any, Dict, List, Optional


class DeepSpeedAccelerator(abc.ABC):
    """Device abstraction seam. Reference: accelerator/abstract_accelerator.py:10."""

    def __init__(self):
        self._name: Optional[str] = None
        self._communication_backend_name: Optional[str] = None

    # --- device management (reference abstract_accelerator.py:14-77) ---
    @abc.abstractmethod
    def device_name(self, device_index: Optional[int] = None) -> str:
        ...

    @abc.abstractmethod
    def devices(self) -> List[Any]:
        """All addressable jax devices for this accelerator."""

    @abc.abstractmethod
    def device_count(self) -> int:
        ...

    def global_device_count(self) -> int:
        import jax

        return jax.device_count()

    def process_index(self) -> int:
        import jax

        return jax.process_index()

    def process_count(self) -> int:
        import jax

        return jax.process_count()

    def synchronize(self, tree: Any = None) -> None:
        """XLA analogue of ``torch.cuda.synchronize``."""
        import jax

        if tree is not None:
            jax.block_until_ready(tree)
        else:
            # Dummy computation forces a round-trip through the runtime.
            jax.block_until_ready(jax.numpy.zeros(()))

    # --- RNG (reference abstract_accelerator.py:101-134) ---
    def default_rng(self, seed: int):
        import jax

        return jax.random.PRNGKey(seed)

    # --- memory (reference abstract_accelerator.py:136-168) ---
    @abc.abstractmethod
    def memory_stats(self, device_index: Optional[int] = None) -> Dict[str, int]:
        ...

    def available_memory(self, device_index: Optional[int] = None) -> int:
        stats = self.memory_stats(device_index)
        return stats.get("bytes_limit", 0) - stats.get("bytes_in_use", 0)

    def total_memory(self, device_index: Optional[int] = None) -> int:
        return self.memory_stats(device_index).get("bytes_limit", 0)

    # --- dtype support (reference abstract_accelerator.py:190-215) ---
    @abc.abstractmethod
    def is_bf16_supported(self) -> bool:
        ...

    @abc.abstractmethod
    def is_fp16_supported(self) -> bool:
        ...

    def supported_dtypes(self) -> List[Any]:
        import jax.numpy as jnp

        dtypes = [jnp.float32]
        if self.is_bf16_supported():
            dtypes.append(jnp.bfloat16)
        if self.is_fp16_supported():
            dtypes.append(jnp.float16)
        return dtypes

    def preferred_dtype(self):
        import jax.numpy as jnp

        return jnp.bfloat16 if self.is_bf16_supported() else jnp.float32

    # --- comms (reference abstract_accelerator.py:181) ---
    def communication_backend_name(self) -> str:
        assert self._communication_backend_name is not None
        return self._communication_backend_name

    # --- profiler ranges (reference abstract_accelerator.py:169-174 nvtx) ---
    def range_push(self, name: str):
        import jax

        return jax.profiler.TraceAnnotation(name).__enter__()

    def range_pop(self) -> None:  # pragma: no cover - paired with range_push
        pass

    def trace_annotation(self, name: str):
        import jax

        return jax.profiler.TraceAnnotation(name)

    # --- op builder hooks (reference abstract_accelerator.py:229-244) ---
    @abc.abstractmethod
    def op_builder_dir(self) -> str:
        ...

    def create_op_builder(self, class_name: str):
        builder_class = self.get_op_builder(class_name)
        return None if builder_class is None else builder_class()

    def get_op_builder(self, class_name: str):
        import importlib

        try:
            module = importlib.import_module(self.op_builder_dir())
        except ImportError:
            return None
        return getattr(module, class_name, None)

    # --- identity ---
    def name(self) -> str:
        assert self._name is not None
        return self._name

    def is_available(self) -> bool:
        return self.device_count() > 0
