"""Accelerator selection.

Analogue of the reference's ``accelerator/real_accelerator.py:45-111``:
``DS_ACCELERATOR`` env override, else auto-detect by probing the JAX backend.
"""
from __future__ import annotations

import os
from typing import Optional

from .abstract_accelerator import DeepSpeedAccelerator

_accelerator: Optional[DeepSpeedAccelerator] = None

SUPPORTED = ("tpu", "cpu")


def _detect_name() -> str:
    override = os.environ.get("DS_ACCELERATOR")
    if override:
        if override not in SUPPORTED:
            raise ValueError(f"DS_ACCELERATOR={override!r} not in {SUPPORTED}")
        return override
    try:
        import jax

        platforms = {d.platform for d in jax.local_devices()}
    except Exception:
        return "cpu"
    if platforms - {"cpu"}:
        return "tpu"  # any non-cpu XLA platform takes the TPU path
    return "cpu"


def get_accelerator() -> DeepSpeedAccelerator:
    global _accelerator
    if _accelerator is None:
        name = _detect_name()
        if name == "tpu":
            from .tpu_accelerator import TPU_Accelerator

            _accelerator = TPU_Accelerator()
        else:
            from .tpu_accelerator import CPU_Accelerator

            _accelerator = CPU_Accelerator()
    return _accelerator


def set_accelerator(accel: DeepSpeedAccelerator) -> None:
    global _accelerator
    _accelerator = accel


def is_current_accelerator_supported() -> bool:
    return get_accelerator().name() in SUPPORTED
