"""TPU accelerator implementation.

The TPU analogue of the reference's ``accelerator/cuda_accelerator.py``.  The
communication backend is "xla" — collectives compile into the program over
ICI/DCN rather than going through an NCCL-style library (see comm/backend).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from .abstract_accelerator import DeepSpeedAccelerator


class TPU_Accelerator(DeepSpeedAccelerator):
    def __init__(self):
        super().__init__()
        self._name = "tpu"
        # XLA collectives over ICI/DCN are the data plane; no NCCL analogue needed.
        self._communication_backend_name = "xla"

    def _platform_devices(self) -> List[Any]:
        import jax

        devs = jax.local_devices()
        tpu_like = [d for d in devs if d.platform not in ("cpu",)]
        return tpu_like if tpu_like else devs

    def device_name(self, device_index: Optional[int] = None) -> str:
        if device_index is None:
            return "tpu"
        return f"tpu:{device_index}"

    def devices(self) -> List[Any]:
        return self._platform_devices()

    def device_count(self) -> int:
        return len(self._platform_devices())

    def memory_stats(self, device_index: Optional[int] = None) -> Dict[str, int]:
        devs = self._platform_devices()
        if not devs:
            return {}
        dev = devs[device_index or 0]
        try:
            stats = dev.memory_stats() or {}
        except Exception:
            stats = {}
        return {k: int(v) for k, v in stats.items()}

    def is_bf16_supported(self) -> bool:
        return True

    def is_fp16_supported(self) -> bool:
        # fp16 compute is supported on TPU but bf16 is native; keep fp16 for
        # loss-scaling parity paths.
        return True

    def op_builder_dir(self) -> str:
        return "deepspeed_tpu.ops.op_builder"


class CPU_Accelerator(DeepSpeedAccelerator):
    """Simulated-mesh accelerator for tests (XLA host platform, N virtual devices).

    Analogue of the reference's ``accelerator/cpu_accelerator.py`` which lets the
    test suite run GPU-less; here it lets the suite run TPU-less with
    ``--xla_force_host_platform_device_count``.
    """

    def __init__(self):
        super().__init__()
        self._name = "cpu"
        self._communication_backend_name = "xla"

    def device_name(self, device_index: Optional[int] = None) -> str:
        return "cpu" if device_index is None else f"cpu:{device_index}"

    def devices(self) -> List[Any]:
        import jax

        return jax.local_devices()

    def device_count(self) -> int:
        return len(self.devices())

    def memory_stats(self, device_index: Optional[int] = None) -> Dict[str, int]:
        import psutil  # stdlib-adjacent; present in this image

        vm = psutil.virtual_memory()
        return {"bytes_limit": int(vm.total), "bytes_in_use": int(vm.used)}

    def is_bf16_supported(self) -> bool:
        return True

    def is_fp16_supported(self) -> bool:
        return True

    def op_builder_dir(self) -> str:
        return "deepspeed_tpu.ops.op_builder"
