"""Monitor backends (reference ``deepspeed/monitor/monitor.py:13,29``).

``MonitorMaster`` fans out ``(name, value, step)`` events to TensorBoard /
W&B / CSV writers on process 0.  TensorBoard uses torch's event writer (torch
is baked into the image, CPU-only, which is all a writer needs); both external
backends degrade to warnings when unavailable.
"""
from __future__ import annotations

import os
import threading
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

from ..utils.logging import logger

Event = Tuple[str, float, int]


class Monitor:
    def __init__(self, monitor_config):
        self.monitor_config = monitor_config

    def write_events(self, event_list: List[Event]) -> None:
        raise NotImplementedError

    def write_report(self, name: str, text: str) -> None:
        """Freeform diagnostic report (watchdog stack dumps, terminal
        supervisor diagnoses).  Backends that can persist text do; the
        default is the log, so a report is never silently dropped."""
        logger.error("monitor report [%s]:\n%s", name, text)


class TensorBoardMonitor(Monitor):
    def __init__(self, tensorboard_config):
        super().__init__(tensorboard_config)
        self.summary_writer = None
        if not tensorboard_config.enabled:
            return
        try:
            from torch.utils.tensorboard import SummaryWriter

            log_dir = os.path.join(tensorboard_config.output_path or "./runs",
                                   tensorboard_config.job_name)
            self.summary_writer = SummaryWriter(log_dir=log_dir)
        except Exception as e:  # pragma: no cover
            logger.warning(f"tensorboard writer unavailable: {e}")

    def write_events(self, event_list: List[Event]) -> None:
        if self.summary_writer is None:
            return
        for name, value, step in event_list:
            self.summary_writer.add_scalar(name, value, step)
        self.summary_writer.flush()


class WandbMonitor(Monitor):
    def __init__(self, wandb_config):
        super().__init__(wandb_config)
        self.enabled = wandb_config.enabled
        if self.enabled:
            try:
                import wandb

                wandb.init(project=wandb_config.project, group=wandb_config.group,
                           entity=wandb_config.team)
                self._wandb = wandb
            except Exception as e:  # pragma: no cover
                logger.warning(f"wandb unavailable: {e}")
                self.enabled = False

    def write_events(self, event_list: List[Event]) -> None:
        if not self.enabled:
            return
        for name, value, step in event_list:
            self._wandb.log({name: value}, step=step)


class csvMonitor(Monitor):
    def __init__(self, csv_config):
        super().__init__(csv_config)
        self.enabled = csv_config.enabled
        self.log_dir = None
        self.filenames: dict = {}
        if self.enabled:
            self.log_dir = os.path.join(csv_config.output_path or "./csv_logs",
                                        csv_config.job_name)
            os.makedirs(self.log_dir, exist_ok=True)

    def write_events(self, event_list: List[Event]) -> None:
        if not self.enabled:
            return
        for name, value, step in event_list:
            fname = os.path.join(self.log_dir, name.replace("/", "_") + ".csv")
            new = not os.path.exists(fname)
            with open(fname, "a") as f:
                if new:
                    f.write("step,value\n")
                f.write(f"{step},{value}\n")

    def write_report(self, name: str, text: str) -> None:
        if not self.enabled:
            return
        fname = os.path.join(self.log_dir, name.replace("/", "_") + ".txt")
        with open(fname, "a") as f:
            f.write(text + "\n")


class InMemoryMonitor(Monitor):
    """Event sink that keeps ``(name, value, step)`` tuples in memory.

    Used by the serving engine's tests/tools to assert on the gauge stream
    (TTFT, tokens/sec, queue depth, slot occupancy — serving.py writes
    ``serve/*`` events every tick) without filesystem or backend setup.

    **Bounded**: the serving loop emits ~10 gauges per working tick, so an
    unbounded list leaks memory linearly under a soak.  ``events`` is a
    ring of the newest ``max_events`` records; evictions are counted on
    ``dropped_events`` (visible to the Prometheus exporter) rather than
    silent.  ``series()``/``latest()`` semantics are unchanged over the
    retained window.

    **Thread-safe**: watchdog / supervisor / async-checkpoint threads emit
    concurrently with the serving loop; writes and snapshot reads hold one
    lock (reads copy, so iteration never races an append)."""

    DEFAULT_MAX_EVENTS = 65536
    DEFAULT_MAX_REPORTS = 256   # reports carry multi-KB flight dumps

    def __init__(self, monitor_config=None, max_events: Optional[int] = None,
                 max_reports: Optional[int] = None):
        super().__init__(monitor_config)
        if max_events is None:
            max_events = self.DEFAULT_MAX_EVENTS
        if max_events < 1:
            raise ValueError(f"max_events={max_events} must be >= 1")
        self.max_events = int(max_events)
        self.max_reports = int(max_reports if max_reports is not None
                               else self.DEFAULT_MAX_REPORTS)
        self.events: Deque[Event] = deque(maxlen=self.max_events)
        self.reports: Deque[Tuple[str, str]] = deque(maxlen=self.max_reports)
        self.dropped_events = 0
        self.dropped_reports = 0
        # name -> newest value, maintained on write: latest() is O(1)
        # instead of a full ring copy+scan — the SLO evaluator polls it
        # per gauge rule per serving tick (observability/slo.py), which a
        # 65536-deque scan would turn into real hot-loop cost.  Bounded by
        # the number of DISTINCT gauge names, not traffic.
        self._latest: Dict[str, float] = {}
        self._lock = threading.Lock()

    def write_events(self, event_list: List[Event]) -> None:
        with self._lock:
            for ev in event_list:
                if len(self.events) == self.max_events:
                    self.dropped_events += 1
                self.events.append(ev)
                self._latest[ev[0]] = ev[1]

    def write_report(self, name: str, text: str) -> None:
        with self._lock:
            if len(self.reports) == self.max_reports:
                self.dropped_reports += 1
            self.reports.append((name, text))

    def events_snapshot(self) -> List[Event]:
        """Locked copy of the retained events — what an exporter on another
        thread must read instead of iterating ``events`` directly."""
        with self._lock:
            return list(self.events)

    def series(self, name: str) -> List[Tuple[int, float]]:
        """[(step, value)] of every retained event with this name, in
        write order."""
        snapshot = self.events_snapshot()
        return [(step, value) for (n, value, step) in snapshot if n == name]

    def latest(self, name: str) -> Optional[float]:
        """Most recent value of a gauge, or None if it never fired —
        what a health/readiness assertion (and every SLO gauge rule)
        usually wants.  O(1): read from the write-maintained map, which
        remembers a name even after its events rotate out of the ring
        (the newest value of a live gauge is never "gone")."""
        with self._lock:
            return self._latest.get(name)

    def latest_map(self) -> Dict[str, float]:
        """Locked copy of name -> newest value ever written.  The
        Prometheus exposition prefers this over scanning the event ring:
        once-at-init gauges (serve/mesh_devices, serve/kv_pool_bytes_*)
        must not vanish from /metrics when per-tick traffic rotates their
        events out of the bounded ring."""
        with self._lock:
            return dict(self._latest)


class MonitorMaster(Monitor):
    """Rank-0 fan-out to all enabled writers (reference monitor.py:29)."""

    def __init__(self, monitor_config):
        super().__init__(monitor_config)
        import jax

        self._is_writer = jax.process_index() == 0
        self.tb_monitor = TensorBoardMonitor(monitor_config.tensorboard) if self._is_writer else None
        self.wandb_monitor = WandbMonitor(monitor_config.wandb) if self._is_writer else None
        self.csv_monitor = csvMonitor(monitor_config.csv_monitor) if self._is_writer else None

    def write_events(self, event_list: List[Event]) -> None:
        if not self._is_writer:
            return
        for mon in (self.tb_monitor, self.wandb_monitor, self.csv_monitor):
            if mon is not None:
                mon.write_events(event_list)

    def write_report(self, name: str, text: str) -> None:
        # every process may report (a hang is per-host); csv persists on the
        # writer, the log carries it everywhere
        logger.error("monitor report [%s]:\n%s", name, text)
        if self.csv_monitor is not None:
            self.csv_monitor.write_report(name, text)
