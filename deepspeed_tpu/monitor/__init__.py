from .monitor import (InMemoryMonitor, Monitor, MonitorMaster,  # noqa: F401
                      TensorBoardMonitor, WandbMonitor, csvMonitor)
