"""``OnDevice`` — construct model parameters on a chosen device, or on no
device at all (reference ``deepspeed/utils/init_on_device.py:81``).

The reference patches ``torch.Tensor`` constructors so ``with
OnDevice(dtype=..., device="meta")`` builds million-dollar models as empty
meta tensors.  The JAX analogue needs no constructor patching: abstract
construction IS a first-class transform (``jax.eval_shape``), and concrete
placement is ``jax.default_device``.  ``OnDevice`` packages both behind the
reference's context-manager surface; model ``init_fn``s that honor it (the
whole ``deepspeed_tpu.models`` family, ``PipelineModule``) consult
:func:`current_on_device`.

    with deepspeed_tpu.OnDevice(dtype=jnp.bfloat16, device="meta"):
        shapes = model.init_fn(rng)       # ShapeDtypeStructs — zero bytes

    with deepspeed_tpu.OnDevice(dtype=jnp.bfloat16, device="cpu"):
        params = model.init_fn(rng)       # host RAM, not HBM

Engine note: ``deepspeed_tpu.initialize`` already materializes params
*born sharded* via ``jit(init, out_shardings=...)`` (the ``zero.Init``
redesign), so OnDevice is for user-side inspection/staging flows — sizing a
model without devices, or staging weights in host RAM before a sharded
device_put.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, Callable, Optional

_STATE = threading.local()


def current_on_device() -> Optional["OnDevice"]:
    """The innermost active OnDevice context (None outside any)."""
    return getattr(_STATE, "ctx", None)


class OnDevice(contextlib.AbstractContextManager):
    def __init__(self, dtype: Any = None, device: str = "meta",
                 enabled: bool = True):
        self.dtype = dtype
        self.device = device
        self.enabled = enabled
        self._prev = None

    def __enter__(self):
        self._prev = current_on_device()
        _STATE.ctx = self if self.enabled else self._prev
        return self

    def __exit__(self, *exc):
        _STATE.ctx = self._prev
        return False

    # -- application -------------------------------------------------------

    def apply_init(self, init_fn: Callable, *args) -> Any:
        """Run ``init_fn(*args)`` under this context's placement rules."""
        import jax
        import jax.numpy as jnp

        if any(isinstance(a, jax.core.Tracer) for a in args):
            # e.g. deepspeed_tpu.initialize() called inside `with OnDevice()`:
            # the engine's jitted sharded-init would trace into this context
            # and hand ShapeDtypeStructs to downstream .astype calls — fail
            # with the actual cause instead
            raise RuntimeError(
                "OnDevice context is active while a jitted initializer is "
                "tracing. Close the OnDevice context before "
                "deepspeed_tpu.initialize(): the engine already materializes "
                "params born-sharded (OnDevice is for user-side "
                "inspection/staging flows).")

        def cast(tree):
            if self.dtype is None:
                return tree
            return jax.tree_util.tree_map(
                lambda x: x.astype(self.dtype)
                if jnp.issubdtype(x.dtype, jnp.floating) else x, tree)

        if self.device == "meta":
            shapes = jax.eval_shape(init_fn, *args)
            if self.dtype is None:
                return shapes
            return jax.tree_util.tree_map(
                lambda s: jax.ShapeDtypeStruct(
                    s.shape, self.dtype
                    if jnp.issubdtype(s.dtype, jnp.floating) else s.dtype),
                shapes)
        devices = [d for d in jax.devices() if d.platform == self.device] \
            or jax.devices(self.device)
        with jax.default_device(devices[0]):
            return cast(init_fn(*args))


def on_device_init(init_fn: Callable) -> Callable:
    """Wrap an ``init_fn(rng) -> params`` so it honors an active OnDevice
    context — how the model family opts in."""
    import functools

    @functools.wraps(init_fn)
    def wrapped(*args):
        ctx = current_on_device()
        if ctx is None:
            return init_fn(*args)
        return ctx.apply_init(init_fn, *args)

    return wrapped
