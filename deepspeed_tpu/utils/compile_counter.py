"""Process-wide XLA compile counter over ``jax.monitoring``.

The ``/jax/core/compile/backend_compile_duration`` duration event fires once
per actual backend compile (cache hits don't), which makes it the honest
instrument for zero-recompile contracts (serving admission, bench steady
state).  ``jax.monitoring`` has no unregister, so the listener is a
process-wide singleton — every caller shares one event list and takes
deltas around the section it cares about.
"""
from __future__ import annotations

from typing import Callable, List

_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_EVENTS: List[str] = []
_INSTALLED = False


def compile_counter() -> Callable[[], int]:
    """Install (once) the backend-compile listener and return a zero-arg
    ``count()``; callers snapshot it before/after a section and diff."""
    global _INSTALLED
    if not _INSTALLED:
        _INSTALLED = True
        import jax.monitoring

        def _listen(name, duration, **kw):
            if name == _BACKEND_COMPILE_EVENT:
                _EVENTS.append(name)

        jax.monitoring.register_event_duration_secs_listener(_listen)
    return lambda: len(_EVENTS)
